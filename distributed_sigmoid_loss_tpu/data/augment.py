"""JAX-native image augmentation for contrastive pre-training.

The reference has no data layer at all; real SigLIP training needs the standard
augmentation stack (Inception-style random resized crop + horizontal flip, optional
color jitter). TPU-first design constraints:

- **Static shapes under jit**: a data-dependent crop SIZE would be a dynamic shape,
  which XLA cannot compile. Instead the sampled crop box becomes a per-sample
  ``scale``/``translation`` for :func:`jax.image.scale_and_translate`, whose output
  shape is fixed — the crop-and-resize is one fused gather/convolution, vmapped over
  the batch.
- **Key-driven determinism**: every op takes an explicit ``jax.random`` key; the same
  key reproduces the same batch bit-for-bit (the reference's seeded-data philosophy,
  test_distributed_sigmoid_loss.py:15-32, applied to augmentation).
- **Device-resident**: all ops are jittable and run on-chip, so augmentation overlaps
  the previous step's compute when composed with ``data.prefetch``.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "random_flip",
    "random_resized_crop",
    "color_jitter",
    "normalize",
    "augment_batch",
]


def random_flip(key: jax.Array, images: jax.Array) -> jax.Array:
    """Per-sample horizontal flip with probability 0.5. images: (b, h, w, c)."""
    flip = jax.random.bernoulli(key, 0.5, (images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


def _sample_crop_box(key, h, w, scale, ratio):
    """Inception-style crop: area fraction ~ U(scale), log-aspect ~ U(log(ratio)).

    Returns (crop_h, crop_w, top, left) as f32 scalars (continuous coordinates —
    the resize interpolates, so there is no need to round to integer pixels).
    Degenerate draws (crop larger than the image) fall back to a center crop of
    the largest valid size, matching torchvision's fallback semantics.
    """
    k_area, k_ratio, k_top, k_left = jax.random.split(key, 4)
    area = h * w * jax.random.uniform(k_area, minval=scale[0], maxval=scale[1])
    log_r = jax.random.uniform(
        k_ratio, minval=jnp.log(ratio[0]), maxval=jnp.log(ratio[1])
    )
    r = jnp.exp(log_r)
    crop_w = jnp.sqrt(area * r)
    crop_h = jnp.sqrt(area / r)
    # Fallback: clamp to the image, preserving the sampled aspect where possible.
    clamp = jnp.minimum(jnp.minimum(h / crop_h, w / crop_w), 1.0)
    crop_h = crop_h * clamp
    crop_w = crop_w * clamp
    top = jax.random.uniform(k_top) * (h - crop_h)
    left = jax.random.uniform(k_left) * (w - crop_w)
    return crop_h, crop_w, top, left


def random_resized_crop(
    key: jax.Array,
    images: jax.Array,
    out_size: int,
    scale: tuple[float, float] = (0.08, 1.0),
    ratio: tuple[float, float] = (3 / 4, 4 / 3),
    method: str = "bilinear",
) -> jax.Array:
    """Per-sample Inception crop + resize to (out_size, out_size), static shapes.

    images: (b, h, w, c) → (b, out_size, out_size, c). The crop box is applied as
    a ``scale_and_translate`` so the whole op is one fixed-shape resize kernel.
    """
    b, h, w, c = images.shape

    def one(img, k):
        crop_h, crop_w, top, left = _sample_crop_box(k, h, w, scale, ratio)
        # Output pixel o maps to input pixel top + o * crop_h/out_size:
        # scale_and_translate computes in = (out - translation) / scale.
        scale_hw = jnp.stack([out_size / crop_h, out_size / crop_w])
        translation = jnp.stack([-top * out_size / crop_h, -left * out_size / crop_w])
        return jax.image.scale_and_translate(
            img, (out_size, out_size, c), (0, 1, 2),
            jnp.concatenate([scale_hw, jnp.ones(1)]),
            jnp.concatenate([translation, jnp.zeros(1)]),
            method=method,
        )

    return jax.vmap(one)(images, jax.random.split(key, b))


def color_jitter(
    key: jax.Array,
    images: jax.Array,
    brightness: float = 0.4,
    contrast: float = 0.4,
    saturation: float = 0.4,
) -> jax.Array:
    """Per-sample brightness/contrast/saturation jitter (factors ~ U(1±x)),
    clamped back to [0, 1] after each op (torchvision ColorJitter semantics —
    inputs are [0, 1] floats)."""
    b = images.shape[0]
    kb, kc, ks = jax.random.split(key, 3)

    def factors(k, amount):
        return jax.random.uniform(
            k, (b, 1, 1, 1), minval=1.0 - amount, maxval=1.0 + amount
        )

    out = jnp.clip(images * factors(kb, brightness), 0.0, 1.0)
    mean = out.mean(axis=(1, 2, 3), keepdims=True)
    out = jnp.clip((out - mean) * factors(kc, contrast) + mean, 0.0, 1.0)
    gray = out.mean(axis=-1, keepdims=True)
    out = jnp.clip((out - gray) * factors(ks, saturation) + gray, 0.0, 1.0)
    return out


def normalize(
    images: jax.Array,
    mean: Sequence[float] = (0.5, 0.5, 0.5),
    std: Sequence[float] = (0.5, 0.5, 0.5),
) -> jax.Array:
    """Channel normalization; SigLIP's published preprocessing is (0.5, 0.5),
    mapping [0, 1] floats to [-1, 1]. Integer input is treated as [0, 255] pixel
    values: scaled to [0, 1] first (casting 0.5 to an int dtype would otherwise
    truncate to 0 and divide by zero)."""
    if not jnp.issubdtype(images.dtype, jnp.floating):
        images = images.astype(jnp.float32) / 255.0
    mean = jnp.asarray(mean, images.dtype)
    std = jnp.asarray(std, images.dtype)
    return (images - mean) / std


def augment_batch(
    key: jax.Array,
    images: jax.Array,
    out_size: int,
    train: bool = True,
    jitter: float = 0.0,
) -> jax.Array:
    """The standard contrastive train transform: random resized crop + flip
    (+ optional color jitter), then SigLIP normalization. ``train=False`` is the
    eval transform: plain resize + normalize. Jittable; fixed output shapes.

    Integer input is [0, 255] pixels, converted to [0, 1] floats HERE — the
    crop/resize would otherwise produce float [0, 255] values that skip
    ``normalize``'s own integer handling."""
    if not jnp.issubdtype(images.dtype, jnp.floating):
        images = images.astype(jnp.float32) / 255.0
    if not train:
        b, h, w, c = images.shape
        resized = jax.image.resize(images, (b, out_size, out_size, c), "bilinear")
        return normalize(resized)
    k_crop, k_flip, k_jit = jax.random.split(key, 3)
    out = random_resized_crop(k_crop, images, out_size)
    out = random_flip(k_flip, out)
    if jitter:
        out = color_jitter(k_jit, out, jitter, jitter, jitter)
    return normalize(out)
