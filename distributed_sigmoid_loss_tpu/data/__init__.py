from distributed_sigmoid_loss_tpu.data.loader import (  # noqa: F401
    PrefetchStats,
    batch_shardings,
    global_batch_from_local,
    prefetch,
    put_batch,
)
from distributed_sigmoid_loss_tpu.data.synthetic import (  # noqa: F401
    SyntheticImageText,
    shard_batch,
)
from distributed_sigmoid_loss_tpu.data.tokenizer import (  # noqa: F401
    BpeTokenizer,
    ByteTokenizer,
)
from distributed_sigmoid_loss_tpu.data.native_loader import (  # noqa: F401
    NativeSyntheticImageText,
    native_available,
)
from distributed_sigmoid_loss_tpu.data.files import (  # noqa: F401
    ImageTextFolder,
    ImageTextShards,
    decode_and_resize,
)
from distributed_sigmoid_loss_tpu.data.augment import (  # noqa: F401
    augment_batch,
    color_jitter,
    normalize,
    random_flip,
    random_resized_crop,
)
from distributed_sigmoid_loss_tpu.data.workers import (  # noqa: F401
    default_data_workers,
    resolve_data_workers,
)
