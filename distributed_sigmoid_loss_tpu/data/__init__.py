from distributed_sigmoid_loss_tpu.data.synthetic import (  # noqa: F401
    SyntheticImageText,
    shard_batch,
)
