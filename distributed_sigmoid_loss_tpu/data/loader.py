"""Input pipeline utilities: device placement, multi-host global batches, prefetch.

The reference's "data layer" is seeded tensors sliced per rank
(/root/reference/test_distributed_sigmoid_loss.py:57-68). A real TPU training job
needs three more things, provided here:

- :func:`batch_shardings` / :func:`put_batch` — commit a host batch to the mesh's
  ``dp`` axis (the pjit analogue of per-rank slicing: one global array, XLA owns
  the distribution).
- :func:`global_batch_from_local` — multi-host assembly: each host contributes the
  shard of the global batch its local devices own, via
  ``jax.make_array_from_process_local_data`` (no cross-host data movement; the DCN
  never sees input data).
- :func:`prefetch` — a background thread keeps N batches ahead, overlapping host
  data work and host→device transfer with device compute.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis

__all__ = [
    "batch_shardings",
    "put_batch",
    "global_batch_from_local",
    "prefetch",
    "PrefetchStats",
]


class PrefetchStats:
    """Starvation counters for one :func:`prefetch` stream.

    The overlap question — "is the device waiting on the host?" — must be a
    measured number, not a guess from throughput deltas. The producer thread
    and the consumer each record how long they spent blocked on the queue:

    - ``consumer_wait_s`` — time the consumer spent blocked in ``get`` with
      the queue empty. This is device starvation: the step loop had nothing
      to run.
    - ``producer_wait_s`` — time the worker spent blocked in ``put`` with the
      queue full. This is the healthy direction (the host is ahead).
    - ``produced`` / ``consumed`` — batch counters (monotonic).
    - ``queue_depth`` — queue occupancy observed at the last consumer get.

    ``input_wait_frac`` is the headline ratio: consumer wait over wall time
    since the first consumer request. ~0 means prefetch keeps the device fed;
    anything materially positive is host-bound feeding and names the gap the
    ``data-bench`` stage table attributes.

    Counter updates are single-writer per field (producer writes
    producer-side fields, consumer the consumer-side ones), so reads need no
    lock — snapshots are approximate by one batch at worst.
    """

    def __init__(self):
        self.produced = 0
        self.consumed = 0
        self.producer_wait_s = 0.0
        self.consumer_wait_s = 0.0
        self.queue_depth = 0
        self._t_first_get: float | None = None

    def input_wait_frac(self) -> float:
        """Fraction of consumer wall time spent starved (0.0 before the first
        get — a log line must never divide by zero)."""
        if self._t_first_get is None:
            return 0.0
        elapsed = time.perf_counter() - self._t_first_get
        if elapsed <= 0.0:
            return 0.0
        return min(1.0, self.consumer_wait_s / elapsed)

    def snapshot(self) -> dict:
        return {
            "produced": self.produced,
            "consumed": self.consumed,
            "producer_wait_s": round(self.producer_wait_s, 4),
            "consumer_wait_s": round(self.consumer_wait_s, 4),
            "queue_depth": self.queue_depth,
            "input_wait_frac": round(self.input_wait_frac(), 4),
        }


def batch_shardings(mesh: Mesh, batch: Any, axis_name: str = data_axis) -> Any:
    """Leading-axis-over-``axis_name`` NamedSharding for every leaf of ``batch``."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda _: sharding, batch)


def put_batch(batch: Any, mesh: Mesh, axis_name: str = data_axis) -> Any:
    """Commit a (host) batch pytree onto the mesh, batch dim sharded over dp."""
    return jax.device_put(batch, batch_shardings(mesh, batch, axis_name))


def global_batch_from_local(local_batch: Any, mesh: Mesh, axis_name: str = data_axis) -> Any:
    """Assemble a global batch from per-host shards (multi-host training).

    Each host passes the rows its own devices will hold — ``global_batch /
    process_count`` examples, in process order. Returns global jax.Arrays whose
    addressable shards are exactly this host's data (zero cross-host transfer).
    On a single host this is equivalent to :func:`put_batch`.
    """
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), local_batch
    )


def prefetch(
    it: Iterable[Any],
    mesh: Mesh,
    size: int = 2,
    axis_name: str = data_axis,
    multihost: bool = False,
    put: Callable[[Any, Mesh, Any], Any] | None = None,
    stats: PrefetchStats | None = None,
) -> Iterator[Any]:
    """Iterate ``it``, keeping ``size`` device-resident batches in flight.

    A daemon thread pulls host batches and issues the (async) host→device
    transfer; consumers receive committed global arrays. Exceptions from the
    source iterator propagate to the consumer at the matching position.
    Abandoning the iterator early (``break``, exception, garbage collection)
    closes it: the worker is woken, JOINED (bounded), and the queued device
    batches are dropped rather than pinned in HBM for the life of the
    process — after close the source iterator has no concurrent reader, so
    the caller may keep using it single-threaded.

    ``put`` overrides the host→device commit (default
    :func:`put_batch` / :func:`global_batch_from_local` per ``multihost``) —
    the CLI threads its multi-process slice-and-place through this. ``stats``
    (a :class:`PrefetchStats`) makes the overlap observable: queue depth,
    producer/consumer blocked time, and the ``input_wait_frac`` starvation
    ratio the train loop logs.
    """
    q: queue.Queue = queue.Queue(maxsize=size)
    _END = object()
    stop = threading.Event()

    if put is None:
        put = global_batch_from_local if multihost else put_batch

    def enqueue(item) -> bool:
        t0 = time.perf_counter() if stats is not None else 0.0
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                if stats is not None:
                    # Time from the put REQUEST to its success: a put that
                    # blocked inside its first timeout window counts too. An
                    # unblocked put adds ~µs — noise, and the healthy sign.
                    stats.producer_wait_s += time.perf_counter() - t0
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in it:
                if not enqueue(put(batch, mesh, axis_name)):
                    return
                if stats is not None:
                    stats.produced += 1
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            enqueue(e)
            return
        enqueue(_END)

    thread = threading.Thread(
        target=worker, daemon=True, name="dsl-prefetch"
    )
    thread.start()
    try:
        while True:
            if stats is not None:
                now = time.perf_counter()
                if stats._t_first_get is None:
                    stats._t_first_get = now
                stats.queue_depth = q.qsize()
                item = q.get()
                stats.consumer_wait_s += time.perf_counter() - now
            else:
                item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            if stats is not None:
                stats.consumed += 1
            yield item
    finally:
        # Generator closed (early break / GC): unblock the worker, then JOIN
        # it before draining — a worker still blocked inside ``q.put`` could
        # otherwise deliver one more (stale) batch into the drained queue,
        # where it outlives the generator pinned in HBM. The worker's put
        # loop polls ``stop`` every 0.1 s, so the bounded join only expires
        # if the SOURCE iterator itself is wedged mid-``next`` — in which
        # case the drain below still runs and the daemon thread cannot
        # enqueue (stop is set).
        stop.set()
        thread.join(timeout=5.0)
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
