"""Input pipeline utilities: device placement, multi-host global batches, prefetch.

The reference's "data layer" is seeded tensors sliced per rank
(/root/reference/test_distributed_sigmoid_loss.py:57-68). A real TPU training job
needs three more things, provided here:

- :func:`batch_shardings` / :func:`put_batch` — commit a host batch to the mesh's
  ``dp`` axis (the pjit analogue of per-rank slicing: one global array, XLA owns
  the distribution).
- :func:`global_batch_from_local` — multi-host assembly: each host contributes the
  shard of the global batch its local devices own, via
  ``jax.make_array_from_process_local_data`` (no cross-host data movement; the DCN
  never sees input data).
- :func:`prefetch` — a background thread keeps N batches ahead, overlapping host
  data work and host→device transfer with device compute.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Iterable, Iterator

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis

__all__ = [
    "batch_shardings",
    "put_batch",
    "global_batch_from_local",
    "prefetch",
]


def batch_shardings(mesh: Mesh, batch: Any, axis_name: str = data_axis) -> Any:
    """Leading-axis-over-``axis_name`` NamedSharding for every leaf of ``batch``."""
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(lambda _: sharding, batch)


def put_batch(batch: Any, mesh: Mesh, axis_name: str = data_axis) -> Any:
    """Commit a (host) batch pytree onto the mesh, batch dim sharded over dp."""
    return jax.device_put(batch, batch_shardings(mesh, batch, axis_name))


def global_batch_from_local(local_batch: Any, mesh: Mesh, axis_name: str = data_axis) -> Any:
    """Assemble a global batch from per-host shards (multi-host training).

    Each host passes the rows its own devices will hold — ``global_batch /
    process_count`` examples, in process order. Returns global jax.Arrays whose
    addressable shards are exactly this host's data (zero cross-host transfer).
    On a single host this is equivalent to :func:`put_batch`.
    """
    sharding = NamedSharding(mesh, P(axis_name))
    return jax.tree.map(
        lambda x: jax.make_array_from_process_local_data(sharding, x), local_batch
    )


def prefetch(
    it: Iterable[Any],
    mesh: Mesh,
    size: int = 2,
    axis_name: str = data_axis,
    multihost: bool = False,
) -> Iterator[Any]:
    """Iterate ``it``, keeping ``size`` device-resident batches in flight.

    A daemon thread pulls host batches and issues the (async) host→device
    transfer; consumers receive committed global arrays. Exceptions from the
    source iterator propagate to the consumer at the matching position.
    Abandoning the iterator early (``break``, exception, garbage collection)
    closes it: the worker stops and the queued device batches are released
    rather than pinned in HBM for the life of the process.
    """
    q: queue.Queue = queue.Queue(maxsize=size)
    _END = object()
    stop = threading.Event()

    put = global_batch_from_local if multihost else put_batch

    def enqueue(item) -> bool:
        while not stop.is_set():
            try:
                q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def worker():
        try:
            for batch in it:
                if not enqueue(put(batch, mesh, axis_name)):
                    return
        except BaseException as e:  # noqa: BLE001 — relayed to the consumer
            enqueue(e)
            return
        enqueue(_END)

    threading.Thread(target=worker, daemon=True).start()
    try:
        while True:
            item = q.get()
            if item is _END:
                return
            if isinstance(item, BaseException):
                raise item
            yield item
    finally:
        # Generator closed (early break / GC): unblock the worker and drop any
        # queued device arrays.
        stop.set()
        while not q.empty():
            try:
                q.get_nowait()
            except queue.Empty:
                break
