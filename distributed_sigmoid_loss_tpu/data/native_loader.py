"""ctypes binding for the native (C++) input-pipeline engine.

``native/dataloader.cc`` is the framework's host-side native runtime component:
a worker pool generates batches into a bounded ring of reusable buffers off the
GIL, and Python drains them in strict batch-index order with one memcpy — the
role torch's native DataLoader workers / tf.data's C++ runtime play for the
reference ecosystem. Batches are a pure function of (seed, batch_index), so the
stream is deterministic regardless of thread count (tested in
tests/test_native_loader.py).

The binding uses ctypes (no pybind11 in this environment); the shared library is
built on first use with g++ (``native/Makefile`` has the same recipe). Callers
should treat :class:`NativeSyntheticImageText` as a faster drop-in for
``data.synthetic.SyntheticImageText`` — same dict-of-arrays batches, compose
with ``data.loader.prefetch`` for the host→device overlap. Use
:func:`native_available` to fall back to the numpy pipeline where no C++
toolchain exists.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator

import numpy as np

from distributed_sigmoid_loss_tpu.data.workers import default_data_workers
from distributed_sigmoid_loss_tpu.utils.config import SigLIPConfig

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = [
    "build_shared_lib",
    "native_available",
    "NativeSyntheticImageText",
    "load_library",
]

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "dataloader.cc")
_LIB = os.path.join(_NATIVE_DIR, "libdsl_data.so")
_build_lock = named_lock("data.native_loader._build_lock")
_lib = None


# One flag list for both build paths (the Makefile defaults to the same set and
# both honor a CXXFLAGS override).
_DEFAULT_CXXFLAGS = "-O3 -std=c++17 -fPIC -Wall -Wextra -pthread"


def build_shared_lib(src: str, lib: str, ldflags: tuple[str, ...] = ()) -> str:
    """Compile ``src`` into shared library ``lib`` when missing or older than
    its source; returns the library path. Shared by every native component
    (dataloader, jpeg decode) so the artifact rules stay identical:

    - A prebuilt ``.so`` without the source (deployment artifact) is used
      as-is.
    - A stale ``.so`` on a machine without a compiler is used with a warning
      rather than failing a working setup.
    """
    have_lib = os.path.exists(lib)
    if not os.path.exists(src):
        if have_lib:
            return lib
        raise RuntimeError(
            f"native build: neither {lib} nor its source {src} exists"
        )
    if have_lib and os.path.getmtime(lib) >= os.path.getmtime(src):
        return lib
    cmd = [
        os.environ.get("CXX", "g++"),
        *os.environ.get("CXXFLAGS", _DEFAULT_CXXFLAGS).split(),
        "-shared", "-o", lib, src, *ldflags,
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True)
        failure = proc.returncode != 0 and (
            f"exit {proc.returncode}:\n{proc.stderr}"
        )
    except OSError as e:  # compiler missing entirely
        failure = str(e)
    if failure:
        if have_lib:
            import warnings

            warnings.warn(
                f"native build: rebuild for newer {src} failed "
                f"({failure}); using the existing (stale) {lib}",
                RuntimeWarning,
                stacklevel=2,
            )
            return lib
        raise RuntimeError(
            f"native build failed ({' '.join(cmd)}): {failure}"
        )
    return lib


def _build() -> str:
    return build_shared_lib(_SRC, _LIB)


def load_library():
    """Build if needed and load the engine; raises where no toolchain exists."""
    global _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        lib = ctypes.CDLL(_build())
        lib.dsl_pipeline_create.restype = ctypes.c_void_p
        lib.dsl_pipeline_create.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int, ctypes.c_int,
        ]
        lib.dsl_pipeline_next.restype = ctypes.c_int64
        lib.dsl_pipeline_next.argtypes = [
            ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_float),
            ctypes.POINTER(ctypes.c_int32),
        ]
        lib.dsl_pipeline_stop.restype = None
        lib.dsl_pipeline_stop.argtypes = [ctypes.c_void_p]
        lib.dsl_pipeline_destroy.restype = None
        lib.dsl_pipeline_destroy.argtypes = [ctypes.c_void_p]
        try:
            # Zero-copy surface (added with the pipelined input layer); a
            # prebuilt .so from before it simply lacks the symbols — the
            # copying path keeps working and batches(zero_copy=True) raises
            # a clear error instead of an AttributeError mid-stream.
            lib.dsl_pipeline_acquire.restype = ctypes.c_int64
            lib.dsl_pipeline_acquire.argtypes = [
                ctypes.c_void_p,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_float)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
            ]
            lib.dsl_pipeline_release.restype = None
            lib.dsl_pipeline_release.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        except AttributeError:
            pass
        _lib = lib
        return _lib


def native_available() -> bool:
    """True when the engine can be used — mirrors :func:`_build`'s requirements:
    a prebuilt .so suffices (even stale: _build warns and keeps it), otherwise
    the source plus a working compiler must be present."""
    if os.path.exists(_LIB):
        return True
    if not os.path.exists(_SRC):
        return False
    try:
        subprocess.run(
            [os.environ.get("CXX", "g++"), "--version"],
            capture_output=True, check=True,
        )
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class NativeSyntheticImageText:
    """Drop-in for ``SyntheticImageText`` backed by the C++ engine.

    Yields ``{"images": (B,H,W,3) f32, "tokens": (B,L) i32}`` numpy batches;
    generation for batch ``n+1..n+queue_depth`` proceeds on C++ threads while
    the caller consumes batch ``n``.
    """

    def __init__(
        self,
        cfg: SigLIPConfig,
        global_batch: int,
        image_seed: int = 42,
        text_seed: int = 40,
        num_threads: int | None = None,
        queue_depth: int = 4,
    ):
        self.cfg = cfg
        self.global_batch = global_batch
        # None = auto: cpu_count minus the prefetch/main threads (the old
        # static 4 oversubscribed small hosts and under-fed big ones).
        self.num_threads = (
            num_threads if num_threads else default_data_workers()
        )
        self._lib = load_library()
        self._handle = self._lib.dsl_pipeline_create(
            global_batch, cfg.vision.image_size, cfg.text.context_length,
            cfg.text.vocab_size, image_seed, text_seed, self.num_threads,
            queue_depth,
        )
        if not self._handle:
            raise ValueError(
                "dsl_pipeline_create rejected the config (all sizes/threads/"
                "depth must be positive)"
            )
        v = cfg.vision
        self._image_shape = (global_batch, v.image_size, v.image_size, 3)
        self._token_shape = (global_batch, cfg.text.context_length)
        self._closed = False
        # Serializes next() calls against close(): close() first wakes any
        # consumer blocked inside the native call (dsl_pipeline_stop, taken
        # WITHOUT this lock), then frees the engine under the lock — so destroy
        # can never race a thread (e.g. the loader.prefetch worker) mid-call.
        self._iter_lock = named_lock("data.native_loader.NativeSyntheticImageText._iter_lock")
        self._close_lock = named_lock("data.native_loader.NativeSyntheticImageText._close_lock")  # serializes concurrent close()rs

    def __iter__(self) -> Iterator[dict]:
        while True:
            images = np.empty(self._image_shape, np.float32)
            tokens = np.empty(self._token_shape, np.int32)
            with self._iter_lock:
                if self._closed:
                    return
                n = self._lib.dsl_pipeline_next(
                    self._handle,
                    images.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                    tokens.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                )
            if n < 0:  # stopped under our feet
                return
            yield {"images": images, "tokens": tokens}

    def batches(self, zero_copy: bool = False) -> Iterator[dict]:
        """Batch stream; ``zero_copy=True`` hands out numpy VIEWS of the C++
        ring slots instead of copying into fresh arrays.

        The views are valid only until the next iteration (or generator
        close) — the slot is handed back to the worker pool then. The
        intended consumer commits the batch inside the loop body (e.g.
        ``data.loader.prefetch``'s worker calling ``put_batch``: the
        host→device transfer reads the ring buffer directly and the
        intermediate numpy copy disappears). Anyone keeping host arrays past
        one iteration must ``np.copy`` them.

        Safe on EVERY backend: jax's CPU client zero-copy-aliases 64-byte-
        aligned host buffers in ``device_put`` (which would leave a live
        "device" array pointing into a recycled slot), so the C++ ring
        deliberately mis-aligns slot payloads (``native/dataloader.cc``
        Slot) — the CPU backend is forced onto its copying path, accelerator
        backends DMA-copy regardless, and "zero-copy" keeps meaning what it
        says: zero HOST-side copies.

        Raises RuntimeError when the loaded library predates the zero-copy
        symbols (stale prebuilt .so on a compiler-less host).
        """
        if not zero_copy:
            yield from self
            return
        if not hasattr(self._lib, "dsl_pipeline_acquire"):
            raise RuntimeError(
                "zero-copy needs dsl_pipeline_acquire/release: the loaded "
                "libdsl_data.so predates them — rebuild native/ (make -C "
                "native) or drop zero_copy"
            )
        img_p = ctypes.POINTER(ctypes.c_float)()
        tok_p = ctypes.POINTER(ctypes.c_int32)()
        while True:
            with self._iter_lock:
                if self._closed:
                    return
                handle = self._handle
                n = self._lib.dsl_pipeline_acquire(
                    handle, ctypes.byref(img_p), ctypes.byref(tok_p)
                )
            if n < 0:  # stopped under our feet
                return
            try:
                images = np.ctypeslib.as_array(img_p, shape=self._image_shape)
                tokens = np.ctypeslib.as_array(tok_p, shape=self._token_shape)
                yield {"images": images, "tokens": tokens}
            finally:
                # Deliberately NOT under _iter_lock: a concurrent close() may
                # already be blocked inside dsl_pipeline_destroy (holding
                # _iter_lock) waiting for exactly this release — taking the
                # lock here would deadlock. The engine cannot be freed while
                # the slot is held (destroy waits for consumers_inside == 0),
                # so the raw call is safe.
                self._lib.dsl_pipeline_release(handle, n)

    def close(self):
        with self._close_lock:
            if self._closed or not self._handle:
                return
            # Wake any blocked consumer first — it holds _iter_lock while inside
            # the native call (ctypes released the GIL), so a locked stop would
            # deadlock.
            self._lib.dsl_pipeline_stop(self._handle)
            with self._iter_lock:
                self._closed = True
                self._lib.dsl_pipeline_destroy(self._handle)
                self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
