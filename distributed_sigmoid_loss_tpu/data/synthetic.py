"""Synthetic image-text data pipeline.

The reference has no data layer — its tests generate the full global batch on every
rank under fixed seeds and slice per rank (test_distributed_sigmoid_loss.py:57-68).
This module keeps that philosophy (deterministic, full-batch-then-shard) but produces
(image, token) pairs shaped for the real towers, with double-buffered host→device
transfer so input feeding overlaps the previous step's compute.
"""

from __future__ import annotations

from typing import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from distributed_sigmoid_loss_tpu.utils.config import SigLIPConfig


def shard_batch(batch: dict, shardings: dict) -> dict:
    """Place a host batch onto the mesh (dp-sharded)."""
    return jax.device_put(batch, shardings)


class SyntheticImageText:
    """Deterministic synthetic (image, tokens) stream for benchmarks and tests.

    Seeded like the reference partition recipe: one seed for images, one for texts
    (42/40, test_distributed_sigmoid_loss.py:57-64), advancing per step.
    """

    def __init__(
        self,
        cfg: SigLIPConfig,
        global_batch: int,
        image_seed: int = 42,
        text_seed: int = 40,
    ):
        self.cfg = cfg
        self.global_batch = global_batch
        self.image_rng = np.random.default_rng(image_seed)
        self.text_rng = np.random.default_rng(text_seed)

    def __iter__(self) -> Iterator[dict]:
        v, t = self.cfg.vision, self.cfg.text
        while True:
            yield {
                "images": jnp.asarray(
                    self.image_rng.standard_normal(
                        (self.global_batch, v.image_size, v.image_size, 3)
                    ).astype(np.float32)
                ),
                "tokens": jnp.asarray(
                    self.text_rng.integers(
                        0, t.vocab_size, (self.global_batch, t.context_length)
                    ),
                    jnp.int32,
                ),
            }
