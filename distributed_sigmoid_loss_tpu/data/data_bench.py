"""Stage-level input-pipeline benchmark — the ``data-bench`` subcommand.

The headline train bench feeds the chip synthetic batches generated on-device;
SigLIP-scale pretraining needs the HOST to sustain the same rate through the
real path: tar shard read → JPEG decode → tokenize → (on-device) augment →
host→device commit. Until this bench existed, none of those stages had a
measured number, so a host-bound headline would have been invisible.

What it measures (one JSON record per line, bench.py's record contract,
validated against ``analysis/bench_schema.py``):

- each stage in ISOLATION (``data_bench_stage`` records: shard_read, decode,
  tokenize, augment, h2d_commit — items/s each), plus a decode
  worker-scaling curve;
- the COMPOSED real-data pipeline (read-ahead shards + fused decode/tokenize
  batcher + ``prefetch`` overlap) vs the synthetic loader on the same host
  (``data_bench_pipeline_pairs_per_sec``), with the starvation ratio
  (``input_wait_frac``) and the ``synthetic_ratio`` acceptance figure: the
  real path must reach >= 95% of synthetic throughput, or the record
  attributes the bound stage.

CPU-runnable end to end (shards are generated when ``--data-shards`` is not
given); the same runner backs ``bench.py --data-bench`` for chip-queueable
runs. jax is imported inside the runner so the module stays importable (e.g.
by argparse plumbing) without initializing a backend.
"""

from __future__ import annotations

import io
import json
import os
import sys
import tarfile
import tempfile
import time

import numpy as np

__all__ = ["add_data_bench_args", "run_data_bench", "make_synthetic_shards"]


def add_data_bench_args(ap) -> None:
    """The data-bench argument surface — shared verbatim by the CLI
    subcommand and (a subset, via defaults) bench.py's ``--data-bench``."""
    ap.add_argument("--batch", type=int, default=64,
                    help="global batch size (pairs per composed-pipeline "
                         "batch)")
    ap.add_argument("--batches", type=int, default=8,
                    help="timed batches per stage measurement")
    ap.add_argument("--model", choices=["b16", "l14", "so400m", "tiny"],
                    default="tiny",
                    help="tower config supplying image_size / "
                         "context_length (tiny = the CPU-runnable shape)")
    ap.add_argument("--data-shards", default="",
                    help="measure THESE webdataset-style tar shards (glob) "
                         "instead of generating a synthetic JPEG shard set")
    ap.add_argument("--data-workers", type=int, default=0,
                    help="host worker threads for decode/generation "
                         "(0 = auto: cpu_count minus the prefetch/main "
                         "threads; the resolved value lands in every record)")
    ap.add_argument("--image-hw", default="240x320", metavar="HxW",
                    help="source resolution of the GENERATED shard images "
                         "(decode cost scales with it; ignored with "
                         "--data-shards)")
    ap.add_argument("--shards", type=int, default=4,
                    help="generated shard count (read-ahead needs >= 2)")
    ap.add_argument("--pil-decode", action="store_true",
                    help="force the PIL decode path (A/B vs the native "
                         "libjpeg engine; default: native when available)")
    ap.add_argument("--no-read-ahead", action="store_true",
                    help="disable shard read-ahead in the composed pipeline "
                         "(A/B the overlap)")
    ap.add_argument("--no-pipelined", action="store_true",
                    help="disable the fused decode+tokenize worker overlap "
                         "in the composed pipeline (A/B)")
    ap.add_argument("--no-zero-copy", action="store_true",
                    help="synthetic reference: copy C++ ring batches into "
                         "numpy instead of the zero-copy device_put handoff "
                         "(A/B)")
    ap.add_argument("--seed", type=int, default=0)


def make_synthetic_shards(
    out_dir: str, num_shards: int, pairs_per_shard: int, hw: tuple[int, int],
    seed: int = 0, quality: int = 90,
) -> list[str]:
    """Write webdataset-style tar shards of synthetic JPEG + caption pairs.

    Images are smooth random sinusoid mixes — they JPEG-compress (and
    therefore decode) like photographic content, unlike uint8 noise, whose
    pathological entropy makes decode ~3x slower than any real photo.
    """
    from PIL import Image

    h, w = hw
    rng = np.random.default_rng(seed)
    yy = np.linspace(0.0, 1.0, h, dtype=np.float32)[:, None, None]
    xx = np.linspace(0.0, 1.0, w, dtype=np.float32)[None, :, None]
    paths = []
    for s in range(num_shards):
        path = os.path.join(out_dir, f"bench-{s:05d}.tar")
        with tarfile.open(path, "w") as tf:
            for i in range(pairs_per_shard):
                f = rng.uniform(1.0, 6.0, (2, 3)).astype(np.float32)
                ph = rng.uniform(0.0, 6.28, (2, 3)).astype(np.float32)
                img = 63.75 * (
                    2.0
                    + np.sin(6.28 * f[0] * yy + ph[0])
                    + np.sin(6.28 * f[1] * xx + ph[1])
                )
                arr = np.clip(img, 0, 255).astype(np.uint8)
                buf = io.BytesIO()
                Image.fromarray(arr).save(buf, "JPEG", quality=quality)
                blob = buf.getvalue()
                name = f"pair-{s:05d}-{i:05d}"
                info = tarfile.TarInfo(f"{name}.jpg")
                info.size = len(blob)
                tf.addfile(info, io.BytesIO(blob))
                cap = f"synthetic scene {s}-{i} hue {i % 11}".encode()
                info = tarfile.TarInfo(f"{name}.txt")
                info.size = len(cap)
                tf.addfile(info, io.BytesIO(cap))
        paths.append(path)
    return paths


def _emit_record(record: dict, collected: list) -> None:
    """One JSON line per record, schema-validated (warn, never drop — same
    contract as bench.py's _emit)."""
    from distributed_sigmoid_loss_tpu.analysis.bench_schema import (
        validate_record,
    )

    problems = validate_record(record)
    if problems:
        print(
            "WARNING: data-bench record schema violation: "
            + "; ".join(problems),
            file=sys.stderr,
        )
    collected.append(record)
    print(json.dumps(record), flush=True)
    # graftledger: data-bench records join the same append-only trajectory
    # as every other bench stream (obs/ledger.py; never fatal).
    from distributed_sigmoid_loss_tpu.obs.ledger import append_record

    append_record(record, source="data-bench", problems=problems)


def _timed(fn, reps: int) -> float:
    t0 = time.perf_counter()
    for _ in range(reps):
        fn()
    return time.perf_counter() - t0


def run_data_bench(args, collected: list | None = None) -> int:
    """Run every stage + the composed comparison; returns the exit code.

    ``collected`` (a list) receives every emitted record dict — the
    introspection channel tests and bench.py's relay use.
    """
    import glob as globmod

    import jax

    from distributed_sigmoid_loss_tpu.data.files import ImageTextShards
    from distributed_sigmoid_loss_tpu.data.loader import (
        PrefetchStats,
        prefetch,
        put_batch,
    )
    from distributed_sigmoid_loss_tpu.data.workers import resolve_data_workers
    from distributed_sigmoid_loss_tpu.parallel.mesh import make_mesh
    from distributed_sigmoid_loss_tpu.utils.config import SigLIPConfig

    cfg = {
        "tiny": SigLIPConfig.tiny_test,
        "b16": SigLIPConfig.b16,
        "l14": SigLIPConfig.l14,
        "so400m": SigLIPConfig.so400m,
    }[args.model]()
    size = cfg.vision.image_size
    workers = resolve_data_workers(args.data_workers)
    batch, n_batches = args.batch, args.batches
    need_pairs = batch * (n_batches + 1)  # +1 warmup batch

    tmp = None
    if args.data_shards:
        shard_paths = sorted(globmod.glob(args.data_shards))
        if not shard_paths:
            print(f"--data-shards matched nothing: {args.data_shards!r}",
                  file=sys.stderr)
            return 2
    else:
        try:
            h, w = (int(x) for x in args.image_hw.lower().split("x"))
        except ValueError:
            print(f"--image-hw must be HxW (e.g. 240x320), got "
                  f"{args.image_hw!r}", file=sys.stderr)
            return 2
        if args.shards < 1:
            print(f"--shards must be >= 1, got {args.shards}", file=sys.stderr)
            return 2
        tmp = tempfile.TemporaryDirectory(prefix="dsl_data_bench_")
        per_shard = -(-need_pairs // args.shards)
        t0 = time.perf_counter()
        shard_paths = make_synthetic_shards(
            tmp.name, args.shards, per_shard, (h, w), seed=args.seed,
        )
        print(
            f"generated {args.shards} shard(s) x {per_shard} pairs "
            f"({h}x{w} JPEG) in {time.perf_counter() - t0:.1f}s",
            file=sys.stderr,
        )

    from distributed_sigmoid_loss_tpu.cli import _byte_tokenize_for

    tokenize = _byte_tokenize_for(cfg)

    native = False
    if not args.pil_decode:
        from distributed_sigmoid_loss_tpu.data.native_decode import (
            native_decode_available,
        )

        native = native_decode_available()
        if not native:
            print("native libjpeg engine unavailable; decode stage runs PIL",
                  file=sys.stderr)

    mesh = make_mesh()
    records: list[dict] = collected if collected is not None else []
    base = {
        "unit": "items/s",
        "model": args.model,
        "global_batch": batch,
        "steps": n_batches,
        "data_workers": workers,
        "native_decode": native,
        "n_devices": len(jax.devices()),
        "device_kind": jax.devices()[0].device_kind,
    }

    def stage(name: str, value: float, **extra) -> None:
        _emit_record(
            {"metric": "data_bench_stage", "stage": name,
             "value": round(value, 1), **base, **extra},
            records,
        )

    probe = ImageTextShards(
        shard_paths, cfg, batch, tokenize, native_decode=native,
        data_workers=workers, read_ahead=False, pipelined=False,
    )

    # --- shard_read: raw pair streaming (tar IO + member pairing only).
    order = np.arange(len(probe.shards))
    t0 = time.perf_counter()
    pairs: list[tuple[bytes, str]] = []
    for p in probe._pairs(order):
        pairs.append(p)
        if len(pairs) >= need_pairs:
            break
    read_s = time.perf_counter() - t0
    if len(pairs) < batch:
        print(f"shards hold {len(pairs)} pairs; need at least one batch of "
              f"{batch}", file=sys.stderr)
        return 2
    read_ips = len(pairs) / read_s
    stage("shard_read", read_ips)

    blobs = [b for b, _ in pairs[:need_pairs]]
    texts = [t for _, t in pairs[:need_pairs]]

    # --- decode (native fans over threads / PIL serial), + scaling curve.
    def decode_ips(threads: int, reps: int = n_batches) -> float:
        if native:
            from distributed_sigmoid_loss_tpu.data.native_decode import (
                decode_batch,
            )

            def one(i):
                decode_batch(
                    blobs[i * batch:(i + 1) * batch], size, threads=threads
                )
        else:
            from distributed_sigmoid_loss_tpu.data.files import (
                decode_and_resize,
            )

            def one(i):
                for b in blobs[i * batch:(i + 1) * batch]:
                    decode_and_resize(b, size)

        reps = min(reps, len(blobs) // batch)
        one(0)  # touch the library/build path outside the clock
        t0 = time.perf_counter()
        for i in range(reps):
            one(i)
        return reps * batch / (time.perf_counter() - t0)

    curve = {}
    w_points = sorted({1, *(2 ** k for k in range(1, 6) if 2 ** k < workers),
                       workers})
    for w_ in w_points:
        curve[str(w_)] = round(decode_ips(w_, reps=max(2, n_batches // 2)), 1)
    dec_ips = decode_ips(workers)
    stage("decode", dec_ips, worker_scaling=curve)

    # --- tokenize.
    tok_reps = min(n_batches, len(texts) // batch)
    tok_s = _timed(
        lambda: [
            tokenize(texts[i * batch:(i + 1) * batch],
                     cfg.text.context_length)
            for i in range(tok_reps)
        ],
        1,
    )
    tok_ips = tok_reps * batch / tok_s
    stage("tokenize", tok_ips)

    # --- augment (on-device, jitted — overlaps the step in production; its
    # stage number shows whether it could ever become the bound).
    from distributed_sigmoid_loss_tpu.data.augment import augment_batch

    host_batch = {
        "images": np.zeros((batch, size, size, 3), np.float32),
        "tokens": np.asarray(
            tokenize(texts[:batch], cfg.text.context_length), np.int32
        ),
    }
    aug = jax.jit(lambda k, im: augment_batch(k, im, size))
    dev_images = jax.device_put(host_batch["images"])
    key = jax.random.key(args.seed)
    jax.block_until_ready(aug(key, dev_images))  # compile outside the clock
    aug_s = _timed(
        lambda: jax.block_until_ready(aug(key, dev_images)), n_batches
    )
    stage("augment", n_batches * batch / aug_s)

    # --- host->device commit (put_batch onto the dp mesh).
    def commit():
        jax.block_until_ready(put_batch(host_batch, mesh))

    commit()  # compile/placement warmup
    h2d_s = _timed(commit, n_batches)
    stage("h2d_commit", n_batches * batch / h2d_s)

    # --- composed real-data pipeline: read-ahead shards -> fused batcher ->
    # prefetch -> device. Warm one batch (thread/pool spin-up), time the rest.
    def run_pipeline(it) -> tuple[float, PrefetchStats]:
        stats = PrefetchStats()
        stream = prefetch(it, mesh, size=2, stats=stats)
        try:
            jax.block_until_ready(next(stream))
            t0 = time.perf_counter()
            for _ in range(n_batches):
                jax.block_until_ready(next(stream))
            dt = time.perf_counter() - t0
        finally:
            stream.close()
        return n_batches * batch / dt, stats

    real_src = ImageTextShards(
        shard_paths, cfg, batch, tokenize, native_decode=native,
        data_workers=workers, read_ahead=not args.no_read_ahead,
        pipelined=not args.no_pipelined, seed=args.seed,
    )
    real_pps, real_stats = run_pipeline(iter(real_src))

    # --- synthetic reference on the same host + mesh (the feeding rate the
    # headline bench implicitly assumes). Native C++ ring with the zero-copy
    # device_put handoff when available; numpy stream otherwise.
    from distributed_sigmoid_loss_tpu.data.native_loader import (
        native_available,
    )

    zero_copy = False
    if native_available():
        from distributed_sigmoid_loss_tpu.data.native_loader import (
            NativeSyntheticImageText,
        )

        ds = NativeSyntheticImageText(cfg, batch, num_threads=workers)
        zero_copy = not args.no_zero_copy and hasattr(
            ds._lib, "dsl_pipeline_acquire"
        )
        with ds:
            syn_pps, _ = run_pipeline(ds.batches(zero_copy=zero_copy))
    else:
        from distributed_sigmoid_loss_tpu.data.synthetic import (
            SyntheticImageText,
        )

        syn_pps, _ = run_pipeline(iter(SyntheticImageText(cfg, batch)))

    ratio = real_pps / syn_pps if syn_pps > 0 else 0.0
    # Host stages that serialize with each other on the real path; the
    # slowest is the bound the composed number inherits (augment/h2d ride the
    # device queue and overlap the step in production).
    host_stages = {
        "shard_read": read_ips, "decode": dec_ips, "tokenize": tok_ips,
    }
    bound = min(host_stages, key=host_stages.get)
    composed = {
        "metric": "data_bench_pipeline_pairs_per_sec",
        "value": round(real_pps, 1),
        **base,
        "unit": "pairs/s",
        "synthetic_pairs_per_sec": round(syn_pps, 1),
        "synthetic_ratio": round(ratio, 3),
        "input_wait_frac": round(real_stats.input_wait_frac(), 4),
        "pipelined": not args.no_pipelined,
        "read_ahead": not args.no_read_ahead,
        "zero_copy": zero_copy,
    }
    if ratio < 0.95:
        # The acceptance contract: either >= 95% of synthetic, or the record
        # names the bound stage and how decode scales with workers.
        composed["bound_stage"] = bound
        composed["worker_scaling"] = curve
    _emit_record(composed, records)
    if tmp is not None:
        tmp.cleanup()
    return 0
