"""ctypes binding for the native JPEG decode path (``native/jpeg_decode.cc``).

Image decode is the host-side cost of real-data training — the work torch's
DataLoader workers / tf.data's C++ ops do natively in the reference ecosystem.
:func:`decode_batch` decodes a list of image blobs to the training layout
((S, S, 3) float32 in [-1, 1], shorter-side resize + center crop — the same
geometry as ``files.decode_and_resize``) with libjpeg fanned over threads, off
the GIL. Non-JPEG formats and corrupt blobs fall back to the PIL path
per-image, so the function accepts anything ``decode_and_resize`` does.

Gated separately from the synthetic engine's ``libdsl_data.so``: this library
links ``-ljpeg``, and :func:`native_decode_available` is False wherever
libjpeg (or a compiler) is missing — callers then use pure PIL.

Numerics note: libjpeg's IDCT and the fused bilinear differ from PIL's
(antialiased) resampling by a few least-significant bits per pixel — fine for
training pixels, not for bitwise-reproducing a PIL-decoded eval set. The
deterministic contract is per-library, not cross-library.
"""

from __future__ import annotations

import ctypes
import os
import threading
import warnings

import numpy as np

from distributed_sigmoid_loss_tpu.data.native_loader import build_shared_lib
from distributed_sigmoid_loss_tpu.data.workers import default_data_workers

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["native_decode_available", "decode_batch", "default_decode_threads"]


def default_decode_threads() -> int:
    """Per-flush thread cap when the caller doesn't pass ``threads``.

    ``DSL_DECODE_THREADS`` overrides; otherwise the shared host-worker
    resolver (``data/workers.py``): cpu_count minus the prefetch/main
    threads, min 1 — each flush spawns raw ``std::thread``s next to the
    pipeline's own threads, so those reserved cores must not be claimed.
    """
    env = os.environ.get("DSL_DECODE_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            warnings.warn(f"DSL_DECODE_THREADS={env!r} is not an int; ignoring")
    return default_data_workers()

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
)
_SRC = os.path.join(_NATIVE_DIR, "jpeg_decode.cc")
_LIB = os.path.join(_NATIVE_DIR, "libdsl_jpeg.so")

_build_lock = named_lock("data.native_decode._build_lock")
_lib = None
_lib_failed = False


def _load():
    global _lib, _lib_failed
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            # Shared artifact rules with the synthetic engine: prebuilt-.so
            # deployments and stale-lib/compiler-less hosts keep working.
            lib = ctypes.CDLL(build_shared_lib(_SRC, _LIB, ldflags=("-ljpeg",)))
            lib.dsl_jpeg_decode_batch.restype = ctypes.c_int64
            lib.dsl_jpeg_decode_batch.argtypes = [
                ctypes.POINTER(ctypes.c_char_p),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64,
                ctypes.c_int64,
                ctypes.c_int,
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_uint8),
            ]
            _lib = lib
        except Exception as e:
            _lib_failed = True
            warnings.warn(f"native JPEG decode unavailable ({e}); using PIL")
        return _lib


def native_decode_available() -> bool:
    return _load() is not None


def decode_batch(
    blobs: list[bytes], image_size: int, threads: int | None = None
) -> np.ndarray:
    """Decode image blobs → ``(len(blobs), S, S, 3)`` float32 in [-1, 1].

    JPEGs go through the native threaded path; anything it rejects (other
    formats, corrupt data) is retried with ``files.decode_and_resize`` (PIL),
    which raises on genuinely undecodable input — same failure surface as the
    pure-PIL loaders.
    """
    from distributed_sigmoid_loss_tpu.data.files import decode_and_resize

    n = len(blobs)
    out = np.zeros((n, image_size, image_size, 3), np.float32)
    lib = _load()
    todo = range(n)
    if lib is not None and n:
        datas = (ctypes.c_char_p * n)(*blobs)
        lens = (ctypes.c_int64 * n)(*[len(b) for b in blobs])
        fail = (ctypes.c_uint8 * n)()
        if threads is None:
            threads = min(n, default_decode_threads())
        lib.dsl_jpeg_decode_batch(
            ctypes.cast(datas, ctypes.POINTER(ctypes.c_char_p)),
            lens,
            n,
            image_size,
            max(1, threads),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            fail,
        )
        todo = [i for i in range(n) if fail[i]]
    for i in todo:
        out[i] = decode_and_resize(blobs[i], image_size)
    return out
