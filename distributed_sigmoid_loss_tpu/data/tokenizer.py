"""Tokenizers — a self-contained text front end for the framework.

The reference consumes pre-embedded text (its "texts" are random tensors,
/root/reference/test_distributed_sigmoid_loss.py:57-64); a usable framework needs a
string → token-ids front end for the text tower. Two implementations share one
interface (``__call__``/``encode``/``decode``):

- :class:`ByteTokenizer` — dependency-free UTF-8 bytes + pad/bos/eos; the
  zero-setup default (vocab 259, fits every
  :class:`~distributed_sigmoid_loss_tpu.utils.config.TextConfig`).
- :class:`BpeTokenizer` — byte-level BPE TRAINED on your caption corpus
  (GPT-2-family merge algorithm, no external artifacts or deps): base vocab =
  the 256 bytes, merges learned greedily by pair frequency up to
  ``vocab_size``. Lossless (any byte sequence encodes; decode inverts), JSON
  save/load, pluggable into the real-data loaders via ``train --tokenizer``.
  Production SigLIP uses a 32k sentencepiece vocab — same idea, same
  interface; this gives the framework a trainable subword path without
  shipping a vocab artifact.

TPU notes: output is a dense (batch, context_length) int32 array — static shape,
pad-to-length — which is exactly what the jitted text tower wants; no ragged
batching ever reaches the device.
"""

from __future__ import annotations

import json
import re

import numpy as np

__all__ = ["ByteTokenizer", "BpeTokenizer"]


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids = byte value + 3; 0/1/2 = pad/bos/eos."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _offset = 3
    vocab_size = 256 + _offset

    def __init__(self, add_bos: bool = True, add_eos: bool = True):
        self.add_bos = add_bos
        self.add_eos = add_eos

    def encode(self, text: str) -> list[int]:
        """Token ids for one string, without padding/truncation."""
        ids = [b + self._offset for b in text.encode("utf-8")]
        if self.add_bos:
            ids.insert(0, self.bos_id)
        if self.add_eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids) -> str:
        """Inverse of :meth:`encode`; pad/bos/eos are dropped. Truncation can split
        a multi-byte UTF-8 character — invalid tails decode with replacement."""
        data = bytes(
            int(i) - self._offset for i in np.asarray(ids).reshape(-1)
            if int(i) >= self._offset
        )
        return data.decode("utf-8", errors="replace")

    def __call__(self, texts, context_length: int) -> np.ndarray:
        """Batch-encode to a dense (len(texts), context_length) int32 array.

        Sequences longer than ``context_length`` are truncated (keeping eos as the
        final token when enabled, matching the usual CLIP/SigLIP convention);
        shorter ones are right-padded with ``pad_id``.
        """
        if isinstance(texts, str):
            texts = [texts]
        out = np.full((len(texts), context_length), self.pad_id, np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)
            if len(ids) > context_length:
                ids = ids[:context_length]
                if self.add_eos:
                    ids[-1] = self.eos_id
            out[row, : len(ids)] = ids
        return out


# Alternating word/whitespace pieces: lossless concatenation, merges never
# cross a word boundary (the classic BPE scoping rule).
_PIECE_RE = re.compile(r"\S+|\s+")


class BpeTokenizer(ByteTokenizer):
    """Byte-level BPE with a trainable merge table (see module docstring).

    Ids: 0/1/2 pad/bos/eos, 3..258 the raw bytes (ByteTokenizer-compatible —
    zero merges IS the byte tokenizer), 259+ one id per learned merge, in
    merge order. ``merges`` is the training artifact: a list of (left, right)
    token-id pairs; encoding applies them greedily by rank, which reproduces
    the training segmentation.
    """

    def __init__(self, merges=(), add_bos: bool = True, add_eos: bool = True):
        super().__init__(add_bos=add_bos, add_eos=add_eos)
        self.merges = [tuple(m) for m in merges]
        self.vocab_size = 256 + self._offset + len(self.merges)
        self._ranks = {pair: i for i, pair in enumerate(self.merges)}
        # id -> bytes, for decode. Built in merge order: children always exist.
        self._token_bytes = {i + self._offset: bytes([i]) for i in range(256)}
        for i, (a, b) in enumerate(self.merges):
            self._token_bytes[256 + self._offset + i] = (
                self._token_bytes[a] + self._token_bytes[b]
            )

    # -- training ----------------------------------------------------------
    @classmethod
    def train(cls, texts, vocab_size: int, **kw) -> "BpeTokenizer":
        """Learn merges from an iterable of strings.

        Classic BPE: count adjacent-pair frequencies over the piece-frequency
        table, merge the most frequent pair (ties broken by token ids for
        determinism), repeat until ``vocab_size`` or no pair occurs twice.
        """
        base = 256 + cls._offset
        if vocab_size < base:
            raise ValueError(
                f"vocab_size must be >= {base} (bytes + specials), got {vocab_size}"
            )
        freqs: dict[tuple, int] = {}
        for text in texts:
            for piece in _PIECE_RE.findall(text):
                ids = tuple(b + cls._offset for b in piece.encode("utf-8"))
                if ids:
                    freqs[ids] = freqs.get(ids, 0) + 1

        # Incremental pair bookkeeping (what makes a 4096-vocab train linear-ish
        # instead of quadratic): pair counts and a pair -> piece-index inverted
        # index are built ONCE; each merge touches only the pieces that contain
        # the merged pair, decrementing their old pairs and adding the new ones.
        pieces = list(freqs.keys())
        counts = [freqs[p] for p in pieces]
        pair_counts: dict[tuple[int, int], int] = {}
        where: dict[tuple[int, int], set[int]] = {}

        def account(idx: int, sign: int) -> None:
            ids, n = pieces[idx], counts[idx]
            for pair in zip(ids, ids[1:]):
                pair_counts[pair] = pair_counts.get(pair, 0) + sign * n
                if sign > 0:
                    where.setdefault(pair, set()).add(idx)
                elif pair_counts[pair] <= 0:
                    pair_counts.pop(pair, None)
                    where.pop(pair, None)

        for i in range(len(pieces)):
            account(i, +1)

        merges: list[tuple[int, int]] = []
        next_id = base
        while next_id < vocab_size and pair_counts:
            best = max(pair_counts, key=lambda p: (pair_counts[p], (-p[0], -p[1])))
            if pair_counts[best] < 2:
                break  # nothing repeats; further merges would memorize noise
            merges.append(best)
            for idx in list(where.get(best, ())):
                account(idx, -1)
                pieces[idx] = cls._merge_ids(list(pieces[idx]), best, next_id)
                account(idx, +1)
                # A piece may keep stale index entries for pairs it no longer
                # contains (sets only grow on +1); account(-1) handles them by
                # count, and the `best` entry itself is dropped below.
            pair_counts.pop(best, None)
            where.pop(best, None)
            next_id += 1
        return cls(merges, **kw)

    @staticmethod
    def _merge_ids(ids, pair, new_id):
        out = []
        i = 0
        while i < len(ids):
            if i + 1 < len(ids) and (ids[i], ids[i + 1]) == pair:
                out.append(new_id)
                i += 2
            else:
                out.append(ids[i])
                i += 1
        return tuple(out)

    # -- encode / decode ---------------------------------------------------
    def encode(self, text: str) -> list[int]:
        out = [self.bos_id] if self.add_bos else []
        for piece in _PIECE_RE.findall(text):
            ids = [b + self._offset for b in piece.encode("utf-8")]
            while len(ids) >= 2:
                pairs = set(zip(ids, ids[1:]))
                best = min(
                    pairs, key=lambda p: self._ranks.get(p, len(self.merges))
                )
                if best not in self._ranks:
                    break
                ids = list(self._merge_ids(
                    ids, best, 256 + self._offset + self._ranks[best]
                ))
            out.extend(ids)
        if self.add_eos:
            out.append(self.eos_id)
        return out

    def decode(self, ids) -> str:
        data = b"".join(
            self._token_bytes[int(i)]
            for i in np.asarray(ids).reshape(-1)
            if int(i) >= self._offset
        )
        return data.decode("utf-8", errors="replace")

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(
                {"format": "dsl-bpe-v1", "merges": self.merges},
                f,
            )

    @classmethod
    def load(cls, path: str, **kw) -> "BpeTokenizer":
        with open(path) as f:
            blob = json.load(f)
        if blob.get("format") != "dsl-bpe-v1":
            raise ValueError(
                f"{path!r} is not a dsl-bpe-v1 vocab file "
                f"(format={blob.get('format')!r})"
            )
        return cls(blob["merges"], **kw)
