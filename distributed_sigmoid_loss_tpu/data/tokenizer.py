"""Byte-level tokenizer — a self-contained text front end for the framework.

The reference consumes pre-embedded text (its "texts" are random tensors,
/root/reference/test_distributed_sigmoid_loss.py:57-64); a usable framework needs a
string → token-ids front end for the text tower. Production SigLIP uses a 32k
sentencepiece vocab; that requires a trained vocab artifact, so the built-in default
is a dependency-free byte-level tokenizer (UTF-8 bytes + pad/bos/eos) with the same
interface — deterministic, reversible, vocab small enough for every
:class:`~distributed_sigmoid_loss_tpu.utils.config.TextConfig`. A sentencepiece/BPE
vocab plugs in by implementing the same two methods (``__call__``/``decode``).

TPU notes: output is a dense (batch, context_length) int32 array — static shape,
pad-to-length — which is exactly what the jitted text tower wants; no ragged
batching ever reaches the device.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ByteTokenizer"]


class ByteTokenizer:
    """UTF-8 byte tokenizer: ids = byte value + 3; 0/1/2 = pad/bos/eos."""

    pad_id = 0
    bos_id = 1
    eos_id = 2
    _offset = 3
    vocab_size = 256 + _offset

    def __init__(self, add_bos: bool = True, add_eos: bool = True):
        self.add_bos = add_bos
        self.add_eos = add_eos

    def encode(self, text: str) -> list[int]:
        """Token ids for one string, without padding/truncation."""
        ids = [b + self._offset for b in text.encode("utf-8")]
        if self.add_bos:
            ids.insert(0, self.bos_id)
        if self.add_eos:
            ids.append(self.eos_id)
        return ids

    def decode(self, ids) -> str:
        """Inverse of :meth:`encode`; pad/bos/eos are dropped. Truncation can split
        a multi-byte UTF-8 character — invalid tails decode with replacement."""
        data = bytes(
            int(i) - self._offset for i in np.asarray(ids).reshape(-1)
            if int(i) >= self._offset
        )
        return data.decode("utf-8", errors="replace")

    def __call__(self, texts, context_length: int) -> np.ndarray:
        """Batch-encode to a dense (len(texts), context_length) int32 array.

        Sequences longer than ``context_length`` are truncated (keeping eos as the
        final token when enabled, matching the usual CLIP/SigLIP convention);
        shorter ones are right-padded with ``pad_id``.
        """
        if isinstance(texts, str):
            texts = [texts]
        out = np.full((len(texts), context_length), self.pad_id, np.int32)
        for row, text in enumerate(texts):
            ids = self.encode(text)
            if len(ids) > context_length:
                ids = ids[:context_length]
                if self.add_eos:
                    ids[-1] = self.eos_id
            out[row, : len(ids)] = ids
        return out
