"""Host worker-count resolution for the input pipeline.

One resolver for every host-side thread pool (native JPEG decode, the C++
synthetic engine, the fused decode+tokenize batcher): derive the worker count
from what the host actually has, instead of the static defaults that shipped
with each component (``cpu_count // 2`` decode threads, ``num_threads=4`` in
the native loader). The train loop always runs a prefetch thread and the main
(dispatch/augment) thread next to the pool, so those cores are reserved —
oversubscribing a 1-core TPU-VM host with 4 generator threads just adds
context-switch tax to the exact path the pipeline is trying to hide.

Stdlib-only: imported by modules (native bindings, bench.py's data mode) that
must not initialize jax at import time.
"""

from __future__ import annotations

import os
import warnings

__all__ = ["RESERVED_HOST_THREADS", "default_data_workers", "resolve_data_workers"]

# Threads the train loop keeps busy outside the data worker pool: the
# data.loader.prefetch producer (decode/tokenize dispatch + host->device
# commit) and the main thread (step dispatch, on-device augment).
RESERVED_HOST_THREADS = 2


def default_data_workers(reserve: int = RESERVED_HOST_THREADS) -> int:
    """Worker threads for host data work: ``cpu_count - reserve``, min 1.

    ``DSL_DATA_WORKERS`` overrides (the same escape hatch pattern as
    ``DSL_DECODE_THREADS``, which stays decode-specific and wins over this
    for the decode pool).
    """
    env = os.environ.get("DSL_DATA_WORKERS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            warnings.warn(f"DSL_DATA_WORKERS={env!r} is not an int; ignoring")
    return max(1, (os.cpu_count() or 1) - reserve)


def resolve_data_workers(requested: int | None) -> int:
    """CLI/bench ``--data-workers`` resolution: 0/None = auto-derive, else the
    explicit positive value. The resolved number is what bench records carry —
    a record that says "auto" is not reproducible on a different host."""
    if requested:
        if requested < 0:
            raise ValueError(f"data workers must be >= 1, got {requested}")
        return requested
    return default_data_workers()
