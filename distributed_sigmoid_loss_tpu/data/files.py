"""Real image-text datasets: folders of pairs and webdataset-style tar shards.

The reference trains on nothing (its data layer is seeded tensors,
/root/reference/test_distributed_sigmoid_loss.py:57-68); contrastive pretraining
in its ecosystem (open_clip) reads webdataset tar shards of (image, caption)
pairs. This module provides the same two on-disk layouts without external
dependencies:

- :class:`ImageTextFolder` — a directory of ``name.{jpg,png,...}`` +
  ``name.txt`` caption pairs (the small-dataset / debugging layout).
- :class:`ImageTextShards` — webdataset-style ``.tar`` shards whose members are
  those same pairs grouped by basename (the at-scale layout; tar is read
  sequentially, one shard at a time — the access pattern object stores like).

Both yield training-ready batches: images decoded (PIL), resized to the tower's
``image_size`` with the standard shorter-side-resize + center-crop, scaled to
[-1, 1] (SigLIP's inference normalization); captions tokenized by any
``(texts, length) -> ids`` callable (e.g. ``data.ByteTokenizer``). Multi-host
jobs compose the usual way: pass ``shard_index/num_shards`` per process so each
host reads a disjoint slice, then feed ``data.global_batch_from_local``.

TPU note: decode/resize is host CPU work — wrap the iterator in
``data.prefetch`` so it overlaps device compute, and batches are full global
batches with static shapes (drop-last), so one compiled step serves the stream.
"""

from __future__ import annotations

import os
import tarfile
from typing import Callable, Iterator, Sequence

import numpy as np

__all__ = ["ImageTextFolder", "ImageTextShards", "decode_and_resize"]

_IMAGE_EXTS = (".jpg", ".jpeg", ".png", ".bmp", ".webp")


def decode_and_resize(data: bytes, image_size: int) -> np.ndarray:
    """bytes → (image_size, image_size, 3) float32 in [-1, 1].

    Shorter-side resize then center crop (the open_clip/SigLIP eval transform),
    bilinear. Grayscale/RGBA inputs are converted to RGB.
    """
    from io import BytesIO

    from PIL import Image

    with Image.open(BytesIO(data)) as im:
        im = im.convert("RGB")
        w, h = im.size
        scale = image_size / min(w, h)
        nw, nh = max(image_size, round(w * scale)), max(image_size, round(h * scale))
        im = im.resize((nw, nh), Image.BILINEAR)
        left, top = (nw - image_size) // 2, (nh - image_size) // 2
        im = im.crop((left, top, left + image_size, top + image_size))
        arr = np.asarray(im, np.float32)
    return arr / 127.5 - 1.0


def _pair_key(name: str) -> tuple[str, str] | None:
    base, ext = os.path.splitext(name)
    ext = ext.lower()
    if ext in _IMAGE_EXTS:
        return base, "image"
    if ext == ".txt":
        return base, "text"
    return None


class _PairBatcher:
    """Accumulate (image_bytes, caption) pairs into static-shape batches.

    Decode + tokenize happen at flush time (:meth:`assemble`), a full batch at
    once: with ``native_decode=True`` the libjpeg engine
    (``data/native_decode.py``) fans the batch over ``data_workers`` threads
    off the GIL — the whole batch crosses the GIL ONCE per stage instead of
    per image; otherwise each image goes through the PIL path. Per-image
    decode-on-add would serialize the native path away.

    :meth:`stage` / :meth:`assemble` are split so the pipelined shard reader
    can run ``assemble`` on a worker thread while the tar stream keeps
    staging the next batch's blobs.
    """

    def __init__(
        self, cfg, batch_size: int, tokenize: Callable, native_decode: bool = False,
        keep_captions: bool = False, data_workers: int | None = None,
    ):
        self.cfg = cfg
        self.batch_size = batch_size
        self.tokenize = tokenize
        self.native_decode = native_decode
        self.data_workers = data_workers
        # keep_captions adds the raw caption strings to each batch (a host-side
        # list, NOT device-transferable) — eval uses them as zero-shot class
        # names; pop the key before put_batch/device_put.
        self.keep_captions = keep_captions
        self._blobs: list[bytes] = []
        self._texts: list[str] = []

    def stage(self, image_bytes: bytes, caption: str) -> tuple[list, list] | None:
        """Buffer one pair; on a full batch, hand back (blobs, texts) for
        :meth:`assemble` and reset the buffers."""
        self._blobs.append(image_bytes)
        self._texts.append(caption)
        if len(self._blobs) < self.batch_size:
            return None
        blobs, texts = self._blobs, self._texts
        self._blobs, self._texts = [], []
        return blobs, texts

    def assemble(self, blobs: list, texts: list) -> dict:
        """(blobs, texts) → the training batch dict: fused decode + tokenize."""
        size = self.cfg.vision.image_size
        if self.native_decode:
            from distributed_sigmoid_loss_tpu.data.native_decode import decode_batch

            images = decode_batch(blobs, size, threads=self.data_workers)
        else:
            images = np.stack([decode_and_resize(b, size) for b in blobs])
        tokens = np.asarray(
            self.tokenize(texts, self.cfg.text.context_length), np.int32
        )
        if tokens.min() < 0 or tokens.max() >= self.cfg.text.vocab_size:
            # Out-of-range ids reach nn.Embed as NaNs under jit (jnp.take fill
            # mode) — fail loudly here instead. E.g. ByteTokenizer needs
            # vocab_size >= 259; fold ids (tokens % vocab_size) to use a
            # smaller test vocab deliberately.
            raise ValueError(
                f"tokenizer produced ids in [{tokens.min()}, {tokens.max()}] "
                f"outside vocab_size {self.cfg.text.vocab_size}"
            )
        batch = {"images": images, "tokens": tokens}
        if self.keep_captions:
            batch["captions"] = list(texts)
        return batch

    def add(self, image_bytes: bytes, caption: str) -> dict | None:
        job = self.stage(image_bytes, caption)
        if job is None:
            return None
        return self.assemble(*job)


class ImageTextFolder:
    """Directory of ``name.jpg`` + ``name.txt`` pairs → global batches.

    Deterministic order (sorted basenames, shuffled per epoch by ``seed`` when
    set); incomplete pairs are skipped; the final partial batch is dropped
    (static shapes). Iterating cycles epochs forever — bound the train loop by
    steps, as the CLI does.
    """

    def __init__(
        self,
        root: str,
        cfg,
        batch_size: int,
        tokenize: Callable,
        seed: int | None = 0,
        native_decode: bool = False,
        keep_captions: bool = False,
        data_workers: int | None = None,
    ):
        self.root = root
        self.keep_captions = keep_captions
        self.cfg = cfg
        self.batch_size = batch_size
        self.tokenize = tokenize
        self.seed = seed
        self.native_decode = native_decode
        self.data_workers = data_workers
        pairs: dict[str, dict] = {}
        for name in sorted(os.listdir(root)):
            key = _pair_key(name)
            if key is None:
                continue
            base, kind = key
            pairs.setdefault(base, {})[kind] = os.path.join(root, name)
        self.items: list[dict] = [
            p for _, p in sorted(pairs.items()) if "image" in p and "text" in p
        ]
        if len(self.items) < batch_size:
            raise ValueError(
                f"{root} holds {len(self.items)} complete pairs; "
                f"need at least one batch of {batch_size}"
            )

    def __len__(self) -> int:
        return len(self.items)

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng(self.seed) if self.seed is not None else None
        while True:
            order = np.arange(len(self.items))
            if rng is not None:
                rng.shuffle(order)
            batcher = _PairBatcher(
                self.cfg, self.batch_size, self.tokenize, self.native_decode,
                keep_captions=self.keep_captions,
                data_workers=self.data_workers,
            )
            for i in order:
                item = self.items[i]
                with open(item["image"], "rb") as f:
                    image_bytes = f.read()
                with open(item["text"], "r", encoding="utf-8") as f:
                    caption = f.read().strip()
                batch = batcher.add(image_bytes, caption)
                if batch is not None:
                    yield batch


class ImageTextShards:
    """Webdataset-style tar shards of ``name.jpg`` + ``name.txt`` members.

    ``shards`` is a list of tar paths (or a glob result); ``shard_index /
    num_shards`` stripes the shard list across hosts (process i reads shards
    i, i+N, i+2N, ... — the standard multi-host split, zero coordination).
    Members are paired by basename within a shard; pairs stream in tar order
    (shard-shuffled per epoch by ``seed``) with an optional bounded
    ``shuffle_buffer`` (webdataset's sample-shuffle: a reservoir of that many
    pairs, emit a random one as each new pair streams in — memory stays
    O(buffer + batch) and the stream is deterministic given ``seed``).

    Overlap (both on by default; the emitted STREAM is identical either way,
    so the flags are perf knobs, not semantics):

    - ``read_ahead`` — the NEXT shard's members are fetched by a background
      reader while the current shard's pairs decode, hiding shard-read
      latency behind decode (memory goes O(batch) → O(shard)).
    - ``pipelined`` — each full batch's decode+tokenize flush runs on a
      worker thread (one batch in flight) while the tar stream stages the
      next batch's blobs, so batch assembly overlaps shard reading.
    """

    def __init__(
        self,
        shards: Sequence[str],
        cfg,
        batch_size: int,
        tokenize: Callable,
        seed: int | None = 0,
        shard_index: int = 0,
        num_shards: int = 1,
        native_decode: bool = False,
        shuffle_buffer: int = 0,
        keep_captions: bool = False,
        data_workers: int | None = None,
        read_ahead: bool = True,
        pipelined: bool = True,
    ):
        self.keep_captions = keep_captions
        if not shards:
            raise ValueError("no shards given")
        if not (0 <= shard_index < num_shards):
            raise ValueError(f"shard_index {shard_index} not in [0, {num_shards})")
        self.shards = sorted(shards)[shard_index::num_shards]
        if not self.shards:
            raise ValueError(
                f"host {shard_index}/{num_shards} received no shards "
                f"({len(shards)} total) — use at least num_shards tar files"
            )
        self.cfg = cfg
        self.batch_size = batch_size
        self.tokenize = tokenize
        self.seed = seed
        self.native_decode = native_decode
        self.data_workers = data_workers
        self.read_ahead = read_ahead
        self.pipelined = pipelined
        if shuffle_buffer < 0:
            raise ValueError(f"shuffle_buffer must be >= 0, got {shuffle_buffer}")
        if shuffle_buffer and seed is None:
            # The reservoir needs an RNG; a shuffling-but-unseeded stream would
            # silently be nondeterministic while every other knob is seeded.
            raise ValueError("shuffle_buffer requires a seed")
        self.shuffle_buffer = shuffle_buffer

    def _shard_pairs(self, path: str) -> Iterator[tuple[bytes, str]]:
        """(image_bytes, caption) pairs of ONE shard, tar order."""
        with tarfile.open(path, "r") as tf:
            pending: dict[str, dict] = {}
            for member in tf:
                if not member.isfile():
                    continue
                key = _pair_key(os.path.basename(member.name))
                if key is None:
                    continue
                base, kind = key
                buf = tf.extractfile(member)
                if buf is None:
                    continue
                entry = pending.setdefault(base, {})
                entry[kind] = buf.read()
                if "image" in entry and "text" in entry:
                    del pending[base]
                    yield entry["image"], entry["text"].decode("utf-8").strip()

    def _pairs(self, order) -> Iterator[tuple[bytes, str]]:
        """(image_bytes, caption) pairs across the epoch's shards, tar order.

        With ``read_ahead`` a single background reader fetches shard k+1's
        members while shard k's pairs are consumed (decoded) — the emitted
        sequence is exactly the serial one, only the blob IO overlaps.
        """
        if not self.read_ahead or len(order) <= 1:
            for si in order:
                yield from self._shard_pairs(self.shards[si])
            return
        from concurrent.futures import ThreadPoolExecutor

        def read(si) -> list[tuple[bytes, str]]:
            return list(self._shard_pairs(self.shards[si]))

        # Exactly one shard in flight: the executor exit joins the reader, so
        # an abandoned epoch (generator close) never leaks the thread.
        with ThreadPoolExecutor(1, thread_name_prefix="dsl-shard-read") as ex:
            fut = ex.submit(read, order[0])
            for k in range(len(order)):
                pairs = fut.result()
                if k + 1 < len(order):
                    fut = ex.submit(read, order[k + 1])
                yield from pairs

    def _shuffled(self, pairs, rng) -> Iterator[tuple[bytes, str]]:
        """Bounded reservoir shuffle (webdataset-style): hold ``shuffle_buffer``
        pairs, emit a uniformly random held one per incoming pair, drain at
        epoch end in random order."""
        held: list = []
        for pair in pairs:
            if len(held) < self.shuffle_buffer:
                held.append(pair)
                continue
            i = int(rng.integers(len(held)))
            held[i], pair = pair, held[i]
            yield pair
        while held:
            i = int(rng.integers(len(held)))
            held[i], last = held[-1], held[i]
            held.pop()
            yield last

    def _epoch_batches(self, pairs, batcher) -> Iterator[dict]:
        """Batches of one epoch. Serial mode flushes inline; pipelined mode
        keeps ONE batch's decode+tokenize in flight on a worker thread while
        the pair stream stages the next batch — same batches, same order."""
        if not self.pipelined:
            for image_bytes, caption in pairs:
                batch = batcher.add(image_bytes, caption)
                if batch is not None:
                    yield batch
            return
        from concurrent.futures import ThreadPoolExecutor

        pending = None
        # Executor exit joins the in-flight flush (one bounded batch), so an
        # abandoned stream (break / GC) never leaks the assembly thread.
        with ThreadPoolExecutor(1, thread_name_prefix="dsl-batch") as ex:
            for image_bytes, caption in pairs:
                job = batcher.stage(image_bytes, caption)
                if job is None:
                    continue
                fut = ex.submit(batcher.assemble, *job)
                if pending is not None:
                    yield pending.result()
                pending = fut
            if pending is not None:
                yield pending.result()

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng(self.seed) if self.seed is not None else None
        while True:
            yielded = False
            order = np.arange(len(self.shards))
            if rng is not None:
                rng.shuffle(order)
            batcher = _PairBatcher(
                self.cfg, self.batch_size, self.tokenize, self.native_decode,
                keep_captions=self.keep_captions,
                data_workers=self.data_workers,
            )
            pairs = self._pairs(order)
            if self.shuffle_buffer:
                pairs = self._shuffled(pairs, rng)
            for batch in self._epoch_batches(pairs, batcher):
                yielded = True
                yield batch
            if not yielded:
                # Mirror ImageTextFolder's too-few-pairs ValueError (which can
                # check up front); here pair counts are only known after a full
                # pass, and spinning on the tars forever would hang next().
                raise ValueError(
                    f"shards {self.shards} hold fewer complete (image, txt) "
                    f"pairs than one batch of {self.batch_size}"
                )
