"""Fused short-sequence multi-head attention — a Pallas TPU kernel for the towers.

Why not the generic flash kernel: at tower scale (ViT-B/16 s=196, text s=64) the
sequence fits in VMEM whole, so blockwise online softmax is pure overhead — the
generic kernel's (batch, head, q-block, kv-block) grid launches thousands of tiny
programs and loses to XLA's dense path (measured: 46ms vs 15ms per fwd+bwd call at
b=512, s=196). What actually hurts the dense path is HBM traffic: the (b, h, s, s)
logits and f32 softmax round-trip through HBM in forward AND backward — the largest
activations in the whole SigLIP step (7G+ stacked across layers at batch 256).

Design: the kernel consumes q/k/v in the towers' NATIVE (b, s, h·dh) layout — no
transposes, no layout padding (a (s, width) tile is exactly aligned); one program =
one batch row, heads handled by a static Python loop over lane slices. Everything
O(s²) lives and dies in VMEM: logits → softmax → out in forward, the 5-matmul
gradient chain in backward (probs recomputed, never stored). HBM traffic collapses
to the unavoidable q/k/v/out (+gradients) reads and writes — measured 5.8× faster
than the dense path at ViT-B/16 scale, 2.9× at text-tower scale. Numerics: f32
logits / softmax / accumulation, matmul inputs in the activation dtype (bf16 in
training) — the same contract as the dense path.

No reference analogue (the reference has no model layer, SURVEY.md §1); this is the
"pallas kernels for the hot ops" piece of the TPU-first design.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "short_self_attention",
    "short_attention_fits",
    "short_attention_vmem_bytes",
    "short_attention_bwd_batched_fits",
    "set_bwd_batch_heads",
    "traced_bwd_batch_heads",
    "reset_traced_bwd_batch_heads",
    "SHORT_ATTENTION_MAX_SEQ",
]

# Process-wide default for the backward kernel choice (see
# short_self_attention's batch_heads): flipped by bench.py --attn-bwd for the
# A/B without threading a knob through every tower config. Baked in at TRACE
# time — set it before building/jitting the step.
_DEFAULT_BATCH_HEADS = False

# Every backward-kernel choice RESOLVED at trace time in this process. The
# default above is mutable global state, so a step traced before
# set_bwd_batch_heads silently keeps the other kernel while argv claims the
# A/B ran (advisor, round 5) — records must cross-check against what actually
# traced, not what was requested (bench.py does, before emitting).
_TRACED_BWD_BATCH_HEADS: set[bool] = set()


def set_bwd_batch_heads(enabled: bool) -> None:
    """Set the process default for ``batch_heads=None`` call sites (the
    towers). Call BEFORE tracing: compiled programs keep the kernel they were
    traced with — :func:`traced_bwd_batch_heads` reports what actually did."""
    global _DEFAULT_BATCH_HEADS
    _DEFAULT_BATCH_HEADS = bool(enabled)


def traced_bwd_batch_heads() -> tuple[bool, ...]:
    """Distinct backward-kernel choices resolved at trace time so far, sorted.

    ``()`` = no fused short-attention backward has been traced in this
    process; ``(False,)`` / ``(True,)`` = every trace used the per-head loop /
    the head-batched kernel; ``(False, True)`` = mixed (some step traced
    before a ``set_bwd_batch_heads`` flip — the exact record-corruption case
    the cross-check exists to catch).
    """
    return tuple(sorted(_TRACED_BWD_BATCH_HEADS))


def reset_traced_bwd_batch_heads() -> None:
    """Clear the trace record (test isolation)."""
    _TRACED_BWD_BATCH_HEADS.clear()

_NEG_INF = -1e30

# Above this sequence length the O(s²) per-head blocks stop fitting VMEM comfortably
# and a blockwise (true flash / ring) kernel wins; dispatch there instead.
SHORT_ATTENTION_MAX_SEQ = 1024

# TPU VMEM is ~16 MiB/core across v4/v5e/v5p; the budget leaves headroom for the
# compiler's own scratch and pipelining buffers. A program over budget fails at
# Mosaic compile time with no fallback, so the dispatcher must pre-check.
_VMEM_BYTES = 16 * 1024 * 1024
_VMEM_BUDGET_FRACTION = 0.7


def short_attention_vmem_bytes(s: int, width: int, dtype_bytes: int) -> int:
    """Worst-case VMEM footprint of ONE grid program (width = h·dh).

    The backward program is the peak: 7 (s, width) I/O blocks (q, k, v, do, dq, dk,
    dv) resident for the whole program, plus ~3 live (s, s) f32 per-head
    intermediates (probs, dp, ds — the compiler can reuse across heads but not
    within the chain).
    """
    return 7 * s * width * dtype_bytes + 3 * s * s * 4


def short_attention_fits(s: int, width: int, dtype_bytes: int) -> bool:
    """True when the fused short kernel's per-program footprint fits the VMEM
    budget AND the sequence is within the design envelope. Callers fall back to
    blockwise flash (TPU) or dense (elsewhere) when False."""
    return (
        s <= SHORT_ATTENTION_MAX_SEQ
        and short_attention_vmem_bytes(s, width, dtype_bytes)
        <= _VMEM_BYTES * _VMEM_BUDGET_FRACTION
    )


def short_attention_bwd_batched_fits(
    s: int, width: int, num_heads: int, dtype_bytes: int
) -> bool:
    """Whether the HEAD-BATCHED backward fits VMEM: it keeps all h (s, s) f32
    chain intermediates (probs, dp, ds) live at once — h× the per-head loop's
    O(s²) footprint — in exchange for issuing each of the 5 gradient matmuls
    ONCE as an h-batched ``dot_general`` instead of h times at contraction
    depth dh (64 on the towers — half the MXU's 128 systolic depth). ViT-B/16
    (s=196, h=12): ~5.5 MB of chain + 2.1 MB of I/O blocks — fits; the
    per-head loop stays the fallback for bigger shapes."""
    return (
        7 * s * width * dtype_bytes + 3 * num_heads * s * s * 4
        <= _VMEM_BYTES * _VMEM_BUDGET_FRACTION
    )


def _dot(a, b, contract_a: int, contract_b: int):
    return lax.dot_general(
        a,
        b,
        (((contract_a,), (contract_b,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _head_probs(qh, kh, *, scale, causal):
    logits = _dot(qh, kh, 1, 1) * scale  # (s, s)
    if causal:
        s = logits.shape[0]
        rows = lax.broadcasted_iota(jnp.int32, (s, s), 0)
        cols = lax.broadcasted_iota(jnp.int32, (s, s), 1)
        logits = jnp.where(rows >= cols, logits, _NEG_INF)
    return jax.nn.softmax(logits, axis=-1)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, num_heads):
    q, k, v = q_ref[0], k_ref[0], v_ref[0]  # (s, h·dh)
    dh = q.shape[-1] // num_heads
    for j in range(num_heads):
        sl = slice(j * dh, (j + 1) * dh)
        p = _head_probs(q[:, sl], k[:, sl], scale=scale, causal=causal)
        o_ref[0, :, sl] = _dot(p.astype(v.dtype), v[:, sl], 1, 0).astype(o_ref.dtype)


def _bwd_kernel(
    q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref, *, scale, causal, num_heads
):
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    dh = q.shape[-1] // num_heads
    for j in range(num_heads):
        sl = slice(j * dh, (j + 1) * dh)
        qh, kh, vh, doh = q[:, sl], k[:, sl], v[:, sl], do[:, sl]
        # Recompute this head's probs entirely in VMEM.
        p = _head_probs(qh, kh, scale=scale, causal=causal)  # (s, s) f32
        p_lo = p.astype(vh.dtype)
        do_lo = doh.astype(vh.dtype)
        dv_ref[0, :, sl] = _dot(p_lo, do_lo, 0, 0).astype(dv_ref.dtype)  # pᵀ @ do
        dp = _dot(do_lo, vh, 1, 1)  # (s, s): do @ vᵀ
        # Softmax VJP: ds = p ⊙ (dp − rowsum(dp ⊙ p)), then the logits scale.
        ds = ((p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))) * scale).astype(
            qh.dtype
        )
        dq_ref[0, :, sl] = _dot(ds, kh, 1, 0).astype(dq_ref.dtype)  # ds @ k
        dk_ref[0, :, sl] = _dot(ds, qh, 0, 0).astype(dk_ref.dtype)  # dsᵀ @ q


def _bwd_kernel_batched(
    q_ref, k_ref, v_ref, do_ref, dq_ref, dk_ref, dv_ref, *, scale, causal, num_heads
):
    """Head-BATCHED backward: the round-3 attribution candidate. One h-batched
    ``dot_general`` per gradient matmul (5 total) instead of a static Python
    loop issuing each at (s, dh)-contraction — trades h× more live O(s²) VMEM
    (see :func:`short_attention_bwd_batched_fits`) for fewer, larger MXU
    dispatches. Numerics identical to :func:`_bwd_kernel`: f32 logits /
    softmax / chain, matmul inputs in the activation dtype."""
    q, k, v, do = q_ref[0], k_ref[0], v_ref[0], do_ref[0]
    s, width = q.shape
    dh = width // num_heads

    def heads(x):  # (s, h·dh) -> (h, s, dh)
        return jnp.swapaxes(x.reshape(s, num_heads, dh), 0, 1)

    def unheads(x):  # (h, s, dh) -> (s, h·dh)
        return jnp.swapaxes(x, 0, 1).reshape(s, width)

    def bdot(a, b_, ca, cb):
        return lax.dot_general(
            a, b_, (((ca,), (cb,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )

    qh, kh, vh, doh = heads(q), heads(k), heads(v), heads(do)
    logits = bdot(qh, kh, 2, 2) * scale  # (h, s, s)
    if causal:
        rows = lax.broadcasted_iota(jnp.int32, (num_heads, s, s), 1)
        cols = lax.broadcasted_iota(jnp.int32, (num_heads, s, s), 2)
        logits = jnp.where(rows >= cols, logits, _NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)  # (h, s, s) f32
    p_lo = p.astype(v.dtype)
    do_lo = doh.astype(v.dtype)
    dv = bdot(p_lo, do_lo, 1, 1)  # pᵀ @ do: (h, s_k, dh)
    dp = bdot(do_lo, vh, 2, 2)  # do @ vᵀ: (h, s_q, s_k)
    ds = ((p * (dp - jnp.sum(dp * p, axis=-1, keepdims=True))) * scale).astype(
        q.dtype
    )
    dq = bdot(ds, kh, 2, 1)  # ds @ k: (h, s_q, dh)
    dk = bdot(ds, qh, 1, 1)  # dsᵀ @ q: (h, s_k, dh)
    dq_ref[0] = unheads(dq).astype(dq_ref.dtype)
    dk_ref[0] = unheads(dk).astype(dk_ref.dtype)
    dv_ref[0] = unheads(dv).astype(dv_ref.dtype)


def _specs(b, s, width, n: int):
    block = pl.BlockSpec((1, s, width), lambda i: (i, 0, 0), memory_space=pltpu.VMEM)
    return dict(grid=(b,), in_specs=[block] * n, out_specs=block)


def _flops(b, s, width, n_matmuls: int) -> int:
    return 2 * b * s * s * width * n_matmuls


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def short_self_attention(q, k, v, causal: bool = False, scale: float | None = None,
                         interpret: bool = False,
                         batch_heads: bool | None = None):
    """Fused self-attention for VMEM-resident sequences: (b, s, h, dh) → same.

    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU testing).
    ``batch_heads`` selects the backward kernel: None/False keep the per-head
    loop (the measured round-4 headline behavior); True runs the head-batched
    gradient chain (requires :func:`short_attention_bwd_batched_fits`) — the
    round-3 attribution candidate, exposed for the bench ``--attn-bwd`` A/B.
    Adopt as default only after a measured win.
    """
    out, _ = _short_attention_fwd(q, k, v, causal, scale, interpret, batch_heads)
    return out


def _short_attention_fwd(q, k, v, causal, scale, interpret, batch_heads=None):
    b, s, h, dh = q.shape
    scale = (dh**-0.5) if scale is None else scale
    wide = (b, s, h * dh)  # free reshape: heads stay on the minor axis
    spec = _specs(b, s, h * dh, 3)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, num_heads=h),
        out_shape=jax.ShapeDtypeStruct(wide, q.dtype),
        grid=spec["grid"],
        in_specs=spec["in_specs"],
        out_specs=spec["out_specs"],
        cost_estimate=pl.CostEstimate(
            flops=_flops(b, s, h * dh, 2),
            bytes_accessed=4 * q.size * q.dtype.itemsize,
            transcendentals=b * h * s * s,
        ),
        interpret=interpret,
    )(q.reshape(wide), k.reshape(wide), v.reshape(wide))
    return out.reshape(q.shape), (q, k, v)


def _short_attention_bwd(causal, scale, interpret, batch_heads, residuals, g):
    q, k, v = residuals
    b, s, h, dh = q.shape
    scale_v = (dh**-0.5) if scale is None else scale
    wide = (b, s, h * dh)
    spec = _specs(b, s, h * dh, 4)
    if batch_heads is None:
        batch_heads = _DEFAULT_BATCH_HEADS
    # This body runs at TRACE time: what lands in the set is the kernel the
    # compiled program will actually execute, not what argv asked for.
    _TRACED_BWD_BATCH_HEADS.add(bool(batch_heads))
    if batch_heads and not short_attention_bwd_batched_fits(
        s, h * dh, h, q.dtype.itemsize
    ):
        raise ValueError(
            f"batch_heads backward does not fit VMEM at s={s}, "
            f"width={h * dh}, h={h}; use the per-head loop"
        )
    kernel = _bwd_kernel_batched if batch_heads else _bwd_kernel
    dq, dk, dv = pl.pallas_call(
        functools.partial(kernel, scale=scale_v, causal=causal, num_heads=h),
        out_shape=[jax.ShapeDtypeStruct(wide, q.dtype)] * 3,
        grid=spec["grid"],
        in_specs=spec["in_specs"],
        out_specs=[spec["out_specs"]] * 3,
        cost_estimate=pl.CostEstimate(
            flops=_flops(b, s, h * dh, 5),
            bytes_accessed=7 * q.size * q.dtype.itemsize,
            transcendentals=b * h * s * s,
        ),
        interpret=interpret,
    )(q.reshape(wide), k.reshape(wide), v.reshape(wide), g.reshape(wide))
    shape = q.shape
    return dq.reshape(shape), dk.reshape(shape), dv.reshape(shape)


short_self_attention.defvjp(_short_attention_fwd, _short_attention_bwd)
