from distributed_sigmoid_loss_tpu.ops.sigmoid_loss import (  # noqa: F401
    init_loss_params,
    pairwise_logits,
    sigmoid_xent,
    sigmoid_loss,
    sigmoid_loss_block,
    l2_normalize,
)
from distributed_sigmoid_loss_tpu.ops.softmax_loss import (  # noqa: F401
    init_clip_loss_params,
    softmax_contrastive_loss,
)
