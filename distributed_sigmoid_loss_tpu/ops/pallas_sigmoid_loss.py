"""Streaming 2-D Pallas TPU kernel for the sigmoid-loss hot op.

The loss block (reference distributed_sigmoid_loss.py:22-33) is a matmul →
scale/shift → logsigmoid → reduce chain. The round-3 kernel fused it, but kept
the whole ``(b, d)`` image block VMEM-resident and grid-ded only over text
tiles — so ``local_b`` was bounded by VMEM (at b=4096, d=768 the image block
alone is 12.6 MB, over the ~11 MB budget), which is exactly the wall the
``_32k_equiv`` push hits. This rebuild streams BOTH operands:

- **Forward**: grid over ``(image-tile i, text-tile j)``; each step does one
  ``(tile_b × tile_n)`` MXU matmul and a VPU softplus reduction into a (1, 1)
  scalar accumulator (same VMEM block across the whole grid — TPU grid
  execution is sequential, so the accumulation is race-free). Per-step VMEM is
  ``(tile_b + tile_n)·d·4 + tile_b·tile_n·4`` bytes regardless of ``b``/``n``.
- **Fused backward**: two Pallas kernels recompute each tile's logits and
  accumulate the gradients in VMEM — ``dzimg``/``dt'``/``dbias`` on a
  ``(i, j)`` grid (``dzimg`` tile ``i`` revisited across the inner ``j``
  steps), ``dztxt`` on a transposed ``(j, i)`` grid. No logits matrix, no
  per-tile residual, ever reaches HBM: the VJP residuals are just the
  embeddings (flash-attention-style rematerialization applied to contrastive
  logits), replacing the round-3 XLA-recompute VJP.
- **int8 MXU path** (``quant="int8"``): operands are symmetric-int8 quantized
  with the SAME shared recipe as the inference dot
  (:func:`~distributed_sigmoid_loss_tpu.ops.quant.quantize_int8` — per-row
  abs-max over the contraction axis, computed once outside the kernel) and
  the per-tile product is ``int8×int8→int32`` on the MXU with the identical
  dequant arithmetic as :func:`~distributed_sigmoid_loss_tpu.ops.quant.
  int8_dot_general` — bit-identical per element to the inference int8 dot on
  the same quantized operands. The backward is the STE contract of
  ``int8_dot_general_ste``: the sigmoid is evaluated at the QUANTIZED
  forward's logits, but the ``dzimg``/``dztxt`` dots run on the saved
  full-precision embeddings — the exact unquantized VJP.

Because no more than one ``(tile_b, tile_n)`` tile is ever live, the kernel is
also the chunk-block body for ``loss_impl="chunked"`` (the all-gather scan)
and the ring's per-hop block — the round-7 "memory-optimal OR kernel-fast"
fork is gone.

Falls back to the XLA path for shapes that don't meet the TPU tiling
constraints (see :func:`pallas_compatible`); the choice RESOLVED at trace time
is recorded process-wide (:func:`traced_loss_kernels`) so bench records can
cross-check engagement against argv instead of trusting the flag.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from distributed_sigmoid_loss_tpu.ops.quant import quantize_int8

__all__ = [
    "streaming_block_loss_sum",
    "streaming_block_loss_or_none",
    "pallas_compatible",
    "traced_loss_kernels",
    "reset_traced_loss_kernels",
    "NEGATIVE_ONLY_OFFSET",
    "DEFAULT_TILE_B",
    "DEFAULT_TILE_N",
]

# Sentinel "positive diagonal offset" that never matches any column: the whole
# block is negatives (ring hops after the first, non-positive scan chunks).
# Exactly representable in float32.
NEGATIVE_ONLY_OFFSET = -(2 ** 24)

# Default tile sizes: one MXU-native 128-sublane image tile against a
# 256-lane text tile keeps the per-step working set ~1.2 MB at d=768 (budget
# math in docs/PERF.md "Streaming 2-D kernel") while the 256-wide tile
# amortizes the revisit traffic on zimg.
DEFAULT_TILE_B = 128
DEFAULT_TILE_N = 256

# Every loss-kernel choice RESOLVED at trace time in this process:
# "streaming" / "streaming_int8" when a dispatch picked the kernel, "xla" when
# a use_pallas request fell back to the XLA block. A record claiming
# use_pallas while every block traced the fallback is the config-drift class
# the attn_bwd round-5 fix exists for — bench.py cross-checks against THIS,
# not argv (registered in analysis/repo_lint.py MUTABLE_GLOBAL_ALLOWLIST).
_TRACED_LOSS_KERNELS: set[str] = set()


def traced_loss_kernels() -> tuple[str, ...]:
    """Distinct loss-kernel choices resolved at trace time so far, sorted.

    ``()`` = no pallas-requested loss block has been traced in this process;
    ``("streaming",)`` / ``("streaming_int8",)`` = every dispatch engaged the
    kernel; any tuple containing ``"xla"`` = at least one block fell back to
    the XLA path while ``use_pallas`` was requested (shape not tileable).
    """
    return tuple(sorted(_TRACED_LOSS_KERNELS))


def reset_traced_loss_kernels() -> None:
    """Clear the trace record (test isolation)."""
    _TRACED_LOSS_KERNELS.clear()


def pallas_compatible(
    b: int,
    n: int,
    d: int,
    tile_b: int = DEFAULT_TILE_B,
    tile_n: int = DEFAULT_TILE_N,
    quant: bool = False,
) -> bool:
    """TPU tiling constraints for the streaming kernel.

    Tiles clamp to the block (``min(tile, dim)``); the dims must then tile
    evenly, the contraction axis must be lane-aligned (``d % 128``), and the
    tile sublanes must match the operand dtype's sublane quantum — 8 for f32,
    32 for the int8 path (int8 min tile is (32, 128)). Unlike the round-3
    kernel there is NO bound on ``b`` itself: the image block streams
    tile-by-tile instead of sitting whole in VMEM.
    """
    tb, tn = min(tile_b, b), min(tile_n, n)
    sub = 32 if quant else 8
    return (
        b % tb == 0
        and n % tn == 0
        and d % 128 == 0
        and tb % sub == 0
        and tn % sub == 0
    )


# ---------------------------------------------------------------------------
# Kernel bodies (shared tile math).
# ---------------------------------------------------------------------------


def _tile_raw_f32(zimg_blk, ztxt_blk):
    """(tile_b, d) @ (tile_n, d)^T with f32 MXU accumulation."""
    return lax.dot_general(
        zimg_blk,
        ztxt_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


def _tile_raw_int8(ziq_blk, zis_blk, ztq_blk, zts_blk):
    """int8×int8→int32 tile product, dequantized with the EXACT arithmetic of
    ops.quant.int8_dot_general (``acc.astype(f32) * lhs_scales * rhs_scales``,
    same association order) — per-element bit-identical to the inference int8
    dot on the same quantized operands, since each output element's int32
    accumulation spans the full contraction axis inside one tile."""
    acc = lax.dot_general(
        ziq_blk,
        ztq_blk,
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return acc.astype(jnp.float32) * zis_blk * jnp.squeeze(zts_blk, 1)


def _tile_labels(tile_b, tile_n, i, j, off):
    """±1 labels for tile (i, j): +1 where global col == global row + off."""
    rows = lax.broadcasted_iota(jnp.int32, (tile_b, tile_n), 0) + i * tile_b
    cols = lax.broadcasted_iota(jnp.int32, (tile_b, tile_n), 1) + j * tile_n
    return jnp.where(cols == rows + jnp.int32(off), 1.0, -1.0)


def _fwd_kernel(quant, tp_ref, bias_ref, off_ref, *refs):
    if quant:
        ziq_ref, zis_ref, ztq_ref, zts_ref, out_ref = refs
        raw = _tile_raw_int8(ziq_ref[:], zis_ref[:], ztq_ref[:], zts_ref[:])
        tile_b, tile_n = raw.shape
    else:
        zimg_ref, ztxt_ref, out_ref = refs
        raw = _tile_raw_f32(zimg_ref[:], ztxt_ref[:])
        tile_b, tile_n = raw.shape
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when((i == 0) & (j == 0))
    def _():
        # Full-ref (1, 1) stores: element-wise scalar stores to VMEM are
        # interpret-mode-only; Mosaic rejects them on hardware.
        out_ref[...] = jnp.zeros_like(out_ref)

    t = jnp.exp(tp_ref[0])
    logits = raw * t + bias_ref[0]
    labels = _tile_labels(tile_b, tile_n, i, j, off_ref[0])
    # -log_sigmoid(x) == softplus(-x)
    out_ref[...] = out_ref[...] + jnp.sum(jax.nn.softplus(-labels * logits))


def _tile_dlogits(quant, tp_ref, bias_ref, off_ref, g_ref, i, j, recompute):
    """Recompute tile (i, j)'s logits and return (dlogits, raw, t).

    ``recompute`` carries the operands the forward actually consumed (f32
    tiles, or quantized tiles + scales) so the sigmoid is evaluated at the
    same point as the forward pass — the STE contract for the int8 path.
    """
    raw = _tile_raw_int8(*recompute) if quant else _tile_raw_f32(*recompute)
    tile_b, tile_n = raw.shape
    t = jnp.exp(tp_ref[0])
    logits = raw * t + bias_ref[0]
    labels = _tile_labels(tile_b, tile_n, i, j, off_ref[0])
    x = labels * logits
    # d/dlogits of softplus(-x) with x = labels*logits: -labels * sigmoid(-x)
    dlogits = g_ref[0] * (-labels * jax.nn.sigmoid(-x))
    return dlogits, raw, t


def _bwd_img_kernel(quant, tp_ref, bias_ref, off_ref, g_ref, *refs):
    """Grid (i, j), j innermost: dzimg tile i accumulates across its j-row in
    VMEM; dt'/dbias accumulate across the whole grid."""
    if quant:
        (ziq_ref, zis_ref, ztq_ref, zts_ref, ztxt_ref,
         dzimg_ref, dtp_ref, dbias_ref) = refs
        recompute = (ziq_ref[:], zis_ref[:], ztq_ref[:], zts_ref[:])
    else:
        zimg_ref, ztxt_ref, dzimg_ref, dtp_ref, dbias_ref = refs
        recompute = (zimg_ref[:], ztxt_ref[:])
    i, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _():
        dzimg_ref[...] = jnp.zeros_like(dzimg_ref)

    @pl.when((i == 0) & (j == 0))
    def _():
        dtp_ref[...] = jnp.zeros_like(dtp_ref)
        dbias_ref[...] = jnp.zeros_like(dbias_ref)

    dlogits, raw, t = _tile_dlogits(
        quant, tp_ref, bias_ref, off_ref, g_ref, i, j, recompute
    )
    # STE: the VJP dot consumes the FULL-PRECISION text tile even when the
    # forward product ran int8 (ops/quant.py int8_dot_general_ste contract).
    dzimg_ref[...] = dzimg_ref[...] + (
        jnp.dot(dlogits, ztxt_ref[:], preferred_element_type=jnp.float32) * t
    )
    dtp_ref[...] = dtp_ref[...] + jnp.sum(dlogits * raw) * t
    dbias_ref[...] = dbias_ref[...] + jnp.sum(dlogits)


def _bwd_txt_kernel(quant, tp_ref, bias_ref, off_ref, g_ref, *refs):
    """Transposed grid (j, i), i innermost: dztxt tile j accumulates across
    its i-column in VMEM."""
    if quant:
        (ziq_ref, zis_ref, ztq_ref, zts_ref, zimg_ref, dztxt_ref) = refs
        recompute = (ziq_ref[:], zis_ref[:], ztq_ref[:], zts_ref[:])
    else:
        zimg_ref, ztxt_ref, dztxt_ref = refs
        recompute = (zimg_ref[:], ztxt_ref[:])
    j, i = pl.program_id(0), pl.program_id(1)

    @pl.when(i == 0)
    def _():
        dztxt_ref[...] = jnp.zeros_like(dztxt_ref)

    dlogits, _, t = _tile_dlogits(
        quant, tp_ref, bias_ref, off_ref, g_ref, i, j, recompute
    )
    dztxt_ref[...] = dztxt_ref[...] + (
        lax.dot_general(
            dlogits,
            zimg_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * t
    )


# ---------------------------------------------------------------------------
# pallas_call plumbing (specs, vma typing, 0.4.x struct compat).
# ---------------------------------------------------------------------------


def _scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _vma_of(*xs) -> frozenset:
    """Union of the inputs' varying-manual-axes (shard_map's replication
    typing). Under ``jax.shard_map`` with ``check_vma=True`` (the 0.6
    default), ``pallas_call`` outputs must declare which mesh axes they vary
    over; the loss varies over every axis any input varies over. Outside
    shard_map (and on jax 0.4.x, whose check_rep machinery infers this
    itself) this is the empty set."""
    vma = frozenset()
    for x in xs:
        try:
            vma |= jax.typeof(x).vma
        except AttributeError:  # plain numpy input or older jax
            pass
    return vma


def _align_vma(x, vma: frozenset):
    """Upcast ``x`` to vary over every axis in ``vma`` (no-op when aligned)."""
    missing = tuple(vma - _vma_of(x))
    return lax.pcast(x, missing, to="varying") if missing else x


def _struct(shape, vma: frozenset, dtype=jnp.float32):
    """ShapeDtypeStruct with vma typing where the jax version supports it
    (0.6+); plain struct on 0.4.x, whose check_rep path needs none."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _operand_pack(zimg, ztxt, quant, vma):
    """(arrays, in_specs) for the streamed operands: f32 tiles, or quantized
    int8 tiles + per-row scales (shared ops.quant recipe, computed ONCE out
    here — each tile sees its rows' full contraction axis, so per-tile and
    whole-array quantization coincide). Index maps take the kernel's OWN grid
    order: axis 0 of the grid picks the image tile for fwd/bwd-img, the text
    tile for bwd-txt — callers pass ``img_axis``/``txt_axis`` accordingly."""
    del vma  # aligned by the callers on the packed arrays

    def pack(img_axis, txt_axis, tile_b, tile_n, d):
        def at(axis):
            return lambda *ids: (ids[axis], 0)

        if quant:
            ziq, zis = quantize_int8(zimg, axis=1)
            ztq, zts = quantize_int8(ztxt, axis=1)
            arrays = (ziq, zis, ztq, zts)
            specs = [
                pl.BlockSpec((tile_b, d), at(img_axis), memory_space=pltpu.VMEM),
                pl.BlockSpec((tile_b, 1), at(img_axis), memory_space=pltpu.VMEM),
                pl.BlockSpec((tile_n, d), at(txt_axis), memory_space=pltpu.VMEM),
                pl.BlockSpec((tile_n, 1), at(txt_axis), memory_space=pltpu.VMEM),
            ]
            return arrays, specs
        arrays = (zimg, ztxt)
        specs = [
            pl.BlockSpec((tile_b, d), at(img_axis), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile_n, d), at(txt_axis), memory_space=pltpu.VMEM),
        ]
        return arrays, specs

    return pack


def streaming_block_loss_or_none(
    zimg,
    ztxt,
    t_prime,
    bias,
    pos_offset,
    *,
    quant: str = "",
    tile_b: int = DEFAULT_TILE_B,
    tile_n: int = DEFAULT_TILE_N,
    normalize: bool = True,
):
    """Dispatch helper for the distributed variants: the streaming block loss
    when shapes meet the TPU tiling constraints, else ``None`` (caller falls
    back to the XLA path). Records the trace-time choice, handles shard_map
    vma alignment and interpret-mode selection (CPU tests) in one place.

    ``normalize=True`` returns the per-image-normalized block loss (what the
    fused/ring block call sites consume); ``normalize=False`` returns the raw
    block SUM (what the chunked scan accumulates before its own ``/ n_img``).
    """
    b, d = zimg.shape
    n = ztxt.shape[0]
    if not pallas_compatible(b, n, d, tile_b, tile_n, quant=bool(quant)):
        _TRACED_LOSS_KERNELS.add("xla")
        return None
    _TRACED_LOSS_KERNELS.add("streaming_int8" if quant else "streaming")
    interpret = jax.default_backend() != "tpu"
    total = streaming_block_loss_sum(
        zimg, ztxt, t_prime, bias,
        jnp.asarray(pos_offset, jnp.float32),
        quant, min(tile_b, b), min(tile_n, n), interpret,
    )
    return total / b if normalize else total


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def streaming_block_loss_sum(
    zimg, ztxt, t_prime, bias, pos_offset,
    quant="", tile_b=DEFAULT_TILE_B, tile_n=DEFAULT_TILE_N, interpret=False,
):
    """SUM of ``-log_sigmoid(labels * (exp(t_prime)·raw + bias))`` over the
    (b × n) block, positives on ``col == row + pos_offset`` (pass
    ``NEGATIVE_ONLY_OFFSET`` for an all-negatives block); ``raw`` is the
    f32-accumulated MXU product, or the int8-dequantized product when
    ``quant="int8"``. Unnormalized — divide by the local batch outside, as the
    reference does (distributed_sigmoid_loss.py:47). ``tile_b``/``tile_n``
    must already be clamped to the block and pass :func:`pallas_compatible`
    (use :func:`streaming_block_loss_or_none` unless you have a reason not
    to)."""
    loss, _ = _fwd(
        zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n, interpret
    )
    return loss


def _prep(zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n, *extra):
    b, d = zimg.shape
    n = ztxt.shape[0]
    assert pallas_compatible(b, n, d, tile_b, tile_n, quant=bool(quant)), (
        b, n, d, tile_b, tile_n, quant,
    )
    vma = _vma_of(zimg, ztxt, t_prime, bias, pos_offset, *extra)
    scalars = [
        _align_vma(jnp.reshape(t_prime.astype(jnp.float32), (1,)), vma),
        _align_vma(jnp.reshape(bias.astype(jnp.float32), (1,)), vma),
        _align_vma(
            jnp.reshape(jnp.asarray(pos_offset, jnp.float32), (1,)), vma
        ),
    ]
    pack = _operand_pack(
        zimg.astype(jnp.float32), ztxt.astype(jnp.float32), bool(quant), vma
    )
    return b, n, d, vma, scalars, pack


def _fwd(zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n, interpret):
    b, n, d, vma, scalars, pack = _prep(
        zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n
    )
    arrays, specs = pack(0, 1, tile_b, tile_n, d)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, bool(quant)),
        grid=(b // tile_b, n // tile_n),
        in_specs=[_scalar_spec()] * 3 + specs,
        out_specs=pl.BlockSpec((1, 1), lambda i, j: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=_struct((1, 1), vma),
        interpret=interpret,
    )(*scalars, *(_align_vma(a, vma) for a in arrays))
    loss = out[0, 0]
    return loss, (zimg, ztxt, t_prime, bias, pos_offset)


def _bwd(quant, tile_b, tile_n, interpret, res, g):
    zimg, ztxt, t_prime, bias, pos_offset = res
    b, n, d, vma, scalars, pack = _prep(
        zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n, g
    )
    scalars.append(_align_vma(jnp.reshape(g.astype(jnp.float32), (1,)), vma))
    zimg32 = _align_vma(zimg.astype(jnp.float32), vma)
    ztxt32 = _align_vma(ztxt.astype(jnp.float32), vma)

    def vspec(shape, index_map):
        return pl.BlockSpec(shape, index_map, memory_space=pltpu.VMEM)

    # Pass 1 — grid (i, j), j innermost: dzimg tile i stays resident across
    # its j-row; dt'/dbias ride the same (1, 1) block across the whole grid.
    # The f32 pack already carries the full-precision text tile the VJP dot
    # consumes; only the int8 pack (quantized recompute operands) needs it
    # appended separately.
    arrays, specs = pack(0, 1, tile_b, tile_n, d)
    extra = ((ztxt32,), [vspec((tile_n, d), lambda i, j: (j, 0))]) if quant \
        else ((), [])
    dzimg, dtp, dbias = pl.pallas_call(
        functools.partial(_bwd_img_kernel, bool(quant)),
        grid=(b // tile_b, n // tile_n),
        in_specs=[_scalar_spec()] * 4 + specs + extra[1],
        out_specs=[
            vspec((tile_b, d), lambda i, j: (i, 0)),
            vspec((1, 1), lambda i, j: (0, 0)),
            vspec((1, 1), lambda i, j: (0, 0)),
        ],
        out_shape=[
            _struct((b, d), vma),
            _struct((1, 1), vma),
            _struct((1, 1), vma),
        ],
        interpret=interpret,
    )(*scalars, *(_align_vma(a, vma) for a in arrays), *extra[0])

    # Pass 2 — transposed grid (j, i), i innermost: dztxt tile j resident
    # across its i-column. One extra logit recompute vs a single-pass kernel;
    # the price of never parking either gradient block in HBM mid-grid.
    arrays, specs = pack(1, 0, tile_b, tile_n, d)
    extra = ((zimg32,), [vspec((tile_b, d), lambda j, i: (i, 0))]) if quant \
        else ((), [])
    (dztxt,) = pl.pallas_call(
        functools.partial(_bwd_txt_kernel, bool(quant)),
        grid=(n // tile_n, b // tile_b),
        in_specs=[_scalar_spec()] * 4 + specs + extra[1],
        out_specs=[vspec((tile_n, d), lambda j, i: (j, 0))],
        out_shape=[_struct((n, d), vma)],
        interpret=interpret,
    )(*scalars, *(_align_vma(a, vma) for a in arrays), *extra[0])

    return (
        dzimg.astype(zimg.dtype),
        dztxt.astype(ztxt.dtype),
        dtp[0, 0].astype(t_prime.dtype),
        dbias[0, 0].astype(bias.dtype),
        jnp.zeros_like(jnp.asarray(pos_offset, jnp.float32)),
    )


def _fwd_rule(zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n,
              interpret):
    return _fwd(
        zimg, ztxt, t_prime, bias, pos_offset, quant, tile_b, tile_n, interpret
    )


streaming_block_loss_sum.defvjp(_fwd_rule, _bwd)
