"""Fused Pallas TPU kernel for the sigmoid-loss hot op.

The loss block (reference distributed_sigmoid_loss.py:22-33) is a matmul → scale/shift →
logsigmoid → reduce chain. XLA fuses most of it, but for large text chunks the (b × n)
logit matrix still round-trips HBM between forward and backward. This kernel computes
the scalar loss tile-by-tile in VMEM — logits never touch HBM — and the custom VJP
recomputes tiles in the backward pass (flash-attention-style rematerialization applied
to contrastive logits).

Layout: grid over text tiles; the image block stays resident in VMEM; each step does one
(b × TILE_N) MXU matmul and a VPU softplus reduction into a scalar accumulator. TPU grid
execution is sequential, so the accumulation is race-free.

Used by both distributed variants (the all-gather's per-chunk loss and the ring's
per-hop block loss). Falls back to the XLA path for shapes that don't meet TPU tiling
constraints (see :func:`pallas_compatible`).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "fused_block_loss_sum",
    "fused_block_loss_or_none",
    "pallas_compatible",
    "NEGATIVE_ONLY_OFFSET",
]

# Sentinel "positive diagonal offset" that never matches any column: the whole block is
# negatives (ring hops after the first). Exactly representable in float32.
NEGATIVE_ONLY_OFFSET = -(2 ** 24)


def pallas_compatible(b: int, n: int, d: int, tile_n: int = 256) -> bool:
    """TPU tiling constraints for the fused kernel (fp32: sublane 8, lane 128)."""
    tile = min(tile_n, n)
    return (
        b % 8 == 0
        and d % 128 == 0
        and n % tile == 0
        and tile % 128 == 0
    )


def _fwd_kernel(tp_ref, bias_ref, off_ref, zimg_ref, ztxt_ref, out_ref):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        # Full-ref (1, 1) stores: element-wise scalar stores to VMEM are interpret-
        # mode-only; Mosaic rejects them on hardware.
        out_ref[...] = jnp.zeros_like(out_ref)

    b, tile_n = zimg_ref.shape[0], ztxt_ref.shape[0]
    t = jnp.exp(tp_ref[0])
    raw = jax.lax.dot_general(
        zimg_ref[:],
        ztxt_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    logits = raw * t + bias_ref[0]
    rows = lax.broadcasted_iota(jnp.int32, (b, tile_n), 0)
    cols = lax.broadcasted_iota(jnp.int32, (b, tile_n), 1) + j * tile_n
    labels = jnp.where(cols == rows + jnp.int32(off_ref[0]), 1.0, -1.0)
    # -log_sigmoid(x) == softplus(-x)
    out_ref[...] = out_ref[...] + jnp.sum(jax.nn.softplus(-labels * logits))


def _bwd_kernel(
    tp_ref, bias_ref, off_ref, g_ref,
    zimg_ref, ztxt_ref,
    dzimg_ref, dztxt_ref, dtp_ref, dbias_ref,
):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _():
        dzimg_ref[:] = jnp.zeros_like(dzimg_ref)
        dtp_ref[...] = jnp.zeros_like(dtp_ref)
        dbias_ref[...] = jnp.zeros_like(dbias_ref)

    b, tile_n = zimg_ref.shape[0], ztxt_ref.shape[0]
    t = jnp.exp(tp_ref[0])
    raw = jax.lax.dot_general(
        zimg_ref[:],
        ztxt_ref[:],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    logits = raw * t + bias_ref[0]
    rows = lax.broadcasted_iota(jnp.int32, (b, tile_n), 0)
    cols = lax.broadcasted_iota(jnp.int32, (b, tile_n), 1) + j * tile_n
    labels = jnp.where(cols == rows + jnp.int32(off_ref[0]), 1.0, -1.0)
    x = labels * logits
    # d/dlogits of softplus(-x) with x = labels*logits: -labels * sigmoid(-x)
    dlogits = g_ref[0] * (-labels * jax.nn.sigmoid(-x))

    dzimg_ref[:] += (
        jnp.dot(dlogits, ztxt_ref[:], preferred_element_type=jnp.float32) * t
    )
    dztxt_ref[:] = (
        jax.lax.dot_general(
            dlogits,
            zimg_ref[:],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        * t
    )
    dtp_ref[...] = dtp_ref[...] + jnp.sum(dlogits * raw) * t
    dbias_ref[...] = dbias_ref[...] + jnp.sum(dlogits)


def _scalar_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM)


def _vma_of(*xs) -> frozenset:
    """Union of the inputs' varying-manual-axes (shard_map's replication typing).

    Under ``jax.shard_map`` with ``check_vma=True`` (the default), ``pallas_call``
    outputs must declare which mesh axes they vary over; the loss varies over every
    axis any input varies over. Outside shard_map this is the empty set.
    """
    vma = frozenset()
    for x in xs:
        try:
            vma |= jax.typeof(x).vma
        except AttributeError:  # plain numpy input or older jax
            pass
    return vma


def _align_vma(x, vma: frozenset):
    """Upcast ``x`` to vary over every axis in ``vma`` (no-op when already varying)."""
    missing = tuple(vma - _vma_of(x))
    return lax.pcast(x, missing, to="varying") if missing else x


def fused_block_loss_or_none(
    zimg, ztxt, t_prime, bias, pos_offset, *, tile_n: int = 256
):
    """Dispatch helper for the distributed variants: the fused per-image-normalized
    block loss when shapes meet the TPU tiling constraints, else ``None`` (caller
    falls back to the XLA path). Handles shard_map vma alignment and interpret-mode
    selection (CPU tests) in one place."""
    b, d = zimg.shape
    n = ztxt.shape[0]
    tile = min(tile_n, n)
    if not pallas_compatible(b, n, d, tile):
        return None
    interpret = jax.default_backend() != "tpu"
    total = fused_block_loss_sum(
        zimg, ztxt, t_prime, bias,
        jnp.asarray(pos_offset, jnp.float32), tile, interpret,
    )
    return total / b


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_block_loss_sum(zimg, ztxt, t_prime, bias, pos_offset, tile_n=256, interpret=False):
    """SUM of ``-log_sigmoid(labels * (exp(t_prime)·zimg@ztxt.T + bias))`` over the
    (b × n) block, positives on ``col == row + pos_offset`` (pass
    ``NEGATIVE_ONLY_OFFSET`` for an all-negatives block). Unnormalized — divide by the
    local batch outside, as the reference does (distributed_sigmoid_loss.py:47)."""
    loss, _ = _fwd(zimg, ztxt, t_prime, bias, pos_offset, tile_n, interpret)
    return loss


def _fwd(zimg, ztxt, t_prime, bias, pos_offset, tile_n, interpret):
    b, d = zimg.shape
    n = ztxt.shape[0]
    tile = min(tile_n, n)
    assert pallas_compatible(b, n, d, tile_n), (b, n, d, tile_n)

    vma = _vma_of(zimg, ztxt, t_prime, bias, pos_offset)
    scalars = [
        _align_vma(jnp.reshape(t_prime.astype(jnp.float32), (1,)), vma),
        _align_vma(jnp.reshape(bias.astype(jnp.float32), (1,)), vma),
        _align_vma(jnp.reshape(jnp.asarray(pos_offset, jnp.float32), (1,)), vma),
    ]
    out = pl.pallas_call(
        _fwd_kernel,
        grid=(n // tile,),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            _scalar_spec(),
            pl.BlockSpec((b, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d), lambda j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, 1), jnp.float32, vma=vma),
        interpret=interpret,
    )(
        *scalars,
        _align_vma(zimg.astype(jnp.float32), vma),
        _align_vma(ztxt.astype(jnp.float32), vma),
    )
    loss = out[0, 0]
    return loss, (zimg, ztxt, t_prime, bias, pos_offset)


def _bwd(tile_n, interpret, res, g):
    zimg, ztxt, t_prime, bias, pos_offset = res
    b, d = zimg.shape
    n = ztxt.shape[0]
    tile = min(tile_n, n)

    vma = _vma_of(zimg, ztxt, t_prime, bias, pos_offset, g)
    scalars = [
        _align_vma(jnp.reshape(t_prime.astype(jnp.float32), (1,)), vma),
        _align_vma(jnp.reshape(bias.astype(jnp.float32), (1,)), vma),
        _align_vma(jnp.reshape(jnp.asarray(pos_offset, jnp.float32), (1,)), vma),
        _align_vma(jnp.reshape(g.astype(jnp.float32), (1,)), vma),
    ]
    dzimg, dztxt, dtp, dbias = pl.pallas_call(
        _bwd_kernel,
        grid=(n // tile,),
        in_specs=[
            _scalar_spec(),
            _scalar_spec(),
            _scalar_spec(),
            _scalar_spec(),
            pl.BlockSpec((b, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d), lambda j: (j, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((b, d), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((tile, d), lambda j: (j, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda j: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((n, d), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((1, 1), jnp.float32, vma=vma),
            jax.ShapeDtypeStruct((1, 1), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(
        *scalars,
        _align_vma(zimg.astype(jnp.float32), vma),
        _align_vma(ztxt.astype(jnp.float32), vma),
    )

    return (
        dzimg.astype(zimg.dtype),
        dztxt.astype(ztxt.dtype),
        dtp[0, 0].astype(t_prime.dtype),
        dbias[0, 0].astype(bias.dtype),
        jnp.zeros_like(jnp.asarray(pos_offset, jnp.float32)),
    )


def _fwd_rule(zimg, ztxt, t_prime, bias, pos_offset, tile_n, interpret):
    return _fwd(zimg, ztxt, t_prime, bias, pos_offset, tile_n, interpret)


fused_block_loss_sum.defvjp(_fwd_rule, _bwd)
