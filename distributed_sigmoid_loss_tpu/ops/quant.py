"""Dynamic int8 quantized matmul for inference — the v5e's second MXU gear.

TPU v5e executes int8×int8→int32 ``dot_general`` at 394 TOPS, exactly 2× the
bf16 peak (public spec sheet), and XLA lowers integer dots to the MXU
directly. For inference (eval/retrieval/zero-shot serving, ``train`` is NOT
the audience — see below) the towers can run their projection matmuls in int8
with dynamic symmetric quantization:

- **activations**: per-row abs-max over the contraction axis, computed on the
  fly (no calibration pass, no stored stats);
- **weights**: per-output-channel abs-max over the contraction axis.

Per-channel weight scales + per-row dynamic activation scales is the standard
PTQ recipe that keeps ViT/text-transformer quality (~1e-3 relative error per
matmul; the model-level contract is pinned in tests/test_quant.py).

The integration point is flax's ``nn.Dense(dot_general=...)`` injection —
the param tree is untouched, so ANY trained/imported checkpoint can be served
quantized by flipping ``quant="int8"`` on the tower config (utils/config.py).

Two gears, one recipe:

- ``int8_dot_general`` — inference. ``round`` has zero gradient almost
  everywhere, so a tower quantized with THIS dot trains to a standstill
  silently; the train-step guard rejects ``quant`` configs in trainable
  contexts.
- ``int8_dot_general_ste`` — training. The standard low-precision-training
  fix: a straight-through estimator (``jax.custom_vjp``) whose forward is
  bit-identical to ``int8_dot_general`` (the MXU's int8 gear) and whose
  backward is EXACTLY the unquantized ``lax.dot_general`` VJP on the saved
  full-precision operands — the gradient the bf16/f32 layer would have
  produced for the same cotangent. This is what breaks the bf16 roofline
  (docs/PERF.md "Why an int8 training track"): the v5e int8 MXU peak is 2x
  bf16, and the bf16 MFU=1.0 ceiling sits below the 1.5x-A100 target.
  ``int8_expert_matmul_ste`` is the MoE-expert analogue.

No reference analogue (the reference has no model/serving layer; SURVEY.md
§2 C8 documents docs-only coverage there) — this is TPU-first scope beyond it.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = [
    "int8_dot_general",
    "int8_dot_general_ste",
    "int8_expert_matmul",
    "int8_expert_matmul_ste",
    "quantize_int8",
    "sign_sketch",
    "sign_sketch_scores",
]

# Symmetric int8: round-to-nearest into [-127, 127] (−128 unused, keeping the
# scale symmetric so dequant is one multiply).
_QMAX = 127.0
# Abs-max floor: an all-zero row/channel would otherwise divide by zero; any
# value below this quantizes to exact zeros with a harmless scale.
_EPS = 1e-12


def quantize_int8(x: jnp.ndarray, axis: int):
    """Symmetric int8 quantization of ``x`` along ``axis``.

    Returns ``(q, scale)`` with ``q`` int8, ``scale`` float32 keeping ``axis``
    as a size-1 dim, such that ``q * scale ≈ x``.
    """
    scale = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True), _EPS
    ) / _QMAX
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -_QMAX, _QMAX).astype(
        jnp.int8
    )
    return q, scale


def int8_expert_matmul(x, w, out_dtype):
    """Batched-expert int8 matmul: ``(E, ..., K) @ (E, K, M) -> (E, ..., M)``.

    The MoE layer's expert MLP einsums (``encd,edh->ench`` / ``ench,ehd->encd``,
    models/moe.py expert_apply) in dynamic int8: per-row activation scales over
    K, per-(expert, out-channel) weight scales, int32 accumulation, expert as a
    dot_general batch dim. Zero rows (unused capacity slots) quantize to exact
    zeros. The one-hot dispatch/combine einsums stay in the model dtype — they
    are <20% of the layer's FLOPs and carry the routing weights whose
    precision sets drop behavior.
    """
    e = x.shape[0]
    xq, xs = quantize_int8(x, axis=-1)          # xs (E, ..., 1)
    wq, ws = quantize_int8(w, axis=1)           # ws (E, 1, M)
    acc = lax.dot_general(
        xq, wq,
        (((x.ndim - 1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )                                            # (E, ..., M)
    ws_b = ws.reshape((e,) + (1,) * (x.ndim - 2) + (w.shape[-1],))
    return (acc.astype(jnp.float32) * xs * ws_b).astype(out_dtype)


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """Drop-in ``lax.dot_general`` that runs the contraction in int8.

    Specialized to the single-contraction, no-batch-dims pattern every
    ``nn.Dense`` emits; anything else falls through to the real
    ``lax.dot_general`` unquantized (correct, just not accelerated).
    ``precision``/``preferred_element_type`` are accepted for signature
    compatibility; the int8 path fixes accumulation to int32 (the MXU's
    native accumulator — there is nothing to configure).
    """
    (lc, rc), (lb, rb) = dimension_numbers
    if lb or rb or len(lc) != 1 or len(rc) != 1:
        return lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type,
        )
    # Same output-dtype rule as lax.dot_general, so both branches of this
    # function (and a swap back to the real dot) are drop-in interchangeable.
    out_dtype = (
        preferred_element_type
        if preferred_element_type is not None
        else jnp.promote_types(lhs.dtype, rhs.dtype)
    )
    lq, ls = quantize_int8(lhs, lc[0])   # activations: per-row over K
    rq, rs = quantize_int8(rhs, rc[0])   # weights: per-out-channel over K
    acc = lax.dot_general(
        lq, rq, dimension_numbers, preferred_element_type=jnp.int32
    )
    # Result dims = lhs-free then rhs-free: lhs scales broadcast from the
    # left (padded with one 1 per rhs-free dim), rhs scales from the right.
    ls_free = jnp.squeeze(ls, axis=lc[0])
    rs_free = jnp.squeeze(rs, axis=rc[0])
    n_rhs_free = rhs.ndim - 1
    ls_b = ls_free.reshape(ls_free.shape + (1,) * n_rhs_free)
    return (acc.astype(jnp.float32) * ls_b * rs_free).astype(out_dtype)


# ---------------------------------------------------------------------------
# Binary sign sketches — the 1-bit coarse gear of the serving ANN tier.
#
# "Dissecting Embedding Bag Performance in DLRM Inference" (PAPERS.md): this
# workload is memory-bandwidth-bound, so the candidate-pruning scan's cost is
# the bytes it streams. int8 rows are 4x smaller than f32; sign bits are 32x.
# For L2-normalized embeddings, sign-agreement count (d - 2*hamming) is a
# monotone proxy for the dot product — good enough to PRUNE, never to RANK
# (serve/ann.py re-ranks the survivors exactly). Host-side numpy on purpose:
# the coarse scan runs where the index lives, outside any traced code.
# ---------------------------------------------------------------------------

_POPCOUNT = np.array([bin(v).count("1") for v in range(256)], dtype=np.uint8)


def sign_sketch(x) -> np.ndarray:
    """(n, d) float rows → (n, ceil(d/8)) packed sign bits (bit = row >= 0)."""
    x = np.asarray(x)
    if x.ndim != 2:
        raise ValueError(f"sign_sketch expects (n, d) rows, got {x.shape}")
    return np.packbits(x >= 0.0, axis=1)


def sign_sketch_scores(qbits: np.ndarray, cbits: np.ndarray, dim: int) -> np.ndarray:
    """Coarse scores (q, n) between packed query/corpus sketches: the
    sign-agreement count ``d - 2*hamming`` (∝ the dot of the sign vectors).
    ``dim`` is the unpacked embedding dim (pad bits beyond it cancel out of
    the ORDERING per query row, so they are left in the count)."""
    # XOR per (query, corpus-row) byte panel, popcount via table lookup.
    xor = np.bitwise_xor(qbits[:, None, :], cbits[None, :, :])  # (q, n, B)
    hamming = _POPCOUNT[xor].sum(axis=-1, dtype=np.int32)
    return (dim - 2 * hamming).astype(np.float32)


# ---------------------------------------------------------------------------
# Straight-through estimators: int8 forward on the MXU, full-precision VJP.
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
def _int8_dot_general_ste(lhs, rhs, dimension_numbers, precision,
                          preferred_element_type):
    return int8_dot_general(
        lhs, rhs, dimension_numbers, precision=precision,
        preferred_element_type=preferred_element_type,
    )


def _ste_dot_fwd(lhs, rhs, dimension_numbers, precision,
                 preferred_element_type):
    out = int8_dot_general(
        lhs, rhs, dimension_numbers, precision=precision,
        preferred_element_type=preferred_element_type,
    )
    # Residuals are the ORIGINAL operands: the backward is the gradient the
    # unquantized layer would have produced, not round()'s zero-a.e. one.
    return out, (lhs, rhs)


def _ste_dot_bwd(dimension_numbers, precision, preferred_element_type, res, g):
    lhs, rhs = res
    _, vjp = jax.vjp(
        lambda l, r: lax.dot_general(
            l, r, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type,
        ),
        lhs, rhs,
    )
    return vjp(g)


_int8_dot_general_ste.defvjp(_ste_dot_fwd, _ste_dot_bwd)


def int8_dot_general_ste(lhs, rhs, dimension_numbers, precision=None,
                         preferred_element_type=None):
    """Trainable ``lax.dot_general`` drop-in: int8 forward, unquantized VJP.

    Forward is bit-identical to :func:`int8_dot_general` (same fall-through
    for non-Dense patterns); backward is EXACTLY the ``lax.dot_general`` VJP
    on the saved full-precision operands (straight-through estimator) — the
    oracle ``tests/test_quant_train.py`` pins both sides to equality. The
    keyword wrapper exists because ``jax.custom_vjp`` takes only positional
    arguments, while flax's ``nn.Dense(dot_general=...)`` injection calls
    with ``precision=`` by keyword.
    """
    return _int8_dot_general_ste(
        lhs, rhs, dimension_numbers, precision, preferred_element_type
    )


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def int8_expert_matmul_ste(x, w, out_dtype):
    """STE twin of :func:`int8_expert_matmul` for trainable MoE experts:
    int8 batched-expert forward, backward = the unquantized einsum VJP."""
    return int8_expert_matmul(x, w, out_dtype)


def _expert_ref(x, w, out_dtype):
    # The unquantized op the STE backward differentiates — the same batched
    # dot_general int8_expert_matmul accelerates, in the model dtype.
    acc = lax.dot_general(
        x, w, (((x.ndim - 1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    return acc.astype(out_dtype)


def _ste_expert_fwd(x, w, out_dtype):
    return int8_expert_matmul(x, w, out_dtype), (x, w)


def _ste_expert_bwd(out_dtype, res, g):
    x, w = res
    _, vjp = jax.vjp(lambda xx, ww: _expert_ref(xx, ww, out_dtype), x, w)
    return vjp(g)


int8_expert_matmul_ste.defvjp(_ste_expert_fwd, _ste_expert_bwd)
