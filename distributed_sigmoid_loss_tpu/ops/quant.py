"""Dynamic int8 quantized matmul for inference — the v5e's second MXU gear.

TPU v5e executes int8×int8→int32 ``dot_general`` at 394 TOPS, exactly 2× the
bf16 peak (public spec sheet), and XLA lowers integer dots to the MXU
directly. For inference (eval/retrieval/zero-shot serving, ``train`` is NOT
the audience — see below) the towers can run their projection matmuls in int8
with dynamic symmetric quantization:

- **activations**: per-row abs-max over the contraction axis, computed on the
  fly (no calibration pass, no stored stats);
- **weights**: per-output-channel abs-max over the contraction axis.

Per-channel weight scales + per-row dynamic activation scales is the standard
PTQ recipe that keeps ViT/text-transformer quality (~1e-3 relative error per
matmul; the model-level contract is pinned in tests/test_quant.py).

The integration point is flax's ``nn.Dense(dot_general=...)`` injection —
the param tree is untouched, so ANY trained/imported checkpoint can be served
quantized by flipping ``quant="int8"`` on the tower config (utils/config.py).

NOT for training: ``round`` has zero gradient almost everywhere, so a
quantized tower trains to a standstill silently. The config guard in the
towers rejects quant + trainable contexts; there is no straight-through
estimator here (add one if QAT ever becomes a target).

No reference analogue (the reference has no model/serving layer; SURVEY.md
§2 C8 documents docs-only coverage there) — this is TPU-first scope beyond it.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["int8_dot_general", "int8_expert_matmul", "quantize_int8"]

# Symmetric int8: round-to-nearest into [-127, 127] (−128 unused, keeping the
# scale symmetric so dequant is one multiply).
_QMAX = 127.0
# Abs-max floor: an all-zero row/channel would otherwise divide by zero; any
# value below this quantizes to exact zeros with a harmless scale.
_EPS = 1e-12


def quantize_int8(x: jnp.ndarray, axis: int):
    """Symmetric int8 quantization of ``x`` along ``axis``.

    Returns ``(q, scale)`` with ``q`` int8, ``scale`` float32 keeping ``axis``
    as a size-1 dim, such that ``q * scale ≈ x``.
    """
    scale = jnp.maximum(
        jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axis, keepdims=True), _EPS
    ) / _QMAX
    q = jnp.clip(jnp.round(x.astype(jnp.float32) / scale), -_QMAX, _QMAX).astype(
        jnp.int8
    )
    return q, scale


def int8_expert_matmul(x, w, out_dtype):
    """Batched-expert int8 matmul: ``(E, ..., K) @ (E, K, M) -> (E, ..., M)``.

    The MoE layer's expert MLP einsums (``encd,edh->ench`` / ``ench,ehd->encd``,
    models/moe.py expert_apply) in dynamic int8: per-row activation scales over
    K, per-(expert, out-channel) weight scales, int32 accumulation, expert as a
    dot_general batch dim. Zero rows (unused capacity slots) quantize to exact
    zeros. The one-hot dispatch/combine einsums stay in the model dtype — they
    are <20% of the layer's FLOPs and carry the routing weights whose
    precision sets drop behavior.
    """
    e = x.shape[0]
    xq, xs = quantize_int8(x, axis=-1)          # xs (E, ..., 1)
    wq, ws = quantize_int8(w, axis=1)           # ws (E, 1, M)
    acc = lax.dot_general(
        xq, wq,
        (((x.ndim - 1,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.int32,
    )                                            # (E, ..., M)
    ws_b = ws.reshape((e,) + (1,) * (x.ndim - 2) + (w.shape[-1],))
    return (acc.astype(jnp.float32) * xs * ws_b).astype(out_dtype)


def int8_dot_general(lhs, rhs, dimension_numbers, precision=None,
                     preferred_element_type=None):
    """Drop-in ``lax.dot_general`` that runs the contraction in int8.

    Specialized to the single-contraction, no-batch-dims pattern every
    ``nn.Dense`` emits; anything else falls through to the real
    ``lax.dot_general`` unquantized (correct, just not accelerated).
    ``precision``/``preferred_element_type`` are accepted for signature
    compatibility; the int8 path fixes accumulation to int32 (the MXU's
    native accumulator — there is nothing to configure).
    """
    (lc, rc), (lb, rb) = dimension_numbers
    if lb or rb or len(lc) != 1 or len(rc) != 1:
        return lax.dot_general(
            lhs, rhs, dimension_numbers, precision=precision,
            preferred_element_type=preferred_element_type,
        )
    # Same output-dtype rule as lax.dot_general, so both branches of this
    # function (and a swap back to the real dot) are drop-in interchangeable.
    out_dtype = (
        preferred_element_type
        if preferred_element_type is not None
        else jnp.promote_types(lhs.dtype, rhs.dtype)
    )
    lq, ls = quantize_int8(lhs, lc[0])   # activations: per-row over K
    rq, rs = quantize_int8(rhs, rc[0])   # weights: per-out-channel over K
    acc = lax.dot_general(
        lq, rq, dimension_numbers, preferred_element_type=jnp.int32
    )
    # Result dims = lhs-free then rhs-free: lhs scales broadcast from the
    # left (padded with one 1 per rhs-free dim), rhs scales from the right.
    ls_free = jnp.squeeze(ls, axis=lc[0])
    rs_free = jnp.squeeze(rs, axis=rc[0])
    n_rhs_free = rhs.ndim - 1
    ls_b = ls_free.reshape(ls_free.shape + (1,) * n_rhs_free)
    return (acc.astype(jnp.float32) * ls_b * rs_free).astype(out_dtype)
