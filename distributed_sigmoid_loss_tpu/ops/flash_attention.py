"""Fused (flash) self-attention on TPU via the Pallas MXU kernel.

The towers' dense attention materializes the (b, h, s, s) logits and f32 softmax in
HBM — at ViT-B/16 scale that is the single largest activation (7G+ per step at
batch 256, see the OOM allocation report) and a pure bandwidth tax. The Pallas flash
kernel (jax.experimental.pallas.ops.tpu.flash_attention) streams K/V blocks through
VMEM with an online softmax, so nothing O(s²) ever touches HBM, and its custom VJP
recomputes blocks in the backward pass instead of storing them.

This wrapper adapts the kernel to the towers' (b, s, h, dh) layout and to sequence
lengths that aren't block-aligned (ViT-B/16 has s=196): inputs are zero-padded to a
block multiple and masked via segment ids (pad tokens get a different segment id, so
real queries never attend them; padded query rows are sliced off afterwards).

There is no reference analogue (the reference has no model layer — SURVEY.md §1); this
is TPU-first engineering for the BASELINE.json end-to-end throughput target.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["flash_self_attention", "flash_attention_available"]

# The kernel's minor-most compute tile: sequence blocks must be multiples of this to
# satisfy the (8, 128) f32 / (16, 128) bf16 TPU tiling on the logits' lane dim.
_SEQ_MULTIPLE = 128


def flash_attention_available() -> bool:
    """True when the current default backend can run the Pallas TPU kernel."""
    return jax.default_backend() == "tpu"


def _pad_len(s: int) -> int:
    return (s + _SEQ_MULTIPLE - 1) // _SEQ_MULTIPLE * _SEQ_MULTIPLE


def _block_size(s_pad: int) -> int:
    """Largest power-of-two block ≤512 dividing the padded length — the kernel
    requires divisibility in BOTH grid directions (backward also blocks q)."""
    return next(b for b in (512, 256, 128) if s_pad % b == 0)


def _prepare_inputs(q, k, v):
    """Transpose to the kernel's (b, h, s, dh) layout, zero-pad the sequence to a
    block multiple, and build pad-masking segment ids.

    Returns ``(qt, kt, vt, segment_id_rows, s_pad)`` where ``segment_id_rows`` is the
    per-position (b, s_pad) int32 id array (1 = real token, 0 = padding) or ``None``
    when no padding was needed. Real queries never attend padding (different segment);
    padded query rows attend only padding (finite softmax) and are sliced off after
    the kernel.
    """
    b, s, h, dh = q.shape
    s_pad = _pad_len(s)

    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    ids = None
    if s_pad != s:
        pad = ((0, 0), (0, 0), (0, s_pad - s), (0, 0))
        qt, kt, vt = (jnp.pad(t, pad) for t in (qt, kt, vt))
        ids = (jnp.arange(s_pad, dtype=jnp.int32) < s).astype(jnp.int32)
        ids = jnp.broadcast_to(ids[None], (b, s_pad))
    return qt, kt, vt, ids, s_pad


def flash_self_attention(
    q, k, v, *, causal: bool = False, scale: float | None = None, kernel_fn=None
):
    """Drop-in replacement for ``dense_attention``: (b, s, h, dh) → (b, s, h, dh).

    Self-attention only (q/k/v share a sequence length). Numerics match the dense
    path (f32 online softmax) up to flash's blockwise summation order.

    ``kernel_fn(qt, kt, vt, segment_ids, causal, sm_scale, block_sizes)`` overrides
    the Pallas kernel — used by CPU tests to verify the padding/masking/slicing
    plumbing with a dense stand-in kernel.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention,
    )

    b, s, h, dh = q.shape
    sm_scale = (dh**-0.5) if scale is None else scale
    qt, kt, vt, ids, s_pad = _prepare_inputs(q, k, v)
    segment_ids = SegmentIds(q=ids, kv=ids) if ids is not None else None

    block = _block_size(s_pad)
    block_sizes = BlockSizes(
        block_q=block,
        block_k_major=block,
        block_k=block,
        block_b=1,
        block_q_major_dkv=block,
        block_k_major_dkv=block,
        block_k_dkv=block,
        block_q_dkv=block,
        block_k_major_dq=block,
        block_k_dq=block,
        block_q_dq=block,
    )
    kernel = kernel_fn if kernel_fn is not None else flash_attention
    out = kernel(
        qt,
        kt,
        vt,
        segment_ids=segment_ids,
        causal=causal,
        sm_scale=sm_scale,
        block_sizes=block_sizes,
    )
    return jnp.transpose(out[:, :, :s, :], (0, 2, 1, 3))
