"""Fused (flash) self-attention on TPU via the Pallas MXU kernel.

The towers' dense attention materializes the (b, h, s, s) logits and f32 softmax in
HBM — at ViT-B/16 scale that is the single largest activation (7G+ per step at
batch 256, see the OOM allocation report) and a pure bandwidth tax. The Pallas flash
kernel (jax.experimental.pallas.ops.tpu.flash_attention) streams K/V blocks through
VMEM with an online softmax, so nothing O(s²) ever touches HBM, and its custom VJP
recomputes blocks in the backward pass instead of storing them.

This wrapper adapts the kernel to the towers' (b, s, h, dh) layout and to sequence
lengths that aren't block-aligned (ViT-B/16 has s=196): inputs are zero-padded to a
block multiple and masked via segment ids (pad tokens get a different segment id, so
real queries never attend them; padded query rows are sliced off afterwards).

There is no reference analogue (the reference has no model layer — SURVEY.md §1); this
is TPU-first engineering for the BASELINE.json end-to-end throughput target.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

__all__ = ["flash_self_attention", "flash_attention_available"]

# The kernel's minor-most compute tile: sequence blocks must be multiples of this to
# satisfy the (8, 128) f32 / (16, 128) bf16 TPU tiling on the logits' lane dim.
_SEQ_MULTIPLE = 128


def flash_attention_available() -> bool:
    """True when the current default backend can run the Pallas TPU kernel."""
    return jax.default_backend() == "tpu"


def _pad_len(s: int) -> int:
    return (s + _SEQ_MULTIPLE - 1) // _SEQ_MULTIPLE * _SEQ_MULTIPLE


@functools.partial(jax.jit, static_argnames=("causal", "scale"))
def flash_self_attention(q, k, v, *, causal: bool = False, scale: float | None = None):
    """Drop-in replacement for ``dense_attention``: (b, s, h, dh) → (b, s, h, dh).

    Self-attention only (q/k/v share a sequence length). Numerics match the dense
    path (f32 online softmax) up to flash's blockwise summation order.
    """
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        BlockSizes,
        SegmentIds,
        flash_attention,
    )

    b, s, h, dh = q.shape
    scale = (dh**-0.5) if scale is None else scale
    s_pad = _pad_len(s)

    # Kernel layout is (b, h, s, dh).
    qt = jnp.transpose(q, (0, 2, 1, 3))
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))

    segment_ids = None
    if s_pad != s:
        pad = ((0, 0), (0, 0), (0, s_pad - s), (0, 0))
        qt, kt, vt = (jnp.pad(t, pad) for t in (qt, kt, vt))
        # Real tokens get segment id 1, padding id 0: real queries never attend
        # padding; padded queries attend only padding (finite softmax, rows are
        # sliced off below).
        ids = (jnp.arange(s_pad, dtype=jnp.int32) < s).astype(jnp.int32)
        ids = jnp.broadcast_to(ids[None], (b, s_pad))
        segment_ids = SegmentIds(q=ids, kv=ids)

    # The kernel requires the sequence length to be divisible by the block size
    # (both directions — backward also blocks the q dim), so pick the largest
    # power-of-two block ≤512 that divides the padded length.
    block = next(b for b in (512, 256, 128) if s_pad % b == 0)
    block_sizes = BlockSizes(
        block_q=block,
        block_k_major=block,
        block_k=block,
        block_b=1,
        block_q_major_dkv=block,
        block_k_major_dkv=block,
        block_k_dkv=block,
        block_q_dkv=block,
        block_k_major_dq=block,
        block_k_dq=block,
        block_q_dq=block,
    )
    out = flash_attention(
        qt,
        kt,
        vt,
        segment_ids=segment_ids,
        causal=causal,
        sm_scale=scale,
        block_sizes=block_sizes,
    )
    return jnp.transpose(out[:, :, :s, :], (0, 2, 1, 3))
