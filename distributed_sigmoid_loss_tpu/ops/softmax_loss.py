"""Softmax (InfoNCE / CLIP) contrastive loss — the second loss family.

The reference repo ships sigmoid losses only, but exists as an alternative to
open_clip's softmax ``ClipLoss`` (its ``SigLipLoss`` is a PR against that file;
rwightman_sigmoid_loss.py:1-10 cites it). A framework replacing the reference
should offer both families over the same distributed machinery, so users can
A/B the losses without changing the comm layer:

- this module: the single-device mathematics — symmetric cross-entropy over
  the (b, b) similarity matrix, ``loss = (CE_rows + CE_cols) / 2``, with the
  CLIP-standard learnable temperature ``t_prime`` (init ``log(1/0.07)``, no
  bias).
- :mod:`distributed_sigmoid_loss_tpu.parallel.contrastive`: the all-gather and
  ring (online-logsumexp streaming) distributed variants.

Unlike the sigmoid loss, softmax rows are NOT independent of the global batch:
each row needs a logsumexp over every negative, which is what makes the
distributed variants interesting (the ring variant streams blocks and keeps a
running (max, sumexp) pair — the ring-attention recurrence applied to a loss).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "init_clip_loss_params",
    "softmax_contrastive_loss",
]


def init_clip_loss_params(dtype=jnp.float32) -> dict:
    """CLIP's learnable temperature: ``t_prime = log(1/0.07)`` (logit scale
    ``exp(t_prime) ≈ 14.3``), no bias — the open_clip ``ClipLoss`` contract."""
    return {"t_prime": jnp.asarray(math.log(1.0 / 0.07), dtype=dtype)}


def softmax_contrastive_loss(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    *,
    precision=lax.Precision.HIGHEST,
) -> jax.Array:
    """Symmetric InfoNCE over L2-normalized embeddings (single device).

    ``logits = exp(t_prime) * zimg @ ztxt.T``; positives on the diagonal;
    ``loss = (mean CE(rows) + mean CE(columns)) / 2``.
    """
    logits = jnp.exp(t_prime) * jnp.dot(zimg, ztxt.T, precision=precision)
    diag = jnp.diagonal(logits)
    i2t = jax.nn.logsumexp(logits, axis=1) - diag
    t2i = jax.nn.logsumexp(logits, axis=0) - diag
    return (jnp.mean(i2t) + jnp.mean(t2i)) / 2
