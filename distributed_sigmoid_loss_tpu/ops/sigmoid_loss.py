"""Core SigLIP sigmoid loss as pure JAX functions (single-device Algorithm 1).

Implements the mathematics of the SigLIP paper (https://arxiv.org/abs/2303.15343,
Algorithm 1) matching the behavior of the reference implementation:

- loss parameters: learnable ``t_prime`` (init ``log 10``) and ``bias`` (init ``-10.0``)
  — reference /root/reference/distributed_sigmoid_loss.py:11-12.
- per-block math: ``logits = zimg @ ztxt.T * exp(t_prime) + bias``; labels are
  ``2*I - 1`` for the positive (same-shard) block and ``-1`` elsewhere; per-element loss
  is ``-log_sigmoid(labels * logits)`` — reference distributed_sigmoid_loss.py:22-33 and
  rwightman_sigmoid_loss.py:43-66.
- normalization: the summed loss is divided by the *local* batch size — reference
  distributed_sigmoid_loss.py:47 (global-mean semantics arise after DP grad averaging).

Everything here is shape-static, jit-friendly, and device-free: the distributed variants
in :mod:`distributed_sigmoid_loss_tpu.parallel` call these block functions inside
``shard_map`` and stitch shards together with XLA collectives.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

__all__ = [
    "init_loss_params",
    "pairwise_logits",
    "sigmoid_xent",
    "sigmoid_loss_block",
    "sigmoid_loss_chunk_scan",
    "sigmoid_loss",
    "l2_normalize",
]


def init_loss_params(dtype=jnp.float32) -> dict:
    """Learnable loss parameters with the reference inits.

    ``t_prime = log(10)`` and ``bias = -10.0``
    (reference distributed_sigmoid_loss.py:11-12; the paper's Algorithm 1 uses the same
    values). Stored as a plain dict pytree so they ride any optax optimizer alongside the
    tower params — the reference README (README.md:20) requires users to hand these to
    the optimizer explicitly; in JAX they are just leaves of the param pytree.
    """
    return {
        "t_prime": jnp.asarray(math.log(10.0), dtype=dtype),
        "bias": jnp.asarray(-10.0, dtype=dtype),
    }


def pairwise_logits(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    bias: jax.Array,
    *,
    precision=jax.lax.Precision.HIGHEST,
) -> jax.Array:
    """``exp(t_prime) * zimg @ ztxt.T + bias`` — the (n_img, n_txt) pairwise logit block.

    Reference: distributed_sigmoid_loss.py:23-24 / rwightman_sigmoid_loss.py:49-53.
    The matmul is the hot MXU op; ``precision`` defaults to HIGHEST (fp32 accumulation)
    for the rtol<1e-4 parity gate and can be relaxed to DEFAULT (bf16) for throughput.
    """
    t = jnp.exp(t_prime)
    logits = jnp.matmul(zimg, ztxt.T, precision=precision)
    return logits * t + bias


def sigmoid_xent(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Per-element sigmoid cross-entropy ``-log_sigmoid(labels * logits)``.

    Reference: distributed_sigmoid_loss.py:32 / rwightman_sigmoid_loss.py:65.
    """
    return -jax.nn.log_sigmoid(labels * logits)


def _block_labels(n_img: int, n_txt: int, positive_diagonal: bool, dtype) -> jax.Array:
    """Label block: all ``-1``; ``+1`` on the diagonal when this is the positive block.

    Reference: distributed_sigmoid_loss.py:26-30 (note the reference builds
    ``2*eye(b) - ones(b)`` with a broadcast 1-D row of ones — numerically identical to
    the full ``2I - 1`` matrix) and rwightman_sigmoid_loss.py:43-47.
    """
    labels = jnp.full((n_img, n_txt), -1.0, dtype=dtype)
    if positive_diagonal:
        eye = jnp.eye(n_img, n_txt, dtype=dtype)
        labels = labels + 2.0 * eye
    return labels


def sigmoid_loss_block(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    bias: jax.Array,
    *,
    negative_only: bool = False,
    precision=jax.lax.Precision.HIGHEST,
) -> jax.Array:
    """Summed loss over one (local_imgs × txt_chunk) block, normalized by local batch.

    This is the building block both distributed variants share: the all-gather variant
    sums one block per world-size chunk (reference distributed_sigmoid_loss.py:41-47),
    the ring variant sums one positive block plus ``W-1`` negative-only blocks as text
    shards ride the ring (reference rwightman_sigmoid_loss.py:55-66, ``_loss``).

    ``negative_only=True`` means every label is ``-1`` (an off-shard negatives block);
    otherwise the diagonal carries the positive pairs.
    """
    logits = pairwise_logits(zimg, ztxt, t_prime, bias, precision=precision)
    labels = _block_labels(
        zimg.shape[0], ztxt.shape[0], not negative_only, logits.dtype
    )
    return sigmoid_xent(logits, labels).sum() / zimg.shape[0]


def sigmoid_loss_chunk_scan(
    zimg: jax.Array,
    txt_chunks: jax.Array,
    t_prime: jax.Array,
    bias: jax.Array,
    *,
    positive_chunk: jax.Array,
    precision=jax.lax.Precision.HIGHEST,
    use_pallas: bool = False,
    quant: str = "",
) -> jax.Array:
    """Streamed-negatives loss: ``lax.scan`` over stacked text chunk-blocks.

    Mathematically :func:`sigmoid_loss_block` summed over the chunks of
    ``txt_chunks`` (shape ``(num_chunks, chunk_b, d)``), with the positive
    diagonal on chunk ``positive_chunk`` (traced or static — the all-gather
    variant passes ``lax.axis_index``) — but only ONE ``(n_img, chunk_b)``
    logits block is ever live: the scan body is ``jax.checkpoint``'d, so the
    backward pass recomputes each block's logits from the (already resident)
    embeddings instead of saving per-iteration residuals. Peak loss memory
    drops ~num_chunks× against the fused single-matmul path; the price is one
    extra block matmul per chunk in the backward.

    The chunk sums accumulate in f32 regardless of the embedding dtype (the
    fused path's big-block reduce is f32-accumulated on the MXU for the same
    reason); per-block values still carry the input dtype's rounding, so bf16
    parity vs the fused path holds at bf16 grade, f32 parity at rtol 1e-5.
    Returns the summed xent over all chunks, divided by ``n_img`` — the same
    local-batch normalization as :func:`sigmoid_loss_block`.

    ``use_pallas=True`` makes the streaming 2-D Pallas kernel the chunk-block
    body (per-block logits→softplus→reduce stays on-chip; its custom VJP
    recomputes tiles, so the checkpoint'd backward never materializes even
    one block's logits), with ``quant="int8"`` routing each block product
    through the int8 MXU path. Shapes that fail the kernel's tiling
    constraints fall back to the XLA block — the fallback is RECORDED
    (ops.pallas_sigmoid_loss.traced_loss_kernels) so a bench record can
    never silently claim kernel engagement.
    """
    n_img = zimg.shape[0]
    num_chunks = txt_chunks.shape[0]

    def body(acc, inputs):
        k, chunk = inputs
        if use_pallas:
            from distributed_sigmoid_loss_tpu.ops.pallas_sigmoid_loss import (
                NEGATIVE_ONLY_OFFSET,
                streaming_block_loss_or_none,
            )

            # The positive diagonal lives on chunk `positive_chunk` (traced):
            # offset 0 there, the never-matching sentinel elsewhere.
            off = jnp.where(
                k == positive_chunk, 0.0, float(NEGATIVE_ONLY_OFFSET)
            ).astype(jnp.float32)
            total = streaming_block_loss_or_none(
                zimg, chunk, t_prime, bias, off, quant=quant, normalize=False
            )
            if total is not None:  # static: same shapes every chunk
                return acc + total.astype(jnp.float32), None
        logits = pairwise_logits(zimg, chunk, t_prime, bias, precision=precision)
        rows = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape, 1)
        positive = (k == positive_chunk) & (rows == cols)
        labels = jnp.where(positive, 1.0, -1.0).astype(logits.dtype)
        return acc + sigmoid_xent(logits, labels).sum().astype(jnp.float32), None

    acc, _ = jax.lax.scan(
        jax.checkpoint(body),
        jnp.zeros((), jnp.float32),
        (jnp.arange(num_chunks), txt_chunks),
    )
    return acc / n_img


def sigmoid_loss(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    bias: jax.Array,
    *,
    precision=jax.lax.Precision.HIGHEST,
) -> jax.Array:
    """Single-device SigLIP sigmoid loss — the paper's Algorithm 1.

    Equals the reference ``DDPSigmoidLoss`` at world_size=1 (one chunk,
    ``same_device=True``, distributed_sigmoid_loss.py:41-47): mean-per-image summed
    sigmoid cross-entropy with positives on the diagonal.

    Inputs are assumed L2-normalized (the reference normalizes *outside* the loss,
    test_distributed_sigmoid_loss.py:96-101 and README.md release note of 25 Sep 2023).
    """
    return sigmoid_loss_block(
        zimg, ztxt, t_prime, bias, negative_only=False, precision=precision
    )


def l2_normalize(x: jax.Array, axis: int = -1, eps: float = 1e-12) -> jax.Array:
    """L2-normalize along ``axis`` — matches ``torch.nn.functional.normalize`` defaults
    (p=2, eps=1e-12, clamped norm) used by the reference harness
    (test_distributed_sigmoid_loss.py:100-101)."""
    norm = jnp.linalg.norm(x, ord=2, axis=axis, keepdims=True)
    return x / jnp.maximum(norm, eps)
