"""Training health watchdog + flight recorder — graftscope's black box.

The in-step scalars (``grad_norm`` / ``param_norm`` / ``update_ratio``,
train/train_step.py) are cheap device-side reductions; this module is the
HOST side that watches them: non-finite detection over every scalar on the
metrics line, loss-spike detection against a rolling median, structured
events instead of buried stderr prints, and a ring-buffered flight recorder
that dumps the last N metrics lines + events when the run dies (crash or
SIGTERM through the ``train/resilience.py`` preemption path) — so a 3am
divergence leaves its trajectory behind, not just a final traceback.

Policy is the caller's: :class:`HealthWatchdog` only DETECTS and reports.
``policy="skip"`` marks skippable events so the train loop can route them
into ``train_resilient``'s existing rollback-and-skip machinery (the one
place a poisoned update can actually be undone — the jitted step donates its
input state, so the host cannot "keep the old state" after the fact).
"""

from __future__ import annotations

import json
import math
import sys
import time
from collections import deque
from dataclasses import dataclass, field

__all__ = ["HealthEvent", "HealthWatchdog", "FlightRecorder"]


@dataclass(frozen=True)
class HealthEvent:
    """One structured watchdog event."""

    step: int
    event: str  # "non_finite" | "loss_spike"
    detail: str
    skippable: bool = False

    def record(self) -> dict:
        """The JSON-lines form (emitted through MetricsLogger.write)."""
        return {
            "metric": "health_event",
            "step": self.step,
            "event": self.event,
            "detail": self.detail,
        }


class HealthWatchdog:
    """Host-side anomaly detection over train metrics lines.

    ``observe(step, metrics)`` returns the (possibly empty) list of events:

    - ``non_finite``: any scalar on the line is NaN/Inf. Always skippable —
      a non-finite loss/grad-norm means the update is poison.
    - ``loss_spike``: loss exceeds ``spike_factor ×`` the rolling median of
      the last ``window`` FINITE losses (armed only once ``min_history``
      samples exist, so warmup noise never trips it). Skippable only under
      ``policy="skip"`` with ``skip_on_spike=True`` — a spike is suspicious,
      a rollback is a judgment call; default is to report, not intervene.

    Cheap by construction: one deque append + a sorted-median over a bounded
    window, only on lines whose loss is finite.
    """

    def __init__(
        self,
        window: int = 64,
        min_history: int = 8,
        spike_factor: float = 4.0,
        policy: str = "warn",  # "warn" | "skip"
        skip_on_spike: bool = False,
    ):
        if policy not in ("warn", "skip"):
            raise ValueError(f"policy must be 'warn' or 'skip', got {policy!r}")
        if spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must be > 1, got {spike_factor} (a factor "
                "<= 1 would flag ordinary fluctuation as a spike)"
            )
        self.window = window
        self.min_history = max(2, min_history)
        self.spike_factor = spike_factor
        self.policy = policy
        self.skip_on_spike = skip_on_spike
        self._losses: deque[float] = deque(maxlen=window)
        self.events: list[HealthEvent] = []

    def observe(self, step: int, metrics: dict) -> list[HealthEvent]:
        out: list[HealthEvent] = []
        bad = []
        for k, v in metrics.items():
            try:
                fv = float(v)
            except (TypeError, ValueError):
                continue
            if not math.isfinite(fv):
                bad.append(k)
        if bad:
            out.append(HealthEvent(
                step, "non_finite",
                f"non-finite metric(s) {bad} — poisoned batch, overflow, or "
                "a flaky interconnect; the update is not trustworthy",
                skippable=self.policy == "skip",
            ))
        loss = metrics.get("loss")
        if loss is not None and not bad:
            fl = float(loss)
            if len(self._losses) >= self.min_history:
                ordered = sorted(self._losses)
                median = ordered[len(ordered) // 2]
                # abs(): the sigmoid loss is positive, but a softmax/debug
                # objective near zero must not divide the factor away.
                if abs(fl) > self.spike_factor * max(abs(median), 1e-12):
                    out.append(HealthEvent(
                        step, "loss_spike",
                        f"loss {fl:.6g} is >{self.spike_factor}x the rolling "
                        f"median {median:.6g} over the last "
                        f"{len(self._losses)} steps",
                        skippable=self.policy == "skip" and self.skip_on_spike,
                    ))
            self._losses.append(fl)
        self.events.extend(out)
        return out

    def should_skip(self, events: list[HealthEvent]) -> bool:
        return any(e.skippable for e in events)


class FlightRecorder:
    """Ring buffer of the last N metrics lines + health events, dumped on
    crash/preemption.

    ``note_metrics`` / ``note_event`` are O(1) deque appends (bounded — a
    week-long run holds exactly ``capacity`` lines). ``dump`` writes ONE
    JSON document with the retained trajectory and the dump reason; it is
    idempotent-safe to call from both an except-path and a finally-path
    (every call writes, callers decide where). Wired through
    ``train_resilient(flight=...)``: divergence raise, loop crash, and the
    SIGTERM preemption stop all dump before control leaves the loop.
    """

    def __init__(self, capacity: int = 256, path: str | None = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.path = path  # default dump target (None -> one stderr line)
        self._metrics: deque[dict] = deque(maxlen=capacity)
        self._events: deque[dict] = deque(maxlen=capacity)
        self.dumps = 0

    def note_metrics(self, step: int, metrics: dict) -> None:
        line = {"step": int(step)}
        for k, v in metrics.items():
            try:
                line[k] = float(v)
            except (TypeError, ValueError):
                line[k] = str(v)
        self._metrics.append(line)

    def note_event(self, event: HealthEvent) -> None:
        self._events.append(event.record())

    def snapshot(self, reason: str) -> dict:
        return {
            "flight_recorder": {
                "reason": reason,
                "wall_time": time.time(),
                "capacity": self.capacity,
                "metrics": list(self._metrics),
                "events": list(self._events),
            }
        }

    def dump(self, reason: str, path: str | None = None, stream=None) -> dict:
        """Write the snapshot to ``path`` (one JSON file; defaults to the
        constructor's ``path``) or ``stream`` (default stderr, one JSON
        line). Returns the snapshot dict."""
        snap = self.snapshot(reason)
        self.dumps += 1
        if path is None and stream is None:
            path = self.path
        if path is not None:
            with open(path, "w", encoding="utf-8") as f:
                json.dump(snap, f, indent=1)
        else:
            print(json.dumps(snap), file=stream or sys.stderr, flush=True)
        return snap
