"""Host-side tracing spans: the host half of graftscope's unified timeline.

``utils.profiling.trace`` captures what the DEVICE did (XLA op spans with
``hlo_category`` / ``model_flops`` annotations); nothing captured what the
HOST did around it — where a step interval went between fetch, h2d commit,
dispatch, eval and checkpoint, or where a serve request sat between queue,
batch assembly and the engine call. :class:`SpanRecorder` fills that half:

- **Thread-safe, ring-buffered**: producers append under a lock into a
  ``deque(maxlen=capacity)`` — a long-lived trainer or service never grows its
  tracing state, the newest ``capacity`` spans win (the flight-recorder
  convention, not the profiler's grow-forever one).
- **Near-zero overhead when disabled**: ``span()`` on a disabled recorder
  returns one preallocated no-op context manager — no object allocation, no
  clock read, no lock. The hot train/serve loops stay instrumented
  unconditionally and pay only an attribute check until someone turns
  recording on (pinned by the bounded-overhead test in tests/test_obs.py).
- **Chrome-trace JSON export**: ``chrome_trace()`` emits the same
  ``traceEvents`` format the device profiler writes, with a distinct pid, so
  the host timeline OVERLAYS the device capture in ui.perfetto.dev — and
  ``obs summarize`` (cli.py) merges both into one offline report.

Nesting needs no explicit tracking: spans carry (tid, ts, dur) and the
Chrome trace model nests same-thread spans by containment, exactly like the
device capture's own tracks.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Iterable

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = [
    "Span",
    "SpanRecorder",
    "summarize_spans",
    "merge_chrome_traces",
]

# One pid for every host span so perfetto groups them as a single "process"
# track alongside the device processes from utils.profiling.trace.
HOST_PID = 1_000_001


@dataclass(frozen=True)
class Span:
    """One completed host span. Times are ``time.perf_counter()`` seconds."""

    name: str
    t0: float
    t1: float
    tid: int

    @property
    def duration_s(self) -> float:
        return self.t1 - self.t0


class _NoopSpan:
    """Reusable disabled-path context manager: no state, so one instance
    serves every call site and thread concurrently — the disabled hot path
    allocates nothing (the property tests/test_obs.py asserts by identity)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP = _NoopSpan()


class _LiveSpan:
    """Enabled-path context manager: records into its recorder on exit."""

    __slots__ = ("_rec", "_name", "_t0")

    def __init__(self, rec: "SpanRecorder", name: str):
        self._rec = rec
        self._name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._rec.record(self._name, self._t0, time.perf_counter())
        return False


class SpanRecorder:
    """Ring-buffered recorder of nested host spans.

    ``with rec.span("step"): ...`` on the caller's thread; ``record(name,
    t0, t1)`` for spans whose start and end are observed on different control
    paths (the serve batcher's queue-wait: enqueue happens on the client
    thread, the batch flush on the worker). ``enabled=False`` (or
    ``disable()``) turns every ``span()`` into the shared no-op.
    """

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.enabled = enabled
        self._spans: deque[Span] = deque(maxlen=capacity)
        self._lock = named_lock("obs.spans.SpanRecorder._lock")
        self.dropped = 0  # spans evicted by the ring (total ever)

    # -- recording -----------------------------------------------------------

    def span(self, name: str):
        """Context manager timing the enclosed block (no-op when disabled)."""
        if not self.enabled:
            return _NOOP
        return _LiveSpan(self, name)

    def record(self, name: str, t0: float, t1: float, tid: int | None = None) -> None:
        """Record one completed span (cross-thread span API)."""
        if not self.enabled:
            return
        s = Span(name, t0, t1, threading.get_ident() if tid is None else tid)
        with self._lock:
            if len(self._spans) == self._spans.maxlen:
                self.dropped += 1
            self._spans.append(s)

    def disable(self) -> None:
        self.enabled = False

    def enable(self) -> None:
        self.enabled = True

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    # -- export --------------------------------------------------------------

    def spans(self) -> list[Span]:
        with self._lock:
            return list(self._spans)

    def chrome_trace(self, label: str = "host") -> dict:
        """``{"traceEvents": [...]}`` — the Perfetto/Chrome format the device
        profiler writes, so this file overlays a ``utils.profiling.trace``
        capture directly. Timestamps are perf_counter microseconds (a shared
        monotonic base across every recorder in the process)."""
        events: list[dict] = [
            {
                "ph": "M",
                "name": "process_name",
                "pid": HOST_PID,
                "args": {"name": f"python-{label}"},
            }
        ]
        tids = {}
        for s in self.spans():
            if s.tid not in tids:
                tids[s.tid] = len(tids)
                events.append({
                    "ph": "M",
                    "name": "thread_name",
                    "pid": HOST_PID,
                    "tid": tids[s.tid],
                    "args": {"name": f"{label}-thread-{tids[s.tid]}"},
                })
            events.append({
                "ph": "X",
                "name": s.name,
                "pid": HOST_PID,
                "tid": tids[s.tid],
                "ts": s.t0 * 1e6,
                "dur": (s.t1 - s.t0) * 1e6,
            })
        return {"traceEvents": events}

    def export(self, path: str, label: str = "host") -> None:
        """Write :meth:`chrome_trace` as JSON to ``path``."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.chrome_trace(label), f)


def summarize_spans(spans: Iterable[Span]) -> dict[str, dict]:
    """Per-name aggregation: ``{name: {count, total_ms, mean_ms, p50_ms,
    p95_ms, max_ms}}`` sorted by total time descending. The host half of the
    ``obs summarize`` report."""
    by_name: dict[str, list[float]] = {}
    for s in spans:
        by_name.setdefault(s.name, []).append(s.duration_s * 1000.0)
    out = {}
    for name, ds in sorted(
        by_name.items(), key=lambda kv: -sum(kv[1])
    ):
        ds.sort()
        n = len(ds)

        def rank(p):  # nearest-rank (the LatencyWindow convention)
            import math

            return ds[max(0, math.ceil(p / 100.0 * n) - 1)]

        out[name] = {
            "count": n,
            "total_ms": round(sum(ds), 3),
            "mean_ms": round(sum(ds) / n, 3),
            "p50_ms": round(rank(50), 3),
            "p95_ms": round(rank(95), 3),
            "max_ms": round(ds[-1], 3),
        }
    return out


def merge_chrome_traces(host_trace: dict, device_events: Iterable[list]) -> dict:
    """One combined ``traceEvents`` stream: host spans + every device event
    list (as yielded by ``utils.profiling._read_trace_files``). Device and
    host events keep their own pids, so perfetto shows them as separate
    processes on one shared timeline."""
    merged = list(host_trace.get("traceEvents", []))
    for events in device_events:
        merged.extend(events)
    return {"traceEvents": merged}
