"""Chip-free perf regression gates: proxy metrics vs committed baselines.

The perf contracts this repo ships — chunked scan 0.25x the fused temp bytes
(PR 3), streaming-fused kernel 0.32x (PR 7), ring == ring-overlap wire
traffic (bitwise accumulation contract, PR 3) — are all *program* properties:
they are visible in compiled temp bytes, closed-form FLOPs, and per-kind
collective wire bytes WITHOUT a TPU in the loop. ``obs regress`` turns them
into a CI gate on CPU:

- **Step-config lattice** (trace-only, seconds): every config in graftlint's
  sampled step-config product (``analysis/jaxpr_audit.step_config_jaxprs``,
  drawn from the ``analysis/config_space`` solver's legal product — the
  fifteen legacy configs plus the coverage extras)
  gets its ``obs/attribution`` proxies — closed-form FLOPs, per-kind
  collective wire bytes, and the roofline ``mfu_est`` ceiling — compared
  against the committed baseline with noise-aware tolerances (closed-form
  counts are deterministic: 1%; ``mfu_est`` is a rounded ratio: +-0.02
  absolute).
- **Loss-island temp bytes** (four small compiles): fused / chunked /
  streaming-fused / streaming-chunked loss islands at a fixed W=8 shape,
  XLA's own ``memory_analysis`` accounting. Values compare against the
  baseline at 10% (allocator packing noise); the RATIO contracts additionally
  hold unconditionally — a removed ``jax.checkpoint`` in the chunked scan
  inflates its temp bytes ~W-fold and fails the gate with the offending
  metric named, no chip required.
- **Structural contracts** (self-relative, no baseline needed): chunked and
  streaming-fused temp < 0.5x fused; streaming-chunked <= 1.1x chunked;
  ring and ring-overlap wire bytes EXACTLY equal per real collective kind
  (all_gather / ppermute / psum / psum_scatter).

Baselines are generated deterministically on the 8-virtual-device CPU mesh
(``obs regress --update``) and committed as ``obs/regress_baseline.json``.
A jax-version mismatch between the baseline and the running environment
downgrades the *absolute* temp-byte comparisons to warnings (XLA's packing
shifts across releases) while the closed-form proxies and the self-relative
ratio contracts stay enforced — they are version-stable by construction.
"""

from __future__ import annotations

import json
import os
import sys

from distributed_sigmoid_loss_tpu.analysis.findings import Finding

__all__ = [
    "BASELINE_PATH",
    "PROXY_METRICS",
    "collect_proxies",
    "compare_proxies",
    "contract_findings",
    "run_regress",
]

BASELINE_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "regress_baseline.json"
)

# The per-config proxies the lattice gate compares, with their tolerance
# model: ("rel", f) = relative drift bound, ("abs", f) = absolute bound.
# Closed-form counts are deterministic — the 1% is slack for benign jaxpr
# reshuffles, not measurement noise.
PROXY_METRICS = {
    "flops_est": ("rel", 0.01),
    "comm_bytes_total": ("rel", 0.01),
    "comm_bytes_all_gather": ("rel", 0.01),
    "comm_bytes_ppermute": ("rel", 0.01),
    "comm_bytes_psum": ("rel", 0.01),
    "comm_bytes_psum_scatter": ("rel", 0.01),
    "comm_bytes_all_to_all": ("rel", 0.01),
    "mfu_est": ("abs", 0.02),
}

# Compiled loss-island temp bytes: deterministic for a fixed XLA, but the
# allocator's packing shifts across releases — hence the looser band and the
# version-mismatch downgrade in compare_proxies.
ISLAND_TOLERANCE = 0.10

# The W=8 island shape: d=128 keeps the streaming Pallas kernel engaged
# (lane-aligned d, local_b % 8 == 0) so the pallas islands measure the real
# kernel, not its XLA fallback; local_b=512 is the PR 7 acceptance shape —
# large enough that BLOCK sizes (not fixed per-call buffers) dominate the
# temp accounting, so the streamed/chunked ratios actually show.
ISLAND_LOCAL_B = 512
ISLAND_D = 128

ISLAND_CONFIGS = {
    "fused": {},
    "chunked": {"loss_impl": "chunked"},
    "streaming_fused": {"use_pallas": True},
    "streaming_chunked": {"loss_impl": "chunked", "use_pallas": True},
}


def collect_step_proxies(n_devices: int | None = None) -> dict:
    """label -> proxy dict for the full jaxpr-audit config lattice
    (trace-only; needs an even mesh of >= 4 devices)."""
    from distributed_sigmoid_loss_tpu.analysis.jaxpr_audit import (
        step_config_jaxprs,
    )
    from distributed_sigmoid_loss_tpu.obs.attribution import (
        jaxpr_costs,
        roofline_estimate,
    )

    out = {}
    for label, (closed, _kwargs) in step_config_jaxprs(n_devices).items():
        costs = jaxpr_costs(closed)
        est = roofline_estimate(
            costs["flops_est"], costs["comm_bytes_total"]
        )
        proxies = {k: round(float(costs[k]), 1) for k in costs
                   if k in PROXY_METRICS}
        proxies["mfu_est"] = est["mfu_est"]
        out[label] = proxies
    return out


def collect_island_temp_bytes(n_devices: int | None = None) -> dict:
    """label -> {temp_bytes, peak_bytes} for the four loss islands at the
    fixed W-island shape (W = min(8, devices)). Four small CPU compiles."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from distributed_sigmoid_loss_tpu.ops.sigmoid_loss import (
        init_loss_params,
        l2_normalize,
    )
    from distributed_sigmoid_loss_tpu.parallel import (
        make_mesh,
        make_sharded_loss_fn,
    )
    from distributed_sigmoid_loss_tpu.utils.profiling import (
        compiled_memory_stats,
    )

    w = min(8, n_devices or len(jax.devices()))
    mesh = make_mesh(w)
    rng = np.random.default_rng(0)
    zi = l2_normalize(jnp.asarray(
        rng.standard_normal((w * ISLAND_LOCAL_B, ISLAND_D)), jnp.float32))
    zt = l2_normalize(jnp.asarray(
        rng.standard_normal((w * ISLAND_LOCAL_B, ISLAND_D)), jnp.float32))
    params = init_loss_params()

    out = {}
    for label, kw in ISLAND_CONFIGS.items():
        fn = make_sharded_loss_fn(mesh, variant="all_gather", jit=False, **kw)
        # Grad through the JITTED fn — the 0.4.x eager shard_map transpose
        # can't type the scan carry / pallas residuals (the train step jits
        # the loss island for the same reason).
        jfn = jax.jit(fn)

        def value_and_grads(p, a, b, _f=jfn):
            return jax.value_and_grad(_f, argnums=(0, 1, 2))(p, a, b)

        m = compiled_memory_stats(value_and_grads, params, zi, zt)
        if m is None:
            raise RuntimeError(
                "memory_analysis unavailable on this backend — the island "
                "temp-byte gate cannot run here"
            )
        out[label] = {
            "temp_bytes": int(m["temp_size_in_bytes"]),
            "peak_bytes": int(m["peak_bytes"]),
        }
    out["_meta"] = {"w": w, "local_b": ISLAND_LOCAL_B, "d": ISLAND_D}
    return out


def collect_proxies(
    n_devices: int | None = None, islands: bool = True,
) -> dict:
    """The full current-tree proxy snapshot: step-config lattice (when the
    mesh allows it) + loss-island temp bytes + environment meta."""
    import jax

    from distributed_sigmoid_loss_tpu.obs.ledger import environment_fingerprint

    n = n_devices or len(jax.devices())
    snap: dict = {
        "meta": {
            "jax": jax.__version__,
            "n_devices": n,
            **{k: v for k, v in environment_fingerprint().items()
               if k in ("git_sha",)},
        }
    }
    if n >= 4 and n % 2 == 0:
        snap["step_configs"] = collect_step_proxies(n)
    if islands:
        snap["loss_islands"] = collect_island_temp_bytes(n)
    return snap


def contract_findings(current: dict) -> list[Finding]:
    """The self-relative structural contracts — enforced with NO baseline,
    so they hold even on a fresh checkout or a jax upgrade."""
    findings: list[Finding] = []
    islands = current.get("loss_islands") or {}
    meta = islands.get("_meta") or {}

    def temp(label):
        return islands.get(label, {}).get("temp_bytes")

    # Ratio contracts only at the full W=8 shape: the chunked/streaming
    # savings scale with W, so a 2-device smoke mesh can't assert them.
    if meta.get("w", 0) >= 8 and temp("fused"):
        fused = temp("fused")
        for label, bound in (("chunked", 0.5), ("streaming_fused", 0.5)):
            t = temp(label)
            if t is None:
                continue
            ratio = t / fused
            if ratio >= bound:
                findings.append(Finding(
                    "regress-contract",
                    f"loss_islands::{label}",
                    f"temp_bytes ratio vs fused is {ratio:.3f} (contract "
                    f"< {bound}): {t} vs {fused} — the streamed/chunked "
                    "memory contract (PR 3 / PR 7) regressed; a dropped "
                    "jax.checkpoint or a materialized logits block looks "
                    "exactly like this",
                ))
        if temp("streaming_chunked") and temp("chunked"):
            ratio = temp("streaming_chunked") / temp("chunked")
            if ratio > 1.1:
                findings.append(Finding(
                    "regress-contract",
                    "loss_islands::streaming_chunked",
                    f"temp_bytes is {ratio:.3f}x the chunked XLA scan "
                    "(contract <= 1.1x): the fused-backward tile recompute "
                    "stopped paying for itself",
                ))
    steps = current.get("step_configs") or {}
    # The ring pair must move IDENTICAL bytes per real collective kind —
    # the overlap reorders hops, never traffic. comm_bytes_all_to_all is
    # excluded at the whole-step level: the 0.4.x shims insert pbroadcast
    # VMA adjustments (bucketed under all_to_all) that differ between the
    # serial and double-buffered loop structures without moving wire bytes;
    # the ISLAND-level identity (overlap == serial, every kind) is pinned by
    # tests/test_obs.py.
    ring_kinds = ("comm_bytes_all_gather", "comm_bytes_ppermute",
                  "comm_bytes_psum", "comm_bytes_psum_scatter")
    for a, b in (("ring", "ring_overlap"), ("pallas_ring",
                                            "pallas_ring_overlap")):
        if a in steps and b in steps:
            for kind in ring_kinds:
                va, vb = steps[a].get(kind), steps[b].get(kind)
                if va != vb:
                    findings.append(Finding(
                        "regress-contract",
                        f"step_configs::{b}::{kind}",
                        f"{kind} differs from {a}: {vb} vs {va} — the "
                        "overlap must reorder hops, never change what goes "
                        "over the wire (bitwise-equal accumulation contract)",
                    ))
    return findings


def compare_proxies(current: dict, baseline: dict) -> tuple[list, list]:
    """(failures, warnings) of the current tree vs the committed baseline.

    Failures are :class:`Finding`s naming the offending config + metric with
    both values; warnings are strings (version-mismatch downgrades, configs
    the baseline doesn't know yet).
    """
    failures: list[Finding] = []
    warnings: list[str] = []
    jax_mismatch = (
        current.get("meta", {}).get("jax") != baseline.get("meta", {}).get("jax")
    )
    if jax_mismatch:
        warnings.append(
            f"jax version differs from the baseline's "
            f"({current.get('meta', {}).get('jax')} vs "
            f"{baseline.get('meta', {}).get('jax')}): absolute temp-byte "
            "comparisons downgraded to warnings (XLA packing shifts across "
            "releases); closed-form proxies and ratio contracts stay enforced"
        )

    cur_steps = current.get("step_configs")
    base_steps = baseline.get("step_configs") or {}
    if cur_steps is not None:
        for label in sorted(base_steps):
            if label not in cur_steps:
                failures.append(Finding(
                    "regress-proxy", f"step_configs::{label}",
                    "config present in the committed baseline but missing "
                    "from the current lattice — a guarded step config was "
                    "removed (or renamed) without `obs regress --update`",
                ))
                continue
            for metric, (mode, tol) in PROXY_METRICS.items():
                if metric not in base_steps[label]:
                    continue
                b = float(base_steps[label][metric])
                c = float(cur_steps[label].get(metric, float("nan")))
                if mode == "abs":
                    drift, bound = abs(c - b), tol
                else:
                    drift = abs(c - b) / b if b else abs(c - b)
                    bound = tol
                if not drift <= bound:  # NaN-safe: NaN fails
                    failures.append(Finding(
                        "regress-proxy",
                        f"step_configs::{label}::{metric}",
                        f"{metric} drifted {drift:.4f} "
                        f"({'rel' if mode == 'rel' else 'abs'} tolerance "
                        f"{bound}): baseline {b} -> current {c}",
                    ))
        for label in sorted(set(cur_steps) - set(base_steps)):
            warnings.append(
                f"step config {label!r} has no committed baseline — run "
                "`obs regress --update` to pin it"
            )

    cur_isl = current.get("loss_islands") or {}
    base_isl = baseline.get("loss_islands") or {}
    shape_match = (
        cur_isl.get("_meta") == base_isl.get("_meta") and cur_isl.get("_meta")
    )
    if not shape_match and base_isl:
        warnings.append(
            "island shape/mesh differs from the baseline's "
            f"({cur_isl.get('_meta')} vs {base_isl.get('_meta')}): absolute "
            "temp-byte comparison skipped (ratio contracts still apply)"
        )
    elif shape_match:
        for label in sorted(set(base_isl) - {"_meta"}):
            if label not in cur_isl:
                failures.append(Finding(
                    "regress-proxy", f"loss_islands::{label}",
                    "island present in the baseline but missing from the "
                    "current tree",
                ))
                continue
            b = float(base_isl[label]["temp_bytes"])
            c = float(cur_isl[label]["temp_bytes"])
            drift = abs(c - b) / b if b else abs(c - b)
            if drift > ISLAND_TOLERANCE:
                msg = (
                    f"temp_bytes drifted {drift:.3f} (tolerance "
                    f"{ISLAND_TOLERANCE}): baseline {int(b)} -> current "
                    f"{int(c)}"
                )
                if jax_mismatch:
                    warnings.append(f"loss_islands::{label}: {msg} "
                                    "(downgraded: jax version mismatch)")
                elif c > b:
                    failures.append(Finding(
                        "regress-proxy", f"loss_islands::{label}",
                        msg + " — compiled peak-temp regression; the memory "
                        "contract the chunked/streaming paths exist for",
                    ))
                else:
                    # An IMPROVEMENT outside tolerance is worth pinning, not
                    # failing: prompt a baseline refresh.
                    warnings.append(
                        f"loss_islands::{label}: {msg} (improvement — "
                        "refresh the baseline with `obs regress --update`)"
                    )
    return failures, warnings


def load_baseline(path: str | None = None) -> dict | None:
    p = path or BASELINE_PATH
    if not os.path.exists(p):
        return None
    with open(p, encoding="utf-8") as f:
        return json.load(f)


def write_baseline(current: dict, path: str | None = None) -> str:
    p = path or BASELINE_PATH
    with open(p, "w", encoding="utf-8") as f:
        json.dump(current, f, indent=1, sort_keys=True)
        f.write("\n")
    return p


def run_regress(
    *,
    baseline_path: str | None = None,
    update: bool = False,
    n_devices: int | None = None,
    stream=None,
    current: dict | None = None,
) -> int:
    """The `obs regress` entry point. Collects the current tree's proxies,
    checks the structural contracts, compares against the committed baseline,
    and prints a per-config summary. Exit 0 = green, 1 = regression (every
    failure names its config + metric), 2 = usage/environment error.

    ``update=True`` rewrites the baseline from the current tree instead of
    comparing. ``current`` injects a pre-collected snapshot (tests).
    """
    out = stream or sys.stdout
    if current is None:
        current = collect_proxies(n_devices=n_devices)
    n_cfg = len(current.get("step_configs") or {})
    isl = {k: v for k, v in (current.get("loss_islands") or {}).items()
           if k != "_meta"}
    print(
        f"obs regress: {n_cfg} step configs traced, {len(isl)} loss islands "
        f"compiled (jax {current.get('meta', {}).get('jax')}, "
        f"{current.get('meta', {}).get('n_devices')} devices)",
        file=out,
    )
    for label in sorted(isl):
        print(f"  island {label:<18} temp_bytes={isl[label]['temp_bytes']}",
              file=out)

    if update:
        path = write_baseline(current, baseline_path)
        print(f"obs regress: baseline written -> {path}", file=out)
        return 0

    failures = contract_findings(current)
    baseline = load_baseline(baseline_path)
    if baseline is None:
        print(
            "obs regress: no committed baseline "
            f"({baseline_path or BASELINE_PATH}); run `obs regress --update` "
            "to generate it — only the structural contracts were checked",
            file=out,
        )
    else:
        cmp_failures, warnings = compare_proxies(current, baseline)
        failures.extend(cmp_failures)
        for w in warnings:
            print(f"obs regress: WARNING: {w}", file=out)
    for f in failures:
        print(f"obs regress: FAIL {f}", file=out)
    verdict = "green" if not failures else f"{len(failures)} regression(s)"
    print(f"obs regress: {verdict}", file=out)
    return 1 if failures else 0
