"""lockwatch: the runtime half of graftguard — a Goodlock-style
potential-deadlock witness for the threaded host stack.

Every lock in the serving/data/obs host tier is created through
``named_lock``/``named_rlock``/``named_condition`` with a name registered in
``WATCHED_LOCKS`` (the single inventory of what each lock guards —
docs/SERVING.md renders it as the threading model). In production the
factories return plain ``threading`` primitives: zero wrappers, zero
overhead. Under ``DSL_LOCKWATCH=1`` they return instrumented locks that
record the runtime lock-acquisition-order graph into a global
:class:`WitnessGraph`: whenever a thread acquires lock B while holding lock
A, the edge A→B is recorded. A cycle in that graph is a POTENTIAL deadlock
— two threads that ever interleave the inverted orders can wedge — detected
even when no deadlock manifested in the run (the Goodlock insight: witness
the order, don't wait for the hang).

The conftest fixture turns every tier-1 threaded suite into a witness run
(``DSL_LOCKWATCH=1 pytest tests/ -q -m 'not slow'`` asserts the session
graph stays acyclic), and graftlint's ``repo-lockwatch-gate`` rule proves
the instrumentation dead in prod exactly the way ``repo-chaos-gate`` proves
the fault points dead: the factories must consult ``lockwatch_enabled()``,
``lockwatch_enabled`` must key on the documented ``DSL_LOCKWATCH`` env
hook, every call site must pass a registered string-constant name, and
stale registry rows are findings.

Known instrumentation limits (documented, not bugs): ``Condition.wait``'s
internal release/re-acquire goes through the wrapped lock's plain
``release``/``acquire`` (the stdlib fallback), so recursive holds deeper
than one level across a ``wait`` are not supported under watch; and the
witness records the order of *successful and attempted* acquisitions — a
timeout'd try-acquire still contributes its edge, which is the conservative
direction for a potential-deadlock detector.

Stdlib-only module (the obs import discipline: no jax at import time).
"""

from __future__ import annotations

import os
import threading

__all__ = [
    "WATCHED_LOCKS",
    "lockwatch_enabled",
    "named_lock",
    "named_rlock",
    "named_condition",
    "watched_lock",
    "WitnessGraph",
    "witness",
]

# The lock inventory: every host-stack lock, with what it guards. This is
# the registry ``repo-lockwatch-gate`` enforces (constant names at call
# sites, non-empty rationales, no stale rows) and the source docs/SERVING.md
# cites for the threading model. Name convention: dotted module path +
# owner + attribute (function-local locks use the function name as owner).
WATCHED_LOCKS = {
    "serve.service.RetrievalRouter._publish_lock": (
        "index-version publication: the _versions map and the _current "
        "pointer swap (search reads _current lock-free by design — "
        "publication is the only writer)"
    ),
    "serve.service.RetrievalRouter._stats_lock": (
        "router counters: _swap_count/_swaps_in_flight/_swap_latency/"
        "_searches/_recall_sum/_recall_n/_last_rerank_k"
    ),
    "serve.service.EmbeddingService._lock": (
        "service request counters: _requests/_items/_rejected/_timeouts/"
        "_shed (client threads increment, stats() snapshots)"
    ),
    "serve.engine.InferenceEngine._lock": (
        "the bucket compile cache (_compiled) and the hot-swapped params "
        "reference — swap_params vs _run vs compile_count"
    ),
    "serve.index.RetrievalIndex._lock": (
        "the chunked corpus blocks/id blocks and size — add() vs the "
        "_snapshot() read that gives search its consistent prefix"
    ),
    "serve.cache.EmbeddingCache._lock": (
        "the LRU map plus hits/misses/evictions counters (get/put mutate "
        "both together; stats() snapshots under the same lock)"
    ),
    "serve.shard_index.ShardedIndex._lock": (
        "the per-query-bucket compile-count bookkeeping (_compiled) on the "
        "sharded top-k path"
    ),
    "serve.swap.SwapController._lock": (
        "swap serialization: at most one build+publish window in flight; "
        "the begin_swap/end_swap degraded-health window opens and closes "
        "inside it"
    ),
    "serve.batcher.MicroBatcher._hist_lock": (
        "the batch-size histogram (_batch_sizes) the worker appends and "
        "batch_size_histogram() snapshots"
    ),
    "serve.admission.AdmissionController._lock": (
        "ALL per-tenant admission state: token buckets, inflight quotas, "
        "shed counters/backoff clocks, the shed-event window, and the "
        "priority thresholds rebuild"
    ),
    "serve.siege._INJECT_LOCK": (
        "the armed-fault registry _INJECTORS (install/clear/count-decrement "
        "of FaultPlans; released before any delay/raise fires)"
    ),
    "serve.siege.EngineProcess._lock": (
        "the child Pipe: exactly one send→poll→recv exchange at a time — "
        "the pipe IS the serialized resource"
    ),
    "serve.siege.run_scenario.tally_lock": (
        "per-tenant request tallies (ok/shed/errors/latencies) shared by "
        "the scenario's client threads"
    ),
    "serve.fleet.leases.LeaseCoordinator._lock": (
        "the lease table: _grants/_members/_epoch/_reclaims — grant, "
        "renew, and the TTL expiry sweep are one atomic step so summed "
        "live fractions can never exceed 1.0 mid-transition"
    ),
    "serve.fleet.leases.LeaseClient._lock": (
        "the host's local lease snapshot (_leases/_partitioned) — the "
        "renew thread republishes it; admission reads fractions from it "
        "(coordinator.acquire is called OUTSIDE this lock)"
    ),
    "serve.fleet.leases.LeasedAdmission._lock": (
        "per-tenant leased buckets (tokens/inflight/shed-backoff) plus the "
        "admit-timestamp evidence deque the over-admission sweep reads"
    ),
    "serve.fleet.router.FleetRouter._lock": (
        "routing state: WRR credits, per-replica+per-session in-flight "
        "counts, lost/draining sets, session pins (replica.call and health "
        "probes happen OUTSIDE this lock; wait_idle polls lock-free)"
    ),
    "serve.fleet.waves.WaveController._lock": (
        "wave serialization: at most one swap wave in flight fleet-wide — "
        "the drain→wait-idle→swap→undrain fan-out runs inside it, the "
        "fleet analogue of SwapController._lock's single-swap window"
    ),
    "obs.telemetry.TelemetryExporter._lock": (
        "the scrape-snapshot cache (_cached/_cached_at) plus scrapes/"
        "render_count — render deliberately happens inside the lock so a "
        "scrape storm collapses to one stats() call per refresh window"
    ),
    "obs.spans.SpanRecorder._lock": (
        "the span ring buffer and dropped counter (record vs clear vs "
        "spans snapshot)"
    ),
    "data.native_loader._build_lock": (
        "one-time native dataloader .so build/load (the _lib cache write)"
    ),
    "data.native_loader.NativeSyntheticImageText._iter_lock": (
        "serializes next() against close(): the native ring is "
        "single-consumer and destroy must not race a blocked "
        "dsl_pipeline_next"
    ),
    "data.native_loader.NativeSyntheticImageText._close_lock": (
        "serializes concurrent close()rs; always taken BEFORE _iter_lock "
        "(the one deliberate nesting in the data tier)"
    ),
    "data.native_decode._build_lock": (
        "one-time libjpeg engine build/load (the _lib/_lib_failed latch)"
    ),
    "utils.logging.LatencyWindow._lock": (
        "the bounded sample deque + count — record() appends vs the "
        "percentiles_ms sorted snapshot"
    ),
}


def lockwatch_enabled() -> bool:
    """True only when the witness is armed via ``DSL_LOCKWATCH=1`` — the
    production off-switch ``repo-lockwatch-gate`` statically pins."""
    return os.environ.get("DSL_LOCKWATCH") == "1"


class WitnessGraph:
    """Runtime lock-acquisition-order graph with per-thread held stacks.

    Nodes are lock *instances* (unique ``name#k`` tokens), so two same-named
    instances never produce a false self-loop — yet a genuine inversion
    between two instances of one class (thread 1 nests A1→A2 while thread 2
    nests A2→A1) is still a reported cycle, because at instance granularity
    it IS a potential deadlock. Cycles are reported with registered names.
    """

    def __init__(self):
        # The graph's own mutex is a raw lock on purpose: the witness must
        # never witness itself.
        self._mu = threading.Lock()
        self._tls = threading.local()
        self._edges: dict[str, set[str]] = {}
        self._names: dict[str, str] = {}
        self._seq = 0

    def new_token(self, name: str) -> str:
        with self._mu:
            self._seq += 1
            token = f"{name}#{self._seq}"
            self._names[token] = name
            return token

    def _stack(self) -> list:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def note_acquiring(self, token: str) -> None:
        """Record held→token edges at ATTEMPT time (a timeout'd acquire
        still witnessed the attempted order — the conservative direction)."""
        st = self._stack()
        if not st:
            return
        with self._mu:
            for held in st:
                if held != token:
                    self._edges.setdefault(held, set()).add(token)

    def note_acquired(self, token: str) -> None:
        self._stack().append(token)

    def note_released(self, token: str) -> None:
        st = self._stack()
        for i in range(len(st) - 1, -1, -1):
            if st[i] == token:
                del st[i]
                return

    def edge_names(self) -> list[tuple[str, str]]:
        """Name-level snapshot of the recorded acquisition-order edges."""
        with self._mu:
            return sorted({
                (self._names[a], self._names[b])
                for a, succs in self._edges.items()
                for b in succs
            })

    def cycles(self) -> list[tuple[str, ...]]:
        """Every distinct cycle in the instance graph, as name tuples —
        non-empty means a potential deadlock was witnessed."""
        with self._mu:
            graph = {u: sorted(vs) for u, vs in self._edges.items()}
            names = dict(self._names)
        color: dict[str, int] = {}  # 0 white / 1 grey / 2 black
        path: list[str] = []
        sigs: set[tuple[str, ...]] = set()
        found: list[tuple[str, ...]] = []

        def visit(start: str) -> None:
            color[start] = 1
            path.append(start)
            stack = [(start, iter(graph.get(start, ())))]
            while stack:
                node, it = stack[-1]
                nxt = next(it, None)
                if nxt is None:
                    color[node] = 2
                    path.pop()
                    stack.pop()
                    continue
                c = color.get(nxt, 0)
                if c == 0:
                    color[nxt] = 1
                    path.append(nxt)
                    stack.append((nxt, iter(graph.get(nxt, ()))))
                elif c == 1:
                    cyc = tuple(
                        names[t] for t in path[path.index(nxt):]
                    )
                    k = min(
                        range(len(cyc)),
                        key=lambda j: cyc[j:] + cyc[:j],
                    )
                    sig = cyc[k:] + cyc[:k]
                    if sig not in sigs:
                        sigs.add(sig)
                        found.append(sig)

        for u in sorted(graph):
            if color.get(u, 0) == 0:
                visit(u)
        return found

    def reset(self) -> None:
        """Drop recorded edges (names/tokens survive). Test scaffolding —
        the session witness is never reset mid-run."""
        with self._mu:
            self._edges.clear()


_WITNESS = WitnessGraph()


def witness() -> WitnessGraph:
    """The process-global witness graph the named factories record into."""
    return _WITNESS


class _WatchedLock:
    """Witness-recording wrapper with the threading lock protocol."""

    def __init__(self, name: str, graph: WitnessGraph, factory):
        self._inner = factory()
        self._graph = graph
        self._token = graph.new_token(name)
        self.name = name

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        self._graph.note_acquiring(self._token)
        ok = self._inner.acquire(blocking, timeout)
        if ok:
            self._graph.note_acquired(self._token)
        return ok

    def release(self) -> None:
        self._graph.note_released(self._token)
        self._inner.release()

    def locked(self) -> bool:
        locked = getattr(self._inner, "locked", None)
        return bool(locked()) if locked is not None else False

    def _is_owned(self) -> bool:
        # threading.Condition probes this; delegate so a watched RLock
        # behaves (the stdlib try-acquire fallback would mis-report an
        # owned RLock as free, reentrancy being reentrant).
        inner = self._inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def __repr__(self) -> str:
        return f"<watched {self.name} {self._inner!r}>"


def _require_registered(name: str) -> None:
    if name not in WATCHED_LOCKS:
        raise KeyError(
            f"unregistered lock name {name!r}: register it in "
            "obs/lockwatch.py WATCHED_LOCKS with a rationale saying what "
            "it guards (repo-lockwatch-gate enforces this statically)"
        )


def named_lock(name: str):
    """A ``threading.Lock`` in production; a witness-recording wrapper
    under ``DSL_LOCKWATCH=1``. ``name`` must be a registered constant."""
    _require_registered(name)
    if lockwatch_enabled():
        return _WatchedLock(name, _WITNESS, threading.Lock)
    return threading.Lock()


def named_rlock(name: str):
    """``named_lock`` for reentrant locks."""
    _require_registered(name)
    if lockwatch_enabled():
        return _WatchedLock(name, _WITNESS, threading.RLock)
    return threading.RLock()


def named_condition(name: str):
    """A ``threading.Condition`` whose underlying lock is witnessed under
    ``DSL_LOCKWATCH=1`` (wait's internal re-acquire included, via the
    stdlib release/acquire fallback)."""
    _require_registered(name)
    if lockwatch_enabled():
        return threading.Condition(
            _WatchedLock(name, _WITNESS, threading.RLock)
        )
    return threading.Condition()


def watched_lock(name: str, graph: WitnessGraph | None = None):
    """Always-instrumented lock on an explicit graph — test scaffolding for
    seeding/fixturing witness scenarios without touching the session
    witness or the registry. Production code uses ``named_lock``."""
    return _WatchedLock(name, graph if graph is not None else _WITNESS,
                        threading.Lock)
