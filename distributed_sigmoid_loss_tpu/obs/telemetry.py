"""Live telemetry: an OpenMetrics-style ``/metrics`` endpoint + atomic
telemetry files.

Post-hoc JSON records answer "what happened"; production serving (millions
of users, ROADMAP north star) additionally needs PULL-based live state — a
scraper hitting ``/metrics`` every few seconds without touching the metrics
log. Two pieces:

- :func:`render_openmetrics` flattens the serving stack's ``stats()``
  snapshot (the declared ``SERVE_STATS_FIELDS`` schema) into Prometheus/
  OpenMetrics text: numeric scalars become gauges, percentile dicts become
  ``quantile``-labelled series, histograms become labelled counters, and
  string fields collect into one ``_info`` series. ``labels=`` stamps a
  constant label set onto EVERY series — the per-tenant scoping hook
  (ROADMAP item 5: one exporter per tenant, ``tenant="..."`` label, same
  schema).
- :class:`TelemetryExporter` serves that text from a stdlib HTTP server on a
  daemon thread, with bounded work under scrape storms: the rendered bytes
  are cached for ``refresh_s`` and concurrent scrapes inside the window are
  answered from the SAME cached buffer — no new snapshot, no re-render, no
  per-request allocation of the payload (pinned by test).

Plus :func:`write_telemetry_file` — the train loop's push-side twin: an
atomic-rename (tmp + ``os.replace``) JSON file a soak run overwrites each
log interval, so ``watch cat telemetry.json`` style tailing never sees a
torn write and never touches the metrics log.

Stdlib-only module (the obs import discipline: no jax at import time).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Iterable, Mapping

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = [
    "render_openmetrics",
    "TelemetryExporter",
    "write_telemetry_file",
]

_PERCENTILE_KEY = re.compile(r"p(\d+)_ms$")

# Intermediate-dict label names for the known nested stats shapes; anything
# else falls back to a generic "key" label (schema-complete beats pretty).
_NEST_LABEL = {
    "stage_latency_ms": "stage",
    "search_stage_latency_ms": "stage",
    "batch_size_hist": "modality",
    "cache": "field",
    # serve/admission.py AdmissionController.stats(): the nested per-tenant
    # rows flatten into tenant="..."-labelled series (the per-tenant hook).
    "per_tenant": "tenant",
    "admission": "field",
}


def _sanitize(name: str) -> str:
    return re.sub(r"[^a-zA-Z0-9_:]", "_", str(name))


def _escape(value) -> str:
    return str(value).replace("\\", "\\\\").replace('"', '\\"')


def _label_str(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_sanitize(k)}="{_escape(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _flatten(
    name: str, value, labels: dict, depth_label: str | None,
) -> Iterable[tuple[str, dict, float]]:
    """Yield (metric_name, labels, numeric_value) triples for one snapshot
    field. Percentile keys become a ``quantile`` label; other nested keys
    become the shape's registered label (or ``key``)."""
    if isinstance(value, bool):
        yield name, labels, 1.0 if value else 0.0
        return
    if isinstance(value, (int, float)):
        yield name, labels, float(value)
        return
    if isinstance(value, Mapping):
        for k, v in value.items():
            m = _PERCENTILE_KEY.fullmatch(str(k))
            if m is not None:
                yield from _flatten(
                    name, v, {**labels, "quantile": m.group(1)}, depth_label
                )
            else:
                lbl = depth_label or "key"
                # The child's own depth label comes from the registry too, so
                # a registered shape nested INSIDE another (admission stats'
                # per_tenant map) still gets its tenant="..." label instead
                # of a colliding generic "key".
                yield from _flatten(
                    name, v,
                    {**labels, lbl: str(k)},
                    _NEST_LABEL.get(str(k), "key"),
                )
    # strings/None are handled by the caller (info series); other types skip


def render_openmetrics(
    snapshot: Mapping,
    *,
    prefix: str = "dsl_serve",
    labels: Mapping[str, str] | None = None,
) -> str:
    """One stats snapshot -> Prometheus/OpenMetrics exposition text.

    Every snapshot key lands in the output: numeric (and nested-numeric)
    fields as ``{prefix}_{field}`` gauges, string fields as label values on
    the single ``{prefix}_info`` gauge — so a scrape is schema-complete by
    construction and a parser can recover the whole declared field set.
    """
    base = dict(labels or {})
    lines: list[str] = []
    info_labels: dict[str, str] = {}
    for key in snapshot:
        value = snapshot[key]
        if value is None:
            continue
        if isinstance(value, str):
            info_labels[_sanitize(key)] = value
            continue
        metric = f"{prefix}_{_sanitize(key)}"
        series = list(_flatten(metric, value, base, _NEST_LABEL.get(key)))
        # The TYPE line is emitted even for a field whose container is still
        # empty (e.g. no stage latencies recorded yet): a scrape stays
        # schema-complete — every declared field is discoverable — from the
        # very first request.
        lines.append(f"# TYPE {metric} gauge")
        for mname, mlabels, mval in series:
            out = f"{mval:.6f}".rstrip("0").rstrip(".") or "0"
            lines.append(f"{mname}{_label_str(mlabels)} {out}")
    info_name = f"{prefix}_info"
    lines.append(f"# TYPE {info_name} gauge")
    lines.append(f"{info_name}{_label_str({**base, **info_labels})} 1")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


class TelemetryExporter:
    """Pull-based live metrics: GET ``/metrics`` (exposition text) and
    ``/healthz`` (JSON liveness) from a stdlib HTTP server thread.

    ``snapshot_fn`` is called at most once per ``refresh_s`` seconds no
    matter how many scrapers hit the endpoint; in between, requests are
    answered from the cached rendered bytes (one shared buffer — the
    bounded/allocation-free snapshot-reuse contract). ``port=0`` binds an
    ephemeral port; read it back from :attr:`port` after :meth:`start`.
    """

    def __init__(
        self,
        snapshot_fn: Callable[[], Mapping],
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        prefix: str = "dsl_serve",
        labels: Mapping[str, str] | None = None,
        refresh_s: float = 0.25,
        health_fn: Callable[[], Mapping] | None = None,
    ):
        self.snapshot_fn = snapshot_fn
        self.host = host
        self.prefix = prefix
        self.labels = dict(labels or {})
        self.refresh_s = float(refresh_s)
        # Optional richer /healthz: merged into the liveness payload, so a
        # serving stack can report status="degraded" (still HTTP 200 — the
        # process is up) while shedding or mid-swap. Without it the payload
        # stays the bare {"ok": true} liveness contract.
        self.health_fn = health_fn
        self._requested_port = port
        self._server: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None
        self._lock = named_lock("obs.telemetry.TelemetryExporter._lock")
        self._cached: bytes = b""
        self._cached_at = 0.0
        self.scrapes = 0
        self.render_count = 0  # how many times snapshot_fn actually ran

    # -- payload -------------------------------------------------------------

    def payload(self) -> bytes:
        """The current ``/metrics`` body — cached across the refresh window."""
        now = time.monotonic()
        with self._lock:
            self.scrapes += 1
            if self._cached and now - self._cached_at < self.refresh_s:
                return self._cached
            # Render INSIDE the lock: a scrape storm collapses onto one
            # snapshot call instead of stampeding the service's stats lock.
            text = render_openmetrics(
                self.snapshot_fn(), prefix=self.prefix, labels=self.labels
            )
            self._cached = text.encode("utf-8")
            self._cached_at = time.monotonic()
            self.render_count += 1
            return self._cached

    # -- server --------------------------------------------------------------

    def start(self) -> int:
        """Bind + serve on a daemon thread; returns the bound port."""
        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib API name
                if self.path.split("?", 1)[0] == "/metrics":
                    body = exporter.payload()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif self.path.split("?", 1)[0] == "/healthz":
                    health: dict = {"ok": True}
                    if exporter.health_fn is not None:
                        health.update(exporter.health_fn())
                    body = json.dumps(health).encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # scrapes must not spam stderr
                pass

        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), Handler
        )
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dsl-metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        return self.port

    @property
    def port(self) -> int:
        return self._server.server_address[1] if self._server else 0

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()


def write_telemetry_file(path: str, payload: Mapping) -> None:
    """Atomically replace ``path`` with ``payload`` as JSON: write to a tmp
    file in the SAME directory, fsync, then ``os.replace`` — a reader can
    open the file at any moment and never observe a torn write. The train
    loop calls this each log interval under ``--obs-dir`` so soak runs can
    be tailed without parsing the metrics log."""
    parent = os.path.dirname(os.path.abspath(path))
    os.makedirs(parent, exist_ok=True)
    tmp = os.path.join(parent, f".{os.path.basename(path)}.tmp.{os.getpid()}")
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(payload, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
