"""graftledger: the append-only perf-trajectory ledger.

The perf stream's records used to be one-shot stdout lines: the driver
captured whatever a round's `python bench.py` printed and the repo kept no
longitudinal memory of it. Rounds 4 and 5 then recorded 0.0 (chip backend
unavailable) and nothing distinguished "the config regressed" from "the chip
was down" — the trajectory itself was blind (ROADMAP item 3 calls landing
real trajectory numbers "part of this item, not an afterthought").

The ledger fixes the memory half: every record emit path (bench.py ``_emit``,
cli ``serve-bench``, ``data-bench``) ALSO appends one JSONL entry to
``LEDGER.jsonl`` at the repo root, carrying

- the schema-validated record itself (unmodified — the stdout contract is
  untouched),
- an environment fingerprint (jax version, device kind/count, host, git sha)
  so any number can be tied to the program AND the machine that produced it,
- an explicit ``status``: ``ok`` / ``no-backend`` / ``deferred`` / ``error``
  — a dead backend lands as ``no-backend`` instead of polluting the
  trajectory with a 0.0 that looks like a measurement.

``obs ledger`` summarizes the per-metric trajectory (no-backend/error rounds
excluded from the baseline stats), ``obs diff A B`` diffs two entries'
records. The graftlint rule ``repo-ledger-emit`` statically enforces that
bench.py record prints only happen inside the ledger-appending ``_emit``.

Stdlib-only module: bench.py imports it at emit time and must not initialize
jax; the fingerprint reads jax ONLY if something else already imported it.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

__all__ = [
    "DEFAULT_LEDGER_BASENAME",
    "ledger_path",
    "environment_fingerprint",
    "record_status",
    "append_record",
    "read_ledger",
    "backfill_round_files",
    "trajectory",
    "trajectory_summary",
    "diff_records",
]

DEFAULT_LEDGER_BASENAME = "LEDGER.jsonl"
LEDGER_SCHEMA_VERSION = 1

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PACKAGE_DIR)

_FINGERPRINT_CACHE: dict = {}


def ledger_path(path: str | None = None) -> str | None:
    """Resolve the ledger file path: an explicit ``path`` wins, then the
    ``DSL_LEDGER_PATH`` env var (set to the empty string to DISABLE ledger
    appends — the test suites do this so CI runs never dirty the committed
    trajectory), then ``<repo_root>/LEDGER.jsonl``."""
    if path:
        return path
    env = os.environ.get("DSL_LEDGER_PATH")
    if env is not None:
        return env or None
    return os.path.join(_REPO_ROOT, DEFAULT_LEDGER_BASENAME)


def _git_sha() -> str:
    if "git_sha" not in _FINGERPRINT_CACHE:
        sha = ""
        try:
            r = subprocess.run(
                ["git", "-C", _REPO_ROOT, "rev-parse", "--short", "HEAD"],
                capture_output=True, text=True, timeout=5,
            )
            if r.returncode == 0:
                sha = r.stdout.strip()
        except Exception:
            pass
        _FINGERPRINT_CACHE["git_sha"] = sha
    return _FINGERPRINT_CACHE["git_sha"]


def environment_fingerprint() -> dict:
    """Who/what produced this entry: host, git sha, jax version and — only
    when a backend is ALREADY initialized — device kind/count.

    Deliberately passive about jax: importing it here would drag a multi-GB
    runtime into a stdlib emit path, and touching ``jax.devices()`` on an
    uninitialized process could hang on a dead tunneled backend (the exact
    situation no-backend entries are recorded in). An already-imported,
    already-initialized jax is read; anything else is left alone.
    """
    env = {"host": socket.gethostname(), "git_sha": _git_sha()}
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None:
        env["jax"] = getattr(jax_mod, "__version__", "?")
        try:
            from jax._src import xla_bridge  # noqa: PLC0415

            if getattr(xla_bridge, "_backends", None):
                devs = jax_mod.devices()
                env["device_kind"] = devs[0].device_kind
                env["device_count"] = len(devs)
        except Exception:
            pass
    return env


def record_status(record: dict) -> str:
    """Classify one bench record for the trajectory: ``deferred`` (compile
    shield handed off to a detached child), ``no-backend`` (the chip was
    dead — the 0.0 is an outage, not a measurement), ``error`` (the bench
    itself failed), else ``ok``."""
    if record.get("deferred"):
        return "deferred"
    err = str(record.get("error") or "")
    if "backend unavailable" in err or "backend init" in err:
        return "no-backend"
    if err:
        return "error"
    return "ok"


def append_record(
    record: dict,
    *,
    path: str | None = None,
    source: str = "bench",
    round_hint: int | None = None,
    problems=None,
) -> dict | None:
    """Append one record to the ledger; returns the written entry (None when
    the ledger is disabled). NEVER raises: a measurement must never be lost
    to its own ledger (the ``_emit`` convention) — failures warn on stderr.
    """
    try:
        target = ledger_path(path)
        if target is None:
            return None
        entry = {
            "schema": LEDGER_SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "source": source,
            "status": record_status(record),
            "env": environment_fingerprint(),
            "record": dict(record),
        }
        if round_hint is not None:
            entry["round"] = int(round_hint)
        if problems:
            entry["schema_violations"] = list(problems)
        line = json.dumps(entry)
        parent = os.path.dirname(os.path.abspath(target))
        os.makedirs(parent, exist_ok=True)
        # A writer killed mid-append leaves a torn final line with no
        # newline; appending straight after it would corrupt THIS entry too.
        # Start on a fresh line so one torn write costs one entry, not two.
        needs_newline = False
        try:
            with open(target, "rb") as rf:
                rf.seek(-1, os.SEEK_END)
                needs_newline = rf.read(1) != b"\n"
        except (OSError, ValueError):
            pass  # missing or empty file: no heal needed
        with open(target, "a", encoding="utf-8") as f:
            f.write(("\n" if needs_newline else "") + line + "\n")
        return entry
    except Exception as e:  # noqa: BLE001 — see docstring
        print(f"WARNING: ledger append failed ({type(e).__name__}: {e})",
              file=sys.stderr)
        return None


def read_ledger(path: str | None = None) -> list[dict]:
    """Parse the ledger into entries, tolerating torn lines (a process killed
    mid-append leaves a truncated final line — skipped, never fatal)."""
    target = ledger_path(path)
    if target is None or not os.path.exists(target):
        return []
    entries = []
    with open(target, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if isinstance(obj, dict) and isinstance(obj.get("record"), dict):
                entries.append(obj)
    return entries


def _records_in_tail(tail: str) -> list[dict]:
    """The JSON record lines embedded in a round file's captured ``tail``
    (same filter as bench.py's ``_emit_valid_json_lines``: dicts carrying
    ``metric``)."""
    out = []
    for line in tail.splitlines():
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            out.append(obj)
    return out


def backfill_round_files(
    repo_root: str | None = None, path: str | None = None,
) -> list[dict]:
    """Backfill ledger entries from the driver's committed round files
    (``BENCH_r*.json`` / ``MULTICHIP_r*.json``), so the trajectory starts at
    round 1 instead of at the ledger's introduction.

    - BENCH files: every JSON record line in the captured ``tail`` becomes an
      entry (rounds 4/5's "backend unavailable" records land as
      ``status="no-backend"`` automatically — the true trajectory then shows
      761.74 @ r3 as the last verified headline, not 0.0).
    - MULTICHIP files: one ``multichip_dryrun`` entry per round (value 1/0 =
      the dryrun's ok flag) so correctness-drill outcomes sit in the same
      stream.

    Idempotent: an entry whose (source, metric) pair already exists in the
    ledger is skipped. Returns the entries actually appended.
    """
    import glob
    import re

    root = repo_root or _REPO_ROOT
    existing = {
        (e.get("source"), e.get("record", {}).get("metric"))
        for e in read_ledger(path)
    }
    appended = []

    def backfill_one(record, source, rnd):
        if (source, record.get("metric")) in existing:
            return
        # Backfilled entries describe PAST runs: the backfilling host's
        # fingerprint would be a lie, so the `backfill:` source prefix marks
        # them and downstream readers trust the record's own device_kind.
        entry = append_record(
            record, path=path, source=source, round_hint=rnd,
        )
        if entry is not None:
            appended.append(entry)

    for kind in ("BENCH", "MULTICHIP"):
        for fp in sorted(glob.glob(os.path.join(root, f"{kind}_r*.json"))):
            m = re.search(r"_r(\d+)\.json$", fp)
            rnd = int(m.group(1)) if m else None
            try:
                with open(fp, encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, ValueError):
                continue
            source = f"backfill:{os.path.basename(fp)}"
            if kind == "BENCH":
                for record in _records_in_tail(data.get("tail", "")):
                    backfill_one(record, source, rnd)
            else:
                ok = bool(data.get("ok"))
                record = {
                    "metric": "multichip_dryrun",
                    "value": 1.0 if ok else 0.0,
                    "unit": "ok",
                    "n_devices": data.get("n_devices"),
                }
                if not ok:
                    record["error"] = (
                        f"dryrun rc={data.get('rc')} (see {os.path.basename(fp)})"
                    )
                backfill_one(record, source, rnd)
    return appended


# Statuses the trajectory summary treats as non-measurements: they appear in
# the listing (outages are information) but never in the baseline stats.
_EXCLUDED_FROM_BASELINE = ("no-backend", "deferred", "error")


def trajectory(
    entries: list[dict], metric: str | None = None,
) -> dict[str, list[dict]]:
    """metric -> ordered points ``{round?, ts?, value, status, source,
    device_kind?}``; ``metric`` filters to one stream."""
    out: dict[str, list[dict]] = {}
    for e in entries:
        rec = e.get("record", {})
        name = rec.get("metric")
        if not name or (metric and name != metric):
            continue
        point = {
            "value": rec.get("value"),
            "unit": rec.get("unit"),
            "status": e.get("status", record_status(rec)),
            "source": e.get("source", "?"),
        }
        if e.get("round") is not None:
            point["round"] = e["round"]
        if e.get("ts") is not None:
            point["ts"] = e["ts"]
        kind = rec.get("device_kind") or e.get("env", {}).get("device_kind")
        if kind:
            point["device_kind"] = kind
        out.setdefault(name, []).append(point)
    if metric and not out:
        # Field fallback: the graftcodec emulation figures
        # (wire_savings_wallclock_ratio, dcn_measured_mbps, error_budget,
        # ...) are FIELDS stamped on other streams' records, not streams of
        # their own — `obs ledger --metric wire_savings_wallclock_ratio`
        # should still render the emulated-A/B trajectory. When no stream
        # matches, build one from every record carrying the named field; the
        # unit column names the host stream so the provenance stays visible.
        for e in entries:
            rec = e.get("record", {})
            if metric not in rec or rec.get("metric") == metric:
                continue
            point = {
                "value": rec.get(metric),
                "unit": f"on {rec.get('metric')}",
                "status": e.get("status", record_status(rec)),
                "source": e.get("source", "?"),
            }
            if e.get("round") is not None:
                point["round"] = e["round"]
            if e.get("ts") is not None:
                point["ts"] = e["ts"]
            kind = (
                rec.get("device_kind") or e.get("env", {}).get("device_kind")
            )
            if kind:
                point["device_kind"] = kind
            out.setdefault(metric, []).append(point)
    return out


def trajectory_summary(points: list[dict]) -> dict:
    """Baseline stats over ONE metric's points with non-measurements
    (no-backend / deferred / error) excluded — the acceptance contract: an
    outage round must never drag the baseline to 0.0."""
    measured = [
        p for p in points
        if p["status"] not in _EXCLUDED_FROM_BASELINE
        and isinstance(p.get("value"), (int, float))
    ]
    excluded = len(points) - len(measured)
    if not measured:
        return {"n": 0, "excluded": excluded, "last": None, "best": None}
    values = [float(p["value"]) for p in measured]
    return {
        "n": len(measured),
        "excluded": excluded,
        "last": measured[-1],
        "best": max(values),
        "mean": sum(values) / len(values),
    }


def diff_records(a: dict, b: dict) -> dict:
    """Field-level diff of two records: ``added`` / ``removed`` field sets
    and ``changed`` with per-field (a, b) pairs plus a relative delta for
    numeric fields — what `obs diff` renders."""
    changed: dict = {}
    for k in sorted(set(a) & set(b)):
        va, vb = a[k], b[k]
        if va == vb:
            continue
        entry = {"a": va, "b": vb}
        if isinstance(va, (int, float)) and isinstance(vb, (int, float)) and (
            not isinstance(va, bool) and not isinstance(vb, bool)
        ):
            entry["delta"] = vb - va
            if va:
                entry["rel"] = round((vb - va) / abs(va), 4)
        changed[k] = entry
    return {
        "added": sorted(set(b) - set(a)),
        "removed": sorted(set(a) - set(b)),
        "changed": changed,
    }
