"""THE declared schema for train metrics lines and serve ``stats()`` fields.

``analysis/bench_schema.py`` fixed per-emit-path drift for bench.py's JSON
records; this module is the same registry for the OTHER two record streams —
the train loop's metrics lines (``MetricsLogger.log``) and the serving
stack's ``stats()`` snapshots / health events (``MetricsLogger.write``).
Before it, a metric field added in ``train_step.py`` but not
``compressed_step.py`` (or vice versa — ``ef_norm`` already only exists on
one path, correctly, but nothing DECLARED that) drifted silently, and
downstream per-metric parsers learned field names from whatever happened to
be emitted.

One registry, three consumers:

- ``utils.logging.MetricsLogger`` validates at emit time when constructed
  with ``schema=...`` (stderr warning; the line still prints — a metric must
  never be lost to its own validator, the bench ``_emit`` convention).
- ``tests/test_obs.py`` asserts real emit paths validate.
- ``analysis/repo_lint.py`` rule ``repo-metrics-schema`` statically
  cross-checks every metric-field string literal in the emitting modules
  against this registry, so an undeclared field fails tier-1 before it ever
  reaches a log parser.

Stdlib-only module (imported by the linter and bench paths that must not
initialize jax).
"""

from __future__ import annotations

__all__ = [
    "TRAIN_METRICS_FIELDS",
    "TRAIN_METRICS_PREFIXES",
    "SERVE_STATS_FIELDS",
    "HEALTH_EVENT_FIELDS",
    "validate_metrics",
]

# Every field a train metrics line may carry, grouped by the layer that owns
# it. Adding a field to a step's metrics dict (or cli.py's log_metrics merge)
# without registering it here fails the repo-metrics-schema lint rule.
TRAIN_METRICS_FIELDS = frozenset({
    # MetricsLogger bookkeeping
    "step", "steps_per_sec",
    # train/train_step.py + train/compressed_step.py step metrics
    "loss", "t", "bias", "grad_norm", "param_norm", "update_ratio",
    "moe_aux", "ef_norm",
    # train/compressed_step.py DCN wire accounting: per-device egress bytes
    # per sync round, payload bits per parameter, the residual-carry norm
    # (ef_norm's registered successor — both emitted), and the adaptive
    # path's per-scheme tensor-count histogram (a small list, not a scalar).
    "dcn_wire_bytes", "bits_per_param", "ef_residual_norm",
    "compression_scheme_hist",
    # parallel/adaptive_compression.py BitController bandwidth EWMA
    # (cli.py's adaptive step wrapper merges it into the line)
    "dcn_bw_est_mbps",
    # data/loader.py prefetch starvation (cli.py log_metrics)
    "input_wait_frac",
    # obs/attribution.py static attribution (cli.py log_metrics)
    "mfu_est", "comm_bytes_total",
    # parallel/update_shard.py (graftshard): the resolved update-sharding
    # mode and the compiler-measured at-rest optimizer bytes per replica
    # (cli.py stamps both on every metrics line when the mode is on).
    "update_sharding", "opt_mem_bytes_per_replica",
    # graftcodec: the learned rung's relative reconstruction error
    # (train/compressed_step.py, compression='learned'), the budgeted
    # controller's spent loss-impact budget + active policy (cli.py adaptive
    # wrapper), and the emulated-DCN measurements — bandwidth from MEASURED
    # transfer time over the throttled pipe (parallel/dcn_emu.py) and the
    # wall-clock step-time ratio vs the fixed-bf16 reference transfer.
    "codec_recon_err", "error_budget", "controller_mode",
    "dcn_measured_mbps", "wire_savings_wallclock_ratio",
})

# Prefix-namespaced families (dynamic keys): the in-training eval hook logs
# eval/i2t_recall@K etc. — any key under a registered prefix validates.
TRAIN_METRICS_PREFIXES = ("eval/",)

# serve/service.py stats() snapshot + the serve_stats/serve-bench records
# built from it (cli.py cmd_serve_bench spreads the snapshot into its
# record, so these are also registered in analysis/bench_schema.py).
SERVE_STATS_FIELDS = frozenset({
    "metric", "uptime_s", "requests", "items", "qps", "items_per_sec",
    "latency_ms", "batch_size_hist", "stage_latency_ms", "rejected",
    "timeouts", "compile_count", "bucket_space", "index_size", "cache",
    # serve/distindex (RetrievalRouter.stats): retrieval tier, versioned
    # hot-swap bookkeeping, measured ann recall, and the per-search-stage
    # (fanout/merge/coarse/rerank/exact) latency percentiles.
    "index_tier", "index_version", "shard_count", "swap_count",
    "swap_latency_ms", "recall_at_k", "rerank_k", "search_stage_latency_ms",
    # serve/admission.py (graftsiege): typed-shed counters distinct from the
    # queue-full "rejected" stream, the trailing-window shed rate that also
    # drives /healthz degraded, the nested AdmissionController.stats() row
    # (capacity/inflight/per_tenant), and the router's mid-swap flag.
    "shed", "shed_rate", "admission", "swap_in_flight",
    "capacity", "inflight", "per_tenant",
    # serve/fleet (graftfleet): the router's replica-health + routing
    # counters, the lease coordinator's epoch/reclaim bookkeeping, and the
    # wave controller's wave counter — every fleet stats() snap emits only
    # these, so the fleet_siege record stays schema-valid end to end.
    "replica_count", "healthy_replicas", "reroutes", "affinity_hits",
    "lease_epoch", "lease_reclaims", "wave_id",
})

# obs/health.py HealthEvent.record() — the structured watchdog events the
# train loop writes through the same logger.
HEALTH_EVENT_FIELDS = frozenset({"metric", "step", "event", "detail"})


def validate_metrics(
    record,
    fields=TRAIN_METRICS_FIELDS,
    prefixes: tuple = TRAIN_METRICS_PREFIXES,
) -> list[str]:
    """Validate one record's field NAMESPACE against a declared field set.

    Returns problem strings (empty = valid). Values are not typed here —
    the namespace is what drifts (the bench_schema convention).
    """
    if not isinstance(record, dict):
        return [f"record must be a dict, got {type(record).__name__}"]
    problems = []
    for key in record:
        if key in fields:
            continue
        if any(key.startswith(p) for p in prefixes):
            continue
        problems.append(
            f"unregistered metric field {key!r} — register it in "
            "obs/metrics_schema.py"
        )
    return problems
