"""Static step attribution: FLOPs, bytes and collective traffic from the
PROGRAM, not the chip.

The perf stream has been blind whenever the backend was (BENCH_r04/r05
recorded 0.0): a number could only be attributed when a chip run succeeded.
This module derives the attribution STATICALLY, two ways:

- :func:`jaxpr_costs` / :func:`static_attribution` walk a traced jaxpr (the
  same trace-only harness graftlint's auditor uses — seconds, no compile) and
  count (a) matmul/conv FLOPs closed-form per ``dot_general`` /
  ``conv_general_dilated`` (2·B·M·N·K, scan trip counts multiplied in), and
  (b) per-device collective bytes BY KIND with the standard wire conventions
  below. Bytes-moved, not FLOPs, is the lever for the memory-bound parts of
  this workload ("Dissecting Embedding Bag Performance in DLRM Inference",
  PAPERS.md) — so the comm traffic gets first-class, per-kind accounting.
- :func:`attribution_of_compiled` reads an already-compiled executable:
  XLA's own ``cost_analysis()`` (executed FLOPs / post-fusion bytes accessed)
  plus ``utils.profiling.memory_stats_of_compiled`` (peak temp HBM).

Per-device collective wire bytes, for a collective whose PER-SHARD operand is
``s`` bytes over a mesh axis (or axes) of total size ``W``:

==================  =======================  =================================
primitive           bytes per device         rationale
==================  =======================  =================================
all_gather          ``(W-1)·s``              each device receives W-1 shards
ppermute            ``s``                    one shard sent, one received
psum                ``2·s·(W-1)/W``          ring all-reduce (reduce-scatter
                                             + all-gather of 1/W chunks)
psum_scatter        ``s·(W-1)/W``            ring reduce-scatter
all_to_all          ``s·(W-1)/W``            every device keeps 1/W locally
==================  =======================  =================================

:func:`roofline_estimate` turns (flops, comm bytes, optionally bytes
accessed) into a chip-free roofline: per-term times against a target chip's
peak MXU rate / HBM bandwidth / ICI bandwidth, ``mfu_est`` = the MFU the
config cannot exceed on that chip, and ``bound`` naming the limiting
resource. ``device_kind`` defaults to the repo's target chip (v5e) so the
estimate exists on CPU-only hosts — that is the point: the next driver-
verified number arrives with its attribution already pinned, and until it
does, every train metrics line and bench record carries the estimate.

``bytes_est`` (trace-only) sums operand+result bytes per equation with scan
multipliers — a fusion-ignorant UPPER bound on HBM traffic, reported but
deliberately NOT fed into ``mfu_est`` (post-fusion truth is 5-20× lower;
use the compiled ``bytes_accessed`` when an executable is at hand).
"""

from __future__ import annotations

import math
from typing import Iterable

__all__ = [
    "CHIP_SPECS",
    "DEFAULT_CHIP",
    "COLLECTIVE_KINDS",
    "jaxpr_costs",
    "static_attribution",
    "attribution_of_compiled",
    "roofline_estimate",
    "step_config_attribution",
    "metrics_line_fields",
]

# device_kind -> (peak dense bf16 TFLOP/s, HBM GB/s, aggregate ICI GB/s per
# chip). Public spec-sheet figures; the TFLOP/s column matches bench.py's
# PEAK_BF16_TFLOPS so MFU and mfu_est share one basis.
CHIP_SPECS = {
    "TPU v4": (275.0, 1228.0, 300.0),
    "TPU v5 lite": (197.0, 819.0, 200.0),
    "TPU v5e": (197.0, 819.0, 200.0),
    "TPU v5": (459.0, 2765.0, 400.0),
    "TPU v5p": (459.0, 2765.0, 400.0),
    "TPU v6 lite": (918.0, 1640.0, 400.0),
    "TPU v6e": (918.0, 1640.0, 400.0),
}

# The repo's roofline target (VERDICT r5 / docs/PERF.md argue against it):
# estimates on chip-less hosts are computed for this part.
DEFAULT_CHIP = "TPU v5 lite"

COLLECTIVE_KINDS = (
    "all_gather", "ppermute", "psum", "psum_scatter", "all_to_all",
)

# Wire-bytes factor as a function of axis size W, per primitive family.
_WIRE_FACTORS = {
    "all_gather": lambda w: w - 1,
    "ppermute": lambda w: 1.0,
    "psum": lambda w: 2.0 * (w - 1) / w,
    "psum_scatter": lambda w: (w - 1) / w,
    "reduce_scatter": lambda w: (w - 1) / w,
    "all_to_all": lambda w: (w - 1) / w,
    "pgather": lambda w: w - 1,
    "pbroadcast": lambda w: (w - 1) / w,
}

# Primitive name -> the kind bucket it reports under.
_KIND_OF = {
    "all_gather": "all_gather",
    "pgather": "all_gather",
    "ppermute": "ppermute",
    "psum": "psum",
    "psum_scatter": "psum_scatter",
    "reduce_scatter": "psum_scatter",
    "all_to_all": "all_to_all",
    "pbroadcast": "all_to_all",
}


def _aval_bytes(v) -> float:
    aval = getattr(v, "aval", None)
    if aval is None:
        return 0.0
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0.0
    return float(size) * getattr(dtype, "itemsize", 4)


def _collective_axes(eqn) -> tuple:
    axes = eqn.params.get("axes", eqn.params.get("axis_name"))
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    flat = []
    for a in axes:
        if isinstance(a, (tuple, list)):
            flat.extend(a)
        else:
            flat.append(a)
    return tuple(a for a in flat if isinstance(a, str))


def _dot_general_flops(eqn) -> float:
    """2·B·M·N·K for one dot_general application."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = getattr(eqn.invars[0], "aval", None)
    rhs = getattr(eqn.invars[1], "aval", None)
    if lhs is None or rhs is None:
        return 0.0
    ls, rs = lhs.shape, rhs.shape
    batch = math.prod(ls[i] for i in lb) if lb else 1
    k = math.prod(ls[i] for i in lc) if lc else 1
    m = math.prod(
        d for i, d in enumerate(ls) if i not in lc and i not in lb
    )
    n = math.prod(
        d for i, d in enumerate(rs) if i not in rc and i not in rb
    )
    return 2.0 * batch * m * n * k


def _conv_flops(eqn) -> float:
    """2 · |out| · (MACs per output element) for conv_general_dilated."""
    out = getattr(eqn.outvars[0], "aval", None)
    rhs = getattr(eqn.invars[1], "aval", None)
    if out is None or rhs is None:
        return 0.0
    dn = eqn.params.get("dimension_numbers")
    try:
        out_features = rhs.shape[dn.rhs_spec[0]]
    except Exception:
        out_features = rhs.shape[-1]
    macs_per_out = math.prod(rhs.shape) / max(1, out_features)
    return 2.0 * math.prod(out.shape) * macs_per_out


def _jaxpr_of(obj):
    if hasattr(obj, "eqns") and hasattr(obj, "invars"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def _sub_jaxprs(params: dict):
    out = []
    for k, v in params.items():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for u in vals:
            j = _jaxpr_of(u)
            if j is not None:
                out.append(j)
    return out


class _Costs:
    __slots__ = ("flops", "bytes_est", "comm")

    def __init__(self):
        self.flops = 0.0
        self.bytes_est = 0.0
        self.comm = {k: 0.0 for k in COLLECTIVE_KINDS}


def _walk(jaxpr, bound: dict, mult: float, acc: _Costs) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name

        if name == "shard_map":
            inner_bound = dict(bound)
            mesh = eqn.params.get("mesh")
            auto = eqn.params.get("auto") or frozenset()
            try:
                inner_bound.update({
                    ax: sz for ax, sz in dict(mesh.shape).items()
                    if ax not in auto
                })
            except Exception:
                pass
            inner = _jaxpr_of(eqn.params.get("jaxpr"))
            if inner is not None:
                _walk(inner, inner_bound, mult, acc)
            continue

        if name == "scan":
            body = _jaxpr_of(eqn.params.get("jaxpr"))
            length = float(eqn.params.get("length", 1) or 1)
            if body is not None:
                _walk(body, bound, mult * length, acc)
            continue

        if name == "pallas_call":
            # The kernel body runs once PER GRID STEP: walk its jaxpr (the
            # per-tile dots are ordinary dot_general eqns there) with the
            # grid product as multiplier — closed-form exact for the loss
            # kernels (grid · 2·tile_b·tile_n·d == 2·b·n·d), the same
            # trip-count treatment the scan case gives the chunked path.
            # Leaving it opaque is how mfu_est undercounted every
            # --use-pallas record before round 10.
            body = _jaxpr_of(eqn.params.get("jaxpr"))
            grid = getattr(eqn.params.get("grid_mapping"), "grid", ()) or ()
            steps = 1.0
            for g in grid:
                try:
                    steps *= float(int(g))
                except (TypeError, ValueError):
                    pass  # dynamic grid dim: count the body once (lower bound)
            if body is not None:
                _walk(body, bound, mult * max(steps, 1.0), acc)
            continue

        if name == "cond":
            # Branches are alternatives, not a sequence: charge the costliest
            # one (the conservative upper bound for a static estimate).
            best = None
            for br in eqn.params.get("branches", ()):
                inner = _jaxpr_of(br)
                if inner is None:
                    continue
                sub = _Costs()
                _walk(inner, bound, mult, sub)
                score = sub.flops + sub.bytes_est + sum(sub.comm.values())
                if best is None or score > (
                    best.flops + best.bytes_est + sum(best.comm.values())
                ):
                    best = sub
            if best is not None:
                acc.flops += best.flops
                acc.bytes_est += best.bytes_est
                for k, v in best.comm.items():
                    acc.comm[k] += v
            continue

        if name in _KIND_OF:
            axes = _collective_axes(eqn)
            w = 1
            for ax in axes:
                w *= int(bound.get(ax, 1))
            if w > 1:
                factor = _WIRE_FACTORS[name](w)
                s = sum(_aval_bytes(v) for v in eqn.invars)
                acc.comm[_KIND_OF[name]] += factor * s * mult
            continue

        subs = _sub_jaxprs(eqn.params)
        if subs:
            # Call-like eqns (pjit / remat2 / custom_vjp / while bodies):
            # recurse only — counting the call's own operand bytes would
            # double what the body already counts. while trip counts are
            # unknowable statically; its body is charged once (documented).
            for inner in subs:
                _walk(inner, bound, mult, acc)
            continue

        if name == "dot_general":
            acc.flops += _dot_general_flops(eqn) * mult
        elif name == "conv_general_dilated":
            acc.flops += _conv_flops(eqn) * mult
        acc.bytes_est += (
            sum(_aval_bytes(v) for v in eqn.invars)
            + sum(_aval_bytes(v) for v in eqn.outvars)
        ) * mult


def jaxpr_costs(jaxpr_or_closed, bound_axes: dict | None = None) -> dict:
    """Walk one (closed) jaxpr into the static cost dict.

    Returns ``{"flops_est", "bytes_est", "comm_bytes_total",
    "comm_bytes_all_gather", "comm_bytes_ppermute", "comm_bytes_psum",
    "comm_bytes_psum_scatter", "comm_bytes_all_to_all"}`` — flops/bytes are
    PER DEVICE (shard_map bodies trace per-shard shapes; the GSPMD outer
    program is counted at its global shapes, which for the dp-replicated
    towers of this repo is the per-device program too).
    """
    j = _jaxpr_of(jaxpr_or_closed)
    if j is None:
        raise TypeError(f"not a jaxpr: {jaxpr_or_closed!r}")
    acc = _Costs()
    _walk(j, dict(bound_axes or {}), 1.0, acc)
    out = {
        "flops_est": acc.flops,
        "bytes_est": acc.bytes_est,
        "comm_bytes_total": sum(acc.comm.values()),
    }
    for kind in COLLECTIVE_KINDS:
        out[f"comm_bytes_{kind}"] = acc.comm[kind]
    return out


def static_attribution(fn, *args, bound_axes: dict | None = None) -> dict:
    """Trace ``fn(*args)`` (abstract — ShapeDtypeStructs work) and return its
    :func:`jaxpr_costs`. The trace-only path: seconds, no compile, CPU-safe —
    what cmd_train stamps onto every metrics line."""
    import jax

    return jaxpr_costs(jax.make_jaxpr(fn)(*args), bound_axes=bound_axes)


def attribution_of_compiled(compiled) -> dict:
    """What XLA says about an already-compiled executable: executed FLOPs and
    post-fusion bytes accessed (``cost_analysis``), plus the static memory
    accounting (``memory_stats_of_compiled`` — ``temp_size_in_bytes`` is the
    peak-temp figure memory optimizations are judged by). Fields are None
    when the backend withholds the analysis."""
    from distributed_sigmoid_loss_tpu.utils.profiling import (
        memory_stats_of_compiled,
    )

    out = {"flops_exec": None, "bytes_accessed": None}
    try:
        cost = compiled.cost_analysis()
        if cost:
            if cost.get("flops", 0) > 0:
                out["flops_exec"] = float(cost["flops"])
            ba = cost.get("bytes accessed", 0)
            if ba > 0:
                out["bytes_accessed"] = float(ba)
    except Exception:
        pass
    mem = memory_stats_of_compiled(compiled)
    out["peak_temp_bytes"] = mem["temp_size_in_bytes"] if mem else None
    out["peak_bytes"] = mem["peak_bytes"] if mem else None
    return out


def roofline_estimate(
    flops: float,
    comm_bytes_total: float,
    bytes_accessed: float | None = None,
    device_kind: str | None = None,
) -> dict:
    """Chip-free roofline: per-resource step-time lower bounds against the
    target chip, the limiting resource, and ``mfu_est`` — the MFU ceiling the
    program's arithmetic/traffic ratio permits there. ``mfu_est`` is an
    upper bound on achievable MFU, not a prediction of the measured one
    (overlap, dispatch and kernel overheads only lower it further)."""
    kind = device_kind if device_kind in CHIP_SPECS else DEFAULT_CHIP
    tflops, hbm_gbps, ici_gbps = CHIP_SPECS[kind]
    compute_s = flops / (tflops * 1e12)
    comm_s = comm_bytes_total / (ici_gbps * 1e9)
    mem_s = (bytes_accessed or 0.0) / (hbm_gbps * 1e9)
    terms = {"compute": compute_s, "comm": comm_s, "memory": mem_s}
    t_bound = max(terms.values())
    bound = max(terms, key=terms.get) if t_bound > 0 else "compute"
    mfu_est = (compute_s / t_bound) if t_bound > 0 else 0.0
    return {
        "mfu_est": round(mfu_est, 3),
        "bound": bound,
        "est_step_ms_lower_bound": round(t_bound * 1e3, 3),
        "roofline_chip": kind,
    }


def step_config_attribution(
    n_devices: int | None = None,
    labels: Iterable[str] | None = None,
    device_kind: str | None = None,
) -> dict:
    """Static attribution for the step configs graftlint already enumerates.

    Reuses ``analysis/jaxpr_audit.step_config_jaxprs`` (the REAL step
    builders traced abstractly on the virtual CPU mesh) — label ->
    ``jaxpr_costs`` + ``roofline_estimate``. Trace-only; the compiled-side
    fields (peak temp) come from :func:`attribution_of_compiled` on whatever
    executable the caller actually compiles.
    """
    from distributed_sigmoid_loss_tpu.analysis.jaxpr_audit import (
        step_config_jaxprs,
    )

    jaxprs = step_config_jaxprs(n_devices)
    want = set(labels) if labels is not None else set(jaxprs)
    out = {}
    for label, (closed, _kwargs) in jaxprs.items():
        if label not in want:
            continue
        costs = jaxpr_costs(closed)
        costs.update(roofline_estimate(
            costs["flops_est"], costs["comm_bytes_total"],
            device_kind=device_kind,
        ))
        out[label] = costs
    return out


def metrics_line_fields(costs: dict, device_kind: str | None = None) -> dict:
    """The two attribution scalars every train metrics line carries:
    ``mfu_est`` (roofline ceiling on the target chip) and
    ``comm_bytes_total`` (per-device wire bytes per step)."""
    est = roofline_estimate(
        costs["flops_est"], costs["comm_bytes_total"], device_kind=device_kind
    )
    return {
        "mfu_est": est["mfu_est"],
        "comm_bytes_total": float(costs["comm_bytes_total"]),
    }
