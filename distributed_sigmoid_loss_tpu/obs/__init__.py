"""graftscope: the unified observability layer — host tracing spans, static
step attribution, a training health watchdog, and the metrics schema.

Four parts, one goal — every perf or robustness claim arrives with its
evidence attached, chip or no chip:

- :mod:`.spans` — thread-safe ring-buffered host spans (train loop stages,
  serve per-request stages) with Chrome-trace export that overlays the
  device captures from ``utils.profiling.trace``; merged offline by the
  ``obs summarize`` CLI subcommand.
- :mod:`.attribution` — static per-step FLOPs, bytes, and per-kind
  collective wire bytes from the traced jaxpr (no compile), plus compiled-
  executable cost/memory readout, and the chip-free roofline ``mfu_est``
  stamped on every train metrics line and bench record.
- :mod:`.health` — host-side NaN/Inf + loss-spike watchdog emitting
  structured events, and the flight recorder that dumps the last N metrics
  lines on crash/SIGTERM through the resilience path.
- :mod:`.metrics_schema` — the declared registry of every train-metrics and
  serve-stats field, validated at emit by ``MetricsLogger`` and enforced
  statically by graftlint's ``repo-metrics-schema`` rule.
- :mod:`.ledger` — graftledger: the append-only JSONL perf-trajectory ledger
  every bench emit path appends to (record + environment fingerprint +
  explicit status, so a dead backend lands as ``no-backend`` instead of a
  0.0 "measurement"); summarized/diffed by ``obs ledger`` / ``obs diff``.
- :mod:`.regress` — chip-free regression gates: the config lattice's proxy
  metrics (closed-form FLOPs, per-kind wire bytes, mfu_est, loss-island
  temp bytes) vs committed baselines, run by ``obs regress`` in CI/dryrun.
- :mod:`.telemetry` — live pull-based metrics: the OpenMetrics-style
  ``/metrics`` exporter the serving stack mounts, plus the atomic-rename
  telemetry file the train loop writes under ``--obs-dir``.
- :mod:`.lockwatch` — graftguard's runtime half: the ``named_lock`` factory
  every host-stack lock routes through, a Goodlock-style potential-deadlock
  witness recording the runtime lock-acquisition graph when
  ``DSL_LOCKWATCH=1`` (raw ``threading.Lock`` otherwise — proven dead in
  prod by the ``repo-lockwatch-gate`` lint), and the ``WATCHED_LOCKS``
  inventory docs/SERVING.md's threading model is sourced from.

Import discipline: this package must stay importable without initializing
jax (the linter and the CLI's argparse layer import the schema); anything
jax-touching lives behind function-level imports in :mod:`.attribution`
and :mod:`.regress`.
"""

from distributed_sigmoid_loss_tpu.obs.health import (  # noqa: F401
    FlightRecorder,
    HealthEvent,
    HealthWatchdog,
)
from distributed_sigmoid_loss_tpu.obs.metrics_schema import (  # noqa: F401
    HEALTH_EVENT_FIELDS,
    SERVE_STATS_FIELDS,
    TRAIN_METRICS_FIELDS,
    TRAIN_METRICS_PREFIXES,
    validate_metrics,
)
from distributed_sigmoid_loss_tpu.obs.lockwatch import (  # noqa: F401
    WATCHED_LOCKS,
    WitnessGraph,
    lockwatch_enabled,
    named_condition,
    named_lock,
    named_rlock,
    watched_lock,
    witness,
)
from distributed_sigmoid_loss_tpu.obs.ledger import (  # noqa: F401
    append_record,
    backfill_round_files,
    diff_records,
    environment_fingerprint,
    read_ledger,
    record_status,
    trajectory,
    trajectory_summary,
)
from distributed_sigmoid_loss_tpu.obs.spans import (  # noqa: F401
    Span,
    SpanRecorder,
    merge_chrome_traces,
    summarize_spans,
)
from distributed_sigmoid_loss_tpu.obs.telemetry import (  # noqa: F401
    TelemetryExporter,
    render_openmetrics,
    write_telemetry_file,
)

__all__ = [
    "Span",
    "SpanRecorder",
    "summarize_spans",
    "merge_chrome_traces",
    "HealthWatchdog",
    "HealthEvent",
    "FlightRecorder",
    "TRAIN_METRICS_FIELDS",
    "TRAIN_METRICS_PREFIXES",
    "SERVE_STATS_FIELDS",
    "HEALTH_EVENT_FIELDS",
    "validate_metrics",
    "append_record",
    "read_ledger",
    "record_status",
    "backfill_round_files",
    "trajectory",
    "trajectory_summary",
    "diff_records",
    "environment_fingerprint",
    "TelemetryExporter",
    "render_openmetrics",
    "write_telemetry_file",
    "WATCHED_LOCKS",
    "WitnessGraph",
    "lockwatch_enabled",
    "named_lock",
    "named_rlock",
    "named_condition",
    "watched_lock",
    "witness",
]
