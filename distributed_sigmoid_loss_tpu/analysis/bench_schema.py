"""THE declared schema for bench.py's JSON record fields.

Every bench mode (train headline, eval-throughput, context, step/MoE
breakdowns, backend-error and shield-deferral records) emits one-line JSON
records that downstream per-metric streams parse. Before this schema each
emit path grew fields independently, so a new config knob (quant_train,
loss_impl, ring_overlap, ...) could land in one path and silently drift from
the others — the exact per-path divergence the bench shield's ADVICE round-5
findings came from.

One registry, three consumers:

- ``bench.py`` routes every record through ``_emit`` → :func:`validate_record`
  (stderr warning on violation; the record still prints — a measurement must
  never be lost to its own validator).
- ``tests/test_bench_shield.py`` / ``tests/test_analysis.py`` assert example
  records from each emit path validate.
- ``analysis/repo_lint.py`` statically cross-checks every record-field string
  literal in bench.py against this registry (rule ``repo-bench-record``), so
  an unregistered field fails tier-1 before it ever runs on a chip.

Stdlib-only module: bench.py's top-level imports must not initialize jax.
"""

from __future__ import annotations

__all__ = [
    "REQUIRED_RECORD_FIELDS",
    "BENCH_RECORD_FIELDS",
    "validate_record",
]

# Present in EVERY record, including error/deferral stubs: the driver's
# one-JSON-line contract keys streams by `metric` and plots `value`/`unit`.
REQUIRED_RECORD_FIELDS = ("metric", "value", "unit")

# The full registered field set, grouped by the emit path that owns them.
# Adding a record field to bench.py without registering it here fails the
# repo-bench-record lint rule (and the schema tests).
BENCH_RECORD_FIELDS = frozenset(
    REQUIRED_RECORD_FIELDS
    + (
        # shared across modes
        "vs_baseline", "model", "steps", "device_kind", "error",
        # train headline
        "a100_ref_pairs_per_sec", "per_chip_batch", "global_batch",
        "accum_steps", "accum_negatives", "steps_per_call", "variant",
        "loss_family", "precision", "use_pallas", "remat_policy",
        "n_devices", "final_loss", "model_tflops_per_sec_per_chip",
        "peak_hbm_gb", "peak_hbm_live_gb", "scan_layers", "attn_impl",
        "text_attn_impl", "attn_bwd", "attn_bwd_argv", "attn_bwd_mismatch",
        "attn_bwd_traced", "pallas_engaged", "pallas_mismatch",
        "moe_experts", "moe_num_selected",
        "moe_group_size", "moe_capacity_factor", "quant_train", "loss_impl",
        "ring_overlap", "zero1", "update_sharding",
        "opt_mem_bytes_per_replica", "adam_mu_dtype", "accum_dtype",
        "gradcache_embed_dtype", "no_text_remat",
        "hw_tflops_per_sec_per_chip", "mfu", "hw_util",
        # train headline, compressed DCN sync (--grad-compression): the
        # config axes plus the step's wire accounting — per-device egress
        # bytes/round, payload bits/param, per-scheme tensor counts, the EF
        # residual norm, and the controller's bandwidth EWMA.
        "grad_compression", "dcn_slices", "dcn_budget_mbps", "topk_frac",
        "dcn_wire_bytes", "bits_per_param", "compression_scheme_hist",
        "ef_residual_norm", "dcn_bw_est_mbps",
        # graftcodec (--controller / --emu-dcn-mbps): the controller policy
        # axis + its spent loss-impact budget, the learned rung's
        # reconstruction error, and the emulated-DCN measurements — the
        # throttle setting, the bandwidth MEASURED through the pipe, and the
        # wall-clock step-time ratio vs the fixed-bf16 reference transfer
        # (> 1 = adaptive saves wall clock at that bandwidth).
        "controller_mode", "error_budget", "codec_recon_err",
        "emu_dcn_mbps", "dcn_measured_mbps", "wire_savings_wallclock_ratio",
        # eval-throughput
        "batch", "quant", "fwd_tflops_per_sec_per_chip", "mfu_bf16_basis",
        # context bench
        "context", "width", "num_heads", "impls",
        # step breakdown
        "parts",
        # moe breakdown
        "dense_mlp_ms", "stages", "tokens", "experts", "num_selected",
        "group", "capacity",
        # shield deferral records
        "deferred", "signal", "child_pid", "child_stdout", "child_stderr",
        # data-bench (stage + composed-pipeline records, data/data_bench.py)
        "stage", "data_workers", "native_decode", "worker_scaling",
        "synthetic_pairs_per_sec", "synthetic_ratio", "input_wait_frac",
        "pipelined", "read_ahead", "zero_copy", "bound_stage",
        # graftscope static attribution (obs/attribution.py): the chip-free
        # roofline estimate + per-kind collective wire bytes stamped on the
        # train headline record (and every train metrics line)
        "mfu_est", "roofline_bound", "comm_bytes_total",
        "comm_bytes_all_gather", "comm_bytes_ppermute", "comm_bytes_psum",
        "comm_bytes_psum_scatter", "comm_bytes_all_to_all",
        # serve-bench record (cli.py cmd_serve_bench: invocation fields +
        # the serve stats() snapshot spread in — the snapshot's own field
        # set is declared in obs/metrics_schema.py SERVE_STATS_FIELDS and
        # mirrored here so the one-JSON-line record validates end to end;
        # stage_latency_ms carries the per-stage p50/p95/p99 percentiles)
        "clients", "requests_sent", "batch_buckets", "max_wait_ms",
        "sharded", "warmup_s", "uptime_s", "requests", "items", "qps",
        "items_per_sec", "latency_ms", "batch_size_hist", "stage_latency_ms",
        "rejected", "timeouts", "compile_count", "bucket_space", "index_size",
        "cache",
        # serve/distindex (RetrievalRouter through cmd_serve_bench): the
        # retrieval tier + churn-mode invocation fields and the router's
        # stats fields the snapshot spread carries (mirrored from
        # obs/metrics_schema.py SERVE_STATS_FIELDS).
        "index_tier", "swap_every", "index_version", "shard_count",
        "swap_count", "swap_latency_ms", "recall_at_k", "rerank_k",
        "search_stage_latency_ms",
        # graftsiege (serve/siege.py run_scenario through cmd_serve_bench
        # --scenario): the degradation record — scenario identity + offered
        # load, the trailing shed rate, per-tenant outcome rows (sent / ok /
        # shed / typed_errors / p99 vs slo), host-loss recovery time, and
        # the zero-silent-drops counter the acceptance drill asserts on;
        # plus the admission/swap fields the stats() snapshot spread carries
        # (mirrored from obs/metrics_schema.py SERVE_STATS_FIELDS).
        "scenario", "offered_load", "duration_s", "tenants", "per_tenant",
        "shed_rate", "recovery_time_s", "silent_drops", "restarts",
        "shed", "admission", "swap_in_flight", "inflight",
        # graftfleet (serve/fleet/scenarios.py run_fleet_scenario through
        # cmd_serve_bench --fleet-scenario): the fleet_siege record — the
        # router/wave/lease stats snaps (mirrored from SERVE_STATS_FIELDS)
        # plus the invocation fields and the over-admission evidence: the
        # global rate ceiling, the peak admitted rate any sliding window
        # saw, and the count of windows that exceeded ceiling + burst
        # (asserted zero — the bounded-staleness lease proof).
        "replica_count", "healthy_replicas", "reroutes", "affinity_hits",
        "lease_epoch", "lease_reclaims", "wave_id", "fleet_replicas",
        "lease_ttl_s", "ceiling_rate", "peak_admitted_rate",
        "over_ceiling_samples",
    )
)


def validate_record(record) -> list[str]:
    """Validate one bench JSON record against the declared schema.

    Returns a list of problem strings (empty = valid). Field VALUES are not
    typed here — the schema pins the field NAMESPACE, which is what drifts.
    """
    if not isinstance(record, dict):
        return [f"record must be a dict, got {type(record).__name__}"]
    problems = []
    for field in REQUIRED_RECORD_FIELDS:
        if field not in record:
            problems.append(f"missing required field {field!r}")
    unknown = sorted(set(record) - BENCH_RECORD_FIELDS)
    if unknown:
        problems.append(
            "unregistered field(s) "
            + ", ".join(repr(u) for u in unknown)
            + " — register in analysis/bench_schema.py BENCH_RECORD_FIELDS"
        )
    return problems
