"""graftguard: lock-discipline static analysis for the threaded host stack.

The serving/obs/data tier is 16+ hand-locked modules, and PR 12's
first-request token-bucket bug (a lock-free read of a lazily-stamped clock)
is exactly the class a guarded-by analysis catches before a chaos drill
does. Five rules, all pure-AST and jax-free (the repo_lint discipline —
explicit source inputs so tests falsify each rule on a known-bad fixture;
the defaults audit the real package):

- ``lock-unguarded-write``: for every class owning a ``Lock``/``RLock``/
  ``Condition`` (raw or via the ``named_lock`` family), the attributes
  mutated inside ``with self._lock`` blocks form its GUARDED set; any
  mutation or compound read-modify-write of a guarded attribute outside the
  lock (``__init__`` construction exempt) is a finding. Plain reads are NOT
  flagged: lock-free snapshot reads of atomically-published references are
  a documented repo idiom (the router's ``_current``, the engine's
  ``params``).
- ``lock-wait-no-loop``: a ``Condition.wait()`` not wrapped in a ``while``
  predicate loop — spurious/steal wakeups make un-looped waits a liveness
  bug (``wait_for`` carries its own loop and is exempt).
- ``lock-blocking-hold``: a blocking call (``Future.result``, pipe
  ``recv``/``poll``, ``join``, queue ``get``/``put``, ``sleep``, jax
  dispatch) made while holding a lock — the convoy/deadlock feeder class.
- ``lock-orphan-thread``: a ``threading.Thread`` started with no join/close
  path (self-attribute threads need a ``self.<attr>.join`` somewhere in the
  class; function-local threads need a ``join`` in the same function).
- ``lock-order-cycle``: the cross-module lock-acquisition graph built from
  lexically nested ``with`` statements over distinct owned locks (class
  attributes, module-level locks, function-local locks); any cycle is a
  potential deadlock. The runtime half — cross-call-graph orders no AST can
  see — is obs/lockwatch.py's witness (``DSL_LOCKWATCH=1``).

Plus ``repo-lockwatch-gate`` (the ``repo-chaos-gate`` pattern): lockwatch
instrumentation provably dead in prod — the ``named_lock`` factories must
consult ``lockwatch_enabled()``, which must key on ``DSL_LOCKWATCH``; every
call site passes a registered string-constant name; registry rows carry
non-empty what-it-guards rationales and stale rows fail; and NO module may
construct ``threading.Lock/RLock/Condition`` directly outside
obs/lockwatch.py — unroutered locks are invisible to the witness.

Findings suppressed by ``LOCK_ALLOWLIST`` need a rationale; stale entries
are findings (the repo-mutable-global pattern). Catalog + allowlist policy:
docs/ANALYSIS.md.
"""

from __future__ import annotations

import ast
import os
import re

from distributed_sigmoid_loss_tpu.analysis.findings import Finding
from distributed_sigmoid_loss_tpu.analysis.repo_lint import (
    _iter_package_sources,
)

__all__ = [
    "LOCK_RULES",
    "LOCK_ALLOWLIST",
    "RAW_LOCK_ALLOWLIST",
    "run_lock_flow",
    "analyze_lock_flow",
    "check_lock_order",
    "check_lockwatch_gate",
    "lock_order_edges",
]

LOCK_RULES = (
    "lock-unguarded-write",
    "lock-wait-no-loop",
    "lock-blocking-hold",
    "lock-orphan-thread",
    "lock-order-cycle",
    "repo-lockwatch-gate",
)

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Findings the repo accepts, keyed "<rule>::<subject>", each with the
# rationale the rule's docstring demands. Policy (docs/ANALYSIS.md): a
# blocking-hold is allowlistable only when the lock IS the serialization
# contract for the blocking resource itself; an unguarded write only when
# the attribute is published atomically by a single writer and every reader
# tolerates either value. Stale entries are findings.
LOCK_ALLOWLIST = {
    "lock-unguarded-write::serve/admission.py::AdmissionController._decisions": (
        "_shed() appends to _decisions lexically outside any `with` block, "
        "but its docstring pins the contract — 'caller raises it; lock "
        "already held' — and its only caller (admit) invokes it inside "
        "`with self._lock`; the guarded-by analysis is lexical and cannot "
        "see cross-function holds (the DSL_LOCKWATCH witness can)"
    ),
    "lock-blocking-hold::serve/siege.py::EngineProcess.call": (
        "the Pipe IS the serialized resource: one request/response exchange "
        "per child at a time is the contract, so send→poll(timeout)→recv "
        "must stay inside _lock — poll carries the deadline that bounds the "
        "hold, and a second caller blocking on _lock is exactly the "
        "intended queueing"
    ),
}

# Raw threading.Lock/RLock/Condition constructions repo-lockwatch-gate
# tolerates outside obs/lockwatch.py, keyed "<relpath>::<scope>". Empty on
# the shipped tree: every host-stack lock routes through the named_lock
# factories so the witness sees it. Stale entries are findings.
RAW_LOCK_ALLOWLIST: dict[str, str] = {}

_LOCK_FACTORIES = {"Lock", "RLock", "named_lock", "named_rlock"}
_CONDITION_FACTORIES = {"Condition", "named_condition"}
_ALL_LOCK_FACTORIES = _LOCK_FACTORIES | _CONDITION_FACTORIES

_MUTATING_METHODS = {
    "add", "append", "extend", "update", "clear", "pop", "popitem",
    "remove", "discard", "insert", "setdefault", "appendleft",
    "move_to_end",
}

# Calls that block the calling thread: flagged whenever an owned lock is
# held. `join` skips str.join (constant receiver) and os.path.join;
# `get`/`put` only fire on queue-ish receivers (`q`/`queue`/`*_q[ueue]`) so
# dict.get stays silent; `wait` on a HELD lock/condition is the legitimate
# Condition.wait (releases what it holds) and is exempt.
_BLOCKING_SIMPLE = {
    "result", "recv", "poll", "sleep",
    "block_until_ready", "device_put", "device_get",
}
_QUEUEISH = re.compile(r"(^|_)(q|queue)$", re.IGNORECASE)


def _call_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return None


def _self_attr(expr: ast.AST) -> str | None:
    """'attr' when expr is exactly ``self.attr``."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "self"
    ):
        return expr.attr
    return None


def _self_attr_base(expr: ast.AST) -> str | None:
    """The first-level attribute a self-rooted expression hangs off:
    ``self._versions[v].x`` → '_versions' (mutating any part of an owned
    structure is a mutation of the owning attribute)."""
    while isinstance(expr, (ast.Attribute, ast.Subscript)):
        got = _self_attr(expr)
        if got is not None:
            return got
        expr = expr.value
    return None


def _terminal_name(expr: ast.AST) -> str | None:
    if isinstance(expr, ast.Name):
        return expr.id
    if isinstance(expr, ast.Attribute):
        return expr.attr
    return None


class _ModuleScan:
    """One module's lock-flow facts, collected in a single AST pass."""

    def __init__(self, rel: str, tree: ast.Module):
        self.rel = rel
        # (rule, subject, detail) rows; order-graph edges separately.
        self.findings: list[Finding] = []
        self.order_edges: set[tuple[str, str]] = set()
        # Module-level locks: name -> lock id.
        self.module_locks: dict[str, str] = {}
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _call_name(node.value) in _ALL_LOCK_FACTORIES
            ):
                name = node.targets[0].id
                self.module_locks[name] = f"{rel}::{name}"
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                self._scan_class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_function(node, owner=node.name)

    # -- class analysis ------------------------------------------------------

    def _scan_class(self, cls: ast.ClassDef) -> None:
        rel = self.rel
        lock_attrs: set[str] = set()
        cond_attrs: set[str] = set()
        methods = [
            n for n in cls.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ]
        for m in methods:
            for node in ast.walk(m):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call
                ):
                    fac = _call_name(node.value)
                    if fac not in _ALL_LOCK_FACTORIES:
                        continue
                    for t in node.targets:
                        attr = _self_attr(t)
                        if attr is None:
                            continue
                        lock_attrs.add(attr)
                        if fac in _CONDITION_FACTORIES:
                            cond_attrs.add(attr)
        thread_attrs: dict[str, int] = {}
        joined_attrs: set[str] = set()
        # mutations: (attr, method, line, guarded)
        mutations: list[tuple[str, str, int, bool]] = []

        for m in methods:
            self._scan_function(
                m,
                owner=f"{cls.name}.{m.name}",
                cls_name=cls.name,
                lock_attrs=lock_attrs,
                cond_attrs=cond_attrs,
                mutations=mutations,
                mutations_method=m.name,
                thread_attrs=thread_attrs,
                joined_attrs=joined_attrs,
            )

        guarded = {
            attr for attr, _m, _l, held in mutations
            if held and attr not in lock_attrs
        }
        for attr, method, line, held in mutations:
            if held or attr not in guarded or method == "__init__":
                continue
            self.findings.append(Finding(
                "lock-unguarded-write",
                f"{rel}::{cls.name}.{attr}",
                f"{cls.name}.{method} writes self.{attr} (line {line}) "
                f"without the lock that guards it elsewhere in the class — "
                "a torn/lost update under the serving stack's thread churn "
                "(the PR 12 token-bucket class). Take the lock, or "
                "allowlist with a single-atomic-writer rationale in "
                "analysis/lock_flow.py",
            ))
        for attr, line in sorted(thread_attrs.items()):
            if attr in joined_attrs:
                continue
            self.findings.append(Finding(
                "lock-orphan-thread",
                f"{rel}::{cls.name}.{attr}",
                f"thread self.{attr} (line {line}) is never joined by any "
                f"method of {cls.name} — no close path means shutdown "
                "races the thread and tests leak it across suites; join "
                "it in close()/stop()",
            ))

    # -- function-level walk -------------------------------------------------

    def _scan_function(
        self,
        fn,
        *,
        owner: str,
        cls_name: str | None = None,
        lock_attrs: set[str] | None = None,
        cond_attrs: set[str] | None = None,
        mutations: list | None = None,
        mutations_method: str | None = None,
        thread_attrs: dict | None = None,
        joined_attrs: set | None = None,
    ) -> None:
        rel = self.rel
        lock_attrs = lock_attrs or set()
        cond_attrs = cond_attrs or set()
        blocking_seen: set[tuple[str, str, int]] = set()

        # Function-local locks (incl. ones closures inherit lexically).
        local_locks: dict[str, str] = {}

        def note_local_locks(f) -> None:
            for node in ast.walk(f):
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and _call_name(node.value) in _ALL_LOCK_FACTORIES
                ):
                    name = node.targets[0].id
                    local_locks.setdefault(
                        name, f"{rel}::{owner}.{name}"
                    )

        note_local_locks(fn)

        fn_has_join = [False]
        fn_makes_thread: list[int] = []

        def lock_ref(expr: ast.AST):
            """(kind, key, lock_id) for an expression naming an owned lock."""
            attr = _self_attr(expr)
            if attr is not None and attr in lock_attrs:
                return ("self", attr, f"{rel}::{cls_name}.{attr}")
            if isinstance(expr, ast.Name):
                if expr.id in local_locks:
                    return ("name", expr.id, local_locks[expr.id])
                if expr.id in self.module_locks:
                    return ("name", expr.id, self.module_locks[expr.id])
            return None

        def note_mutation(attr: str, line: int, held) -> None:
            if mutations is not None and attr not in lock_attrs:
                mutations.append(
                    (attr, mutations_method or owner, line,
                     any(h[0] == "self" for h in held))
                )

        def visit(node: ast.AST, held: tuple, in_while: bool) -> None:
            for child in ast.iter_child_nodes(node):
                dispatch(child, held, in_while)

        def dispatch(child: ast.AST, held: tuple, in_while: bool) -> None:
            # Handle ONE node, then recurse. Bodies of with/while are fed
            # back through dispatch (not bare visit) so a statement that is
            # the direct child of a with body — the common `with self._lock:
            # self._n += 1` shape — still gets its own Assign/Call handling.
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                # A nested def/lambda body does not run under the
                # enclosing lexical lock hold (it runs whenever it is
                # CALLED — often on another thread).
                visit(child, (), False)
                return
            if isinstance(child, (ast.With, ast.AsyncWith)):
                cur = held
                for item in child.items:
                    dispatch(item.context_expr, held, in_while)
                    ref = lock_ref(item.context_expr)
                    if ref is None:
                        continue
                    for h in cur:
                        if h[2] != ref[2]:
                            self.order_edges.add((h[2], ref[2]))
                    cur = cur + (ref,)
                for stmt in child.body:
                    dispatch(stmt, cur, in_while)
                return
            if isinstance(child, ast.While):
                dispatch(child.test, held, in_while)
                for stmt in child.body + child.orelse:
                    dispatch(stmt, held, True)
                return

            if isinstance(child, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    child.targets if isinstance(child, ast.Assign)
                    else [child.target]
                )
                value_is_thread = (
                    isinstance(getattr(child, "value", None), ast.Call)
                    and _call_name(child.value) == "Thread"
                )
                for t in targets:
                    base = _self_attr_base(t)
                    if base is not None:
                        note_mutation(base, child.lineno, held)
                        if value_is_thread and thread_attrs is not None:
                            thread_attrs.setdefault(base, child.lineno)

            if isinstance(child, ast.Call):
                self._visit_call(
                    child, held, in_while, owner=owner,
                    cls_name=cls_name, cond_attrs=cond_attrs,
                    note_mutation=note_mutation,
                    joined_attrs=joined_attrs,
                    fn_has_join=fn_has_join,
                    fn_makes_thread=fn_makes_thread,
                    blocking_seen=blocking_seen,
                )

            visit(child, held, in_while)

        dispatch(fn, (), False)

        # Function-local orphan threads: a function that constructs a
        # Thread but contains no .join anywhere (self-attribute threads are
        # judged class-wide above instead).
        if (
            cls_name is None
            and fn_makes_thread
            and not fn_has_join[0]
        ):
            self.findings.append(Finding(
                "lock-orphan-thread",
                f"{rel}::{owner}",
                f"{owner} starts a thread (line {fn_makes_thread[0]}) but "
                "contains no join — no close path; join it (bounded) "
                "before returning, or hand ownership to an object with a "
                "close()",
            ))

    def _visit_call(
        self, call: ast.Call, held: tuple, in_while: bool, *, owner,
        cls_name, cond_attrs, note_mutation, joined_attrs, fn_has_join,
        fn_makes_thread, blocking_seen,
    ) -> None:
        rel = self.rel
        name = _call_name(call)
        if name == "Thread":
            fn_makes_thread.append(call.lineno)
        if name is None or not isinstance(call.func, ast.Attribute):
            return
        recv = call.func.value
        base = _self_attr_base(recv)

        # Mutating-method calls on owned structures.
        if name in _MUTATING_METHODS and base is not None:
            note_mutation(base, call.lineno, held)

        if name == "join":
            fn_has_join[0] = True
            if base is not None and joined_attrs is not None:
                joined_attrs.add(base)

        # Condition.wait outside a predicate loop.
        attr = _self_attr(recv)
        if (
            name == "wait"
            and attr is not None
            and attr in cond_attrs
            and not in_while
        ):
            self.findings.append(Finding(
                "lock-wait-no-loop",
                f"{rel}::{owner}",
                f"Condition self.{attr}.wait() at line {call.lineno} is "
                "not wrapped in a `while <predicate>` loop — spurious and "
                "stolen wakeups make an if/bare wait return with the "
                "predicate false; loop it (or use wait_for)",
            ))

        if not held:
            return
        blocking = None
        if name in _BLOCKING_SIMPLE:
            blocking = name
        elif name == "join":
            terminal = _terminal_name(recv)
            if not isinstance(recv, ast.Constant) and terminal != "path":
                blocking = name
        elif name in ("get", "put"):
            terminal = _terminal_name(recv)
            if terminal is not None and _QUEUEISH.search(terminal):
                blocking = name
        elif name == "wait":
            ref_attr = _self_attr(recv)
            held_keys = {h[1] for h in held if h[0] == "self"}
            held_names = {h[1] for h in held if h[0] == "name"}
            is_held = (
                (ref_attr is not None and ref_attr in held_keys)
                or (isinstance(recv, ast.Name) and recv.id in held_names)
            )
            if not is_held:
                blocking = name
        if blocking is None:
            return
        key = (f"{rel}::{owner}", blocking, call.lineno)
        if key in blocking_seen:
            return
        blocking_seen.add(key)
        held_desc = ", ".join(sorted(h[2].split("::", 1)[1] for h in held))
        self.findings.append(Finding(
            "lock-blocking-hold",
            f"{rel}::{owner}",
            f".{blocking}(...) at line {call.lineno} blocks while holding "
            f"{held_desc} — every thread needing that lock convoys behind "
            "the slow call (and a cycle through the blocked resource is a "
            "deadlock). Move the blocking call outside the lock, or "
            "allowlist with a the-lock-IS-the-contract rationale in "
            "analysis/lock_flow.py",
        ))


def _scan_sources(sources) -> list[_ModuleScan]:
    scans = []
    for rel, src in sorted(sources.items()):
        rel = rel.replace(os.sep, "/")
        scans.append(_ModuleScan(rel, ast.parse(src)))
    return scans


def _default_sources():
    return dict(_iter_package_sources(_PACKAGE_DIR))


def analyze_lock_flow(sources=None) -> list[Finding]:
    """The four guarded-by rules (unguarded-write, wait-no-loop,
    blocking-hold, orphan-thread) over ``{relpath: source}`` — raw findings,
    no allowlist applied (``run_lock_flow`` applies LOCK_ALLOWLIST)."""
    if sources is None:
        sources = _default_sources()
    findings: list[Finding] = []
    for scan in _scan_sources(sources):
        findings.extend(scan.findings)
    return findings


def lock_order_edges(sources=None) -> set[tuple[str, str]]:
    """The static lock-acquisition graph: lexically nested ``with`` over
    distinct owned locks → (outer, inner) edges."""
    if sources is None:
        sources = _default_sources()
    edges: set[tuple[str, str]] = set()
    for scan in _scan_sources(sources):
        edges |= scan.order_edges
    return edges


def check_lock_order(sources=None) -> list[Finding]:
    """lock-order-cycle: any cycle in the static acquisition graph."""
    edges = lock_order_edges(sources)
    graph: dict[str, list[str]] = {}
    for a, b in sorted(edges):
        graph.setdefault(a, []).append(b)
    findings = []
    color: dict[str, int] = {}
    path: list[str] = []
    sigs: set[tuple[str, ...]] = set()

    def visit(start: str) -> None:
        color[start] = 1
        path.append(start)
        stack = [(start, iter(graph.get(start, ())))]
        while stack:
            node, it = stack[-1]
            nxt = next(it, None)
            if nxt is None:
                color[node] = 2
                path.pop()
                stack.pop()
                continue
            c = color.get(nxt, 0)
            if c == 0:
                color[nxt] = 1
                path.append(nxt)
                stack.append((nxt, iter(graph.get(nxt, ()))))
            elif c == 1:
                cyc = tuple(path[path.index(nxt):])
                k = min(range(len(cyc)), key=lambda j: cyc[j:] + cyc[:j])
                sig = cyc[k:] + cyc[:k]
                if sig not in sigs:
                    sigs.add(sig)
                    findings.append(Finding(
                        "lock-order-cycle",
                        " -> ".join(sig + (sig[0],)),
                        "lock-acquisition cycle: two threads entering this "
                        "ring from different locks deadlock. Impose one "
                        "global order (docs/SERVING.md threading model) "
                        "and acquire along it",
                    ))

    for u in sorted(graph):
        if color.get(u, 0) == 0:
            visit(u)
    return findings


# ---------------------------------------------------------------------------
# repo-lockwatch-gate
# ---------------------------------------------------------------------------

_NAMED_FACTORIES = ("named_lock", "named_rlock", "named_condition")


def _watched_registry(tree: ast.Module) -> dict[str, str] | None:
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "WATCHED_LOCKS"
            and isinstance(node.value, ast.Dict)
        ):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (
                    isinstance(k, ast.Constant) and isinstance(k.value, str)
                ):
                    continue
                rationale = ""
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    rationale = v.value
                elif isinstance(v, ast.JoinedStr):
                    rationale = "<dynamic>"
                out[k.value] = rationale
            return out
    return None


def _calls_name(fn: ast.AST, target: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == target:
                return True
            if isinstance(f, ast.Attribute) and f.attr == target:
                return True
    return False


def _scoped_walk(tree: ast.Module):
    """(node, scope) pairs where scope is the enclosing def/class qualname
    (or '<module>')."""

    def rec(node, scope):
        for child in ast.iter_child_nodes(node):
            child_scope = scope
            if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ):
                child_scope = (
                    child.name if scope == "<module>"
                    else f"{scope}.{child.name}"
                )
            yield child, scope
            yield from rec(child, child_scope)

    yield from rec(tree, "<module>")


def check_lockwatch_gate(
    lockwatch_source: str | None = None,
    sources=None,
    raw_allowlist=None,
) -> list[Finding]:
    """repo-lockwatch-gate: the witness provably dead in prod, the registry
    an honest inventory, and every lock visible to it.

    Five statically-checkable halves: (a) the ``named_lock`` factory family
    must consult ``lockwatch_enabled()``, and ``lockwatch_enabled`` must key
    on the documented ``DSL_LOCKWATCH`` env hook; (b) every ``WATCHED_LOCKS``
    row carries a non-empty what-it-guards rationale; (c) every factory call
    site in the package passes a registered STRING CONSTANT name; (d) no
    registry row is stale (registered but never constructed — a lock the
    docs describe but the code dropped); (e) no module outside
    obs/lockwatch.py constructs ``threading.Lock/RLock/Condition`` directly
    unless allowlisted — a raw lock is invisible to the witness AND to the
    docs' threading model.
    """
    if lockwatch_source is None:
        with open(
            os.path.join(_PACKAGE_DIR, "obs", "lockwatch.py"),
            encoding="utf-8",
        ) as f:
            lockwatch_source = f.read()
    if sources is None:
        sources = _default_sources()
    raw_allowlist = (
        RAW_LOCK_ALLOWLIST if raw_allowlist is None else raw_allowlist
    )
    findings = []
    lw_tree = ast.parse(lockwatch_source)
    fns = {
        node.name: node
        for node in ast.walk(lw_tree)
        if isinstance(node, ast.FunctionDef)
    }

    # (a) the gate itself.
    for fac in _NAMED_FACTORIES:
        if fac not in fns:
            findings.append(Finding(
                "repo-lockwatch-gate", f"obs/lockwatch.py::{fac}",
                f"no {fac} function found — the lock factory family is "
                "incomplete and call sites would crash",
            ))
        elif not _calls_name(fns[fac], "lockwatch_enabled"):
            findings.append(Finding(
                "repo-lockwatch-gate", f"obs/lockwatch.py::{fac}",
                f"{fac} does not consult lockwatch_enabled() — it would "
                "hand out instrumented locks in production; gate it",
            ))
    if "lockwatch_enabled" not in fns:
        findings.append(Finding(
            "repo-lockwatch-gate", "obs/lockwatch.py::lockwatch_enabled",
            "no lockwatch_enabled function found — nothing defines the "
            "DSL_LOCKWATCH gate",
        ))
    elif not any(
        isinstance(n, ast.Constant) and n.value == "DSL_LOCKWATCH"
        for n in ast.walk(fns["lockwatch_enabled"])
    ):
        findings.append(Finding(
            "repo-lockwatch-gate", "obs/lockwatch.py::lockwatch_enabled",
            "lockwatch_enabled does not reference the 'DSL_LOCKWATCH' env "
            "hook — the documented off-switch is not what the gate checks",
        ))

    # (b) the registry + rationales.
    registry = _watched_registry(lw_tree)
    if registry is None:
        findings.append(Finding(
            "repo-lockwatch-gate", "obs/lockwatch.py::WATCHED_LOCKS",
            "no WATCHED_LOCKS dict found — the lock inventory (and the "
            "SERVING.md threading model it sources) is gone",
        ))
        registry = {}
    for name, rationale in sorted(registry.items()):
        if not rationale.strip():
            findings.append(Finding(
                "repo-lockwatch-gate", f"obs/lockwatch.py::{name}",
                f"watched lock {name!r} has no rationale — the registry "
                "row must say what the lock guards",
            ))

    used: set[str] = set()
    for rel in sorted(sources):
        rel_norm = rel.replace(os.sep, "/")
        if rel_norm.endswith("obs/lockwatch.py"):
            continue
        tree = ast.parse(sources[rel])
        for node, scope in _scoped_walk(tree):
            if not isinstance(node, ast.Call):
                continue
            cname = _call_name(node)
            # (c) constant, registered factory names.
            if cname in _NAMED_FACTORIES:
                arg = node.args[0] if node.args else None
                if not (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                ):
                    findings.append(Finding(
                        "repo-lockwatch-gate", f"{rel_norm}::{scope}",
                        f"{cname} call at line {node.lineno} passes a "
                        "computed name — unauditable; lock names must be "
                        "string constants registered in WATCHED_LOCKS",
                    ))
                    continue
                used.add(arg.value)
                if arg.value not in registry:
                    findings.append(Finding(
                        "repo-lockwatch-gate", f"{rel_norm}::{arg.value}",
                        f"{cname}({arg.value!r}) at line {node.lineno} is "
                        "not registered in obs/lockwatch.py WATCHED_LOCKS "
                        "— register it with a what-it-guards rationale",
                    ))
            # (e) raw constructions.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("Lock", "RLock", "Condition")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "threading"
            ):
                key = f"{rel_norm}::{scope}"
                if key not in raw_allowlist:
                    findings.append(Finding(
                        "repo-lockwatch-gate", key,
                        f"raw threading.{node.func.attr}() at line "
                        f"{node.lineno} — invisible to the lockwatch "
                        "witness and to the WATCHED_LOCKS inventory; route "
                        "it through obs.lockwatch.named_lock (or allowlist "
                        "with a rationale in analysis/lock_flow.py)",
                    ))

    # (d) stale registry rows.
    for name in sorted(set(registry) - used):
        findings.append(Finding(
            "repo-lockwatch-gate", f"obs/lockwatch.py::{name}",
            f"watched lock {name!r} is registered but no module constructs "
            "it — stale inventory row; drop it or wire the lock back in",
        ))
    # Stale raw allowlist entries: key should have suppressed something.
    seen_raw = {
        f"{rel.replace(os.sep, '/')}" for rel in sources
    }
    for key in sorted(raw_allowlist):
        rel = key.split("::", 1)[0]
        if rel not in seen_raw:
            findings.append(Finding(
                "repo-lockwatch-gate", key,
                "stale raw-lock allowlist entry: module not in the scanned "
                "set — drop it",
            ))
    return findings


def _apply_allowlist(findings, allowlist) -> list[Finding]:
    kept, seen = [], set()
    for f in findings:
        key = f"{f.rule}::{f.subject}"
        if key in allowlist:
            seen.add(key)
        else:
            kept.append(f)
    for key in sorted(set(allowlist) - seen):
        rule, subject = key.split("::", 1)
        kept.append(Finding(
            rule, subject,
            "stale allowlist entry: the finding it suppresses no longer "
            "fires — drop it so LOCK_ALLOWLIST stays an honest inventory",
        ))
    return kept


def run_lock_flow(disabled=()) -> list[Finding]:
    """Run every graftguard rule against the real tree (LOCK_ALLOWLIST
    applied, stale entries flagged)."""
    disabled = set(disabled)
    sources = _default_sources()
    findings: list[Finding] = []
    findings.extend(analyze_lock_flow(sources))
    findings.extend(check_lock_order(sources))
    findings = _apply_allowlist(findings, LOCK_ALLOWLIST)
    if "repo-lockwatch-gate" not in disabled:
        findings.extend(check_lockwatch_gate(sources=sources))
    return [f for f in findings if f.rule not in disabled]
