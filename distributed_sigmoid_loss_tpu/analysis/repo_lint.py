"""graftlint's AST half: repo invariants that are statically checkable.

Every rule here encodes a bug class this repo actually hit (or a contract a
prior PR established), enforced at lint time instead of re-litigated in
review:

- ``repo-mutable-global``: module-level mutable state that can influence
  traced behavior must be allowlisted WITH a rationale naming its traced-choice
  recorder (the ``_DEFAULT_BATCH_HEADS`` bench-record-corruption class —
  ops/pallas_short_attention.py, ADVICE round 5).
- ``repo-bench-shield``: every bench.py flag must be classified — either read
  by ``_fresh_compile_config`` (shield trigger) or listed in
  ``_SHIELD_EXEMPT_FLAGS`` with a rationale. Cross-checked against bench.py's
  ACTUAL argparse tree, not a hand-copied list (the --gradcache-bf16 class:
  a compile-changing flag that bypassed the shield, ADVICE round 5).
- ``repo-doc-stale``: every CLI flag and LossConfig field must appear in
  README.md or docs/ (a flag nobody can discover is a flag nobody A/Bs).
- ``repo-slow-marker``: the registered multi-minute suites must carry the
  module-level ``slow`` marker (protects the 870 s time-boxed tier-1 budget).
- ``repo-bench-record``: every record-field string literal in bench.py must
  be registered in ``analysis/bench_schema.py`` (per-emit-path field drift).
- ``repo-metrics-schema``: every train metrics-line / serve ``stats()`` /
  health-event field literal in the emitting modules must be registered in
  ``obs/metrics_schema.py`` — the same drift class as repo-bench-record, for
  the OTHER two record streams (a metric added in one step builder but not
  declared is invisible to every downstream parser until it breaks one).
- ``repo-ledger-emit``: bench.py's record prints (``print(json.dumps(...))``)
  may happen ONLY inside ``_emit``, and ``_emit`` must append to the run
  ledger (``obs/ledger.py append_record``) — a new emit path that prints its
  own JSON bypasses both the schema validator and the perf trajectory, the
  blind-spot class rounds 4/5 recorded 0.0 into.
- ``repo-chaos-gate``: every fault-injection point in serve/ must be a
  ``maybe_inject("<point>")`` call whose point is a string constant
  registered in ``serve/siege.py CHAOS_POINTS`` with a non-empty rationale,
  ``maybe_inject`` itself must check the ``chaos_enabled()`` gate, and
  ``chaos_enabled`` must key on the ``DSL_CHAOS`` env hook — so injection
  code is provably dead in production paths, and the registry stays an
  honest inventory (stale rows fail too).

All checks take explicit source/path inputs so tests can falsify each rule on
a known-bad fixture; the defaults audit the real repo.
"""

from __future__ import annotations

import ast
import os

from distributed_sigmoid_loss_tpu.analysis.findings import Finding

__all__ = [
    "REPO_RULES",
    "run_repo_lint",
    "check_mutable_globals",
    "check_bench_shield",
    "check_doc_staleness",
    "check_slow_markers",
    "check_bench_record_fields",
    "check_metrics_schema",
    "check_ledger_emit",
    "check_chaos_gate",
    "MUTABLE_GLOBAL_ALLOWLIST",
    "SLOW_REQUIRED_TEST_MODULES",
    "METRICS_SCHEMA_FILES",
]

REPO_RULES = (
    "repo-mutable-global",
    "repo-bench-shield",
    "repo-doc-stale",
    "repo-slow-marker",
    "repo-bench-record",
    "repo-metrics-schema",
    "repo-ledger-emit",
    "repo-chaos-gate",
)

_PACKAGE_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_REPO_ROOT = os.path.dirname(_PACKAGE_DIR)

# Module-level mutable globals the repo accepts, each with the rationale the
# rule's docstring demands. Policy (docs/ANALYSIS.md): state that selects a
# TRACED behavior is allowlistable only when a trace-time recorder exists and
# the record emitters cross-check it; host-side caches must never be read
# inside traced code.
MUTABLE_GLOBAL_ALLOWLIST = {
    "ops/pallas_short_attention.py::_DEFAULT_BATCH_HEADS": (
        "trace-time kernel choice; every resolution is recorded in "
        "_TRACED_BWD_BATCH_HEADS and bench.py cross-checks records against "
        "the traced truth (_attn_bwd_record_fields)"
    ),
    "ops/pallas_short_attention.py::_TRACED_BWD_BATCH_HEADS": (
        "IS the traced-choice recorder for _DEFAULT_BATCH_HEADS (append-only "
        "at trace time; cleared only by the test-isolation reset)"
    ),
    "ops/pallas_sigmoid_loss.py::_TRACED_LOSS_KERNELS": (
        "trace-time recorder for the streaming-loss-kernel dispatch "
        "(streaming / streaming_int8 / xla fallback); bench.py cross-checks "
        "records against it (_pallas_record_fields) so use_pallas can never "
        "be claimed while every block fell back (append-only at trace time; "
        "cleared only by the test-isolation reset)"
    ),
    "data/native_loader.py::_lib": (
        "host-side ctypes build/load cache for the C++ dataloader; never "
        "read inside traced code (data feeding happens on the host)"
    ),
    "data/native_decode.py::_lib": (
        "host-side ctypes build/load cache for the libjpeg engine; never "
        "read inside traced code"
    ),
    "data/native_decode.py::_lib_failed": (
        "host-side build-failure latch paired with _lib; never read inside "
        "traced code"
    ),
    "obs/ledger.py::_FINGERPRINT_CACHE": (
        "host-side memo for the ledger's environment fingerprint (git sha "
        "subprocess result); never read inside traced code — the ledger is "
        "a stdlib emit path"
    ),
    "serve/siege.py::_INJECTORS": (
        "host-side armed-fault registry for the chaos harness; never read "
        "inside traced code (injection happens on worker/host threads), "
        "mutated only by install_fault/clear_faults under _INJECT_LOCK, and "
        "dead in production: maybe_inject is gated on DSL_CHAOS "
        "(statically enforced by repo-chaos-gate)"
    ),
    "analysis/jaxpr_audit.py::_STEP_CONFIG_CACHE": (
        "host-side per-label memo of the deterministic step-config traces "
        "(auditor + obs/attribution + obs/regress share one sampled "
        "product, and the full-product pass reuses the tier-1 labels; the "
        "trace used to run 3x per tier-1); never read inside traced code — "
        "it CONTAINS closed jaxprs, which are inert data"
    ),
}

# The suites whose full-module runtime is multi-minute on the 1-core tier-1
# host (measured; see CHANGES.md PR 1-3): each must carry a module-level
# `pytestmark = pytest.mark.slow` so the time-boxed gate never collects them.
SLOW_REQUIRED_TEST_MODULES = (
    "test_cli.py",
    "test_grad_compression.py",
    "test_train_step.py",
    "test_pp_towers.py",
    "test_zero1.py",
    "test_long_context.py",
    "test_quant_train_convergence.py",
)

_MUTATING_METHODS = {
    "add", "append", "extend", "update", "clear", "pop", "popitem",
    "remove", "discard", "insert", "setdefault", "appendleft",
}

_MUTABLE_CTORS = {"set", "dict", "list", "deque", "defaultdict", "OrderedDict"}


def _module_level_names(tree: ast.Module) -> set[str]:
    names = set()
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            targets = [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


def _local_bindings(fn: ast.AST) -> set[str]:
    """Names bound locally in a function (params + assignments), EXCLUDING
    names it declares ``global``."""
    bound, globals_ = set(), set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Global):
            globals_.update(node.names)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bound.add(t.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                bound.add(node.target.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for a in (
                node.args.args + node.args.posonlyargs + node.args.kwonlyargs
            ):
                bound.add(a.arg)
    return bound - globals_


def _mutated_module_globals(tree: ast.Module) -> dict[str, int]:
    """name -> line of the first detected mutation of a module-level name."""
    module_names = _module_level_names(tree)
    mutable_containers = set()
    for node in tree.body:
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and isinstance(
            node.targets[0], ast.Name
        ):
            target = node.targets[0].id
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            target = node.target.id
        if target is None or node.value is None:
            continue
        v = node.value
        is_container = isinstance(v, (ast.List, ast.Dict, ast.Set)) or (
            isinstance(v, ast.Call)
            and isinstance(v.func, ast.Name)
            and v.func.id in _MUTABLE_CTORS
        )
        if is_container:
            mutable_containers.add(target)

    mutated: dict[str, int] = {}

    def note(name: str, line: int) -> None:
        mutated.setdefault(name, line)

    for fn in ast.walk(tree):
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        declared_global = {
            n for node in ast.walk(fn) if isinstance(node, ast.Global)
            for n in node.names
        }
        local = _local_bindings(fn)
        for node in ast.walk(fn):
            # `global N` + assignment: rebinding a module global from a function.
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in declared_global:
                        note(t.id, node.lineno)
                    # container[k] = v on a module-level container
                    if (
                        isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and t.value.id in mutable_containers
                        and t.value.id not in local
                    ):
                        note(t.value.id, node.lineno)
            # container.add/append/... on a module-level container
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATING_METHODS
                and isinstance(node.func.value, ast.Name)
            ):
                name = node.func.value.id
                if name in module_names and name in mutable_containers and (
                    name not in local
                ):
                    note(name, node.lineno)
    return mutated


def _iter_package_sources(package_dir: str):
    for dirpath, dirnames, filenames in os.walk(package_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            rel = os.path.relpath(path, package_dir)
            with open(path, encoding="utf-8") as f:
                yield rel, f.read()


def check_mutable_globals(
    sources=None, allowlist=None,
) -> list[Finding]:
    """repo-mutable-global: unallowlisted mutated module-level state.

    ``sources``: ``{relpath: source}`` (default: every package module).
    """
    if sources is None:
        sources = dict(_iter_package_sources(_PACKAGE_DIR))
    allowlist = MUTABLE_GLOBAL_ALLOWLIST if allowlist is None else allowlist
    findings = []
    seen_keys = set()
    for rel, src in sources.items():
        rel = rel.replace(os.sep, "/")
        tree = ast.parse(src)
        for name, line in sorted(_mutated_module_globals(tree).items()):
            key = f"{rel}::{name}"
            seen_keys.add(key)
            if key not in allowlist:
                findings.append(Finding(
                    "repo-mutable-global",
                    key,
                    f"module-level {name!r} is mutated (line {line}) — "
                    "trace-time mutable global state; a step traced before "
                    "the mutation silently keeps the other behavior while "
                    "records claim otherwise (the _DEFAULT_BATCH_HEADS "
                    "class). Either remove it or allowlist it in "
                    "analysis/repo_lint.py with a rationale naming its "
                    "traced-choice recorder",
                ))
    for key in sorted(set(allowlist) - seen_keys):
        findings.append(Finding(
            "repo-mutable-global",
            key,
            "stale allowlist entry: no such mutated module global exists "
            "anymore — drop it so the allowlist stays an honest inventory",
        ))
    return findings


def _argparse_dests(tree: ast.Module) -> dict[str, int]:
    """dest -> lineno for every add_argument call in the module."""
    dests: dict[str, int] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
        ):
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Constant) and isinstance(first.value, str)):
            continue
        flag = first.value
        dest = flag[2:].replace("-", "_") if flag.startswith("--") else flag
        if dest:
            dests.setdefault(dest, node.lineno)
    return dests


def _argparse_flags(tree: ast.Module) -> dict[str, int]:
    """'--flag' -> lineno for every OPTIONAL add_argument in the module."""
    flags: dict[str, int] = {}
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
            and node.args
        ):
            continue
        first = node.args[0]
        if (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)
            and first.value.startswith("--")
        ):
            flags.setdefault(first.value, node.lineno)
    return flags


def _attr_reads_of(tree: ast.Module, func_name: str, obj: str = "args") -> set[str]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == func_name:
            return {
                n.attr
                for n in ast.walk(node)
                if isinstance(n, ast.Attribute)
                and isinstance(n.value, ast.Name)
                and n.value.id == obj
            }
    return set()


def _module_dict_keys(tree: ast.Module, var_name: str) -> set[str]:
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == var_name
            and isinstance(node.value, ast.Dict)
        ):
            return {
                k.value
                for k in node.value.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)
            }
    return set()


def check_bench_shield(bench_source: str | None = None) -> list[Finding]:
    """repo-bench-shield: every bench flag classified as shield-trigger or
    exempt-with-rationale — enumerated from the REAL argparse tree."""
    if bench_source is None:
        with open(os.path.join(_REPO_ROOT, "bench.py"), encoding="utf-8") as f:
            bench_source = f.read()
    tree = ast.parse(bench_source)
    dests = _argparse_dests(tree)
    reads = _attr_reads_of(tree, "_fresh_compile_config")
    exempt = _module_dict_keys(tree, "_SHIELD_EXEMPT_FLAGS")
    findings = []
    if not reads:
        findings.append(Finding(
            "repo-bench-shield", "bench.py::_fresh_compile_config",
            "no _fresh_compile_config function found (or it reads no args) — "
            "the compile shield has no trigger set",
        ))
    for dest, line in sorted(dests.items()):
        if dest not in reads and dest not in exempt:
            findings.append(Finding(
                "repo-bench-shield",
                f"bench.py::{dest}",
                f"flag --{dest.replace('_', '-')} (line {line}) is neither "
                "read by _fresh_compile_config nor listed in "
                "_SHIELD_EXEMPT_FLAGS: a config-changing flag outside the "
                "shield runs fresh XLA compiles unprotected (the "
                "--gradcache-bf16 ADVICE class). Classify it.",
            ))
    for dest in sorted(exempt - set(dests)):
        findings.append(Finding(
            "repo-bench-shield",
            f"bench.py::{dest}",
            "_SHIELD_EXEMPT_FLAGS names a flag that is not in the argparse "
            "tree — stale exemption; drop it",
        ))
    for dest in sorted(exempt & reads):
        findings.append(Finding(
            "repo-bench-shield",
            f"bench.py::{dest}",
            "flag is BOTH a _fresh_compile_config trigger and exempt — "
            "contradictory classification; pick one",
        ))
    return findings


def check_doc_staleness(
    cli_source: str | None = None,
    config_source: str | None = None,
    docs_text: str | None = None,
) -> list[Finding]:
    """repo-doc-stale: CLI flags and LossConfig fields must appear in
    README.md or docs/*.md."""
    if cli_source is None:
        with open(
            os.path.join(_PACKAGE_DIR, "cli.py"), encoding="utf-8"
        ) as f:
            cli_source = f.read()
    if config_source is None:
        with open(
            os.path.join(_PACKAGE_DIR, "utils", "config.py"), encoding="utf-8"
        ) as f:
            config_source = f.read()
    if docs_text is None:
        chunks = []
        readme = os.path.join(_REPO_ROOT, "README.md")
        if os.path.exists(readme):
            with open(readme, encoding="utf-8") as f:
                chunks.append(f.read())
        docs_dir = os.path.join(_REPO_ROOT, "docs")
        if os.path.isdir(docs_dir):
            for fn in sorted(os.listdir(docs_dir)):
                if fn.endswith(".md"):
                    with open(
                        os.path.join(docs_dir, fn), encoding="utf-8"
                    ) as f:
                        chunks.append(f.read())
        docs_text = "\n".join(chunks)

    findings = []
    cli_tree = ast.parse(cli_source)
    for flag, line in sorted(_argparse_flags(cli_tree).items()):
        # Positionals (e.g. `export out`) are visible in --help usage strings;
        # only true --flags are held to the doc rule.
        if flag not in docs_text:
            findings.append(Finding(
                "repo-doc-stale",
                f"cli.py::{flag}",
                f"CLI flag {flag} (line {line}) appears in no README.md "
                "or docs/*.md — undocumented surface goes un-A/B'd and "
                "rots; add a line where the subcommand is documented",
            ))
    cfg_tree = ast.parse(config_source)
    for node in ast.walk(cfg_tree):
        if isinstance(node, ast.ClassDef) and node.name == "LossConfig":
            for stmt in node.body:
                if isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name
                ):
                    field = stmt.target.id
                    if field not in docs_text:
                        findings.append(Finding(
                            "repo-doc-stale",
                            f"LossConfig.{field}",
                            f"LossConfig field {field!r} appears in no "
                            "README.md or docs/*.md",
                        ))
    return findings


def check_slow_markers(
    sources=None, required=None,
) -> list[Finding]:
    """repo-slow-marker: registered multi-minute suites carry the module-level
    slow pytestmark (the 870 s tier-1 budget's structural guard)."""
    required = SLOW_REQUIRED_TEST_MODULES if required is None else required
    if sources is None:
        sources = {}
        tests_dir = os.path.join(_REPO_ROOT, "tests")
        for fn in required:
            path = os.path.join(tests_dir, fn)
            if os.path.exists(path):
                with open(path, encoding="utf-8") as f:
                    sources[fn] = f.read()
            else:
                sources[fn] = None
    findings = []
    for fn in required:
        src = sources.get(fn)
        if src is None:
            findings.append(Finding(
                "repo-slow-marker", f"tests/{fn}",
                "registered as slow-required but the file does not exist — "
                "update SLOW_REQUIRED_TEST_MODULES",
            ))
            continue
        tree = ast.parse(src)
        if not _has_module_slow_mark(tree):
            findings.append(Finding(
                "repo-slow-marker", f"tests/{fn}",
                "multi-minute suite without a module-level `pytestmark = "
                "pytest.mark.slow` — it would land inside the time-boxed "
                "870 s tier-1 gate and blow the budget",
            ))
    return findings


def _has_module_slow_mark(tree: ast.Module) -> bool:
    def is_slow_mark(node) -> bool:
        # pytest.mark.slow, possibly wrapped: pytest.mark.slow / mark.slow
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "slow"
            and isinstance(node.value, ast.Attribute)
            and node.value.attr == "mark"
        )

    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "pytestmark"
            for t in node.targets
        ):
            v = node.value
            elems = v.elts if isinstance(v, (ast.List, ast.Tuple)) else [v]
            if any(is_slow_mark(e) for e in elems):
                return True
            # pytest.mark.skipif(...) etc: calls wrapping a mark — check func
            if any(
                isinstance(e, ast.Call) and is_slow_mark(e.func) for e in elems
            ):
                return True
    return False


def check_bench_record_fields(bench_source: str | None = None) -> list[Finding]:
    """repo-bench-record: record-field string literals in bench.py are all
    registered in the shared schema (analysis/bench_schema.py)."""
    from distributed_sigmoid_loss_tpu.analysis.bench_schema import (
        BENCH_RECORD_FIELDS,
    )

    if bench_source is None:
        with open(os.path.join(_REPO_ROOT, "bench.py"), encoding="utf-8") as f:
            bench_source = f.read()
    tree = ast.parse(bench_source)
    # Names whose dict keys ARE record fields: the per-mode `record` dicts,
    # the `fields` dict _attn_bwd_record_fields merges into records, and any
    # dict literal passed straight to _emit(...)/json.dumps(...).
    record_names = {"record", "fields"}
    findings = []

    def check_keys(keys, line) -> None:
        for k in keys:
            if k not in BENCH_RECORD_FIELDS:
                findings.append(Finding(
                    "repo-bench-record",
                    f"bench.py::{k}",
                    f"record field {k!r} (line {line}) is not registered in "
                    "analysis/bench_schema.py BENCH_RECORD_FIELDS — "
                    "unregistered fields drift per emit path; register it "
                    "(and document it if it encodes a new config knob)",
                ))

    def dict_keys(d: ast.Dict) -> list[str]:
        return [
            k.value
            for k in d.keys
            if isinstance(k, ast.Constant) and isinstance(k.value, str)
        ]

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id in record_names
                    and isinstance(node.value, ast.Dict)
                ):
                    check_keys(dict_keys(node.value), node.lineno)
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in record_names
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    check_keys([t.slice.value], node.lineno)
        if isinstance(node, ast.Call):
            fname = None
            if isinstance(node.func, ast.Name):
                fname = node.func.id
            elif isinstance(node.func, ast.Attribute):
                fname = node.func.attr
            if fname in ("_emit", "dumps") and node.args and isinstance(
                node.args[0], ast.Dict
            ):
                check_keys(dict_keys(node.args[0]), node.lineno)
    return findings


_METRIC_DICT_NAMES = {"metrics", "line", "snap"}

# The modules whose metric-field literals repo-metrics-schema audits, and the
# registry (obs/metrics_schema.py) each validates against. Package-relative
# paths; a module emitting a NEW record stream registers itself here.
METRICS_SCHEMA_FILES = {
    "train/train_step.py": "train",
    "train/compressed_step.py": "train",
    "cli.py": "train",
    "serve/service.py": "serve",
    "serve/admission.py": "serve",
    "serve/fleet/leases.py": "serve",
    "serve/fleet/router.py": "serve",
    "serve/fleet/waves.py": "serve",
    "obs/health.py": "health",
}


def _metric_literals(tree: ast.Module) -> list[tuple[str, int]]:
    """(field, lineno) for every metric-field string literal in a module:
    dict literals bound to the conventional record names (``metrics`` /
    ``line`` / ``snap``), subscript-assigns onto them, dict literals passed
    to ``.log(step, {...})`` / ``.write({...})``, and the dict a function
    named ``record`` returns (the HealthEvent convention). Dynamic keys
    (f-strings like ``eval/{k}``) are invisible to AST and covered by the
    registered prefixes at emit time instead."""
    out: list[tuple[str, int]] = []

    def take(d: ast.Dict, line: int) -> None:
        for k in d.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.append((k.value, line))

    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if (
                    isinstance(t, ast.Name)
                    and t.id in _METRIC_DICT_NAMES
                    and isinstance(node.value, ast.Dict)
                ):
                    take(node.value, node.lineno)
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in _METRIC_DICT_NAMES
                    and isinstance(t.slice, ast.Constant)
                    and isinstance(t.slice.value, str)
                ):
                    out.append((t.slice.value, node.lineno))
        elif isinstance(node, ast.Call) and isinstance(
            node.func, ast.Attribute
        ):
            if (
                node.func.attr == "log"
                and len(node.args) >= 2
                and isinstance(node.args[1], ast.Dict)
            ):
                take(node.args[1], node.lineno)
            elif (
                node.func.attr == "write"
                and node.args
                and isinstance(node.args[0], ast.Dict)
            ):
                take(node.args[0], node.lineno)
        elif isinstance(node, ast.FunctionDef) and node.name == "record":
            for stmt in ast.walk(node):
                if isinstance(stmt, ast.Return) and isinstance(
                    stmt.value, ast.Dict
                ):
                    take(stmt.value, stmt.lineno)
    return out


def check_metrics_schema(sources=None, files=None) -> list[Finding]:
    """repo-metrics-schema: metric-field literals in the emitting modules are
    all registered in obs/metrics_schema.py (train lines / serve stats /
    health events — the repo-bench-record discipline for the other two
    record streams)."""
    from distributed_sigmoid_loss_tpu.obs.metrics_schema import (
        HEALTH_EVENT_FIELDS,
        SERVE_STATS_FIELDS,
        TRAIN_METRICS_FIELDS,
        TRAIN_METRICS_PREFIXES,
    )

    schemas = {
        "train": (TRAIN_METRICS_FIELDS, TRAIN_METRICS_PREFIXES),
        "serve": (SERVE_STATS_FIELDS, ()),
        "health": (HEALTH_EVENT_FIELDS, ()),
    }
    files = METRICS_SCHEMA_FILES if files is None else files
    if sources is None:
        sources = {}
        for rel in files:
            path = os.path.join(_PACKAGE_DIR, rel.replace("/", os.sep))
            with open(path, encoding="utf-8") as f:
                sources[rel] = f.read()
    findings = []
    for rel, kind in files.items():
        src = sources.get(rel)
        if src is None:
            continue
        fields, prefixes = schemas[kind]
        for field_name, line in _metric_literals(ast.parse(src)):
            if field_name in fields:
                continue
            if any(field_name.startswith(p) for p in prefixes):
                continue
            findings.append(Finding(
                "repo-metrics-schema",
                f"{rel}::{field_name}",
                f"metric field {field_name!r} (line {line}) is not "
                f"registered in obs/metrics_schema.py ({kind} schema) — "
                "undeclared fields drift per emit path and are invisible "
                "to downstream parsers; register it (and document it in "
                "docs/OBSERVABILITY.md if it encodes a new signal)",
            ))
    return findings


def _json_record_prints(tree: ast.Module) -> dict[str, list[int]]:
    """function_name -> lines where ``print(json.dumps(...))`` (or
    ``print(dumps(...))``) occurs — the record-emit signature the ledger rule
    keys on. Module-level prints land under the pseudo-name ``<module>``."""

    def is_dumps(call: ast.AST) -> bool:
        if not isinstance(call, ast.Call):
            return False
        f = call.func
        return (isinstance(f, ast.Attribute) and f.attr == "dumps") or (
            isinstance(f, ast.Name) and f.id == "dumps"
        )

    out: dict[str, list[int]] = {}

    def visit(node: ast.AST, owner: str) -> None:
        for child in ast.iter_child_nodes(node):
            name = owner
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = child.name
            if (
                isinstance(child, ast.Call)
                and isinstance(child.func, ast.Name)
                and child.func.id == "print"
                and child.args
                and is_dumps(child.args[0])
            ):
                out.setdefault(owner, []).append(child.lineno)
            visit(child, name)

    visit(tree, "<module>")
    return out


def check_ledger_emit(bench_source: str | None = None) -> list[Finding]:
    """repo-ledger-emit: every bench.py record print routes through the ONE
    ledger-appending emitter.

    Two statically-checkable halves: (a) ``_emit`` must call the ledger
    append (``append_record``); (b) no ``print(json.dumps(...))`` may appear
    outside ``_emit`` — a path printing its own JSON bypasses the ledger (and
    the schema validator) exactly the way pre-round-4 emit paths drifted.
    """
    if bench_source is None:
        with open(os.path.join(_REPO_ROOT, "bench.py"), encoding="utf-8") as f:
            bench_source = f.read()
    tree = ast.parse(bench_source)
    findings = []
    emit_fns = [
        node for node in ast.walk(tree)
        if isinstance(node, ast.FunctionDef) and node.name == "_emit"
    ]
    if not emit_fns:
        findings.append(Finding(
            "repo-ledger-emit", "bench.py::_emit",
            "no _emit function found — bench.py has no single schema-"
            "validating, ledger-appending emit path",
        ))
    else:
        calls_append = any(
            isinstance(node, ast.Call)
            and (
                (isinstance(node.func, ast.Name)
                 and node.func.id == "append_record")
                or (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "append_record")
            )
            for node in ast.walk(emit_fns[0])
        )
        if not calls_append:
            findings.append(Finding(
                "repo-ledger-emit", "bench.py::_emit",
                "_emit does not call obs.ledger append_record — records "
                "print to stdout but never enter the perf trajectory; the "
                "next backend outage is invisible again (the BENCH_r04/r05 "
                "blind spot)",
            ))
    for owner, lines in sorted(_json_record_prints(tree).items()):
        if owner == "_emit":
            continue
        for line in lines:
            findings.append(Finding(
                "repo-ledger-emit", f"bench.py::{owner}",
                f"print(json.dumps(...)) at line {line} outside _emit — a "
                "record emit path bypassing the ledger append (and the "
                "schema validator); route it through _emit",
            ))
    return findings


def _chaos_registry(tree: ast.Module) -> dict[str, str] | None:
    """CHAOS_POINTS {point: rationale} from siege's module body (string
    constants only), or None when the dict is missing entirely."""
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "CHAOS_POINTS"
            and isinstance(node.value, ast.Dict)
        ):
            out = {}
            for k, v in zip(node.value.keys, node.value.values):
                if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
                    continue
                rationale = ""
                if isinstance(v, ast.Constant) and isinstance(v.value, str):
                    rationale = v.value
                elif isinstance(v, ast.JoinedStr):
                    rationale = "<dynamic>"
                out[k.value] = rationale
            return out
    return None


def _maybe_inject_calls(tree: ast.Module) -> list[tuple[str | None, int]]:
    """(point-or-None, lineno) for every maybe_inject(...) call; None marks
    a non-constant point argument (unauditable — itself a finding)."""
    calls = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        name = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else None
        )
        if name != "maybe_inject":
            continue
        point = None
        if node.args and isinstance(node.args[0], ast.Constant) and isinstance(
            node.args[0].value, str
        ):
            point = node.args[0].value
        calls.append((point, node.lineno))
    return calls


def _calls_name(fn: ast.AST, target: str) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == target:
                return True
            if isinstance(f, ast.Attribute) and f.attr == target:
                return True
    return False


def check_chaos_gate(
    siege_source: str | None = None, serve_sources=None,
) -> list[Finding]:
    """repo-chaos-gate: fault injection provably dead in production paths.

    Four statically-checkable halves: (a) ``maybe_inject`` must check the
    ``chaos_enabled()`` gate before any fault can fire, and ``chaos_enabled``
    must key on the ``DSL_CHAOS`` env hook; (b) every point in
    ``CHAOS_POINTS`` carries a non-empty rationale; (c) every
    ``maybe_inject(...)`` call site in serve/ names a registered point with
    a STRING CONSTANT (a computed point is unauditable); (d) no registry row
    is stale — a registered point nobody calls is a drill that silently
    stopped existing.
    """
    serve_dir = os.path.join(_PACKAGE_DIR, "serve")
    if siege_source is None:
        with open(
            os.path.join(serve_dir, "siege.py"), encoding="utf-8"
        ) as f:
            siege_source = f.read()
    if serve_sources is None:
        serve_sources = {
            f"serve/{rel}": src
            for rel, src in _iter_package_sources(serve_dir)
        }
    findings = []
    siege_tree = ast.parse(siege_source)

    # (a) the gate itself.
    fns = {
        node.name: node
        for node in ast.walk(siege_tree)
        if isinstance(node, ast.FunctionDef)
    }
    if "maybe_inject" not in fns:
        findings.append(Finding(
            "repo-chaos-gate", "serve/siege.py::maybe_inject",
            "no maybe_inject function found — the chaos harness has no "
            "gated injection entry point",
        ))
    elif not _calls_name(fns["maybe_inject"], "chaos_enabled"):
        findings.append(Finding(
            "repo-chaos-gate", "serve/siege.py::maybe_inject",
            "maybe_inject does not check chaos_enabled() — an armed fault "
            "would fire in production without the DSL_CHAOS hook; gate it",
        ))
    if "chaos_enabled" not in fns:
        findings.append(Finding(
            "repo-chaos-gate", "serve/siege.py::chaos_enabled",
            "no chaos_enabled function found — nothing defines the "
            "DSL_CHAOS gate",
        ))
    else:
        reads_hook = any(
            isinstance(n, ast.Constant) and n.value == "DSL_CHAOS"
            for n in ast.walk(fns["chaos_enabled"])
        )
        if not reads_hook:
            findings.append(Finding(
                "repo-chaos-gate", "serve/siege.py::chaos_enabled",
                "chaos_enabled does not reference the 'DSL_CHAOS' env hook "
                "— the documented production off-switch is not what the "
                "gate actually checks",
            ))

    # (b) the registry + rationales.
    registry = _chaos_registry(siege_tree)
    if registry is None:
        findings.append(Finding(
            "repo-chaos-gate", "serve/siege.py::CHAOS_POINTS",
            "no CHAOS_POINTS dict found — injection points have no "
            "registered inventory",
        ))
        registry = {}
    for point, rationale in sorted(registry.items()):
        if not rationale.strip():
            findings.append(Finding(
                "repo-chaos-gate", f"serve/siege.py::{point}",
                f"chaos point {point!r} has no rationale — the registry "
                "must say which failure mode the drill exists for",
            ))

    # (c) every call site names a registered constant point.
    called: set[str] = set()
    for rel in sorted(serve_sources):
        for point, line in _maybe_inject_calls(ast.parse(serve_sources[rel])):
            if rel.endswith("siege.py"):
                continue  # the definition module, not an injection site
            if point is None:
                findings.append(Finding(
                    "repo-chaos-gate", f"{rel}::maybe_inject",
                    f"maybe_inject call at line {line} passes a computed "
                    "point — unauditable; injection points must be string "
                    "constants registered in CHAOS_POINTS",
                ))
                continue
            called.add(point)
            if point not in registry:
                findings.append(Finding(
                    "repo-chaos-gate", f"{rel}::{point}",
                    f"maybe_inject({point!r}) at line {line} is not "
                    "registered in serve/siege.py CHAOS_POINTS — register "
                    "it with a rationale (ungated/undocumented injection "
                    "points are exactly what this rule exists to prevent)",
                ))

    # (d) stale registry rows.
    for point in sorted(set(registry) - called):
        findings.append(Finding(
            "repo-chaos-gate", f"serve/siege.py::{point}",
            f"chaos point {point!r} is registered but no serve/ module "
            "calls maybe_inject with it — stale inventory row; drop it or "
            "wire the drill back in",
        ))
    return findings


def run_repo_lint(disabled=()) -> list[Finding]:
    """Run every repo rule against the real tree."""
    checks = {
        "repo-mutable-global": check_mutable_globals,
        "repo-bench-shield": check_bench_shield,
        "repo-doc-stale": check_doc_staleness,
        "repo-slow-marker": check_slow_markers,
        "repo-bench-record": check_bench_record_fields,
        "repo-metrics-schema": check_metrics_schema,
        "repo-ledger-emit": check_ledger_emit,
        "repo-chaos-gate": check_chaos_gate,
    }
    findings: list[Finding] = []
    for rule, fn in checks.items():
        if rule not in disabled:
            findings.extend(fn())
    return findings
