"""graftprove half 2: sharding/state dataflow rules over the traced jaxprs.

Extends jaxpr_audit's ``_Auditor`` invariance walk (per-value ``(inv, red)``
frozenset pairs: axes a value is replicated over, and the subset it is
replicated over BECAUSE it was already reduced/gathered) with rules for bug
classes the base auditor's communication checks don't see:

- ``jaxpr-redundant-gather``: an ``all_gather`` whose operand is already
  known-invariant (replicated) over every gathered axis — W identical copies
  concatenated, pure wire + HBM waste. Scoped to gathers on purpose: a
  ``psum`` of a replicated-but-not-reduced value is jax's own sanctioned
  psum-self-transpose convention (the pmean backward, compensated by 1/S)
  and must stay silent, and a psum of an already-REDUCED value is already
  ``jaxpr-double-psum``. Unknown ⇒ varying ⇒ silent, the base walk's
  no-false-positive direction.
- ``jaxpr-state-drop``: a ``scan`` carry that the body READS and UPDATES
  with data from outside the carry, whose final value then never leaves the
  scan — state the program pretends to maintain but actually discards (the
  historical pp-silently-dropped-quant bug; the class the compression
  stream's error-feedback residual lives in). Pure carry rotations
  (``ppermute`` of the carry itself, counters ``c+1``) are exempt: their
  update depends on nothing outside the carry, so dropping the final value
  loses no information that entered the loop. GPipe's drained shift
  registers (parallel/pipeline.py) are updated WITH external microbatch data
  by design and legitimately drained — pp step configs opt out via
  ``check_state_drop=False``, same per-config-kwarg pattern as
  ``expect_chunk_checkpoint``.
- ``jaxpr-collective-order``: across ``cond`` branches, the per-axis
  sequence of collectives must match whenever the predicate is not
  known-invariant over that axis — shards disagreeing on the branch would
  enter different collective sequences and deadlock the mesh (the multihost
  hang class).
- ``jaxpr-ef-threaded``: for error-feedback step configs, each EF-residual
  OUTPUT leaf must transitively depend on non-EF step inputs (the gradient
  data) — a residual with no input dependence was dropped/re-zeroed, one
  depending only on the incoming EF leaves was passed through un-updated.
  Backward-dependence pass (``_outvar_deps``) that recurses positionally
  through pjit/remat/shard_map and goes conservative (all-inputs union)
  elsewhere, so it can only under-fire, never false-fire. Armed per config
  via ``ef_indices`` from ``jaxpr_audit.step_config_jaxprs``.
- ``jaxpr-codec-threaded``: for learned-rung step configs (graftcodec), the
  codec operands entering the step (``state.comp`` ``codec_enc``/
  ``codec_dec``, host-trained and replicated) must transitively reach the
  updated params — a step that takes the codec but never lets the decode
  touch the gradient path silently trains on the ENCODER-SIDE reconstruction
  while claiming the learned rung; and the per-round codec stats the host
  trainer consumes (``blockmoment``, ``codec_recon_err``) must depend on
  non-codec step inputs (this round's gradient data) — a constant or
  passed-through stat starves the trainer and freezes the codec at its DCT
  cold start with nothing ever reporting it. Same ``_outvar_deps`` backward
  pass as jaxpr-ef-threaded (conservative unions can only under-fire).
  Armed per config via ``codec_indices`` from
  ``jaxpr_audit.step_config_jaxprs``.
- ``jaxpr-gather-placement``: for ``update_sharding="full"`` step configs
  (graftshard), an ``all_gather`` over the update-shard axis whose operand
  was produced (transitively) by a ``psum_scatter``/``reduce_scatter`` over
  that same axis — the exact regression that silently re-replicates the
  1/W update the reduce-scatter just paid to shard, turning the single
  post-update param publish into a per-gradient gather storm. Forward taint
  pass: scatters over the axis taint their outputs, taint propagates
  through eqns (positionally through ``_POSITIONAL_CALLS``, coarsely
  elsewhere), and a gather of a tainted value over the same axis fires.
  Gathers of un-tainted values (the loss island's embedding all-gathers)
  stay silent — scatter-then-gather is the discriminator, not the gather
  itself. Armed per config via ``update_shard_axis`` from
  ``jaxpr_audit.step_config_jaxprs``.

Run alongside the base audit by ``audit_default_step_configs`` for every
config in the sampled product; rule catalog in docs/ANALYSIS.md.
"""

from __future__ import annotations

from distributed_sigmoid_loss_tpu.analysis.findings import Finding
from distributed_sigmoid_loss_tpu.analysis.jaxpr_audit import (
    _ALL_COLLECTIVES,
    _GATHER_PRIMS,
    _Auditor,
    _collective_axes,
    _is_literal,
    _jaxpr_of,
    _sub_jaxprs,
)

__all__ = ["SHARD_FLOW_RULES", "audit_shard_flow"]

SHARD_FLOW_RULES = (
    "jaxpr-redundant-gather",
    "jaxpr-state-drop",
    "jaxpr-collective-order",
    # The EF residual entering a compressed step must leave it UPDATED with
    # gradient data — never dropped (a constant output) and never passed
    # through as a pure function of the old residual (see
    # _check_ef_threading; ROADMAP item 2's named rule).
    "jaxpr-ef-threaded",
    # The learned rung's codec operands must reach the update path and its
    # host-trainer stats must draw on this round's gradients — never a
    # dropped decode or a frozen stat (see _check_codec_threading;
    # graftcodec's named rule).
    "jaxpr-codec-threaded",
    # Under update_sharding="full", a reduce-scattered value must never be
    # all-gathered back over the shard axis before the optimizer update
    # (see _check_gather_placement; graftshard's named rule).
    "jaxpr-gather-placement",
)

# Collectives that synchronize across shards of an axis — the ones whose
# cross-branch ordering matters for the deadlock check. axis_index is pure
# (no communication) and ppermute of nothing deadlocks nothing by itself,
# but a mismatched ppermute still leaves peers waiting, so everything but
# axis_index counts.
_SYNC_COLLECTIVES = _ALL_COLLECTIVES - {"axis_index"}


def _collective_sequence(jaxpr, out: list) -> None:
    """Flat (prim_name, axes) sequence of every named-axis collective under
    ``jaxpr``, in program order, recursing through call-like/scan/shard_map
    sub-jaxprs (a collective inside a scan body synchronizes every
    iteration; for cross-branch comparison its one-body order is what must
    agree)."""
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _SYNC_COLLECTIVES:
            axes = _collective_axes(eqn)
            if axes:
                out.append((name, axes))
        for _, inner in _sub_jaxprs(eqn.params):
            _collective_sequence(inner, out)


class _FlowAuditor(_Auditor):
    """The base invariance walk plus the redundant-gather and
    collective-order emissions (state-drop is a separate structural pass —
    it needs liveness, not invariance)."""

    def _walk_collective(self, eqn, env, bound, emit, get) -> None:
        name = eqn.primitive.name
        if name in _GATHER_PRIMS and emit:
            axes = _collective_axes(eqn)
            v = eqn.invars[0]
            # Scalars exempt: a gathered scalar is bookkeeping wire (the
            # compressed hop's quant-scale exchange double-syncs the two
            # scalar params whose grads the loss island already psum'd over
            # dcn — 4 bytes, uniform-tree compression by design), not the
            # W-identical-HBM-blocks waste this rule exists for.
            if (
                axes
                and not _is_literal(v)
                and getattr(getattr(v, "aval", None), "size", 1) > 1
            ):
                inv = get(v)[0]
                covered = sorted(ax for ax in axes if ax in inv)
                if len(covered) == len(axes):
                    self.add(
                        "jaxpr-redundant-gather",
                        f"{name} over axis(es) {covered} of a value already "
                        "replicated over them — every shard contributes an "
                        "identical copy, so the gather is W identical "
                        "blocks of wire traffic and HBM for data each "
                        "shard already holds; drop the gather (or shard "
                        "the producer)",
                    )
        super()._walk_collective(eqn, env, bound, emit, get)

    def _walk_cond(self, eqn, env, bound, emit, get) -> None:
        if emit:
            branches = eqn.params.get("branches", ())
            seqs = []
            for br in branches:
                inner = _jaxpr_of(br)
                seq: list = []
                if inner is not None:
                    _collective_sequence(inner, seq)
                seqs.append(tuple(seq))
            pred_inv = get(eqn.invars[0])[0] if eqn.invars else frozenset()
            axes_seen = sorted(
                {ax for seq in seqs for _, axes in seq for ax in axes}
            )
            for ax in axes_seen:
                if ax in pred_inv:
                    # Every shard of ax agrees on the predicate, so they all
                    # take the same branch — differing sequences can't split
                    # the axis.
                    continue
                if ax not in bound:
                    continue  # foreign axis: jaxpr-collective-axis's beat
                per_branch = [
                    tuple((n, axes) for n, axes in seq if ax in axes)
                    for seq in seqs
                ]
                if len(set(per_branch)) > 1:
                    shapes = ", ".join(
                        "[" + " ".join(n for n, _ in pb) + "]"
                        for pb in per_branch
                    )
                    self.add(
                        "jaxpr-collective-order",
                        f"cond branches run different collective sequences "
                        f"over axis {ax!r} ({shapes}) and the predicate is "
                        "not known replicated over it — shards that "
                        "disagree on the branch enter mismatched "
                        "collectives and the mesh deadlocks (multihost "
                        "hang class); hoist the collectives out of the "
                        "cond or make the predicate axis-invariant",
                    )
        super()._walk_cond(eqn, env, bound, emit, get)


# ---------------------------------------------------------------------------
# jaxpr-state-drop: a structural liveness pass, independent of invariance.


def _external_deps(body, var, carry_invars: set) -> bool:
    """Does ``var``'s transitive definition inside ``body`` draw on anything
    beyond the carry invars (consts, xs slices, constvars)? False for pure
    carry rotations/counters — the exempt class."""
    produced_by: dict = {}
    for eqn in body.eqns:
        for ov in eqn.outvars:
            produced_by[ov] = eqn
    seen: set = set()
    stack = [var]
    while stack:
        v = stack.pop()
        if _is_literal(v) or v in seen:
            continue
        seen.add(v)
        eqn = produced_by.get(v)
        if eqn is None:
            # A leaf: a body invar or constvar. External unless it is one of
            # the carry's own invars.
            if v not in carry_invars:
                return True
            continue
        stack.extend(eqn.invars)
        # Sub-jaxpr closures (scan/cond/pjit bodies) see only their mapped
        # operands, which are already in eqn.invars; constvars of the OUTER
        # body reached through them are leaves handled above.
    return False


def _live_vars(jaxpr) -> set:
    live = set(v for v in jaxpr.outvars if not _is_literal(v))
    for eqn in jaxpr.eqns:
        live.update(v for v in eqn.invars if not _is_literal(v))
    return live


def _is_drop_var(v) -> bool:
    return type(v).__name__ == "DropVar"


def _check_state_drops(jaxpr, add) -> None:
    """Recursively flag scan carries that are read, updated with external
    data, and whose final value is dead at the scan's own level."""
    live = _live_vars(jaxpr)
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            body = _jaxpr_of(eqn.params.get("jaxpr"))
            if body is not None and not any(
                beqn.primitive.name == "add_any" for beqn in body.eqns
            ):
                # add_any is a transpose-only primitive: a scan body holding
                # one is AD-generated cotangent accumulation (the reversed
                # scan legitimately drops the cotangent of a constant carry
                # init), not user state — only forward-authored scans are in
                # scope for the drop check.
                nc = eqn.params.get("num_consts", 0)
                ncar = eqn.params.get("num_carry", 0)
                carry_invars = set(body.invars[nc : nc + ncar])
                reads: set = set()
                for beqn in body.eqns:
                    reads.update(
                        v for v in beqn.invars
                        if not _is_literal(v) and v in carry_invars
                    )
                # A carry passed through to a ys output is also a read.
                for ov in body.outvars[ncar:]:
                    if not _is_literal(ov) and ov in carry_invars:
                        reads.add(ov)
                for i in range(min(ncar, len(eqn.outvars))):
                    ci = body.invars[nc + i]
                    co = body.outvars[i]
                    scan_out = eqn.outvars[i]
                    if ci not in reads:
                        continue  # write-only slot; not "read then dropped"
                    if co is ci or _is_literal(co):
                        continue  # passthrough / constant: nothing updated
                    if not (_is_drop_var(scan_out) or scan_out not in live):
                        continue  # the final value IS consumed
                    if not _external_deps(body, co, carry_invars):
                        # Pure rotation/counter (ring ppermute buffers,
                        # c + 1): dropping it loses nothing that entered
                        # the loop.
                        continue
                    aval = getattr(ci, "aval", None)
                    add(
                        "jaxpr-state-drop",
                        f"scan carry #{i} ({aval}) is read by the body and "
                        "updated with non-carry data, but the updated value "
                        "never leaves the scan — state the program "
                        "maintains and then silently discards (the "
                        "pp-dropped-quant / error-feedback-residual "
                        "class); thread the final carry to an output or "
                        "stop carrying it",
                    )
        for _, inner in _sub_jaxprs(eqn.params):
            _check_state_drops(inner, add)


# Call-like primitives whose inner jaxpr maps 1:1 positionally onto the
# eqn's invars/outvars — the cases _outvar_deps can recurse through exactly.
# Anything else (scan's consts+carry+xs layout, while, cond branches) falls
# back to the conservative all-inputs union, which can only make dependence
# sets LARGER — the rule's silent direction (it misses nothing on the
# shipped tree, and never false-fires).
_POSITIONAL_CALLS = frozenset({
    "pjit", "jit", "closed_call", "core_call", "remat", "remat2",
    "checkpoint", "custom_jvp_call", "custom_vjp_call", "shard_map", "smap",
})


def _positional_inner(eqn):
    if eqn.primitive.name not in _POSITIONAL_CALLS:
        return None
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        if key in eqn.params:
            inner = _jaxpr_of(eqn.params[key])
            if (
                inner is not None
                and len(inner.invars) == len(eqn.invars)
                and len(inner.outvars) == len(eqn.outvars)
            ):
                return inner
    return None


def _outvar_deps(jaxpr, memo: dict) -> list:
    """Per-outvar transitive dependence on the jaxpr's OWN invar positions.

    Forward pass over the (topologically ordered) eqns; recurses positionally
    through _POSITIONAL_CALLS eqns and unions all inputs otherwise. Returns
    ``[frozenset[int], ...]`` aligned with ``jaxpr.outvars``; literals and
    constvars contribute nothing (a constant has no input dependence).
    """
    key = id(jaxpr)
    if key in memo:
        return memo[key]
    memo[key] = [frozenset() for _ in jaxpr.outvars]  # cycle guard
    dep: dict = {v: frozenset([i]) for i, v in enumerate(jaxpr.invars)}

    def get(v):
        if _is_literal(v):
            return frozenset()
        return dep.get(v, frozenset())

    for eqn in jaxpr.eqns:
        inner = _positional_inner(eqn)
        if inner is not None:
            inner_deps = _outvar_deps(inner, memo)
            outsets = [
                frozenset().union(*(get(eqn.invars[i]) for i in ideps))
                if ideps else frozenset()
                for ideps in inner_deps
            ]
        else:
            u = (
                frozenset().union(*(get(iv) for iv in eqn.invars))
                if eqn.invars else frozenset()
            )
            outsets = [u] * len(eqn.outvars)
        for ov, s in zip(eqn.outvars, outsets):
            dep[ov] = s
    result = [get(v) for v in jaxpr.outvars]
    memo[key] = result
    return result


def _check_ef_threading(jaxpr, ef_indices, add) -> None:
    """jaxpr-ef-threaded: every EF-residual output must depend on non-EF
    inputs (gradient data). A residual that depends on NOTHING is a dropped/
    re-zeroed carry; one that depends ONLY on the EF inputs is passed through
    (or merely decayed) un-updated — both are the silent-drop bug class the
    pp/quant composition already taught us (compression runs, the claimed
    error feedback never happens, the quantization bias accumulates
    un-carried)."""
    ef_in, ef_out = ef_indices
    ef_in_set = frozenset(ef_in)
    dep_sets = _outvar_deps(jaxpr, {})
    for o in ef_out:
        if o >= len(dep_sets):
            add(
                "jaxpr-ef-threaded",
                f"ef output index {o} out of range for {len(dep_sets)} "
                "outputs — stale ef_indices plumbing",
            )
            continue
        deps = dep_sets[o]
        if not deps:
            add(
                "jaxpr-ef-threaded",
                f"EF residual output #{o} depends on NO step inputs — the "
                "carried residual is dropped or re-zeroed instead of "
                "accumulating this round's compression error",
            )
        elif deps <= ef_in_set:
            add(
                "jaxpr-ef-threaded",
                f"EF residual output #{o} depends only on the incoming EF "
                f"state (inputs {sorted(deps)}) — passed through un-updated; "
                "the compressed hop's error is silently discarded",
            )


def _check_codec_threading(jaxpr, codec_indices, add) -> None:
    """jaxpr-codec-threaded: the learned rung's two dataflow obligations.

    ``codec_indices`` is ``(codec_in, stat_out, update_out)`` — flattened
    positions of the codec operands among the step inputs, the codec stats
    (blockmoment / codec_recon_err) among the outputs, and the updated-param
    leaves among the outputs. (1) Every stat output must depend on non-codec
    step inputs: empty dependence is a constant stat, codec-only dependence
    is a stat computed from the codec itself — either way the host trainer
    EWMAs noise and the codec never leaves its DCT cold start. (2) At least
    one updated-param output must draw on the codec operands: the decode is
    what turns the wire latents back into a gradient, and a step that drops
    it applies rung-6 "compression" that never actually happened."""
    codec_in, stat_out, update_out = codec_indices
    codec_in_set = frozenset(codec_in)
    dep_sets = _outvar_deps(jaxpr, {})
    for o in stat_out:
        if o >= len(dep_sets):
            add(
                "jaxpr-codec-threaded",
                f"codec stat output index {o} out of range for "
                f"{len(dep_sets)} outputs — stale codec_indices plumbing",
            )
            continue
        deps = dep_sets[o]
        if not deps:
            add(
                "jaxpr-codec-threaded",
                f"codec stat output #{o} depends on NO step inputs — a "
                "constant stat; the host codec trainer would EWMA zeros and "
                "the learned rung freezes at its DCT cold start",
            )
        elif deps <= codec_in_set:
            add(
                "jaxpr-codec-threaded",
                f"codec stat output #{o} depends only on the codec operands "
                f"(inputs {sorted(deps)}) — not on this round's gradients; "
                "the trainer's moment stream carries no new information",
            )
    live_updates = [o for o in update_out if o < len(dep_sets)]
    if codec_in and live_updates and not any(
        dep_sets[o] & codec_in_set for o in live_updates
    ):
        add(
            "jaxpr-codec-threaded",
            "no updated-param output depends on the codec operands "
            "(codec_enc/codec_dec) — the learned rung's decode never reaches "
            "the optimizer update, so the step claims rung-6 compression "
            "while training on something else entirely",
        )


# ---------------------------------------------------------------------------
# jaxpr-gather-placement: the graftshard scatter-then-gather taint pass.

# The primitives that produce a shard-axis-partial value: lax.psum_scatter
# spells either name depending on the tiled lowering, so accept both (same
# both-spellings hedge as jaxpr_audit._SUM_PRIMS).
_SCATTER_PRIMS = frozenset({"psum_scatter", "reduce_scatter"})


def _check_gather_placement(jaxpr, axis, add, taint_in=None) -> list:
    """Forward taint pass for one jaxpr level; returns per-outvar taint.

    A value is TAINTED once a psum_scatter/reduce_scatter over ``axis``
    produced it — it now holds a 1/W shard of a cross-replica sum, the thing
    graftshard's update path must carry through the optimizer un-gathered.
    An ``all_gather`` over the same axis of a tainted value fires: it
    re-replicates the update the scatter just sharded (param publish is the
    ONE sanctioned gather, and it happens on the post-update params — a
    fresh, never-scattered value — so it cannot taint-match). Propagation is
    positional through ``_POSITIONAL_CALLS`` (shard_map bodies included, so
    the compressed step's manual region is walked exactly) and coarse
    any-in-taints-all-out elsewhere; scan/cond/while interiors are scanned
    for self-contained scatter→gather pairs without seeding, the
    under-fire-never-false-fire direction the module promises.
    """
    taint: dict = {}
    if taint_in:
        for v, t in zip(jaxpr.invars, taint_in):
            if t:
                taint[v] = True

    def tainted(v):
        return not _is_literal(v) and taint.get(v, False)

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _SCATTER_PRIMS or name in _GATHER_PRIMS:
            axes = _collective_axes(eqn)
            if name in _SCATTER_PRIMS and axis in axes:
                for ov in eqn.outvars:
                    taint[ov] = True
                continue
            if (
                name in _GATHER_PRIMS
                and axis in axes
                and any(tainted(iv) for iv in eqn.invars)
            ):
                aval = getattr(eqn.invars[0], "aval", None)
                add(
                    "jaxpr-gather-placement",
                    f"{name} over axis {axis!r} of a value produced by a "
                    f"reduce-scatter over the same axis ({aval}) — the 1/W "
                    "update shard is re-replicated BEFORE the optimizer "
                    "update, undoing graftshard's sharding and paying a "
                    "per-gradient gather the single post-update param "
                    "publish exists to avoid; keep the optimizer on the "
                    "shard and gather only the updated params",
                )
                # The gathered output is whole again; redundant follow-on
                # gathers are jaxpr-redundant-gather's beat, not this rule's.
                continue
        inner = _positional_inner(eqn)
        if inner is not None:
            inner_taint = _check_gather_placement(
                inner, axis, add, [tainted(iv) for iv in eqn.invars]
            )
            for ov, t in zip(eqn.outvars, inner_taint):
                if t:
                    taint[ov] = True
            continue
        for _, sub in _sub_jaxprs(eqn.params):
            _check_gather_placement(sub, axis, add)
        if any(tainted(iv) for iv in eqn.invars):
            for ov in eqn.outvars:
                taint[ov] = True
    return [tainted(v) for v in jaxpr.outvars]


def audit_shard_flow(
    jaxpr_or_closed,
    *,
    label: str,
    bound_axes: dict | None = None,
    check_state_drop: bool = True,
    ef_indices: tuple | None = None,
    codec_indices: tuple | None = None,
    update_shard_axis: str | None = None,
) -> list[Finding]:
    """Run the shard-flow rules over one (closed) jaxpr.

    ``check_state_drop=False`` is the pp opt-out: GPipe's shift-register
    carries are drained by design (see module docstring). ``ef_indices``
    (``(in_positions, out_positions)`` of the flattened EF-residual leaves,
    computed by jaxpr_audit.step_config_jaxprs for error-feedback configs)
    arms the ``jaxpr-ef-threaded`` dataflow check; None skips it.
    ``codec_indices`` (``(codec_in, stat_out, update_out)`` positions, set
    by step_config_jaxprs for learned-rung configs) arms
    ``jaxpr-codec-threaded`` the same way. ``update_shard_axis`` (the dp axis name, set by step_config_jaxprs for
    ``update_sharding="full"`` configs) arms ``jaxpr-gather-placement``;
    None skips it.
    """
    j = _jaxpr_of(jaxpr_or_closed)
    if j is None:
        raise TypeError(f"not a jaxpr: {jaxpr_or_closed!r}")
    auditor = _FlowAuditor(label)
    bound = dict(bound_axes or {})
    env: dict = {}
    for iv in j.invars:
        env[iv] = (frozenset(), frozenset())
    for cv in getattr(j, "constvars", ()):
        env[cv] = (frozenset(bound), frozenset())
    auditor.walk(j, env, bound, True)
    if check_state_drop:
        _check_state_drops(j, auditor.add)
    if ef_indices is not None:
        _check_ef_threading(j, ef_indices, auditor.add)
    if codec_indices is not None:
        _check_codec_threading(j, codec_indices, auditor.add)
    if update_shard_axis is not None:
        _check_gather_placement(j, update_shard_axis, auditor.add)
    return [f for f in auditor.findings if f.rule in SHARD_FLOW_RULES]
