"""graftlint's jaxpr half: static audit of the distributed loss/train-step
programs' communication structure and dtype hygiene.

Every distributed-correctness bug this repo hit was statically visible in the
jaxpr before a single device cycle: a broken ring permutation silently
zero-fills the shards nobody sends to; a psum of an already-reduced
(axis-invariant) value overcounts S-fold in an unchecked shard_map transpose;
a python-scalar input leaks a weak-typed aval and recompiles per call-site
flavor; dropping the chunk scan's ``jax.checkpoint`` silently re-materializes
the full logits matrix in the backward. This auditor traces the REAL step
builders (make_train_step / make_compressed_train_step) on the virtual-device
CPU mesh — trace only, no compile, no execution — and walks the closed
jaxprs. The "verify the sharded program's communication structure, don't
trust the author" discipline of XLA's cross-replica sharding work (Xu et al.,
arXiv:2004.13336) applied to this repo's own programs.

Rules (ids used by ``lint --disable`` and the Finding records):

- ``jaxpr-ppermute-bijection``: every ppermute perm is a total bijection on a
  live mesh axis (shared check with parallel/collectives.validate_ring_perm).
- ``jaxpr-collective-axis``: every named-axis collective names axes actually
  bound by an enclosing shard_map.
- ``jaxpr-double-psum``: no value reduced TWICE over the same axis along one
  path (the S-fold overcount class). Two taints ride the dataflow: axes a
  value is *invariant* (replicated) over, and axes it was already
  *reduced/gathered* over. Only a psum/psum_scatter of a still-reduced value
  trips the rule: jax's own psum-self-transpose convention (the pmean
  backward psums a replicated cotangent, exactly compensated by the 1/S)
  consumes values that are replicated but NOT reduced, so it stays silent —
  as do psums of literals (the symbolic-zero transpose artifact and the
  ``psum(1)`` axis-size idiom). Mixing a reduced value with varying data
  clears the taint (a later psum is then a genuine new reduction);
  unknown ⇒ varying ⇒ silent, the no-false-positive direction.
- ``jaxpr-f64``: no float64/complex128 avals anywhere (silent x64 promotion).
- ``jaxpr-weak-type``: no weak-typed input avals (python-scalar leak — the
  recompile-per-callsite hazard).
- ``jaxpr-chunk-checkpoint``: the chunked loss's scan carries a
  ``jax.checkpoint``'d body (remat eqn inside a dot-bearing scan) — pins
  PR 3's memory contract structurally, complementing the byte-count
  regression test in tests/test_streamed_loss.py.
- ``jaxpr-bf16-upcast``: (opt-in, ``check_bf16_upcast=True``) no explicit
  bf16→f32 convert feeding a dot_general inside a declared-bf16 region — the
  silent half-MXU-rate upcast; f32 ACCUMULATION via
  ``preferred_element_type`` is the sanctioned pattern and does not trip it.
"""

from __future__ import annotations

from distributed_sigmoid_loss_tpu.analysis.findings import Finding

__all__ = [
    "JAXPR_RULES",
    "audit_jaxpr",
    "step_config_jaxprs",
    "audit_default_step_configs",
    "DEFAULT_STEP_CONFIGS",
]

JAXPR_RULES = (
    "jaxpr-ppermute-bijection",
    "jaxpr-collective-axis",
    "jaxpr-double-psum",
    "jaxpr-f64",
    "jaxpr-weak-type",
    "jaxpr-chunk-checkpoint",
    "jaxpr-bf16-upcast",
)

# The fifteen step configs the acceptance gate requires coverage of (the
# round-4 six plus the round-10 streaming-pallas compositions); see
# step_config_jaxprs for how each is built. The pallas_* configs trace at
# kernel-compatible shapes (embed 128, local_b 8 f32 / 32 int8) so the
# pallas_call genuinely appears in the audited jaxpr — an incompatible shape
# would silently audit the XLA fallback instead.
DEFAULT_STEP_CONFIGS = (
    "fused",
    "chunked",
    "ring",
    "ring_overlap",
    "compressed_dcn",
    "quant_train_int8",
    "pallas_fused",
    "pallas_chunked",
    "pallas_ring",
    "pallas_ring_overlap",
    "pallas_int8_fused",
    "pallas_int8_chunked",
    "pallas_int8_ring",
    "pallas_int8_ring_overlap",
    "compressed_pallas_chunked",
)

# Collectives that SUM over their named axes: a second application over the
# same axis to an already-invariant value is the S-fold overcount.
_SUM_PRIMS = {"psum", "reduce_scatter"}
# Reductions whose repeat is idempotent (max of replicated = same value) —
# still tracked for axis binding, never for double-reduce.
_IDEMPOTENT_REDUCE_PRIMS = {"pmin", "pmax"}
_GATHER_PRIMS = {"all_gather"}
_OTHER_COLLECTIVES = {"ppermute", "all_to_all", "pgather", "pbroadcast"}
_ALL_COLLECTIVES = (
    _SUM_PRIMS | _IDEMPOTENT_REDUCE_PRIMS | _GATHER_PRIMS | _OTHER_COLLECTIVES
    | {"axis_index"}
)

_REMAT_PRIMS = {"remat2", "remat", "checkpoint"}

# (invariant-over, reduced-over) for a value we know nothing about.
_VARYING = (frozenset(), frozenset())


def _collective_axes(eqn) -> tuple:
    axes = eqn.params.get("axes", eqn.params.get("axis_name"))
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    flat = []
    for a in axes:
        if isinstance(a, (tuple, list)):
            flat.extend(a)
        else:
            flat.append(a)
    # positional (int) axes come from vmap, not meshes — not our concern
    return tuple(a for a in flat if isinstance(a, str))


def _jaxpr_of(obj):
    """Open jaxpr of a Jaxpr/ClosedJaxpr, else None."""
    if hasattr(obj, "eqns") and hasattr(obj, "invars"):
        return obj
    inner = getattr(obj, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        return inner
    return None


def _sub_jaxprs(params: dict):
    """Every (param_key, open_jaxpr) nested in an eqn's params."""
    out = []
    for k, v in params.items():
        vals = v if isinstance(v, (tuple, list)) else (v,)
        for u in vals:
            j = _jaxpr_of(u)
            if j is not None:
                out.append((k, j))
    return out


def _is_literal(v) -> bool:
    # core.Literal has a `val`; Vars do not.
    return hasattr(v, "val") and not hasattr(v, "count")


class _Auditor:
    """One audit pass over a closed jaxpr; collects deduplicated Findings."""

    def __init__(self, label: str, check_bf16_upcast: bool = False):
        self.label = label
        self.check_bf16_upcast = check_bf16_upcast
        self.findings: list[Finding] = []
        self._seen: set = set()

    def add(self, rule: str, detail: str) -> None:
        key = (rule, detail)
        if key not in self._seen:
            self._seen.add(key)
            self.findings.append(Finding(rule, self.label, detail))

    # -- invariance/reduction-tracking walk ---------------------------------

    def walk(self, jaxpr, env: dict, bound: dict, emit: bool) -> dict:
        """Walk one open jaxpr.

        ``env``: var -> ``(inv, red)`` pair of frozensets: the mesh axes the
        value is known INVARIANT over (replicated; identical on every shard),
        and the subset of those it is invariant over BECAUSE it was already
        reduced/gathered over them (the double-psum taint; always
        ``red ⊆ inv``). Unknown vars default to varying ``(∅, ∅)`` — the
        conservative direction: it can only suppress a finding, never
        fabricate one. Returns the env (callers map outvars through it).
        """

        def get(v):
            if _is_literal(v):
                return (frozenset(bound), frozenset())
            return env.get(v, _VARYING)

        for eqn in jaxpr.eqns:
            name = eqn.primitive.name

            if emit:
                for ov in eqn.outvars:
                    aval = getattr(ov, "aval", None)
                    dt = getattr(aval, "dtype", None)
                    if dt is not None and str(dt) in ("float64", "complex128"):
                        self.add(
                            "jaxpr-f64",
                            f"{name} produces a {dt} value — silent f64 "
                            "promotion (x64 leak); TPU executes f64 in "
                            "software emulation and parity gates assume f32",
                        )

            if name == "shard_map":
                self._walk_shard_map(eqn, env, bound, emit, get)
                continue

            if name in _ALL_COLLECTIVES:
                self._walk_collective(eqn, env, bound, emit, get)
                continue

            if name == "scan":
                self._walk_scan(eqn, env, bound, emit, get)
                continue

            if name == "cond":
                self._walk_cond(eqn, env, bound, emit, get)
                continue

            subs = _sub_jaxprs(eqn.params)
            if subs:
                if name == "while":
                    # Loop-carried invariance needs a fixpoint; assume varying
                    # everywhere inside (silent, never wrong).
                    for _, inner in subs:
                        self.walk(inner, {}, bound, emit)
                    for ov in eqn.outvars:
                        env[ov] = _VARYING
                else:
                    # Call-like eqns (pjit, remat2, custom_jvp/vjp, ...): map
                    # operands through positionally when the arity matches.
                    self._walk_call(eqn, subs, env, bound, emit, get)
                continue

            # Default: elementwise/structural op — invariance is preserved
            # only when EVERY operand is invariant over the axis; the
            # reduced taint survives only while the value stays invariant
            # (mixing with varying data makes a later psum a NEW reduction).
            inv, red = None, frozenset()
            for v in eqn.invars:
                ii, rr = get(v)
                inv = ii if inv is None else (inv & ii)
                red = red | rr
            if inv is None:
                inv = frozenset(bound)  # no operands (iota, rng seeds, ...)
            for ov in eqn.outvars:
                env[ov] = (inv, red & inv)

        if self.check_bf16_upcast and emit:
            self._check_bf16_upcasts(jaxpr)
        return env

    def _walk_shard_map(self, eqn, env, bound, emit, get) -> None:
        mesh = eqn.params.get("mesh")
        auto = eqn.params.get("auto") or frozenset()
        try:
            mesh_axes = dict(mesh.shape)
        except Exception:
            mesh_axes = {}
        inner_bound = dict(bound)
        inner_bound.update(
            {ax: sz for ax, sz in mesh_axes.items() if ax not in auto}
        )
        inner = _jaxpr_of(eqn.params.get("jaxpr"))
        if inner is None:
            for ov in eqn.outvars:
                env[ov] = _VARYING
            return
        in_names = eqn.params.get("in_names") or ()
        inner_env: dict = {}
        for i, iv in enumerate(inner.invars):
            sharded_over: set = set()
            if i < len(in_names):
                for axes_tuple in in_names[i].values():
                    sharded_over.update(axes_tuple)
            # A P()-replicated input is invariant over every bound axis; a
            # P("dp")-sharded one varies over dp. Neither is REDUCED yet.
            inner_env[iv] = (
                frozenset(ax for ax in inner_bound if ax not in sharded_over),
                frozenset(),
            )
        for cv in getattr(inner, "constvars", ()):
            inner_env[cv] = (frozenset(inner_bound), frozenset())
        self.walk(inner, inner_env, inner_bound, emit)
        for ov in eqn.outvars:
            env[ov] = _VARYING

    def _walk_collective(self, eqn, env, bound, emit, get) -> None:
        name = eqn.primitive.name
        axes = _collective_axes(eqn)
        if emit:
            for ax in axes:
                if ax not in bound:
                    self.add(
                        "jaxpr-collective-axis",
                        f"{name} over axis {ax!r} which no enclosing "
                        f"shard_map binds (bound: {sorted(bound) or 'none'})"
                        " — the collective would resolve against a stale or "
                        "foreign axis environment",
                    )
        if name == "ppermute" and emit and axes:
            size = bound.get(axes[0])
            if size is not None:
                from distributed_sigmoid_loss_tpu.parallel.collectives import (
                    ring_perm_problems,
                )

                for problem in ring_perm_problems(
                    eqn.params.get("perm", ()), size
                ):
                    self.add(
                        "jaxpr-ppermute-bijection",
                        f"ppermute over {axes[0]!r} (size {size}): {problem}",
                    )
        if name in _SUM_PRIMS and emit:
            for v in eqn.invars:
                if _is_literal(v):
                    # psum of a trace-time constant: either a symbolic-zero
                    # transpose artifact or the deliberate psum(1) axis-size
                    # idiom — never the overcount bug.
                    continue
                already = sorted(set(axes) & get(v)[1])
                if already:
                    self.add(
                        "jaxpr-double-psum",
                        f"{name} over axis(es) {already} of a value that was "
                        "already reduced/gathered over them — each shard "
                        "contributes the identical summed value, so the "
                        "result is S-fold the intended sum (the shard_map-"
                        "transpose overcount class)",
                    )
        # Output invariance + reduction taint:
        axset = frozenset(axes)
        if name == "psum" or name in _IDEMPOTENT_REDUCE_PRIMS:
            for ov, v in zip(eqn.outvars, eqn.invars):
                inv, red = get(v)
                taint = axset if name == "psum" else frozenset()
                env[ov] = (inv | axset, (red | taint) & (inv | axset))
        elif name in _GATHER_PRIMS:
            inv, red = get(eqn.invars[0])
            for ov in eqn.outvars:
                env[ov] = (inv | axset, (red | axset) & (inv | axset))
        elif name == "axis_index":
            for ov in eqn.outvars:
                env[ov] = (frozenset(bound) - axset, frozenset())
        elif name == "ppermute":
            # permuting a replicated value is the identity; varying stays varying
            for ov in eqn.outvars:
                env[ov] = get(eqn.invars[0])
        else:  # reduce_scatter, all_to_all, ...: shards end up with distinct pieces
            for ov in eqn.outvars:
                env[ov] = _VARYING

    def _walk_scan(self, eqn, env, bound, emit, get) -> None:
        body = _jaxpr_of(eqn.params.get("jaxpr"))
        if body is None:
            for ov in eqn.outvars:
                env[ov] = _VARYING
            return
        nc = eqn.params.get("num_consts", 0)
        ncar = eqn.params.get("num_carry", 0)
        in_inv = [get(v) for v in eqn.invars]
        carry_inv = list(in_inv[nc : nc + ncar])

        def meet(a, b):
            inv = a[0] & b[0]
            return (inv, (a[1] | b[1]) & inv)

        def body_pass(carry, do_emit):
            ienv: dict = {}
            seq = list(in_inv[:nc]) + list(carry) + list(in_inv[nc + ncar :])
            for iv, inv in zip(body.invars, seq):
                ienv[iv] = inv
            for cv in getattr(body, "constvars", ()):
                ienv[cv] = (frozenset(bound), frozenset())
            self.walk(body, ienv, bound, do_emit)
            outs = []
            for ov in body.outvars:
                outs.append(
                    (frozenset(bound), frozenset()) if _is_literal(ov)
                    else ienv.get(ov, _VARYING)
                )
            return outs

        # Fixpoint on the carry's invariance (the invariant set only shrinks,
        # so this terminates fast); findings emit only on the settled pass.
        for _ in range(2 * len(bound) * max(ncar, 1) + 2):
            outs = body_pass(carry_inv, do_emit=False)
            new_carry = [meet(a, b) for a, b in zip(carry_inv, outs[:ncar])]
            if new_carry == carry_inv:
                break
            carry_inv = new_carry
        outs = body_pass(carry_inv, do_emit=emit)
        for i, ov in enumerate(eqn.outvars):
            if i < ncar:
                env[ov] = carry_inv[i] if i < len(carry_inv) else _VARYING
            else:
                env[ov] = outs[i] if i < len(outs) else _VARYING

    def _walk_cond(self, eqn, env, bound, emit, get) -> None:
        branches = eqn.params.get("branches", ())
        ops = eqn.invars[1:]
        out_inv = None
        for br in branches:
            inner = _jaxpr_of(br)
            if inner is None:
                continue
            ienv: dict = {}
            if len(inner.invars) == len(ops):
                for iv, v in zip(inner.invars, ops):
                    ienv[iv] = get(v)
            for cv in getattr(inner, "constvars", ()):
                ienv[cv] = (frozenset(bound), frozenset())
            self.walk(inner, ienv, bound, emit)
            outs = [
                (frozenset(bound), frozenset()) if _is_literal(ov)
                else ienv.get(ov, _VARYING)
                for ov in inner.outvars
            ]
            out_inv = outs if out_inv is None else [
                ((a[0] & b[0]), (a[1] | b[1]) & (a[0] & b[0]))
                for a, b in zip(out_inv, outs)
            ]
        for i, ov in enumerate(eqn.outvars):
            env[ov] = (
                out_inv[i] if out_inv is not None and i < len(out_inv)
                else _VARYING
            )

    def _walk_call(self, eqn, subs, env, bound, emit, get) -> None:
        """pjit / remat2 / custom_jvp / custom_vjp / closed_call: positional
        1:1 operand mapping when the arity matches, varying otherwise."""
        _, inner = subs[0]
        ienv: dict = {}
        if len(inner.invars) == len(eqn.invars):
            for iv, v in zip(inner.invars, eqn.invars):
                ienv[iv] = get(v)
        for cv in getattr(inner, "constvars", ()):
            ienv[cv] = (frozenset(bound), frozenset())
        self.walk(inner, ienv, bound, emit)
        # Extra sub-jaxprs (e.g. custom_vjp's fwd/bwd thunks are not Jaxprs;
        # anything that is gets a conservative varying walk for the
        # axis/bijection/f64 checks).
        for _, extra in subs[1:]:
            self.walk(extra, {}, bound, emit)
        if len(inner.outvars) == len(eqn.outvars):
            for ov, io in zip(eqn.outvars, inner.outvars):
                env[ov] = (
                    (frozenset(bound), frozenset()) if _is_literal(io)
                    else ienv.get(io, _VARYING)
                )
        else:
            for ov in eqn.outvars:
                env[ov] = _VARYING

    # -- bf16 upcast post-scan ----------------------------------------------

    def _check_bf16_upcasts(self, jaxpr) -> None:
        produced_by = {}
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                produced_by[ov] = eqn
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "dot_general":
                continue
            for v in eqn.invars:
                src = produced_by.get(v)
                if src is None or src.primitive.name != "convert_element_type":
                    continue
                src_in = src.invars[0]
                in_aval = getattr(src_in, "aval", None)
                out_aval = getattr(v, "aval", None)
                if (
                    in_aval is not None
                    and out_aval is not None
                    and str(getattr(in_aval, "dtype", "")) == "bfloat16"
                    and str(getattr(out_aval, "dtype", "")) == "float32"
                    and getattr(out_aval, "size", 1) > 1
                ):
                    self.add(
                        "jaxpr-bf16-upcast",
                        "dot_general consumes an explicitly f32-upcast bf16 "
                        "array inside a declared-bf16 region — halves the "
                        "MXU rate silently; keep operands bf16 and use "
                        "preferred_element_type=f32 for the accumulation",
                    )


def _collect_scans(jaxpr, out: list) -> None:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name == "scan":
            body = _jaxpr_of(eqn.params.get("jaxpr"))
            if body is not None:
                out.append(body)
        for _, inner in _sub_jaxprs(eqn.params):
            _collect_scans(inner, out)


def _contains_prim(jaxpr, names: set) -> bool:
    for eqn in jaxpr.eqns:
        if eqn.primitive.name in names:
            return True
        for _, inner in _sub_jaxprs(eqn.params):
            if _contains_prim(inner, names):
                return True
    return False


def audit_jaxpr(
    jaxpr_or_closed,
    *,
    label: str,
    bound_axes: dict | None = None,
    expect_chunk_checkpoint: bool = False,
    check_bf16_upcast: bool = False,
) -> list[Finding]:
    """Audit one (closed) jaxpr; returns the Findings.

    ``bound_axes``: axis name -> size already bound OUTSIDE this jaxpr (for
    auditing a bare shard_map body); normally empty — the walk binds axes at
    the shard_map eqns it encounters.
    """
    auditor = _Auditor(label, check_bf16_upcast=check_bf16_upcast)
    j = _jaxpr_of(jaxpr_or_closed)
    if j is None:
        raise TypeError(f"not a jaxpr: {jaxpr_or_closed!r}")
    import numpy as np

    bound = dict(bound_axes or {})
    env: dict = {}
    for iv in j.invars:
        aval = getattr(iv, "aval", None)
        dt = getattr(aval, "dtype", None)
        # Float/complex only: a weak-typed float input is the classic python-
        # scalar leak (0.1 vs np.float32(0.1) recompiles). Weak INT scalars
        # are the flax convention (TrainState.step counts in a weak int32,
        # stable across the whole run) — flagging them would be pure noise.
        if (
            getattr(aval, "weak_type", False)
            and dt is not None
            and np.issubdtype(dt, np.inexact)
        ):
            auditor.add(
                "jaxpr-weak-type",
                f"input aval {aval} is weak-typed — a python-scalar leak; "
                "the compiled cache keys on weak_type, so passing a numpy "
                "or jax scalar later recompiles the whole program",
            )
        # Top-level inputs are assumed varying (per-shard) — conservative.
        env[iv] = _VARYING
    for cv in getattr(j, "constvars", ()):
        env[cv] = (frozenset(bound), frozenset())
    auditor.walk(j, env, bound, emit=True)

    if expect_chunk_checkpoint:
        scans: list = []
        _collect_scans(j, scans)
        ok = any(
            _contains_prim(body, _REMAT_PRIMS)
            and _contains_prim(body, {"dot_general"})
            for body in scans
        )
        if not ok:
            auditor.add(
                "jaxpr-chunk-checkpoint",
                "no scan with a jax.checkpoint'd (remat) dot-bearing body "
                "found — the chunked loss's backward would save every "
                "block's logits instead of recomputing them, silently "
                "re-materializing the full (local_b, W*local_b) matrix the "
                "chunked path exists to avoid (PR 3 memory contract)",
            )
    return auditor.findings


# ---------------------------------------------------------------------------
# The fifteen real step configs, traced abstractly (no compile, no execution).
# ---------------------------------------------------------------------------


def _abstract_batch(cfg, global_b: int):
    import jax
    import jax.numpy as jnp

    v, t = cfg.vision, cfg.text
    return {
        "images": jax.ShapeDtypeStruct(
            (global_b, v.image_size, v.image_size, 3), jnp.float32
        ),
        "tokens": jax.ShapeDtypeStruct(
            (global_b, t.context_length), jnp.int32
        ),
    }


def _abstract_params(model, batch):
    import jax

    import flax.linen as nn

    boxed = jax.eval_shape(
        lambda r, im, tk: model.init(r, im, tk)["params"],
        jax.random.key(0), batch["images"], batch["tokens"],
    )
    return jax.tree.map(
        lambda x: x.value if isinstance(x, nn.meta.AxisMetadata) else x,
        boxed,
        is_leaf=lambda x: isinstance(x, nn.meta.AxisMetadata),
    )


def _abstract_state(
    model, tx, batch,
    ef_slices: int | None = None,
    comp_tensors: int | None = None,
    ef_full_w: int | None = None,
    learned: bool = False,
):
    import jax
    import jax.numpy as jnp

    from distributed_sigmoid_loss_tpu.train.train_step import TrainState

    params = _abstract_params(model, batch)
    state = jax.eval_shape(
        lambda p: TrainState.create(apply_fn=model.apply, params=p, tx=tx),
        params,
    )
    if ef_slices is not None:
        if ef_full_w:
            # update_sharding="full": the residual is shard-local, so the
            # abstract EF must carry with_error_feedback's padded
            # (n_dcn, padded_rows(d0, W), ...) layout or the traced step
            # would reject the carry's shapes.
            from distributed_sigmoid_loss_tpu.parallel.update_shard import (
                ef_slot_shape,
            )

            ef = jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    ef_slot_shape(x.shape, ef_slices, ef_full_w, "full"),
                    x.dtype,
                ),
                params,
            )
        else:
            from distributed_sigmoid_loss_tpu.train.compressed_step import (
                init_error_feedback,
            )

            ef = jax.eval_shape(
                lambda p: init_error_feedback(p, ef_slices), params
            )
        state = state.replace(ef=ef)
    if comp_tensors is not None:
        # Abstract twin of with_adaptive_compression's carry: one scheme /
        # stat scalar per flattened param leaf, replicated on device.
        comp = {
            "scheme": jax.ShapeDtypeStruct((comp_tensors,), jnp.int32),
            "gnorm": jax.ShapeDtypeStruct((comp_tensors,), jnp.float32),
            "gvar": jax.ShapeDtypeStruct((comp_tensors,), jnp.float32),
            "ef_ratio": jax.ShapeDtypeStruct((comp_tensors,), jnp.float32),
        }
        if learned:
            # graftcodec's learned-rung extension of the carry: the host-
            # trained codec operands plus the step-written trainer stats
            # (with_adaptive_compression(..., learned=True) shapes).
            from distributed_sigmoid_loss_tpu.parallel import (
                adaptive_compression as ac,
            )

            g, b, l = ac.CODEC_GROUPS, ac.CODEC_BLOCK, ac.CODEC_LATENT
            comp.update({
                "codec_enc": jax.ShapeDtypeStruct((g, b, l), jnp.float32),
                "codec_dec": jax.ShapeDtypeStruct((g, l, b), jnp.float32),
                "blockmoment": jax.ShapeDtypeStruct((g, b, b), jnp.float32),
                "codec_recon_err": jax.ShapeDtypeStruct((), jnp.float32),
            })
        state = state.replace(comp=comp)
    return state


# Memo for step_config_jaxprs keyed by the RESOLVED mesh size: the traces
# are deterministic (tiny towers, abstract state, fixed mesh), and the
# auditor, obs/attribution, and obs/regress all enumerate the same sampled
# product — one tier-1 run used to pay the trace three times over. The memo
# is INCREMENTAL per label: the dryrun's --full-product pass reuses every
# trace the tier-1 sample already paid for and adds only the extra configs.
# Host-side only; never read inside traced code (allowlisted in repo_lint).
_STEP_CONFIG_CACHE: dict = {}


def _build_step_config(cfg, n_devices: int):
    """(abstract_state, abstract_batch, build_fn, audit_kwargs) for one
    declarative StepConfig (analysis/config_space.py) — the solver-driven
    generalization of the old hand-written fifteen-entry builds table.

    Shape discipline: the pallas_* configs trace at kernel-compatible shapes
    (embed 128 lane-aligned, per-microstep local_b % 8 for f32 / % 32 for
    the int8 sublane quantum) so the pallas_call genuinely appears in the
    audited jaxpr — an incompatible shape would silently audit the XLA
    fallback instead. Mesh axes are allocated (dcn?, dp, pp?) with dcn and
    pp fixed at 2 (tiny_test towers have depth 2, so 2 pp stages is the
    divisible choice) and dp taking the rest.
    """
    import dataclasses

    import jax
    import numpy as np

    from distributed_sigmoid_loss_tpu.models import SigLIP
    from distributed_sigmoid_loss_tpu.train import (
        make_compressed_train_step,
        make_optimizer,
        make_train_step,
    )
    from distributed_sigmoid_loss_tpu.utils.config import (
        LossConfig,
        SigLIPConfig,
        TrainConfig,
    )
    from jax.sharding import Mesh

    axis_names, shape = ["dp"], [0]
    if cfg.compression:
        axis_names.insert(0, "dcn")
        shape.insert(0, 2)
    if cfg.pp:
        axis_names.append("pp")
        shape.append(2)
    fixed = int(np.prod([s for s in shape if s]))
    dp_size = max(n_devices // max(fixed, 1), 1)
    shape[axis_names.index("dp")] = dp_size
    n_used = int(np.prod(shape))
    mesh = Mesh(
        np.asarray(jax.devices()[:n_used]).reshape(shape), tuple(axis_names)
    )

    mcfg = SigLIPConfig.tiny_test()
    if cfg.use_pallas:
        mcfg = dataclasses.replace(
            mcfg,
            vision=dataclasses.replace(mcfg.vision, embed_dim=128),
            text=dataclasses.replace(mcfg.text, embed_dim=128),
        )
    if cfg.quant_train:
        mcfg = dataclasses.replace(
            mcfg,
            vision=dataclasses.replace(
                mcfg.vision, quant_train=cfg.quant_train
            ),
            text=dataclasses.replace(mcfg.text, quant_train=cfg.quant_train),
        )
    if cfg.moe:
        mcfg = dataclasses.replace(
            mcfg,
            vision=dataclasses.replace(mcfg.vision, moe_experts=4),
            text=dataclasses.replace(
                mcfg.text, moe_experts=4, moe_num_selected=2
            ),
        )
    if cfg.pp:
        # Stage params are the nn.scan-stacked block leaves; tiny_test's
        # depth-2 towers pipeline as 2 stages x 1 block.
        mcfg = dataclasses.replace(
            mcfg,
            vision=dataclasses.replace(mcfg.vision, scan_layers=True),
            text=dataclasses.replace(mcfg.text, scan_layers=True),
        )
    model = SigLIP(mcfg)

    accum_steps = 2 if cfg.accum else 1
    pp_microbatches = 2 if cfg.pp else 0
    # Per-microstep loss-island batch quantum (pallas sublane contract),
    # scaled back up by the microbatch splits that happen before the island.
    quantum = 32 if (cfg.use_pallas and cfg.quant_train) else (
        8 if cfg.use_pallas else 2
    )
    local_b = quantum * accum_steps * max(pp_microbatches, 1)
    # Batch rows shard over the data axes (dcn and dp; pp stages all see the
    # same rows) — for the legacy labels this reproduces the exact historic
    # global sizes (2n / 8n / 32n), keeping their memoized traces and the
    # committed obs/regress baselines byte-comparable.
    batch_shards = dp_size * (2 if cfg.compression else 1)
    batch = _abstract_batch(mcfg, local_b * batch_shards)
    tx = make_optimizer(TrainConfig(warmup_steps=1, total_steps=10))
    comp_tensors = None
    if cfg.compression in ("adaptive", "learned"):
        comp_tensors = len(
            jax.tree_util.tree_leaves(_abstract_params(model, batch))
        )
    full_shard = cfg.update_sharding == "full"
    state = _abstract_state(
        model, tx, batch,
        ef_slices=2 if cfg.error_feedback else None,
        comp_tensors=comp_tensors,
        ef_full_w=dp_size if (full_shard and cfg.error_feedback) else None,
        learned=cfg.compression == "learned",
    )

    loss_cfg = LossConfig(
        variant=cfg.variant,
        family=cfg.family,
        loss_impl=cfg.loss_impl,
        ring_overlap=cfg.ring_overlap,
        use_pallas=cfg.use_pallas,
    )
    if cfg.compression:
        def build():
            return make_compressed_train_step(
                model, mesh, loss_cfg,
                compression=cfg.compression,
                error_feedback=cfg.error_feedback,
                update_sharding=cfg.update_sharding,
                accum_steps=accum_steps,
                accum_negatives=cfg.accum_negatives,
                pp_microbatches=pp_microbatches,
                moe_aux_weight=0.01 if cfg.moe else None,
            )[0]
    else:
        def build():
            return make_train_step(
                model, mesh, loss_cfg,
                accum_steps=accum_steps,
                update_sharding=cfg.update_sharding,
                moe_aux_weight=0.01 if cfg.moe else None,
                pp_microbatches=pp_microbatches,
                accum_negatives=cfg.accum_negatives,
            )[0]

    audit_kwargs: dict = {}
    if cfg.loss_impl == "chunked":
        audit_kwargs["expect_chunk_checkpoint"] = True
    if cfg.error_feedback:
        # Arms shard_flow's jaxpr-ef-threaded rule: step_config_jaxprs
        # resolves the flag into flattened (invar, outvar) index sets once
        # the trace's output structure is known.
        audit_kwargs["check_ef_threading"] = True
    if cfg.compression == "learned":
        # Arms shard_flow's jaxpr-codec-threaded rule the same way: resolved
        # into (codec_in, stat_out, update_out) positions post-trace.
        audit_kwargs["check_codec_threading"] = True
    if cfg.pp:
        # GPipe's shift-register carries are drained by design
        # (parallel/pipeline.py); see shard_flow's module docstring.
        audit_kwargs["check_state_drop"] = False
    if full_shard:
        # Arms shard_flow's jaxpr-gather-placement rule: an all_gather of a
        # reduce-scattered value over this axis before the update would
        # silently re-replicate what graftshard sharded.
        audit_kwargs["update_shard_axis"] = "dp"
    return state, batch, build, audit_kwargs


def step_config_jaxprs(
    n_devices: int | None = None, full_product: bool = False,
) -> dict:
    """label -> (closed_jaxpr, audit_kwargs) for the sampled step-config
    product (config_space.tier1_sample, or .full_product_sample when
    ``full_product=True``), traced on virtual CPU devices. Trace-only: tiny
    towers, abstract state/batch — seconds, not the minutes a compile would
    cost. Traces are memoized per (mesh size, label), so the full-product
    pass pays only for the configs tier-1 didn't already trace (a shallow
    copy is returned so callers can't disturb the memo)."""
    import jax

    from distributed_sigmoid_loss_tpu.analysis.config_space import (
        full_product_sample,
        tier1_sample,
    )

    devices = jax.devices()
    if n_devices is None:
        n_devices = min(8, len(devices))
    if n_devices < 4 or n_devices % 2:
        raise RuntimeError(
            f"the jaxpr audit needs an even mesh of >= 4 devices to cover "
            f"the sampled step configs (got {n_devices}; run under "
            f"--xla_force_host_platform_device_count or lint --cpu-devices)"
        )
    sample = full_product_sample() if full_product else tier1_sample()
    cache = _STEP_CONFIG_CACHE.setdefault(n_devices, {})
    for label, cfg in sample.items():
        if label in cache:
            continue
        state, batch, build, kwargs = _build_step_config(cfg, n_devices)
        step = build()
        want_ef = kwargs.pop("check_ef_threading", False)
        want_codec = kwargs.pop("check_codec_threading", False)
        if want_ef or want_codec:
            closed, out_shape = jax.make_jaxpr(step, return_shape=True)(
                state, batch
            )
            if want_ef:
                kwargs["ef_indices"] = (
                    _leaf_indices_named((state, batch), "ef"),
                    _leaf_indices_named(out_shape, "ef"),
                )
            if want_codec:
                # (codec_in, stat_out, update_out) for jaxpr-codec-threaded:
                # the codec operands among the inputs, the trainer stats
                # among the outputs, and the updated params the decode must
                # reach.
                kwargs["codec_indices"] = (
                    _leaf_indices_named((state, batch), "codec_enc")
                    + _leaf_indices_named((state, batch), "codec_dec"),
                    _leaf_indices_named(out_shape, "blockmoment")
                    + _leaf_indices_named(out_shape, "codec_recon_err"),
                    _leaf_indices_named(out_shape, "params"),
                )
            cache[label] = (closed, kwargs)
        else:
            cache[label] = (jax.make_jaxpr(step)(state, batch), kwargs)
    return {label: cache[label] for label in sample}


def _leaf_indices_named(tree, name: str) -> tuple:
    """Flattened leaf positions whose pytree path contains an entry exactly
    named ``name`` (dataclass field or dict key). Exact match — the state's
    ``ef`` residual leaves, not the metrics dict's ``ef_norm`` scalar. Used
    to locate the EF carry among a traced step's invars/outvars for
    shard_flow's jaxpr-ef-threaded rule."""
    import jax

    leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
    hits = []
    for i, (path, _leaf) in enumerate(leaves):
        for entry in path:
            key = getattr(entry, "name", None)
            if key is None:
                key = getattr(entry, "key", None)
            if key == name:
                hits.append(i)
                break
    return tuple(hits)


def audit_default_step_configs(
    n_devices: int | None = None, full_product: bool = False,
) -> list[Finding]:
    """Audit the sampled step-config product — base jaxpr rules plus the
    shard-flow dataflow rules — the tier-1/dryrun entry point."""
    from distributed_sigmoid_loss_tpu.analysis.shard_flow import (
        audit_shard_flow,
    )

    findings: list[Finding] = []
    jaxprs = step_config_jaxprs(n_devices, full_product=full_product)
    for label, (closed, kwargs) in jaxprs.items():
        flow_kwargs = {
            "check_state_drop": kwargs.get("check_state_drop", True)
        }
        if "ef_indices" in kwargs:
            flow_kwargs["ef_indices"] = kwargs["ef_indices"]
        if "codec_indices" in kwargs:
            flow_kwargs["codec_indices"] = kwargs["codec_indices"]
        if "update_shard_axis" in kwargs:
            flow_kwargs["update_shard_axis"] = kwargs["update_shard_axis"]
        base_kwargs = {
            k: v for k, v in kwargs.items()
            if k not in ("check_state_drop", "ef_indices", "codec_indices",
                         "update_shard_axis")
        }
        findings.extend(audit_jaxpr(closed, label=label, **base_kwargs))
        findings.extend(
            audit_shard_flow(closed, label=label, **flow_kwargs)
        )
    return findings
