"""graftprove half 1: the declarative step-config feature model.

The step-builder lattice is six-ish orthogonal axes (loss-impl x comm x
pallas x quant-train x pp/update-sharding/accum/MoE x compression) whose
legality was,
until this module, encoded ONLY as imperative refusals scattered across
``parallel/api.py``, ``train/train_step.py``, ``train/compressed_step.py``
and the CLI's ``cmd_train`` conflict block. This module states the same
rules ONCE, declaratively (:data:`CONSTRAINTS`), derives the full legal
product from them (:func:`enumerate_legal`), and cross-checks the
declaration against the real imperative layers by probing every config in
the raw product through the actual builders/validators
(:func:`config_space_drift_findings`). A config the table calls legal but
any layer refuses — or vice versa — is a ``config-space-drift`` finding:
somebody changed a refusal without updating the table (or the reverse), and
the audited sample no longer describes what users can build.

The sampled products (:func:`tier1_sample`, :func:`full_product_sample`)
replace jaxpr_audit's hand-maintained fifteen-config list as the lattice
source for the jaxpr auditor, obs/attribution and obs/regress. The
``ema`` axis is constraint-only (it changes state contents, not the traced
step dataflow) and is projected out of every trace sample.

Import-cheap on purpose: stdlib-only at module level; the imperative probe
imports the real builders lazily so ``lint --no-jaxpr`` processes never pay
the jax import.
"""

from __future__ import annotations

import dataclasses
import functools
import itertools
from typing import Callable, Iterator

from distributed_sigmoid_loss_tpu.analysis.findings import Finding

__all__ = [
    "AXES",
    "CONFIG_SPACE_RULES",
    "CONSTRAINTS",
    "Constraint",
    "LEGACY_CONFIGS",
    "StepConfig",
    "config_space_drift_findings",
    "enumerate_legal",
    "full_product_sample",
    "is_legal",
    "iter_product",
    "label_of",
    "probe_imperative",
    "tier1_sample",
    "violations",
]

# The rule this module emits (catalog constant, mirrored in
# analysis.CONFIG_RULES; tests/test_analysis.py pins the agreement).
CONFIG_SPACE_RULES = ("config-space-drift",)


@dataclasses.dataclass(frozen=True)
class StepConfig:
    """One point in the step-config product.

    Axis semantics mirror the user-facing knobs, not the builders' internal
    derived values: ``quant_train`` is the towers' quant mode (the loss
    kernel's int8 path is DERIVED — active iff ``quant_train`` and
    ``use_pallas``, train_step.resolve_loss_quant); ``accum`` means
    ``accum_steps > 1``; ``pp`` means ``pp > 1`` with microbatching;
    ``compression`` implies the compressed (dcn) step builder.
    """

    family: str = "sigmoid"  # sigmoid | softmax
    variant: str = "all_gather"  # all_gather | ring
    loss_impl: str = "fused"  # fused | chunked
    ring_overlap: bool = False
    use_pallas: bool = False
    quant_train: str = ""  # "" | "int8" (tower STE mode)
    # "" | "int8" | "topk" | "adaptive" | "learned" (dcn grad hop; "learned"
    # is the adaptive ladder with the graftcodec autoencoder rung armed)
    compression: str = ""
    controller: str = ""  # "" | "greedy" | "budgeted" (adaptive bit policy)
    error_feedback: bool = False
    pp: bool = False
    update_sharding: str = ""  # "" | "zero1" | "full" (graftshard modes)
    accum: bool = False
    accum_negatives: str = "local"  # local | global
    moe: bool = False
    ema: bool = False


# Axis name -> the values the product ranges over. Order is the product's
# enumeration order (deterministic labels, deterministic sampling).
AXES: dict = {
    "family": ("sigmoid", "softmax"),
    "variant": ("all_gather", "ring"),
    "loss_impl": ("fused", "chunked"),
    "ring_overlap": (False, True),
    "use_pallas": (False, True),
    "quant_train": ("", "int8"),
    "compression": ("", "int8", "topk", "adaptive", "learned"),
    "controller": ("", "greedy", "budgeted"),
    "error_feedback": (False, True),
    "pp": (False, True),
    "update_sharding": ("", "zero1", "full"),
    "accum": (False, True),
    "accum_negatives": ("local", "global"),
    "moe": (False, True),
    "ema": (False, True),
}


@dataclasses.dataclass(frozen=True)
class Constraint:
    """One declarative compatibility rule.

    ``source``: where the imperative refusal lives — the location a
    ``config-space-drift`` finding points at. ``ok`` returns True when the
    config SATISFIES the constraint.
    """

    name: str
    source: str
    reason: str
    ok: Callable[[StepConfig], bool]

    def __str__(self) -> str:
        return f"{self.name} [{self.source}]: {self.reason}"


CONSTRAINTS: tuple = (
    Constraint(
        "chunked-needs-allgather",
        "parallel/api.py::make_per_shard_loss",
        "the chunked scan streams the all_gather's W chunks; the ring "
        "already streams negatives one chunk per hop",
        lambda c: c.loss_impl != "chunked" or c.variant == "all_gather",
    ),
    Constraint(
        "overlap-needs-ring",
        "parallel/api.py::make_per_shard_loss",
        "the all-gather loss has no hop loop to overlap",
        lambda c: not c.ring_overlap or c.variant == "ring",
    ),
    Constraint(
        "softmax-fused-only",
        "parallel/api.py::make_per_shard_loss",
        "chunked/ring_overlap apply to the sigmoid family only (the softmax "
        "ring already streams its logsumexp)",
        lambda c: c.family != "softmax"
        or (c.loss_impl == "fused" and not c.ring_overlap),
    ),
    Constraint(
        "pallas-sigmoid-only",
        "parallel/api.py::make_per_shard_loss",
        "the streaming kernel computes the sigmoid family's block math",
        lambda c: not c.use_pallas or c.family == "sigmoid",
    ),
    Constraint(
        "compression-needs-allgather",
        "train/compressed_step.py::validate_compressed_step_args",
        "the ring ppermute has no joint-(dcn, dp) axis form",
        lambda c: not c.compression or c.variant == "all_gather",
    ),
    Constraint(
        "topk-needs-error-feedback",
        "train/compressed_step.py::validate_compressed_step_args",
        "top-k without error feedback silently drops ~99% of every gradient "
        "as pure bias",
        lambda c: c.compression != "topk" or c.error_feedback,
    ),
    Constraint(
        "adaptive-needs-error-feedback",
        "train/compressed_step.py::validate_compressed_step_args",
        "the adaptive controller's sign/topk rungs are pure bias without the "
        "residual carry, and scheme changes lean on it to absorb transitions",
        lambda c: c.compression != "adaptive" or c.error_feedback,
    ),
    Constraint(
        "learned-needs-error-feedback",
        "train/compressed_step.py::validate_compressed_step_args",
        "the learned rung's autoencoder reconstruction is biased between "
        "codec retrains; only the EF residual carry absorbs that bias",
        lambda c: c.compression != "learned" or c.error_feedback,
    ),
    Constraint(
        "adaptive-excludes-pp",
        "train/compressed_step.py::validate_compressed_step_args",
        "the controller's scheme table and stats are per GLOBAL tensor; pp "
        "shards block-stack gradients stage-locally (learned is the same "
        "adaptive step with the codec rung armed)",
        lambda c: not (c.compression in ("adaptive", "learned") and c.pp),
    ),
    Constraint(
        "controller-needs-adaptive",
        "cli.py::_train_config_conflicts",
        "the bit controller only exists inside the adaptive/learned step "
        "wrapper; a fixed scheme has no per-round policy to select",
        lambda c: not c.controller
        or c.compression in ("adaptive", "learned"),
    ),
    Constraint(
        "error-feedback-needs-compression",
        "train/compressed_step.py::with_error_feedback",
        "the EF residual is the compressor's quantization error; there is "
        "nothing to feed back without a compressed hop",
        lambda c: not c.error_feedback or bool(c.compression),
    ),
    Constraint(
        "gradcache-excludes-pp",
        "train/train_step.py::validate_step_args",
        "the pp forward is already whole-batch per accumulation step",
        lambda c: not (c.pp and c.accum and c.accum_negatives == "global"),
    ),
    Constraint(
        # Subsumes the zero1-era "pp-excludes-zero1" row (graftshard, PR 17):
        # "full" is pp-excluded for the same reason, so one mode-agnostic row
        # replaces it rather than multiplying. The other full-mode refusal —
        # full-requires-dp>1 — is an ENVIRONMENT check (a property of the
        # mesh instance, not the config product; this module's docstring
        # keeps those in the builders/cmd_train) and is pinned by the exit-2
        # CLI tests in tests/test_update_shard.py instead.
        "pp-excludes-update-sharding",
        "train/train_step.py::validate_step_args",
        "the sharded update would re-shard the stage-local moments dp-wise "
        "every step (zero1's constrain and full's reduce-scatter alike)",
        lambda c: not (c.pp and c.update_sharding),
    ),
    Constraint(
        "pp-excludes-moe",
        "train/train_step.py::validate_step_args",
        "pp towers are dense (Block.apply drops sown aux losses)",
        lambda c: not (c.pp and c.moe),
    ),
    Constraint(
        "ema-excludes-compression",
        "cli.py::_train_config_conflicts",
        "the compressed step maintains no EMA (no ema_decay parameter); the "
        "CLI refuses rather than silently dropping the flag",
        lambda c: not (c.ema and c.compression),
    ),
)


def iter_product() -> Iterator[StepConfig]:
    """Every point in the raw (unconstrained) product, in AXES order."""
    names = tuple(AXES)
    for values in itertools.product(*AXES.values()):
        yield StepConfig(**dict(zip(names, values)))


def violations(cfg: StepConfig) -> tuple:
    """The constraints ``cfg`` breaks (empty tuple == legal)."""
    return tuple(c for c in CONSTRAINTS if not c.ok(cfg))


def is_legal(cfg: StepConfig) -> bool:
    return not violations(cfg)


@functools.lru_cache(maxsize=1)
def enumerate_legal() -> tuple:
    """The full legal product, enumerated (deterministic order)."""
    return tuple(c for c in iter_product() if is_legal(c))


# The fifteen hand-picked configs the auditor traced before this module
# existed, stated declaratively. Pinned by tests/test_config_space.py:
# the solver's legal product must stay a superset of these.
LEGACY_CONFIGS: dict = {
    "fused": StepConfig(),
    "chunked": StepConfig(loss_impl="chunked"),
    "ring": StepConfig(variant="ring"),
    "ring_overlap": StepConfig(variant="ring", ring_overlap=True),
    "compressed_dcn": StepConfig(compression="int8", error_feedback=True),
    "quant_train_int8": StepConfig(variant="ring", quant_train="int8"),
    "pallas_fused": StepConfig(use_pallas=True),
    "pallas_chunked": StepConfig(loss_impl="chunked", use_pallas=True),
    "pallas_ring": StepConfig(variant="ring", use_pallas=True),
    "pallas_ring_overlap": StepConfig(
        variant="ring", ring_overlap=True, use_pallas=True
    ),
    "pallas_int8_fused": StepConfig(use_pallas=True, quant_train="int8"),
    "pallas_int8_chunked": StepConfig(
        loss_impl="chunked", use_pallas=True, quant_train="int8"
    ),
    "pallas_int8_ring": StepConfig(
        variant="ring", use_pallas=True, quant_train="int8"
    ),
    "pallas_int8_ring_overlap": StepConfig(
        variant="ring", ring_overlap=True, use_pallas=True, quant_train="int8"
    ),
    "compressed_pallas_chunked": StepConfig(
        loss_impl="chunked", use_pallas=True,
        compression="int8", error_feedback=True,
    ),
}

_LEGACY_BY_CONFIG = {cfg: name for name, cfg in LEGACY_CONFIGS.items()}


def label_of(cfg: StepConfig) -> str:
    """Stable human label: the historical name for the fifteen legacy
    configs, else a canonical generated one (non-default axes, AXES order)."""
    legacy = _LEGACY_BY_CONFIG.get(cfg)
    if legacy is not None:
        return legacy
    base = StepConfig()
    parts = []
    for name in AXES:
        v = getattr(cfg, name)
        if v == getattr(base, name):
            continue
        if v is True:
            parts.append(name)
        else:
            parts.append(f"{name}={v}")
    return "+".join(parts) if parts else "fused"


# ---------------------------------------------------------------------------
# Trace samples: which legal configs the jaxpr auditor actually traces.

# Coverage configs added on top of the legacy fifteen: one per previously
# untraced axis (pp / zero1 / accum / GradCache / MoE / softmax / top-k EF)
# — this is exactly the lattice corner where the pp-silently-dropped-quant
# bug class lived, and what ROADMAP item 4 asked the audit to reach.
_TIER1_EXTRAS = (
    StepConfig(variant="ring", update_sharding="zero1"),
    StepConfig(variant="ring", accum=True),
    StepConfig(accum=True, accum_negatives="global"),  # GradCache
    StepConfig(variant="ring", moe=True),
    StepConfig(pp=True),
    StepConfig(family="softmax"),
    StepConfig(family="softmax", variant="ring"),
    StepConfig(compression="topk", error_feedback=True),
    StepConfig(compression="adaptive", error_feedback=True),
    # graftshard (PR 17): the sharded-update corners — the regular step's
    # reduce-scatter+gather publish, and both compressed shapes that must
    # prove shard-local EF threading (jaxpr-ef-threaded) and gather
    # placement (jaxpr-gather-placement).
    StepConfig(update_sharding="full"),
    StepConfig(compression="int8", error_feedback=True,
               update_sharding="full"),
    StepConfig(compression="adaptive", error_feedback=True,
               update_sharding="full"),
    # graftcodec (PR 18): the learned-rung corners — the codec operands must
    # thread to every switch branch (jaxpr-codec-threaded) alongside the EF
    # carry, both replicated and under the shard-sized full-sharding flow;
    # the budgeted controller is a host-side policy swap (same trace), so
    # one budgeted config pins that the axis does not fork the jaxpr.
    StepConfig(compression="learned", error_feedback=True),
    StepConfig(compression="learned", error_feedback=True,
               controller="budgeted"),
    StepConfig(compression="learned", error_feedback=True,
               update_sharding="full"),
)


def tier1_sample() -> dict:
    """label -> StepConfig for the tier-1 (and default ``lint``) trace set:
    the fifteen legacy configs plus one coverage config per previously
    untraced axis. ~23 traces — sized for the 870 s tier-1 budget."""
    out = dict(LEGACY_CONFIGS)
    for cfg in _TIER1_EXTRAS:
        assert is_legal(cfg), f"tier1 extra violates the table: {cfg}"
        out[label_of(cfg)] = cfg
    return out


def _traceable(cfg: StepConfig) -> bool:
    # ema is constraint-only: it swaps state contents (an EMA param copy),
    # not the traced step dataflow — project it out of every trace sample.
    return not cfg.ema


@functools.lru_cache(maxsize=1)
def full_product_sample() -> dict:
    """label -> StepConfig for ``lint --full-product`` / the dryrun: the
    tier-1 sample plus a deterministic greedy pairwise-covering sample of
    the remaining legal product (every legal VALUE PAIR of distinct axes
    appears in at least one traced config, ema projected out). Pairwise is
    the sweet spot: the historical step-builder bugs (pp x quant drop,
    chunked x pallas checkpoint, compression x accum) were all two-axis
    interactions."""
    sample = tier1_sample()
    names = tuple(n for n in AXES if n != "ema")

    def pairs(cfg):
        vals = [(n, getattr(cfg, n)) for n in names]
        return set(itertools.combinations(vals, 2))

    covered = set()
    for cfg in sample.values():
        covered |= pairs(cfg)
    # Pairs no legal config exhibits (constraint-excluded) can never be
    # covered; restrict the target to the achievable set.
    legal = [c for c in enumerate_legal() if _traceable(c)]
    achievable = set()
    for cfg in legal:
        achievable |= pairs(cfg)
    remaining = achievable - covered
    while remaining:
        best, best_gain = None, 0
        for cfg in legal:
            gain = len(pairs(cfg) & remaining)
            if gain > best_gain:
                best, best_gain = cfg, gain
        if best is None:  # pragma: no cover - achievable set guarantees progress
            break
        label = label_of(best)
        assert label not in sample or sample[label] == best
        sample[label] = best
        remaining -= pairs(best)
    return dict(sample)


# ---------------------------------------------------------------------------
# The imperative cross-check ("probe"): run every config in the RAW product
# through the real refusal layers and compare with the table's verdict.


def _derived_loss_quant(cfg: StepConfig) -> str:
    # train_step.resolve_loss_quant: the loss matmul takes the int8 MXU path
    # iff the towers train int8-STE AND the pallas kernel carries the loss.
    return "int8" if (cfg.quant_train == "int8" and cfg.use_pallas) else ""


def probe_imperative(cfg: StepConfig) -> tuple[bool, str]:
    """Would the real builders accept ``cfg``? Returns (accepted, detail).

    Three layers, same order a real run hits them: the CLI conflict block
    (cli._train_config_conflicts on a synthesized arg namespace), the loss
    builder (parallel.api.make_per_shard_loss), and the step builders' pure
    validators (train_step.validate_step_args /
    compressed_step.validate_compressed_step_args, called with a superset
    mesh so environment-only refusals never fire). Tower-shape and
    state-content checks (validate_pp_tower, state.ema presence) are
    environmental, not config-space, and are out of probe scope.
    """
    import argparse

    from distributed_sigmoid_loss_tpu.cli import _train_config_conflicts

    ns = argparse.Namespace(
        ep=1,
        moe_experts=4 if cfg.moe else 0,
        moe_aux_weight=0.01 if cfg.moe else None,
        pp=2 if cfg.pp else 1,
        pp_microbatches=2 if cfg.pp else 0,
        zero1=False,  # legacy alias flag; the axis rides update_sharding
        update_sharding=cfg.update_sharding,
        accum=2 if cfg.accum else 1,
        accum_bf16=False,
        accum_negatives=cfg.accum_negatives,
        gradcache_bf16=False,
        loss_family=cfg.family,
        variant=cfg.variant,
        loss_impl=cfg.loss_impl,
        ring_overlap=cfg.ring_overlap,
        use_pallas=cfg.use_pallas,
        watchdog="off",
        ckpt_dir="",
        dcn_slices=2 if cfg.compression else 1,
        grad_compression=cfg.compression,
        topk_frac=0.01,
        topk_exact=False,
        dcn_budget_mbps=None,
        # graftcodec knobs: the controller axis maps 1:1 onto --controller
        # (None == flag unset); the DCN emulator is an environment knob (a
        # harness, not a step shape), so the probe leaves it off — its
        # dcn-axis refusal is pinned by the exit-2 CLI tests instead.
        controller=cfg.controller or None,
        emu_dcn_mbps=None,
        ema_decay=0.999 if cfg.ema else None,
    )
    conflict = _train_config_conflicts(ns)
    if conflict is not None:
        return False, f"cli: {conflict}"
    # The compressed step exists only behind --grad-compression; EF without a
    # compressed hop is not expressible through any imperative surface, so the
    # CLI layer is its refusal point (with_error_feedback is compressed-only).
    if cfg.error_feedback and not cfg.compression:
        return False, "cli: error feedback requires --grad-compression"

    import jax

    from distributed_sigmoid_loss_tpu.parallel.api import make_per_shard_loss

    try:
        make_per_shard_loss(
            family=cfg.family,
            variant=cfg.variant,
            axis_name=("dcn", "dp") if cfg.compression else "dp",
            bidir=False,
            precision=jax.lax.Precision.HIGHEST,
            use_pallas=cfg.use_pallas,
            loss_impl=cfg.loss_impl,
            ring_overlap=cfg.ring_overlap,
            quant=_derived_loss_quant(cfg),
        )
    except ValueError as e:
        return False, f"parallel/api: {e}"

    accum_steps = 2 if cfg.accum else 1
    pp_microbatches = 2 if cfg.pp else 0
    try:
        if cfg.compression:
            from distributed_sigmoid_loss_tpu.train.compressed_step import (
                validate_compressed_step_args,
            )

            validate_compressed_step_args(
                accum_steps=accum_steps,
                accum_dtype=None,
                accum_negatives=cfg.accum_negatives,
                pp_microbatches=pp_microbatches,
                moe_aux_weight=0.01 if cfg.moe else None,
                gradcache_embed_dtype=None,
                compression=cfg.compression,
                error_feedback=cfg.error_feedback,
                topk_frac=0.01,
                loss_variant=cfg.variant,
                mesh_axis_names=("dcn", "dp", "pp"),
                update_sharding=cfg.update_sharding,
            )
        else:
            from distributed_sigmoid_loss_tpu.train.train_step import (
                validate_step_args,
            )

            validate_step_args(
                accum_steps=accum_steps,
                accum_dtype=None,
                accum_negatives=cfg.accum_negatives,
                pp_microbatches=pp_microbatches,
                moe_aux_weight=0.01 if cfg.moe else None,
                gradcache_embed_dtype=None,
                mesh_axis_names=("dp", "pp"),
                update_sharding=cfg.update_sharding,
            )
    except ValueError as e:
        return False, f"step builder: {e}"
    return True, "accepted"


def config_space_drift_findings(
    probe: Callable[[StepConfig], tuple[bool, str]] | None = None,
    configs=None,
) -> list[Finding]:
    """Cross-check the declarative table against the imperative refusals
    over the full raw product. ``probe``/``configs`` are injectable for the
    falsification fixtures (tests/test_config_space.py)."""
    probe = probe or probe_imperative
    configs = list(configs) if configs is not None else list(iter_product())
    findings: list[Finding] = []
    for cfg in configs:
        declared = violations(cfg)
        accepted, detail = probe(cfg)
        if accepted and declared:
            broken = declared[0]
            findings.append(
                Finding(
                    "config-space-drift",
                    label_of(cfg),
                    f"the imperative layers ACCEPT this config but the "
                    f"declarative table forbids it ({broken.name}: "
                    f"{broken.reason}) — a refusal was relaxed without "
                    f"updating analysis/config_space.py, or the constraint "
                    f"is stale",
                    location=broken.source,
                )
            )
        elif not accepted and not declared:
            findings.append(
                Finding(
                    "config-space-drift",
                    label_of(cfg),
                    f"the declarative table calls this config legal but an "
                    f"imperative layer refuses it ({detail}) — a refusal "
                    f"was added without a matching Constraint, so the "
                    f"audited sample no longer spans what users can build",
                    location="analysis/config_space.py::CONSTRAINTS",
                )
            )
    return findings
