"""The one Finding type every graftlint rule reports through.

Stdlib-only on purpose: ``bench_schema`` (imported by bench.py, whose
top-level imports must stay stdlib-only) and the AST linter share it without
pulling jax into processes that never trace anything.
"""

from __future__ import annotations

import dataclasses

__all__ = ["Finding"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint/audit finding.

    ``rule``: the rule id (stable, used by ``lint --disable``).
    ``subject``: what was audited — a step-config label for jaxpr rules, a
    ``path::name`` for repo rules.
    ``detail``: human-readable description of the violation and why it bites.
    """

    rule: str
    subject: str
    detail: str

    def __str__(self) -> str:  # the `lint` CLI's text output line
        return f"[{self.rule}] {self.subject}: {self.detail}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)
