"""The one Finding type every graftlint rule reports through.

Stdlib-only on purpose: ``bench_schema`` (imported by bench.py, whose
top-level imports must stay stdlib-only) and the AST linter share it without
pulling jax into processes that never trace anything.
"""

from __future__ import annotations

import dataclasses

__all__ = ["Finding"]


@dataclasses.dataclass(frozen=True)
class Finding:
    """One lint/audit finding.

    ``rule``: the rule id (stable, used by ``lint --disable``).
    ``subject``: what was audited — a step-config label for jaxpr rules, a
    ``path::name`` for repo rules.
    ``detail``: human-readable description of the violation and why it bites.
    ``location``: where to annotate — ``path:line`` for repo rules, a
    constraint/refusal source for config rules, a step-config label for
    jaxpr rules. Optional; empty when a rule has no better anchor than
    ``subject``.
    """

    rule: str
    subject: str
    detail: str
    location: str = ""

    def __str__(self) -> str:  # the `lint` CLI's text output line
        loc = f" ({self.location})" if self.location else ""
        return f"[{self.rule}] {self.subject}{loc}: {self.detail}"

    def as_dict(self) -> dict:
        d = dataclasses.asdict(self)
        # CI annotators key on rule_id; keep it alongside the short name so
        # `lint --json` consumers never parse the text line.
        d["rule_id"] = self.rule
        return d

    def key(self) -> tuple[str, str]:
        """Stable identity used by ``lint --baseline`` suppression."""
        return (self.rule, self.subject)
