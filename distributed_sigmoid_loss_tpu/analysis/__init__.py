"""graftlint: static analyzers for the distributed-correctness bug classes
this repo has actually hit.

Three halves, one Finding stream:

- :mod:`.jaxpr_audit` traces the real loss/train-step builders on the
  virtual-device CPU mesh and walks their closed jaxprs (collective axis
  binding, ppermute bijections, S-fold psum overcounts, dtype/weak-type
  hygiene, the chunked scan's checkpoint contract). Trace-only — no compile.
  :mod:`.shard_flow` ("graftprove") extends the walk with per-value
  sharded/replicated dataflow rules: redundant gathers of replicated
  values, scan state that is read-then-silently-dropped, and cross-branch
  collective-order consistency.
- :mod:`.config_space` ("graftprove") is the declarative feature model of
  the step-config axes: a constraint table, a solver enumerating the legal
  product (the lattice source for the traced sample), and a drift check
  probing every config through the real imperative refusal layers.
- :mod:`.repo_lint` is an AST pass over the package + bench.py enforcing
  repo invariants (trace-time mutable globals, bench compile-shield
  coverage, doc staleness, slow markers, bench record schema).
- :mod:`.lock_flow` ("graftguard") is the concurrency half: guarded-by
  inference over every lock-owning class (unguarded writes, un-looped
  ``Condition.wait``, blocking calls under a lock, orphan threads), the
  static lock-acquisition graph with cycle detection, and the
  ``repo-lockwatch-gate`` proof that :mod:`..obs.lockwatch`'s runtime
  witness is dead in prod and every lock routes through it.

Run via ``python -m distributed_sigmoid_loss_tpu lint`` (exit 1 on findings,
``--json``, per-rule ``--disable``, ``--full-product`` for the
pairwise-covering sample, ``--baseline`` for ratchet mode), via the dryrun's
graftlint + graftprove tokens (__graft_entry__.py), and via
tests/test_analysis.py + tests/test_config_space.py so the gate is
self-enforcing on every future PR. Rule catalog + allowlist policy:
docs/ANALYSIS.md.
"""

from __future__ import annotations

from distributed_sigmoid_loss_tpu.analysis.findings import Finding  # noqa: F401
from distributed_sigmoid_loss_tpu.analysis.lock_flow import (  # noqa: F401
    LOCK_RULES,
    run_lock_flow,
)
from distributed_sigmoid_loss_tpu.analysis.repo_lint import (  # noqa: F401
    REPO_RULES,
    run_repo_lint,
)

__all__ = [
    "Finding",
    "ALL_RULES",
    "REPO_RULES",
    "LOCK_RULES",
    "JAXPR_RULES",
    "CONFIG_RULES",
    "META_RULES",
    "run_lint",
    "run_lock_flow",
    "load_lint_baseline",
    "apply_lint_baseline",
]

# jaxpr rule ids duplicated here (not imported) so listing rules — the CLI's
# --disable choices — never pays the jax import. The first seven live in
# jaxpr_audit, the last six in shard_flow; tests/test_analysis.py pins the
# literals against the source catalogs.
JAXPR_RULES = (
    "jaxpr-ppermute-bijection",
    "jaxpr-collective-axis",
    "jaxpr-double-psum",
    "jaxpr-f64",
    "jaxpr-weak-type",
    "jaxpr-chunk-checkpoint",
    "jaxpr-bf16-upcast",
    "jaxpr-redundant-gather",
    "jaxpr-state-drop",
    "jaxpr-collective-order",
    "jaxpr-ef-threaded",
    "jaxpr-codec-threaded",
    "jaxpr-gather-placement",
)

# config_space's declarative-vs-imperative cross-check (jax-light: the probe
# imports the builders but never traces).
CONFIG_RULES = ("config-space-drift",)

# Rules about the lint run itself: a --baseline entry that no longer fires.
META_RULES = ("lint-stale-suppression",)

ALL_RULES = REPO_RULES + LOCK_RULES + JAXPR_RULES + CONFIG_RULES + META_RULES


def run_lint(
    disabled=(),
    jaxpr: bool = True,
    n_devices: int | None = None,
    full_product: bool = False,
) -> list[Finding]:
    """Run the repo linter, the lock-flow analyzer, and (unless
    ``jaxpr=False``) the config-space drift check plus the jaxpr auditor
    over the sampled step-config product.

    ``disabled``: rule ids to drop from the result. ``n_devices``: virtual
    mesh size for the auditor (default: min(8, available)).
    ``full_product``: audit the pairwise-covering sample of the full legal
    config product instead of the tier-1 sample (reserved for the
    dryrun/driver — extra traces cost ~30 s).
    """
    disabled = set(disabled)
    findings = run_repo_lint(disabled=disabled)
    findings.extend(run_lock_flow(disabled=disabled))
    if jaxpr:
        # Imported lazily: the AST half must stay usable (and fast) in
        # processes that never initialize jax.
        from distributed_sigmoid_loss_tpu.analysis.config_space import (
            config_space_drift_findings,
        )
        from distributed_sigmoid_loss_tpu.analysis.jaxpr_audit import (
            audit_default_step_configs,
        )

        findings.extend(config_space_drift_findings())
        findings.extend(
            audit_default_step_configs(
                n_devices=n_devices, full_product=full_product
            )
        )
    return [f for f in findings if f.rule not in disabled]


def load_lint_baseline(path) -> list:
    """Parse a ``--baseline`` file: either a saved ``lint --json`` report
    (``{"findings": [...]}``) or a bare JSON list of finding dicts. Returns
    ``(rule, subject)`` keys — the stable identity findings are matched on
    (details may legitimately reword across versions)."""
    import json

    with open(path) as f:
        data = json.load(f)
    entries = data.get("findings", data) if isinstance(data, dict) else data
    keys = []
    for e in entries:
        if not isinstance(e, dict) or "rule" not in e or "subject" not in e:
            raise ValueError(
                f"baseline entry {e!r} needs 'rule' and 'subject' keys "
                "(write one with: lint --json > baseline.json)"
            )
        keys.append((e["rule"], e["subject"]))
    return keys


def apply_lint_baseline(findings: list, baseline_keys: list) -> list:
    """Ratchet mode: drop findings matching a baseline entry; every baseline
    entry that no longer fires becomes a ``lint-stale-suppression`` finding
    (the ratchet only tightens — fixed findings must leave the baseline)."""
    baseline = set(baseline_keys)
    kept = [f for f in findings if f.key() not in baseline]
    fired = {f.key() for f in findings}
    stale = [k for k in baseline_keys if k not in fired]
    for rule, subject in sorted(set(stale)):
        kept.append(
            Finding(
                "lint-stale-suppression",
                subject,
                f"baseline suppresses [{rule}] here but it no longer fires "
                "— remove the entry so the ratchet stays tight",
                location="lint --baseline",
            )
        )
    return kept
