"""graftlint: static analyzers for the distributed-correctness bug classes
this repo has actually hit.

Two halves, one Finding stream:

- :mod:`.jaxpr_audit` traces the real loss/train-step builders on the
  virtual-device CPU mesh and walks their closed jaxprs (collective axis
  binding, ppermute bijections, S-fold psum overcounts, dtype/weak-type
  hygiene, the chunked scan's checkpoint contract). Trace-only — no compile.
- :mod:`.repo_lint` is an AST pass over the package + bench.py enforcing
  repo invariants (trace-time mutable globals, bench compile-shield
  coverage, doc staleness, slow markers, bench record schema).

Run via ``python -m distributed_sigmoid_loss_tpu lint`` (exit 1 on findings,
``--json``, per-rule ``--disable``), via the dryrun's graftlint token
(__graft_entry__.py), and via tests/test_analysis.py so the gate is
self-enforcing on every future PR. Rule catalog + allowlist policy:
docs/ANALYSIS.md.
"""

from __future__ import annotations

from distributed_sigmoid_loss_tpu.analysis.findings import Finding  # noqa: F401
from distributed_sigmoid_loss_tpu.analysis.repo_lint import (  # noqa: F401
    REPO_RULES,
    run_repo_lint,
)

__all__ = ["Finding", "ALL_RULES", "REPO_RULES", "JAXPR_RULES", "run_lint"]

# jaxpr rule ids duplicated here (not imported) so listing rules — the CLI's
# --disable choices — never pays the jax import.
JAXPR_RULES = (
    "jaxpr-ppermute-bijection",
    "jaxpr-collective-axis",
    "jaxpr-double-psum",
    "jaxpr-f64",
    "jaxpr-weak-type",
    "jaxpr-chunk-checkpoint",
    "jaxpr-bf16-upcast",
)

ALL_RULES = REPO_RULES + JAXPR_RULES


def run_lint(
    disabled=(), jaxpr: bool = True, n_devices: int | None = None,
) -> list[Finding]:
    """Run the repo linter and (unless ``jaxpr=False``) the jaxpr auditor.

    ``disabled``: rule ids to drop from the result. ``n_devices``: virtual
    mesh size for the auditor (default: min(8, available)).
    """
    disabled = set(disabled)
    findings = run_repo_lint(disabled=disabled)
    if jaxpr:
        # Imported lazily: the AST half must stay usable (and fast) in
        # processes that never initialize jax.
        from distributed_sigmoid_loss_tpu.analysis.jaxpr_audit import (
            audit_default_step_configs,
        )

        findings.extend(audit_default_step_configs(n_devices=n_devices))
    return [f for f in findings if f.rule not in disabled]
