"""Mixture-of-Experts MLP with expert parallelism over an ``ep`` mesh axis.

The reference has no model layer at all (its towers are toy Linears,
/root/reference/test_distributed_sigmoid_loss.py:71-76); MoE is part of this
framework's beyond-reference scale story — the standard way to grow tower
capacity without growing per-token FLOPs.

TPU-native design (GShard/Switch, not a torch-style loop over experts):

- **Dispatch is einsum, not gather.** Routing builds one-hot dispatch/combine
  tensors and moves tokens with two (T,E,C)-shaped einsums — dense matmuls the
  MXU executes directly, with no data-dependent shapes or scatter ops that would
  defeat XLA. Capacity ``C`` is static: ``ceil(k·T/E · capacity_factor)``.
- **Expert parallelism is a sharding annotation.** Expert kernels are stacked
  ``(E, d, h)`` and partitioned over ``ep`` (composable with ``tp`` on the hidden
  dim); under jit GSPMD turns the dispatch einsums into the all-to-alls that ship
  token slots to their expert's chip — no hand-written comm, same recipe as the
  tp all-reduces in models/transformer.py.
- **Static drop semantics.** Tokens routed past a full expert buffer contribute
  zero output (the residual connection carries them through unchanged) — the
  schedule every tick is shape-identical, which is what keeps one compiled step.
- **Router in f32.** Softmax over expert logits runs in float32 regardless of the
  activation dtype (bf16 router logits visibly perturb top-k order); the expert
  matmuls themselves stay in the model dtype.

The load-balancing auxiliary loss (Switch Transformers eq. 4: ``E · Σ_e f_e·P_e``)
is sown into the ``"intermediates"`` collection as ``"moe_aux_loss"``; training
code pulls it with ``mutable=["intermediates"]`` and adds
``moe_aux_weight · mean`` to the task loss (see train/train_step.py).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

# Mesh axis name for expert parallelism (mirrors TP_AXIS in transformer.py).
EP_AXIS = "ep"

__all__ = ["MoeMlp", "EP_AXIS"]


class MoeMlp(nn.Module):
    """Drop-in MoE replacement for the dense transformer ``Mlp``.

    Args:
      width: model dim d.
      mlp_ratio: expert hidden dim = ``round(width * mlp_ratio)``.
      num_experts: E, total experts (shard-count over ``ep`` divides this).
      num_selected: k experts per token (1 = Switch, 2 = GShard-style top-2 with
        renormalized gates).
      capacity_factor: per-expert buffer slack over the perfectly-balanced
        ``k·T/E`` load; tokens past the buffer are dropped (residual carries them).
      dtype: activation dtype for the expert matmuls (router stays f32).
    """

    width: int
    mlp_ratio: int | float
    num_experts: int
    dtype: Any
    num_selected: int = 1
    capacity_factor: float = 1.25

    @nn.compact
    def __call__(self, x):
        if self.num_selected not in (1, 2):
            raise ValueError(f"num_selected must be 1 or 2, got {self.num_selected}")
        if self.num_experts < 2:
            raise ValueError(f"num_experts must be >= 2, got {self.num_experts}")
        d, e, k = self.width, self.num_experts, self.num_selected
        hidden = int(round(self.width * self.mlp_ratio))
        *lead, d_in = x.shape
        assert d_in == d, f"input dim {d_in} != width {d}"
        tokens = 1
        for n in lead:
            tokens *= n
        xt = x.reshape(tokens, d)

        # --- Router (f32 end-to-end) ------------------------------------------
        wr = self.param(
            "router", nn.initializers.normal(0.02), (d, e), jnp.float32
        )
        logits = jnp.einsum("td,de->te", xt.astype(jnp.float32), wr)
        probs = jax.nn.softmax(logits, axis=-1)  # (T, E)
        gates, idx = jax.lax.top_k(probs, k)  # (T, k)
        if k > 1:
            gates = gates / jnp.sum(gates, axis=-1, keepdims=True)

        # --- Capacity assignment ----------------------------------------------
        # Slot positions via a cumulative count in choice-major order: every
        # token's 1st choice outranks any token's 2nd choice (GShard's priority
        # rule), and within a choice earlier tokens win — all static-shape.
        capacity = min(
            tokens, max(1, int(-(-k * tokens * self.capacity_factor // e)))
        )
        choice_onehot = jax.nn.one_hot(
            jnp.swapaxes(idx, 0, 1), e, dtype=jnp.float32
        )  # (k, T, E)
        position = (
            jnp.cumsum(choice_onehot.reshape(k * tokens, e), axis=0) - 1.0
        ).reshape(k, tokens, e)
        slot = jnp.sum(position * choice_onehot, axis=-1).astype(jnp.int32)  # (k, T)
        keep = (slot < capacity).astype(jnp.float32)
        slot_onehot = jax.nn.one_hot(slot, capacity, dtype=jnp.float32) * keep[
            ..., None
        ]  # (k, T, C)
        # (k, T, E, C) per-choice dispatch; choices land in disjoint slots so the
        # sum over k is still one-hot per (E, C) slot.
        dispatch = jnp.einsum("kte,ktc->ktec", choice_onehot, slot_onehot)
        combine = jnp.einsum("tk,ktec->tec", gates.astype(jnp.float32),
                             dispatch)  # gate-weighted
        dispatch = jnp.sum(dispatch, axis=0)  # (T, E, C)

        # --- Load-balancing auxiliary loss (Switch eq. 4) ---------------------
        # f_e: fraction of tokens whose first choice is e; P_e: mean router prob.
        first_choice = jax.nn.one_hot(idx[:, 0], e, dtype=jnp.float32)
        aux = e * jnp.sum(
            jnp.mean(first_choice, axis=0) * jnp.mean(probs, axis=0)
        )
        self.sow("intermediates", "moe_aux_loss", aux)

        # --- Expert compute (model dtype; E sharded over ep) ------------------
        wi = self.param(
            "wi",
            nn.with_partitioning(
                nn.initializers.xavier_uniform(), (EP_AXIS, None, "tp")
            ),
            (e, d, hidden),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(
                nn.initializers.xavier_uniform(), (EP_AXIS, "tp", None)
            ),
            (e, hidden, d),
            jnp.float32,
        )
        expert_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )
        # Same checkpoint tag as the dense Mlp (transformer.py): the save_hot /
        # save_mlp remat policies keep the expert hidden activation, so backward
        # recompute stops at the elementwise gelu for MoE blocks too.
        hidden_act = checkpoint_name(
            jnp.einsum("ecd,edh->ech", expert_in, wi.astype(self.dtype)),
            "mlp_hidden",
        )
        h = nn.gelu(hidden_act, approximate=True)
        expert_out = jnp.einsum("ech,ehd->ecd", h, wo.astype(self.dtype))
        y = jnp.einsum(
            "tec,ecd->td", combine.astype(self.dtype), expert_out
        )
        return y.reshape(*lead, d)
