"""Mixture-of-Experts MLP with expert parallelism over an ``ep`` mesh axis.

The reference has no model layer at all (its towers are toy Linears,
/root/reference/test_distributed_sigmoid_loss.py:71-76); MoE is part of this
framework's beyond-reference scale story — the standard way to grow tower
capacity without growing per-token FLOPs.

TPU-native design (GShard/Switch, not a torch-style loop over experts):

- **Dispatch is einsum, not gather.** Routing builds one-hot dispatch/combine
  tensors and moves tokens with group-batched einsums — dense matmuls the MXU
  executes directly, with no data-dependent shapes or scatter ops that would
  defeat XLA. Tokens route within fixed-size GROUPS (GShard's groups), so the
  static capacity ``C = ceil(k·group/E · capacity_factor)`` — and with it the
  dispatch/combine memory — is independent of the global batch.
- **Expert parallelism is a sharding annotation.** Expert kernels are stacked
  ``(E, d, h)`` and partitioned over ``ep`` (composable with ``tp`` on the hidden
  dim); under jit GSPMD turns the dispatch einsums into the all-to-alls that ship
  token slots to their expert's chip — no hand-written comm, same recipe as the
  tp all-reduces in models/transformer.py.
- **Static drop semantics.** Tokens routed past a full expert buffer contribute
  zero output (the residual connection carries them through unchanged) — the
  schedule every tick is shape-identical, which is what keeps one compiled step.
- **Router in f32.** Softmax over expert logits runs in float32 regardless of the
  activation dtype (bf16 router logits visibly perturb top-k order); the expert
  matmuls themselves stay in the model dtype.

The load-balancing auxiliary loss (Switch Transformers eq. 4: ``E · Σ_e f_e·P_e``)
is sown into the ``"intermediates"`` collection as ``"moe_aux_loss"``; training
code pulls it with ``mutable=["intermediates"]`` and adds
``moe_aux_weight · mean`` to the task loss (see train/train_step.py).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

# Mesh axis name for expert parallelism (mirrors TP_AXIS in transformer.py).
EP_AXIS = "ep"

__all__ = [
    "MoeMlp",
    "EP_AXIS",
    "router_topk",
    "build_dispatch",
    "expert_apply",
    "moe_capacity",
]


# Pure stages of the MoE layer, factored out so the per-component perf
# breakdown (bench.py --moe-breakdown) times EXACTLY the code the module runs.


def router_topk(xg: jax.Array, wr: jax.Array, k: int):
    """Router in f32: ``(probs, gates, idx)`` for grouped tokens ``(n, g, d)``."""
    logits = jnp.einsum("ntd,de->nte", xg.astype(jnp.float32), wr)
    probs = jax.nn.softmax(logits, axis=-1)  # (n, g, E)
    gates, idx = jax.lax.top_k(probs, k)  # (n, g, k)
    if k > 1:
        gates = gates / jnp.sum(gates, axis=-1, keepdims=True)
    return probs, gates, idx


def moe_capacity(group: int, e: int, k: int, capacity_factor: float) -> int:
    """Static per-expert buffer: ``min(group, ceil(k·group/E · cf))``."""
    return min(group, max(1, int(-(-k * group * capacity_factor // e))))


def build_dispatch(
    gates: jax.Array, idx: jax.Array, e: int, capacity: int, dtype=jnp.float32
):
    """One-hot dispatch/combine tensors from the router's top-k choices.

    Slot positions via a cumulative count in choice-major order within each
    group: every token's 1st choice outranks any token's 2nd choice (GShard's
    priority rule), and within a choice earlier tokens win — all static-shape.
    Returns ``(dispatch (n,g,E,C), combine (n,g,E,C))``.

    ``dtype`` is the OUTPUT dtype of the dispatch/combine tensors (the model
    activation dtype in the layer). The slot arithmetic — the cumulative
    count, whose values reach ``group`` and would corrupt past 256 in bf16 —
    always runs in f32; only the one-hots and gate weights, whose exact
    values (0/1 and softmax gates) bf16 carries fine, are emitted in
    ``dtype``. That halves the HBM traffic of the (tokens, E, C) tensors,
    the round-3 breakdown's "dispatch build" cost.
    """
    n_groups, group, k = idx.shape
    choice_f32 = jax.nn.one_hot(
        jnp.moveaxis(idx, -1, 1), e, dtype=jnp.float32
    )  # (n, k, g, E)
    position = (
        jnp.cumsum(choice_f32.reshape(n_groups, k * group, e), axis=1) - 1.0
    ).reshape(n_groups, k, group, e)
    slot = jnp.sum(position * choice_f32, axis=-1).astype(jnp.int32)  # (n, k, g)
    choice_onehot = choice_f32.astype(dtype)
    # Over-capacity drops come free: one_hot emits an all-zero row for any
    # slot >= capacity (out-of-range index), so no separate keep mask exists.
    slot_onehot = jax.nn.one_hot(slot, capacity, dtype=dtype)  # (n, k, g, C)
    if k == 1:
        # Switch top-1 (the headline MoE config): the (n, k, g, E, C)
        # per-choice tensor collapses — build dispatch directly and weight by
        # the single gate, skipping one 5-D einsum materialization.
        dispatch = jnp.einsum(
            "nte,ntc->ntec", choice_onehot[:, 0], slot_onehot[:, 0]
        )
        combine = dispatch * gates.astype(dtype)[..., 0][:, :, None, None]
        return dispatch, combine
    # Per-choice dispatch (n, k, g, E, C); choices land in disjoint slots so
    # the sum over k is still one-hot per (E, C) slot.
    per_choice = jnp.einsum("nkte,nktc->nktec", choice_onehot, slot_onehot)
    combine = jnp.einsum(
        "ntk,nktec->ntec", gates.astype(dtype), per_choice
    )  # gate-weighted
    dispatch = jnp.sum(per_choice, axis=1)  # (n, g, E, C)
    return dispatch, combine


def expert_apply(xg, dispatch, combine, wi, wo, dtype, quant=False):
    """Dispatch-einsum → per-expert MLP → combine-einsum (model dtype).

    ``quant="int8"`` (legacy ``True``) runs the two expert MLP matmuls in
    dynamic int8 (ops/quant.py int8_expert_matmul — inference only, like the
    dense towers' quant flag); ``quant="int8_ste"`` uses the trainable
    straight-through twin (int8 forward, unquantized VJP). Dispatch/combine
    stay in the model dtype either way (one-hot routing, <20% of layer FLOPs).
    """
    expert_in = jnp.einsum(
        "ntec,ntd->encd", dispatch.astype(dtype), xg.astype(dtype)
    )
    if quant:
        from distributed_sigmoid_loss_tpu.ops.quant import (
            int8_expert_matmul,
            int8_expert_matmul_ste,
        )

        matmul = (
            int8_expert_matmul_ste if quant == "int8_ste" else int8_expert_matmul
        )
        # Same checkpoint tag as the dense path (moot at inference, but the
        # remat policies stay total over block variants).
        hidden_act = checkpoint_name(
            matmul(expert_in, wi, dtype), "mlp_hidden"
        )
        h = nn.gelu(hidden_act, approximate=True)
        return jnp.einsum(
            "ntec,encd->ntd", combine.astype(dtype),
            matmul(h, wo, dtype),
        )
    # Same checkpoint tag as the dense Mlp (transformer.py): the save_hot /
    # save_mlp remat policies keep the expert hidden activation, so backward
    # recompute stops at the elementwise gelu for MoE blocks too.
    hidden_act = checkpoint_name(
        jnp.einsum("encd,edh->ench", expert_in, wi.astype(dtype)),
        "mlp_hidden",
    )
    h = nn.gelu(hidden_act, approximate=True)
    expert_out = jnp.einsum("ench,ehd->encd", h, wo.astype(dtype))
    return jnp.einsum("ntec,encd->ntd", combine.astype(dtype), expert_out)


class MoeMlp(nn.Module):
    """Drop-in MoE replacement for the dense transformer ``Mlp``.

    Args:
      width: model dim d.
      mlp_ratio: expert hidden dim = ``round(width * mlp_ratio)``.
      num_experts: E, total experts (shard-count over ``ep`` divides this).
      num_selected: k experts per token (1 = Switch, 2 = GShard-style top-2 with
        renormalized gates).
      capacity_factor: per-expert buffer slack over the perfectly-balanced
        ``k·T/E`` load; tokens past the buffer are dropped (residual carries them).
      dtype: activation dtype for the expert matmuls (router stays f32).
    """

    width: int
    mlp_ratio: int | float
    num_experts: int
    dtype: Any
    num_selected: int = 1
    capacity_factor: float = 1.25
    # Routing-group TARGET size (GShard "groups"): tokens route and compete for
    # capacity within fixed-size groups, so the (tokens, E, C) dispatch/combine
    # tensors stay O(tokens · E · group/E · cf) instead of O(tokens²·cf) — at
    # bench scale (50k tokens/step) single-group routing OOMs 16G HBM. The
    # actual group is the largest divisor of the token count ≤ this target.
    group_size: int = 512
    # "" | "int8" (inference) | "int8_ste" (trainable STE) expert MLP matmuls.
    quant: bool | str = False

    @nn.compact
    def __call__(self, x):
        if self.num_selected not in (1, 2):
            raise ValueError(f"num_selected must be 1 or 2, got {self.num_selected}")
        if self.num_experts < 2:
            raise ValueError(f"num_experts must be >= 2, got {self.num_experts}")
        if self.group_size < 1:
            raise ValueError(f"group_size must be >= 1, got {self.group_size}")
        d, e, k = self.width, self.num_experts, self.num_selected
        hidden = int(round(self.width * self.mlp_ratio))
        *lead, d_in = x.shape
        assert d_in == d, f"input dim {d_in} != width {d}"
        tokens = 1
        for n in lead:
            tokens *= n
        group = max(
            g for g in range(1, min(self.group_size, tokens) + 1) if tokens % g == 0
        )
        n_groups = tokens // group
        xg = x.reshape(n_groups, group, d)

        # --- Router (f32 end-to-end) ------------------------------------------
        wr = self.param(
            "router", nn.initializers.normal(0.02), (d, e), jnp.float32
        )
        probs, gates, idx = router_topk(xg, wr, k)

        # --- Per-group capacity assignment ------------------------------------
        capacity = moe_capacity(group, e, k, self.capacity_factor)
        dispatch, combine = build_dispatch(
            gates, idx, e, capacity, dtype=self.dtype
        )

        # --- Load-balancing auxiliary loss (Switch eq. 4, over all tokens) ----
        # f_e: fraction of tokens whose first choice is e; P_e: mean router prob.
        first_choice = jax.nn.one_hot(idx[..., 0], e, dtype=jnp.float32)
        aux = e * jnp.sum(
            jnp.mean(first_choice, axis=(0, 1)) * jnp.mean(probs, axis=(0, 1))
        )
        self.sow("intermediates", "moe_aux_loss", aux)

        # --- Expert compute (model dtype; E sharded over ep) ------------------
        # Each expert processes its n_groups · C slots in one batched matmul.
        wi = self.param(
            "wi",
            nn.with_partitioning(
                nn.initializers.xavier_uniform(), (EP_AXIS, None, "tp")
            ),
            (e, d, hidden),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.with_partitioning(
                nn.initializers.xavier_uniform(), (EP_AXIS, "tp", None)
            ),
            (e, hidden, d),
            jnp.float32,
        )
        y = expert_apply(
            xg, dispatch, combine, wi, wo, self.dtype, quant=self.quant
        )
        return y.reshape(*lead, d)
