"""Import HF-format SigLIP checkpoints (``google/siglip-*``) into this framework.

The reference repo implements the SigLIP *loss*; the models people pair it with are
the released SigLIP towers. This module maps a ``transformers`` SigLIP state dict
onto our flax param tree so a reference user can bring their pretrained weights —
covering every tensor: patch/token/position embeddings, the pre-LN encoder stacks,
the MAP vision pooling head (torch ``nn.MultiheadAttention`` packed qkv unpacked),
the last-token text head, and the loss scalars (HF ``logit_scale``/``logit_bias``
≡ our ``t_prime``/``bias`` — same semantics: ``logits = z @ z.T * exp(t') + b``).

Verified numerically by ``tests/test_hf_import.py``: a randomly initialized
``transformers.SiglipModel`` and the converted flax model agree on image/text
embeddings and pairwise logits at fp32.

Layout notes (torch → flax):
- ``nn.Linear.weight`` is (out, in) → dense ``kernel`` (in, out): transpose.
- ``nn.Conv2d.weight`` is (out, in, kh, kw) → conv ``kernel`` (kh, kw, in, out).
- ``nn.MultiheadAttention.in_proj_weight`` is rows-stacked [q; k; v].
- Conversion targets the unscanned layout (``scan_layers=False``, per-block
  subtrees ``block{i}``); :func:`stack_for_scan` restacks for ``scan_layers=True``.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax.numpy as jnp
import numpy as np

from distributed_sigmoid_loss_tpu.utils.config import (
    SigLIPConfig,
    TextConfig,
    ViTConfig,
)

__all__ = ["config_from_hf", "params_from_hf", "stack_for_scan"]


def config_from_hf(hf_config: Any, dtype: str = "bfloat16") -> SigLIPConfig:
    """Build the matching :class:`SigLIPConfig` from a ``transformers.SiglipConfig``.

    The returned config is HF-shaped: no vision projection (``use_proj=False``,
    ``embed_dim = hidden_size``), last-token text pooling, unscanned layers
    (the layout :func:`params_from_hf` targets).
    """
    v, t = hf_config.vision_config, hf_config.text_config
    if v.hidden_size % v.num_attention_heads or t.hidden_size % t.num_attention_heads:
        raise ValueError(
            f"num_attention_heads must divide hidden_size (got vision "
            f"{v.hidden_size}/{v.num_attention_heads}, text "
            f"{t.hidden_size}/{t.num_attention_heads})"
        )

    def ratio(intermediate: int, hidden: int) -> float:
        # mlp_ratio may be fractional (so400m: 4304/1152); Mlp rounds
        # width*ratio back to an integer — assert the round trip is exact.
        r = intermediate / hidden
        if int(round(hidden * r)) != intermediate:
            raise ValueError(
                f"cannot represent intermediate_size {intermediate} as a ratio "
                f"of hidden_size {hidden}"
            )
        return r

    vision = ViTConfig(
        image_size=v.image_size,
        patch_size=v.patch_size,
        width=v.hidden_size,
        depth=v.num_hidden_layers,
        num_heads=v.num_attention_heads,
        mlp_ratio=ratio(v.intermediate_size, v.hidden_size),
        embed_dim=v.hidden_size,
        pool="map",
        use_proj=False,
        dtype=dtype,
        scan_layers=False,
    )
    text = TextConfig(
        vocab_size=t.vocab_size,
        context_length=t.max_position_embeddings,
        width=t.hidden_size,
        depth=t.num_hidden_layers,
        num_heads=t.num_attention_heads,
        mlp_ratio=ratio(t.intermediate_size, t.hidden_size),
        embed_dim=t.projection_size,
        pool="last",
        dtype=dtype,
        scan_layers=False,
    )
    if vision.embed_dim != text.embed_dim:
        raise ValueError(
            f"HF vision hidden_size ({vision.embed_dim}) must equal text "
            f"projection_size ({text.embed_dim}) for a shared embedding space"
        )
    return SigLIPConfig(vision=vision, text=text)


def _np(t) -> np.ndarray:
    """torch tensor / array-like → float32 numpy (conversion is layout work;
    the model's own dtype policy applies at apply time)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t, np.float32)


def _linear(sd: Mapping, prefix: str) -> dict:
    return {"kernel": _np(sd[f"{prefix}.weight"]).T, "bias": _np(sd[f"{prefix}.bias"])}


def _layernorm(sd: Mapping, prefix: str) -> dict:
    return {"scale": _np(sd[f"{prefix}.weight"]), "bias": _np(sd[f"{prefix}.bias"])}


def _block(sd: Mapping, prefix: str) -> dict:
    return {
        "ln1": _layernorm(sd, f"{prefix}.layer_norm1"),
        "ln2": _layernorm(sd, f"{prefix}.layer_norm2"),
        "attn": {
            "q": _linear(sd, f"{prefix}.self_attn.q_proj"),
            "k": _linear(sd, f"{prefix}.self_attn.k_proj"),
            "v": _linear(sd, f"{prefix}.self_attn.v_proj"),
            "out": _linear(sd, f"{prefix}.self_attn.out_proj"),
        },
        "mlp": {
            "wi": _linear(sd, f"{prefix}.mlp.fc1"),
            "wo": _linear(sd, f"{prefix}.mlp.fc2"),
        },
    }


def _encoder(sd: Mapping, prefix: str, depth: int, final_ln: str) -> dict:
    enc = {f"block{i}": _block(sd, f"{prefix}.layers.{i}") for i in range(depth)}
    enc["ln_final"] = _layernorm(sd, final_ln)
    return enc


def _map_head(sd: Mapping, prefix: str, width: int) -> dict:
    """torch MultiheadAttention packed [q; k; v] in_proj → separate q/k/v denses."""
    in_w = _np(sd[f"{prefix}.attention.in_proj_weight"])
    in_b = _np(sd[f"{prefix}.attention.in_proj_bias"])
    qw, kw, vw = in_w[:width], in_w[width : 2 * width], in_w[2 * width :]
    qb, kb, vb = in_b[:width], in_b[width : 2 * width], in_b[2 * width :]
    return {
        "probe": _np(sd[f"{prefix}.probe"]),
        "attn": {
            "q": {"kernel": qw.T, "bias": qb},
            "k": {"kernel": kw.T, "bias": kb},
            "v": {"kernel": vw.T, "bias": vb},
            "out": _linear(sd, f"{prefix}.attention.out_proj"),
        },
        "ln": _layernorm(sd, f"{prefix}.layernorm"),
        "mlp": {
            "wi": _linear(sd, f"{prefix}.mlp.fc1"),
            "wo": _linear(sd, f"{prefix}.mlp.fc2"),
        },
    }


def params_from_hf(state_dict: Mapping, cfg: SigLIPConfig) -> dict:
    """``transformers.SiglipModel`` state dict → this framework's param pytree.

    ``cfg`` must be HF-shaped (see :func:`config_from_hf`). Every produced leaf is
    float32 numpy; feed the result anywhere ``SigLIP`` params go (train state,
    ``model.apply({"params": ...})``).
    """
    sd = state_dict
    if (cfg.vision.use_proj or cfg.text.pool != "last"
            or cfg.vision.scan_layers or cfg.text.scan_layers):
        raise ValueError(
            "cfg must be HF-shaped (use_proj=False, text pool='last', "
            "scan_layers=False) — build it with config_from_hf"
        )
    v = {
        "patch_embed": {
            # (out, in, kh, kw) -> (kh, kw, in, out)
            "kernel": _np(
                sd["vision_model.embeddings.patch_embedding.weight"]
            ).transpose(2, 3, 1, 0),
            "bias": _np(sd["vision_model.embeddings.patch_embedding.bias"]),
        },
        "pos_embed": _np(
            sd["vision_model.embeddings.position_embedding.weight"]
        )[None],
        "encoder": _encoder(
            sd, "vision_model.encoder", cfg.vision.depth,
            "vision_model.post_layernorm",
        ),
        "map_head": _map_head(sd, "vision_model.head", cfg.vision.width),
    }
    t = {
        "token_embed": {
            "embedding": _np(sd["text_model.embeddings.token_embedding.weight"])
        },
        "pos_embed": _np(
            sd["text_model.embeddings.position_embedding.weight"]
        )[None],
        "encoder": _encoder(
            sd, "text_model.encoder", cfg.text.depth,
            "text_model.final_layer_norm",
        ),
        "proj": _linear(sd, "text_model.head"),
    }
    return {
        "visual": v,
        "textual": t,
        # HF logit_scale/logit_bias are shape-(1,) params; ours are scalars with
        # identical semantics: logits = zimg @ ztxt.T * exp(t_prime) + bias.
        "t_prime": _np(sd["logit_scale"]).reshape(()),
        "bias": _np(sd["logit_bias"]).reshape(()),
    }


def stack_for_scan(encoder_params: dict, depth: int) -> dict:
    """Restack per-block subtrees (``block{i}``) into the ``scan_layers=True``
    layout (one ``blocks`` subtree with a leading depth axis on every leaf)."""
    import jax

    blocks = [encoder_params[f"block{i}"] for i in range(depth)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *blocks)
    out = {k: v for k, v in encoder_params.items() if not k.startswith("block")}
    out["blocks"] = {"block": stacked}
    return out
