"""SigLIP model: ViT image tower + text transformer producing the L2-normalized
embedding pair the distributed loss consumes.

The learnable loss scalars (``t_prime``/``bias``) live in the model's params — the
TPU-native answer to the reference README's contract "pass the loss parameters to your
optimizer" (/root/reference/README.md:20): here they are just leaves of the param
pytree, so any optax optimizer updates them with everything else.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp

from distributed_sigmoid_loss_tpu.models.text import TextTransformer
from distributed_sigmoid_loss_tpu.models.vit import ViT
from distributed_sigmoid_loss_tpu.ops.sigmoid_loss import l2_normalize
from distributed_sigmoid_loss_tpu.utils.config import SigLIPConfig


class SigLIP(nn.Module):
    cfg: SigLIPConfig

    def setup(self):
        self.visual = ViT(self.cfg.vision)
        self.textual = TextTransformer(self.cfg.text)
        # Family-specific inits. Sigmoid (reference): t_prime = log(10),
        # bias = -10 (distributed_sigmoid_loss.py:11-12). Softmax (CLIP):
        # t_prime = log(1/0.07) — the open_clip logit-scale contract
        # (ops/softmax_loss.py); bias exists but is unused (zero grad).
        t0 = (
            math.log(1.0 / 0.07)
            if self.cfg.loss.family == "softmax"
            else math.log(10.0)
        )
        self.t_prime = self.param(
            "t_prime", nn.initializers.constant(t0), (), jnp.float32
        )
        self.bias = self.param(
            "bias", nn.initializers.constant(-10.0), (), jnp.float32
        )

    def __call__(self, images, token_ids):
        """→ (zimg, ztxt, loss_params): L2-normalized embeddings + loss scalars."""
        zimg = l2_normalize(self.visual(images))
        ztxt = l2_normalize(self.textual(token_ids))
        return zimg, ztxt, {"t_prime": self.t_prime, "bias": self.bias}

    def encode_image(self, images, normalize=True):
        z = self.visual(images)
        return l2_normalize(z) if normalize else z

    def encode_text(self, token_ids, normalize=True):
        z = self.textual(token_ids)
        return l2_normalize(z) if normalize else z
