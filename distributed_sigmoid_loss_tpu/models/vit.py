"""ViT image tower (BASELINE.json configs #4/#5: ViT-B/16, ViT-L/14).

Patchify is a strided conv — XLA lowers it to one MXU matmul over (patches × 3·p²).
Output is the L2-normalizable image embedding; normalization stays OUTSIDE the model,
matching the reference's convention of normalizing outside the loss
(/root/reference/test_distributed_sigmoid_loss.py:96-101, README.md release note).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributed_sigmoid_loss_tpu.models.transformer import Encoder, MapHead, _dtype
from distributed_sigmoid_loss_tpu.utils.config import ViTConfig


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        """images: (batch, H, W, 3) → (batch, embed_dim) unnormalized embeddings."""
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        x = images.astype(dtype)

        x = nn.Conv(
            cfg.width,
            kernel_size=(cfg.patch_size, cfg.patch_size),
            strides=(cfg.patch_size, cfg.patch_size),
            padding="VALID",
            dtype=dtype,
            name="patch_embed",
        )(x)
        b, h, w, c = x.shape
        x = x.reshape(b, h * w, c)

        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, h * w, cfg.width),
            jnp.float32,
        )
        x = x + pos.astype(dtype)

        x = Encoder(
            cfg.width, cfg.depth, cfg.num_heads, cfg.mlp_ratio, dtype,
            remat=cfg.remat, scan_layers=cfg.scan_layers, attn_impl=cfg.attn_impl,
            remat_policy=cfg.remat_policy,
            sp_axis=cfg.sequence_parallel_axis,
            sp_impl=cfg.sequence_parallel_impl,
            moe_experts=cfg.moe_experts,
            moe_num_selected=cfg.moe_num_selected,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_group_size=cfg.moe_group_size, name="encoder",
        )(x)

        if cfg.pool == "map":
            x = MapHead(cfg.width, cfg.num_heads, cfg.mlp_ratio, dtype, name="map_head")(x)
        else:
            x = x.mean(axis=1)

        if cfg.use_proj:
            x = nn.Dense(cfg.embed_dim, dtype=dtype, name="proj")(x)
        elif cfg.embed_dim != cfg.width:
            raise ValueError(
                f"use_proj=False (HF-format) requires embed_dim == width, got "
                f"{cfg.embed_dim} != {cfg.width}"
            )
        return x.astype(jnp.float32)
