"""ViT image tower (BASELINE.json configs #4/#5: ViT-B/16, ViT-L/14).

Patchify is an explicit reshape + ONE MXU matmul, not a strided conv: with
stride == kernel the conv is mathematically a per-patch dot product, and the
explicit form makes the MXU lowering visible instead of trusting XLA's conv
path. Measured A/B on the chip: perf-NEUTRAL vs nn.Conv (773.4 vs 771.6
pairs/s headline, run noise) — XLA was already lowering this conv well. (A
trace initially suggested otherwise: `convolution_add_fusion` at 11.8% of
device time — but on TPU that op name is XLA's label for MATMUL+bias fusions,
which run at 175 TFLOP/s there; see docs/PERF.md round-3 notes.) Params keep
nn.Conv's exact HWIO kernel layout so checkpoints are interchangeable with the
conv form.
Output is the L2-normalizable image embedding; normalization stays OUTSIDE the
model, matching the reference's convention of normalizing outside the loss
(/root/reference/test_distributed_sigmoid_loss.py:96-101, README.md release note).
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributed_sigmoid_loss_tpu.models.transformer import Encoder, MapHead, _dtype
from distributed_sigmoid_loss_tpu.utils.config import ViTConfig, tower_quant_mode


class PatchEmbed(nn.Module):
    """Non-overlapping patchify as reshape + matmul (see module docstring).

    Param tree is identical to ``nn.Conv(width, (p, p), strides=(p, p),
    padding="VALID")``: ``kernel`` (p, p, 3, width) HWIO + ``bias`` (width,).
    """

    width: int
    patch_size: int
    dtype: jnp.dtype

    @nn.compact
    def __call__(self, images):
        b, hh, ww, c = images.shape
        p = self.patch_size
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (p, p, c, self.width),
            jnp.float32,
        )
        bias = self.param("bias", nn.initializers.zeros, (self.width,), jnp.float32)
        # (b, H, W, c) -> (b, nh, p, nw, p, c) -> (b, nh·nw, p·p·c); the
        # per-patch (ph, pw, c) order matches the HWIO kernel reshape below.
        x = images.astype(self.dtype)  # promote inputs like nn.Conv(dtype=...) did
        if hh % p or ww % p:
            # nn.Conv(padding="VALID") silently cropped the remainder (e.g.
            # L/14 at 384: 384 % 14 = 6 px); keep that drop-in behavior.
            x = x[:, : hh // p * p, : ww // p * p, :]
        x = x.reshape(b, hh // p, p, ww // p, p, c)
        x = x.transpose(0, 1, 3, 2, 4, 5).reshape(b, (hh // p) * (ww // p), p * p * c)
        w = kernel.reshape(p * p * c, self.width)
        return x @ w.astype(self.dtype) + bias.astype(self.dtype)


class ViT(nn.Module):
    cfg: ViTConfig

    @nn.compact
    def __call__(self, images):
        """images: (batch, H, W, 3) → (batch, embed_dim) unnormalized embeddings."""
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)
        x = images.astype(dtype)

        x = PatchEmbed(
            cfg.width, cfg.patch_size, dtype, name="patch_embed"
        )(x)
        n = x.shape[1]  # patch count from the ACTUAL input (e.g. 384-res finetune)

        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, n, cfg.width),
            jnp.float32,
        )
        x = x + pos.astype(dtype)

        x = Encoder(
            cfg.width, cfg.depth, cfg.num_heads, cfg.mlp_ratio, dtype,
            remat=cfg.remat, scan_layers=cfg.scan_layers, attn_impl=cfg.attn_impl,
            remat_policy=cfg.remat_policy,
            sp_axis=cfg.sequence_parallel_axis,
            sp_impl=cfg.sequence_parallel_impl,
            moe_experts=cfg.moe_experts,
            moe_num_selected=cfg.moe_num_selected,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_group_size=cfg.moe_group_size, quant=tower_quant_mode(cfg),
            name="encoder",
        )(x)

        if cfg.pool == "map":
            x = MapHead(cfg.width, cfg.num_heads, cfg.mlp_ratio, dtype, name="map_head")(x)
        else:
            x = x.mean(axis=1)

        if cfg.use_proj:
            x = nn.Dense(cfg.embed_dim, dtype=dtype, name="proj")(x)
        elif cfg.embed_dim != cfg.width:
            raise ValueError(
                f"use_proj=False (HF-format) requires embed_dim == width, got "
                f"{cfg.embed_dim} != {cfg.width}"
            )
        return x.astype(jnp.float32)
