"""Toy linear towers — the reference test harness's stand-in encoders.

Reference: ``nn.Linear(emb_dim, 2, bias=False)`` applied to seeded random inputs
(/root/reference/test_distributed_sigmoid_loss.py:71-76). Kept as both a flax module
(for train-state plumbing tests) and a bare function (for parity tests that hand-carry
torch-initialized weights).
"""

from __future__ import annotations

import flax.linen as nn
import jax


def toy_tower_apply(weight: jax.Array, x: jax.Array) -> jax.Array:
    """``x @ W.T`` with torch ``nn.Linear`` weight layout (out_dim, in_dim)."""
    return x @ weight.T


class LinearTower(nn.Module):
    """Bias-free linear projection tower (torch ``nn.Linear(d, out, bias=False)``)."""

    output_dim: int = 2

    @nn.compact
    def __call__(self, x):
        return nn.Dense(self.output_dim, use_bias=False, name="proj")(x)
