from distributed_sigmoid_loss_tpu.models.towers import LinearTower, toy_tower_apply  # noqa: F401
from distributed_sigmoid_loss_tpu.models.vit import ViT  # noqa: F401
from distributed_sigmoid_loss_tpu.models.text import TextTransformer  # noqa: F401
from distributed_sigmoid_loss_tpu.models.siglip import SigLIP  # noqa: F401
from distributed_sigmoid_loss_tpu.models.moe import MoeMlp  # noqa: F401
from distributed_sigmoid_loss_tpu.models.hf_import import (  # noqa: F401
    config_from_hf,
    params_from_hf,
    stack_for_scan,
)
