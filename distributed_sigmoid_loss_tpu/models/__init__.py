from distributed_sigmoid_loss_tpu.models.towers import LinearTower, toy_tower_apply  # noqa: F401
from distributed_sigmoid_loss_tpu.models.vit import ViT  # noqa: F401
from distributed_sigmoid_loss_tpu.models.text import TextTransformer  # noqa: F401
from distributed_sigmoid_loss_tpu.models.siglip import SigLIP  # noqa: F401
