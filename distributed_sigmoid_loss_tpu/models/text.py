"""Text tower: non-causal transformer over tokenized captions (SigLIP-style), with MAP
("map") or last-token ("last", HF-format) pooling and projection into the shared
embedding space. Embedding normalization stays outside the model (reference
convention, test_distributed_sigmoid_loss.py:96-101)."""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from distributed_sigmoid_loss_tpu.models.transformer import Encoder, MapHead, _dtype
from distributed_sigmoid_loss_tpu.utils.config import TextConfig, tower_quant_mode


class TextTransformer(nn.Module):
    cfg: TextConfig

    @nn.compact
    def __call__(self, token_ids):
        """token_ids: (batch, context_length) int32 → (batch, embed_dim)."""
        cfg = self.cfg
        dtype = _dtype(cfg.dtype)

        emb = nn.Embed(
            cfg.vocab_size,
            cfg.width,
            embedding_init=nn.initializers.normal(stddev=0.02),
            name="token_embed",
        )(token_ids)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (1, cfg.context_length, cfg.width),
            jnp.float32,
        )
        x = emb.astype(dtype) + pos.astype(dtype)

        x = Encoder(
            cfg.width, cfg.depth, cfg.num_heads, cfg.mlp_ratio, dtype,
            remat=cfg.remat, scan_layers=cfg.scan_layers, attn_impl=cfg.attn_impl,
            remat_policy=cfg.remat_policy,
            sp_axis=cfg.sequence_parallel_axis, sp_impl=cfg.sequence_parallel_impl,
            causal=cfg.causal, moe_experts=cfg.moe_experts,
            moe_num_selected=cfg.moe_num_selected,
            moe_capacity_factor=cfg.moe_capacity_factor,
            moe_group_size=cfg.moe_group_size, quant=tower_quant_mode(cfg),
            name="encoder",
        )(x)

        if cfg.pool == "map":
            x = MapHead(cfg.width, cfg.num_heads, cfg.mlp_ratio, dtype, name="map_head")(x)
        else:
            # HF-format SigLIP: the LAST token's hidden state is the pooled
            # representation (modeling_siglip.SiglipTextTransformer.forward).
            x = x[:, -1]
        x = nn.Dense(cfg.embed_dim, dtype=dtype, name="proj")(x)
        return x.astype(jnp.float32)
