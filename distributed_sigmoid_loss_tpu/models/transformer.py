"""Shared transformer core for both towers — designed for TPU from the start.

The reference has no model layer (its "towers" are toy Linears); the BASELINE.json
end-to-end target adds ViT-B/16 + text transformer. This core is built TPU-first:

- **MXU-friendly**: fused QKV projection (one big matmul), bf16 activations with fp32
  params, static shapes throughout.
- **Tensor parallelism**: weight kernels carry ``nn.with_partitioning`` annotations over
  the ``"tp"`` mesh axis — attention heads and MLP hidden dim are sharded, so under jit
  XLA inserts the all-reduces (Megatron-style column→row split) automatically.
- **Memory**: optional ``nn.remat`` per block (rematerialize activations in backward)
  and ``nn.scan`` over layers (constant compile time in depth).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name

# Mesh axis name used by tensor-parallel kernel annotations (parallel/mesh.py).
TP_AXIS = "tp"


def _dtype(name: str):
    return {"float32": jnp.float32, "bfloat16": jnp.bfloat16}[name]


def _dot_general(quant):
    """None = flax's default (lax.dot_general). ``"int8"`` (or legacy ``True``)
    injects the inference-only int8 dot; ``"int8_ste"`` the trainable
    straight-through variant (int8 forward, unquantized VJP — ops/quant.py)."""
    if not quant:
        return None
    from distributed_sigmoid_loss_tpu.ops.quant import (
        int8_dot_general,
        int8_dot_general_ste,
    )

    if quant == "int8_ste":
        return int8_dot_general_ste
    return int8_dot_general


def _remat_policy(name: str):
    """None = rematerialize everything (jax.checkpoint default)."""
    if name == "nothing":
        return None
    if name == "save_hot":
        # Save the two expensive-to-recompute intermediates (attention core output,
        # MLP hidden): backward recompute shrinks to qkv projections + layernorms +
        # elementwise gelu (~25% of forward instead of 100%), costing
        # b·s·(width + hidden) of HBM per layer.
        return jax.checkpoint_policies.save_only_these_names(
            "attn_core", "mlp_hidden"
        )
    if name == "save_all_hot":
        # save_hot plus q/k/v: backward recompute is layernorms + gelu only.
        return jax.checkpoint_policies.save_only_these_names(
            "attn_core", "mlp_hidden", "q_proj", "k_proj", "v_proj"
        )
    if name == "save_mlp":
        # The single biggest matmul output only — the low-memory selective option.
        return jax.checkpoint_policies.save_only_these_names("mlp_hidden")
    raise ValueError(f"unknown remat_policy: {name!r}")


class Mlp(nn.Module):
    width: int
    # May be fractional (HF so400m: 4304/1152); the hidden dim is rounded back
    # to the exact integer.
    mlp_ratio: int | float
    dtype: Any
    quant: bool | str = False  # "" | "int8" | "int8_ste" (see _dot_general)

    @nn.compact
    def __call__(self, x):
        hidden = int(round(self.width * self.mlp_ratio))
        dg = _dot_general(self.quant)
        # Column-parallel in, row-parallel out: the tp all-reduce happens once, after wo.
        wi = nn.Dense(
            hidden,
            dtype=self.dtype,
            dot_general=dg,
            kernel_init=nn.with_partitioning(
                nn.initializers.xavier_uniform(), (None, TP_AXIS)
            ),
            name="wi",
        )
        wo = nn.Dense(
            self.width,
            dtype=self.dtype,
            dot_general=dg,
            kernel_init=nn.with_partitioning(
                nn.initializers.xavier_uniform(), (TP_AXIS, None)
            ),
            name="wo",
        )
        # Name the wi output so the "save_hot" remat policy keeps it: backward then
        # recomputes only the cheap elementwise gelu, not the big wi matmul.
        hidden_act = checkpoint_name(wi(x), "mlp_hidden")
        return wo(nn.gelu(hidden_act, approximate=True))


class Attention(nn.Module):
    """Multi-head attention; with ``sp_axis`` set, the attention core runs
    sequence-parallel over that mesh axis (long-context path) — ``sp_impl`` picks
    ring (ppermute) or ulysses (all-to-all) attention. Requires an ambient mesh
    (``jax.set_mesh``) containing the axis; the projections stay per-token and are
    partitioned by GSPMD as usual.

    ``attn_impl`` selects the single-device core: "dense" (XLA einsum softmax),
    "flash" (Pallas fused kernel, TPU only), or "auto" (flash on TPU when the shape
    qualifies, dense otherwise)."""

    width: int
    num_heads: int
    dtype: Any
    sp_axis: str | None = None
    sp_impl: str = "ring"  # "ring" (ppermute) or "ulysses" (all-to-all)
    attn_impl: str = "auto"  # "dense" | "flash" | "auto"
    causal: bool = False
    quant: bool | str = False  # "" | "int8" | "int8_ste" (see _dot_general)

    @nn.compact
    def __call__(self, x_q, x_kv=None):
        is_self_attention = x_kv is None
        x_kv = x_q if x_kv is None else x_kv
        head_dim = self.width // self.num_heads
        dg = _dot_general(self.quant)

        qkv_init = nn.with_partitioning(nn.initializers.xavier_uniform(), (None, TP_AXIS))
        out_init = nn.with_partitioning(nn.initializers.xavier_uniform(), (TP_AXIS, None))

        q = nn.Dense(self.width, dtype=self.dtype, dot_general=dg, kernel_init=qkv_init, name="q")(x_q)
        k = nn.Dense(self.width, dtype=self.dtype, dot_general=dg, kernel_init=qkv_init, name="k")(x_kv)
        v = nn.Dense(self.width, dtype=self.dtype, dot_general=dg, kernel_init=qkv_init, name="v")(x_kv)

        def split(t):
            return t.reshape(t.shape[:-1] + (self.num_heads, head_dim))

        # Named for the "save_all_hot" remat policy (saves the projections too, so
        # backward recompute is layernorm+gelu only).
        q, k, v = (checkpoint_name(t, n) for t, n in
                   ((split(q), "q_proj"), (split(k), "k_proj"), (split(v), "v_proj")))
        if self.sp_axis is not None and is_self_attention:
            # Sequence-parallel exact attention: manual over sp only, GSPMD keeps
            # handling any other mesh axes (dp/tp) automatically.
            from functools import partial

            from jax.sharding import PartitionSpec as P

            from distributed_sigmoid_loss_tpu.parallel.ring_attention import (
                ring_self_attention,
            )
            from distributed_sigmoid_loss_tpu.parallel.ulysses_attention import (
                ulysses_self_attention,
            )

            sp_impls = {
                "ring": ring_self_attention,
                "ulysses": ulysses_self_attention,
            }
            if self.sp_impl not in sp_impls:
                raise ValueError(
                    f"unknown sp_impl: {self.sp_impl!r} (expected one of "
                    f"{sorted(sp_impls)})"
                )
            sp_fn = sp_impls[self.sp_impl]
            spec = P(None, self.sp_axis)
            out = jax.shard_map(
                partial(sp_fn, axis_name=self.sp_axis, causal=self.causal),
                in_specs=(spec, spec, spec),
                out_specs=spec,
                axis_names={self.sp_axis},
            )(q, k, v)
        else:
            from distributed_sigmoid_loss_tpu.ops.flash_attention import (
                flash_attention_available,
                flash_self_attention,
            )
            from distributed_sigmoid_loss_tpu.ops.pallas_short_attention import (
                short_attention_fits,
                short_self_attention,
            )
            from distributed_sigmoid_loss_tpu.parallel.ring_attention import (
                dense_attention,
            )

            # "auto" picks a fused Pallas kernel only for bf16 self-attention: the
            # fused backward matmuls are bf16-grade, which is exactly right for
            # bf16 training but would silently degrade an f32 parity run. Short
            # sequences (towers) take the VMEM-resident kernel when its per-program
            # footprint fits the VMEM budget; otherwise the blockwise flash kernel.
            if self.attn_impl == "flash" and not is_self_attention:
                raise ValueError(
                    "attn_impl='flash' requires self-attention (the fused kernels "
                    "assume q/k/v share one sequence); use 'auto' or 'dense' for "
                    "cross-attention"
                )
            if self.attn_impl == "flash" and not flash_attention_available():
                raise ValueError(
                    "attn_impl='flash' requires a TPU backend (current: "
                    f"{jax.default_backend()!r}); use 'auto' to fall back to the "
                    "dense path automatically"
                )
            use_fused = self.attn_impl == "flash" or (
                self.attn_impl == "auto"
                and is_self_attention
                and self.dtype == jnp.bfloat16
                and flash_attention_available()
            )
            if use_fused and short_attention_fits(
                q.shape[1], self.width, jnp.dtype(self.dtype).itemsize
            ):
                out = short_self_attention(q, k, v, self.causal)
            elif use_fused:
                out = flash_self_attention(q, k, v, causal=self.causal)
            else:
                out = dense_attention(q, k, v, causal=self.causal)
            out = out.astype(self.dtype)
        # Named for the "save_hot" remat policy: with the core output saved, the
        # backward pass needs only q/k/v (for the attention VJP) — the s² core
        # forward is never re-run.
        out = checkpoint_name(out, "attn_core")
        out = out.reshape(out.shape[:-2] + (self.width,))
        return nn.Dense(
            self.width, dtype=self.dtype, dot_general=dg, kernel_init=out_init,
            name="out",
        )(out)


class Block(nn.Module):
    """Pre-LN transformer block. ``moe_experts > 0`` swaps the dense MLP for a
    mixture-of-experts layer (models/moe.py) whose expert weights shard over the
    ``ep`` mesh axis; the residual stream is unchanged, so MoE composes with
    remat/scan/sp exactly like the dense block."""

    width: int
    num_heads: int
    mlp_ratio: int | float
    dtype: Any
    sp_axis: str | None = None
    sp_impl: str = "ring"
    attn_impl: str = "auto"
    causal: bool = False
    moe_experts: int = 0
    moe_num_selected: int = 1
    moe_capacity_factor: float = 1.25
    moe_group_size: int = 512
    quant: bool | str = False

    @nn.compact
    def __call__(self, x):
        x = x + Attention(
            self.width, self.num_heads, self.dtype,
            sp_axis=self.sp_axis, sp_impl=self.sp_impl,
            attn_impl=self.attn_impl, causal=self.causal,
            quant=self.quant,
            name="attn",
        )(nn.LayerNorm(dtype=self.dtype, name="ln1")(x))
        if self.moe_experts > 0:
            from distributed_sigmoid_loss_tpu.models.moe import MoeMlp

            mlp = MoeMlp(
                self.width, self.mlp_ratio, self.moe_experts, self.dtype,
                num_selected=self.moe_num_selected,
                capacity_factor=self.moe_capacity_factor,
                group_size=self.moe_group_size,
                quant=self.quant,
                name="moe",
            )
        else:
            mlp = Mlp(
                self.width, self.mlp_ratio, self.dtype, quant=self.quant,
                name="mlp",
            )
        x = x + mlp(nn.LayerNorm(dtype=self.dtype, name="ln2")(x))
        return x


class _ScanBody(nn.Module):
    """Scan-compatible block wrapper: ``(carry, _) -> (carry, None)``."""

    width: int
    num_heads: int
    mlp_ratio: int | float
    dtype: Any
    sp_axis: str | None = None
    sp_impl: str = "ring"
    attn_impl: str = "auto"
    causal: bool = False
    moe_experts: int = 0
    moe_num_selected: int = 1
    moe_capacity_factor: float = 1.25
    moe_group_size: int = 512
    quant: bool | str = False

    @nn.compact
    def __call__(self, carry, _):
        carry = Block(
            self.width, self.num_heads, self.mlp_ratio, self.dtype,
            sp_axis=self.sp_axis, sp_impl=self.sp_impl,
            attn_impl=self.attn_impl, causal=self.causal,
            moe_experts=self.moe_experts,
            moe_num_selected=self.moe_num_selected,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_group_size=self.moe_group_size,
            quant=self.quant,
            name="block",
        )(carry)
        return carry, None


class Encoder(nn.Module):
    """Stack of blocks; optionally remat'd and scanned over depth."""

    width: int
    depth: int
    num_heads: int
    mlp_ratio: int | float
    dtype: Any
    remat: bool = False
    scan_layers: bool = False
    # "nothing" = full remat; "save_hot" = save attention-core + MLP-hidden
    # outputs; "save_all_hot" adds q/k/v; "save_mlp" = MLP hidden only. See
    # _remat_policy for the recompute/HBM tradeoffs.
    remat_policy: str = "nothing"
    sp_axis: str | None = None
    sp_impl: str = "ring"
    attn_impl: str = "auto"
    causal: bool = False
    moe_experts: int = 0
    moe_num_selected: int = 1
    moe_capacity_factor: float = 1.25
    moe_group_size: int = 512
    quant: bool | str = False

    @nn.compact
    def __call__(self, x):
        moe_kw = dict(
            moe_experts=self.moe_experts,
            moe_num_selected=self.moe_num_selected,
            moe_capacity_factor=self.moe_capacity_factor,
            moe_group_size=self.moe_group_size,
            quant=self.quant,
        )
        if self.scan_layers:
            body_cls = _ScanBody
            if self.remat:
                # prevent_cse=False is safe (and faster) under scan.
                body_cls = nn.remat(
                    _ScanBody, prevent_cse=False, static_argnums=(),
                    policy=_remat_policy(self.remat_policy),
                )
            # One set of stacked params, compiled once: lax.scan over depth.
            # The sown MoE aux losses ride the scan with a leading depth axis.
            scanned = nn.scan(
                body_cls,
                variable_axes={"params": 0, "intermediates": 0},
                split_rngs={"params": True},
                length=self.depth,
                metadata_params={nn.PARTITION_NAME: None},
            )
            x, _ = scanned(
                self.width, self.num_heads, self.mlp_ratio, self.dtype,
                sp_axis=self.sp_axis, sp_impl=self.sp_impl,
                attn_impl=self.attn_impl, causal=self.causal, **moe_kw,
                name="blocks",
            )(x, None)
        else:
            block_cls = (
                nn.remat(Block, policy=_remat_policy(self.remat_policy))
                if self.remat
                else Block
            )
            for i in range(self.depth):
                x = block_cls(
                    self.width, self.num_heads, self.mlp_ratio, self.dtype,
                    sp_axis=self.sp_axis, sp_impl=self.sp_impl,
                    attn_impl=self.attn_impl, causal=self.causal, **moe_kw,
                    name=f"block{i}",
                )(x)
        return nn.LayerNorm(dtype=self.dtype, name="ln_final")(x)


class MapHead(nn.Module):
    """SigLIP's MAP (multihead attention pooling) head: a learned probe token attends
    over the sequence, followed by an MLP residual."""

    width: int
    num_heads: int
    mlp_ratio: int | float
    dtype: Any

    @nn.compact
    def __call__(self, tokens):
        b = tokens.shape[0]
        probe = self.param(
            "probe", nn.initializers.xavier_uniform(), (1, 1, self.width), jnp.float32
        ).astype(self.dtype)
        probe = jnp.broadcast_to(probe, (b, 1, self.width))
        x = Attention(self.width, self.num_heads, self.dtype, name="attn")(probe, tokens)
        x = x + Mlp(self.width, self.mlp_ratio, self.dtype, name="mlp")(
            nn.LayerNorm(dtype=self.dtype, name="ln")(x)
        )
        return x[:, 0]
