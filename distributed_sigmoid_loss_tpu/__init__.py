"""distributed_sigmoid_loss_tpu — a TPU-native (JAX/XLA/pjit/shard_map) framework with
the capabilities of the reference ``ahmdtaha/distributed_sigmoid_loss``.

Built from scratch for TPU: the compute path is pure-functional JAX jitted onto the MXU,
the communication path is XLA collectives (``jax.lax.all_gather`` / ``jax.lax.ppermute``)
over a ``jax.sharding.Mesh``, and the learnable temperature/bias scalars are replicated
optax parameters.

Public surface (mirrors the reference component inventory, see SURVEY.md §2):

- :mod:`.ops.sigmoid_loss` — the paper's Algorithm 1 as pure functions (single device).
- :mod:`.parallel.collectives` — differentiable neighbor exchange (ring P2P) built on
  ``ppermute`` (reference: distributed_utils.py).
- :mod:`.parallel.allgather_loss` — the all-gather variant
  (reference: distributed_sigmoid_loss.py ``DDPSigmoidLoss``).
- :mod:`.parallel.ring_loss` — the ring / neighbor-exchange variant
  (reference: rwightman_sigmoid_loss.py ``SigLipLoss``).
- :mod:`.parallel.ring_attention` — sequence-parallel exact attention over the same
  ppermute ring topology (long-context path).
- :mod:`.ops.pallas_sigmoid_loss` — streaming 2-D Pallas TPU kernel (fused
  backward, int8 MXU path) for the loss hot op.
- :mod:`.ops.pallas_short_attention` / :mod:`.ops.flash_attention` — fused attention
  kernels for the towers (VMEM-resident short-sequence kernel; blockwise flash for
  long context).
- :mod:`.models` — toy linear towers (reference test harness) plus real ViT + text
  transformer towers for the SigLIP training target.
- :mod:`.train` — pjit train step (with gradient accumulation), optax optimizer
  wiring, orbax checkpointing.
- :mod:`.eval` — zero-shot retrieval recall@K, sharded over the mesh.
- :mod:`.data` / :mod:`.utils` — synthetic data + input pipeline (multi-host global
  batches, prefetch), configs, parity-data recipe, metrics logging, profiling.
"""

__version__ = "0.1.0"

import distributed_sigmoid_loss_tpu._jax_compat  # noqa: F401  (installs jax shims first)

from distributed_sigmoid_loss_tpu.ops.sigmoid_loss import (  # noqa: F401
    init_loss_params,
    pairwise_logits,
    sigmoid_xent,
    sigmoid_loss,
    sigmoid_loss_block,
)
