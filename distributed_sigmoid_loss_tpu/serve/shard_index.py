"""dp-mesh-sharded retrieval index: per-shard exact top-k, merged candidates.

``serve.index.RetrievalIndex`` is a single-host O(corpus) scan per query —
correct, but the whole corpus streams through one host's memory bus on every
search, and "Dissecting Embedding Bag Performance in DLRM Inference"
(PAPERS.md) says that bus IS the bottleneck for this workload. Sharding is
the first lever: partition the corpus rows over the mesh's ``dp`` axis so
each device scans 1/W of the rows (1/W the bytes, W-way parallel), compute
the per-shard exact top-k inside a ``shard_map`` region, and merge the
gathered ``(score, id)`` candidate lists on the host.

The merge is ranking-identical to the one-matrix oracle
(:func:`eval.retrieval.topk_ids`) including tie order, by construction:

- rows are partitioned CONTIGUOUSLY (shard w holds insertion positions
  ``[w*n_per, (w+1)*n_per)``), so within a shard ascending local index is
  ascending global id;
- ``lax.top_k`` is stable (ties keep the lower index) — a shard's own top-k
  list already prefers the lower id, so truncating to k per shard can never
  drop a candidate the global merge would have picked;
- the host merge (:func:`eval.retrieval.merge_topk`) resolves cross-shard
  ties toward the lower id — exactly ``topk_ids``'s lower-index tie break
  when ids are insertion positions (the default).

Snapshot semantics are IMMUTABLE: an instance is built once from a corpus
array and never mutated. Live refresh is a new instance published atomically
by ``serve.swap.SwapController`` / ``RetrievalRouter`` — in-flight searches
keep the segments they started with (the double-buffer contract), and there
is no lock on the search path at all.

Compile discipline mirrors the engine's: queries are padded up to a fixed
``query_buckets`` grid and the shard_map program is compiled once per
(query bucket, k_local) point — steady-state search traffic never triggers a
fresh XLA compile (``compile_count`` introspection included).
"""

from __future__ import annotations

import threading
from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_sigmoid_loss_tpu.eval.retrieval import merge_topk
from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["ShardedIndex"]


def _shard_topk(q, rows, ids, *, k_local: int):
    """Per-shard exact top-k; runs inside the shard_map region.

    ``q`` (qb, d) replicated; ``rows`` (n_per, d) / ``ids`` (n_per,) this
    shard's contiguous corpus slice (id -1 = padding). Returns
    ``(scores, ids)`` shaped (1, qb, k_local) so the ``P(axis)`` out_spec
    concatenates the per-shard candidate lists on the leading axis — the
    gathered lists the host merge consumes.
    """
    sims = q @ rows.T  # (qb, n_per)
    sims = jnp.where(ids[None, :] >= 0, sims, -jnp.inf)
    scores, idx = lax.top_k(sims, k_local)  # stable: ties keep the lower index
    return scores[None], ids[idx][None]


@lru_cache(maxsize=32)
def _shard_topk_fn(mesh: Mesh, axis_name: str, k_local: int):
    """One compiled fan-out program per (mesh, axis, k_local); jit adds the
    per-query-bucket specialization. Bounded LRU like eval/retrieval's."""
    return jax.jit(
        jax.shard_map(
            partial(_shard_topk, k_local=k_local),
            mesh=mesh,
            in_specs=(P(None), P(axis_name), P(axis_name)),
            out_specs=(P(axis_name), P(axis_name)),
        )
    )


class ShardedIndex:
    """Immutable dp-sharded exact top-k index over embedding rows.

    ``search`` returns ``(scores (q, k), ids (q, k))``, score-descending,
    exact ties broken toward the LOWER id — with default ids (insertion
    positions) this is bit-for-bit the ``eval.retrieval.topk_ids`` ranking.
    ``candidates`` exposes the raw gathered per-shard lists so callers (the
    ``RetrievalRouter``) can time fan-out and merge as separate stages.
    """

    def __init__(
        self,
        embeddings,
        ids=None,
        *,
        mesh: Mesh,
        axis_name: str = data_axis,
        query_buckets=(1, 8, 64),
        dtype=np.float32,
    ):
        rows = np.ascontiguousarray(embeddings, dtype=dtype)
        if rows.ndim != 2 or not len(rows):
            raise ValueError(
                f"embeddings must be a non-empty (n, d) array, got {rows.shape}"
            )
        if ids is None:
            ids = np.arange(len(rows), dtype=np.int64)
        else:
            ids = np.asarray(ids, dtype=np.int64)
            if ids.shape != (len(rows),):
                raise ValueError(f"ids shape {ids.shape} != ({len(rows)},)")
            if (ids < 0).any():
                raise ValueError("ids must be >= 0 (negative marks padding)")
        self.mesh = mesh
        self.axis_name = axis_name
        self.query_buckets = tuple(sorted(set(int(b) for b in query_buckets)))
        if not self.query_buckets or self.query_buckets[0] < 1:
            raise ValueError(f"bad query_buckets {query_buckets!r}")
        self.size = len(rows)
        self.dim = rows.shape[1]
        self.shard_count = int(mesh.shape[axis_name])
        # Contiguous partition, padded so every shard holds n_per rows; pad
        # rows are zeros with id -1 (masked to -inf inside the region).
        self.rows_per_shard = -(-self.size // self.shard_count)
        n_pad = self.shard_count * self.rows_per_shard
        if n_pad != self.size:
            rows = np.concatenate(
                [rows, np.zeros((n_pad - self.size, self.dim), dtype=rows.dtype)]
            )
            ids = np.concatenate(
                [ids, np.full(n_pad - self.size, -1, dtype=np.int64)]
            )
        sharding = NamedSharding(mesh, P(axis_name))
        # int32 on device: x64 is disabled repo-wide; sizes < 2**31 by far.
        self._rows = jax.device_put(rows, sharding)
        self._ids = jax.device_put(ids.astype(np.int32), sharding)
        self._compiled: set[tuple[int, int]] = set()
        self._lock = named_lock("serve.shard_index.ShardedIndex._lock")

    def __len__(self) -> int:
        return self.size

    @property
    def compile_count(self) -> int:
        """Distinct (query bucket, k_local) fan-out programs run so far —
        the engine's compile-discipline introspection, for the index."""
        with self._lock:
            return len(self._compiled)

    def _query_bucket(self, n: int) -> int:
        for b in self.query_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"query batch {n} exceeds the largest query bucket "
            f"{self.query_buckets[-1]}; split the request or extend "
            "query_buckets"
        )

    def candidates(self, queries, k: int) -> tuple[np.ndarray, np.ndarray]:
        """Gathered per-shard candidate lists: ``(scores, ids)`` each
        (q, W * k_local) — the fan-out stage. ``merge_topk`` of these is the
        global top-k; :meth:`search` does exactly that."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        if q.ndim == 1:
            q = q[None]
        if q.shape[1] != self.dim:
            raise ValueError(f"query dim {q.shape[1]} != index dim {self.dim}")
        k = int(k)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        k = min(k, self.size)
        k_local = min(k, self.rows_per_shard)
        qb = self._query_bucket(len(q))
        padded = np.zeros((qb, self.dim), dtype=np.float32)
        padded[: len(q)] = q
        with self._lock:
            self._compiled.add((qb, k_local))
        fn = _shard_topk_fn(self.mesh, self.axis_name, k_local)
        s, i = fn(padded, self._rows, self._ids)  # (W, qb, k_local) each
        s = np.asarray(s)[:, : len(q)]
        i = np.asarray(i)[:, : len(q)]
        # (W, q, k_local) -> (q, W * k_local) gathered candidate lists.
        cand_s = np.moveaxis(s, 0, 1).reshape(len(q), -1)
        cand_i = np.moveaxis(i, 0, 1).reshape(len(q), -1).astype(np.int64)
        return cand_s, cand_i

    def search(self, queries, k: int) -> tuple[np.ndarray, np.ndarray]:
        """(q, d) or (d,) queries → top-k ``(scores, ids)`` under the shared
        ranking contract. k clamps to the corpus size."""
        squeeze = np.asarray(queries).ndim == 1
        cand_s, cand_i = self.candidates(queries, k)
        k = min(int(k), self.size)
        scores, ids = merge_topk(cand_s, cand_i, k)
        if squeeze:
            return scores[0], ids[0]
        return scores, ids

    def stats(self) -> dict:
        return {
            "size": self.size,
            "shard_count": self.shard_count,
            "rows_per_shard": self.rows_per_shard,
            "compile_count": self.compile_count,
        }
