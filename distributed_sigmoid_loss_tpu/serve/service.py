"""EmbeddingService — the serving front end tying engine, batcher, cache and
index together.

One request flows: content hash → cache probe → (on miss) micro-batcher →
bucketed jitted engine → cache fill → caller, with the whole round trip
bounded by a per-request timeout. Text and image traffic get SEPARATE
batchers: their engine programs differ anyway (different buckets compile
apart), and coalescing them would make one modality's burst stall the other's
deadline.

``stats()`` is the operational contract: qps, p50/p95 latency, per-modality
batch-size histograms, cache hit rate, engine compile count vs bucket space,
and the backpressure/timeout reject counters — emitted as one JSON record via
``utils.logging.MetricsLogger.write`` (the `serve-bench` CLI prints exactly
this snapshot).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass
from typing import Callable, Sequence

import numpy as np

from distributed_sigmoid_loss_tpu.eval.retrieval import merge_topk
from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis
from distributed_sigmoid_loss_tpu.serve.admission import (
    AdmissionController,
    ShedError,
)
from distributed_sigmoid_loss_tpu.serve.ann import AnnIndex
from distributed_sigmoid_loss_tpu.serve.batcher import MicroBatcher, QueueFullError
from distributed_sigmoid_loss_tpu.serve.cache import EmbeddingCache, content_key
from distributed_sigmoid_loss_tpu.serve.engine import InferenceEngine
from distributed_sigmoid_loss_tpu.serve.index import RetrievalIndex
from distributed_sigmoid_loss_tpu.serve.shard_index import ShardedIndex
from distributed_sigmoid_loss_tpu.utils.logging import LatencyWindow, MetricsLogger

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["EmbeddingService", "RequestTimeoutError", "RetrievalRouter"]


class RequestTimeoutError(TimeoutError):
    """The request's deadline passed before its batch finished encoding."""


@dataclass(frozen=True)
class _IndexVersion:
    """One immutable published generation of index segments. A search reads
    the CURRENT version once and keeps it for its whole lifetime — a swap
    mid-search can never hand it a torn mix of old and new segments."""

    version: int
    exact: RetrievalIndex
    sharded: ShardedIndex | None
    ann: AnnIndex | None
    size: int


class RetrievalRouter:
    """Versioned, tiered retrieval front end: ``exact`` / ``sharded`` / ``ann``.

    Drop-in for ``EmbeddingService``'s ``index=`` slot (same ``search`` /
    ``__len__`` surface) with three additions the plain index cannot offer:

    - **tier routing** — ``exact`` is the single-host chunked oracle scan,
      ``sharded`` fans per-shard top-k over the dp mesh and merges the
      gathered candidates (``serve/shard_index.py``), ``ann`` prunes with
      quantized coarse scores then re-ranks exactly (``serve/ann.py``);
    - **versioned publication** — ``publish`` builds fresh index segments
      double-buffered (the old version keeps serving during the build) and
      swaps one reference atomically; every response can report the version
      it was served from (``return_version=True``), which is monotonically
      non-decreasing across a client's requests;
    - **measured recall** — on the ann tier every ``measure_every``-th
      search is ALSO answered by the exact oracle and the id overlap feeds
      the running ``recall_at_k`` in :meth:`stats` (exact/sharded report
      1.0 by construction — they are ranking-identical to the oracle).

    Per-stage latencies (fan-out / merge / coarse / re-rank / exact scan)
    land in :meth:`stats` and, when ``spans`` is wired, on the graftscope
    host timeline as ``serve/search/<stage>`` spans.
    """

    TIERS = ("exact", "sharded", "ann")
    STAGES = ("exact", "fanout", "merge", "coarse", "rerank")

    def __init__(
        self,
        *,
        tier: str = "exact",
        mesh=None,
        axis_name: str = data_axis,
        coarse: str = "int8",
        rerank_k: int | None = None,
        measure_every: int = 16,
        chunk_size: int = 4096,
        query_buckets=(1, 8, 64),
        spans=None,
    ):
        if tier not in self.TIERS:
            raise ValueError(f"tier must be one of {self.TIERS}, got {tier!r}")
        if tier == "sharded" and mesh is None:
            raise ValueError(
                "tier='sharded' needs a mesh= (the dp axis the corpus "
                "partitions over); pass parallel.mesh.make_mesh()"
            )
        self.tier = tier
        self.mesh = mesh
        self.axis_name = axis_name
        self.coarse = coarse
        self.rerank_k = rerank_k if rerank_k else None
        self.measure_every = max(int(measure_every), 0)
        self.chunk_size = chunk_size
        self.query_buckets = tuple(query_buckets)
        self.spans = spans
        self._current: _IndexVersion | None = None
        self._publish_lock = named_lock("serve.service.RetrievalRouter._publish_lock")
        self._versions = 0
        self._stats_lock = named_lock("serve.service.RetrievalRouter._stats_lock")
        self._swap_count = 0
        self._swaps_in_flight = 0
        self._swap_window = LatencyWindow(1024)
        self._stage_windows = {s: LatencyWindow(4096) for s in self.STAGES}
        self._searches = 0
        self._recall_sum = 0.0
        self._recall_n = 0
        self._last_rerank_k = 0

    # -- publication ---------------------------------------------------------

    def build(self, embeddings, ids=None) -> dict:
        """Build fresh index segments for a corpus WITHOUT publishing them —
        the double-buffer half: runs outside any lock while the current
        version keeps serving. Feed the result to :meth:`publish_built`."""
        emb = np.ascontiguousarray(embeddings, dtype=np.float32)
        exact = RetrievalIndex(chunk_size=self.chunk_size)
        exact.add(emb, ids)
        sharded = ann = None
        if self.tier == "sharded":
            sharded = ShardedIndex(
                emb, ids, mesh=self.mesh, axis_name=self.axis_name,
                query_buckets=self.query_buckets,
            )
        elif self.tier == "ann":
            ann = AnnIndex(emb, ids, coarse=self.coarse, rerank_k=self.rerank_k)
        return {"exact": exact, "sharded": sharded, "ann": ann, "size": len(emb)}

    def publish_built(self, built: dict | None) -> int:
        """Atomically publish segments from :meth:`build` (None re-publishes
        the current segments under a new version — a params-only swap).
        Returns the new version number; in-flight searches finish on the
        version they started with."""
        with self._publish_lock:
            if built is None:
                cur = self._current
                if cur is None:
                    raise ValueError("publish_built(None) before any publish()")
                built = {
                    "exact": cur.exact, "sharded": cur.sharded,
                    "ann": cur.ann, "size": cur.size,
                }
            self._versions += 1
            self._current = _IndexVersion(version=self._versions, **built)
            return self._versions

    def publish(self, embeddings, ids=None) -> int:
        """Build + atomically publish a new corpus; returns the version."""
        return self.publish_built(self.build(embeddings, ids))

    @property
    def version(self) -> int:
        v = self._current
        return v.version if v is not None else 0

    def record_swap(self, seconds: float) -> None:
        """Swap bookkeeping (called by ``serve.swap.SwapController``)."""
        with self._stats_lock:
            self._swap_count += 1
        self._swap_window.record(seconds)

    def begin_swap(self) -> None:
        """Mark a hot swap mid-flight (SwapController, before the build);
        ``/healthz`` reports ``degraded`` while any swap is in progress."""
        with self._stats_lock:
            self._swaps_in_flight += 1

    def end_swap(self) -> None:
        with self._stats_lock:
            self._swaps_in_flight = max(0, self._swaps_in_flight - 1)

    @property
    def swap_in_flight(self) -> bool:
        with self._stats_lock:
            return self._swaps_in_flight > 0

    # -- search --------------------------------------------------------------

    def _stage(self, stage: str, t0: float, t1: float) -> None:
        self._stage_windows[stage].record(t1 - t0)
        if self.spans is not None:
            self.spans.record(f"serve/search/{stage}", t0, t1)

    def search(self, queries, k: int = 10, *, return_version: bool = False):
        """Top-k under the shared ranking contract, routed by tier. Returns
        ``(scores, ids)`` — or ``(scores, ids, version)`` with
        ``return_version=True``, where version is the index generation this
        answer was computed from."""
        v = self._current
        if v is None:
            raise ValueError("search() before the first publish()")
        arr = np.asarray(queries)
        squeeze = arr.ndim == 1
        k = min(int(k), v.size)
        if self.tier == "exact":
            t0 = time.monotonic()
            scores, ids = v.exact.search(arr, k)
            self._stage("exact", t0, time.monotonic())
        elif self.tier == "sharded":
            t0 = time.monotonic()
            cand_s, cand_i = v.sharded.candidates(arr, k)
            t1 = time.monotonic()
            self._stage("fanout", t0, t1)
            scores, ids = merge_topk(cand_s, cand_i, k)
            if squeeze:
                scores, ids = scores[0], ids[0]
            self._stage("merge", t1, time.monotonic())
        else:  # ann
            rk = v.ann._resolve_rerank_k(k, None)
            t0 = time.monotonic()
            pos = v.ann.coarse_positions(arr, rk)
            t1 = time.monotonic()
            self._stage("coarse", t0, t1)
            scores, ids = v.ann.rerank(arr, pos, k)
            if squeeze:
                scores, ids = scores[0], ids[0]
            self._stage("rerank", t1, time.monotonic())
            self._measure_recall(v, arr, k, ids, rk)
        with self._stats_lock:
            self._searches += 1
        if return_version:
            return scores, ids, v.version
        return scores, ids

    def _measure_recall(self, v, queries, k, ann_ids, rk) -> None:
        """Every measure_every-th ann search is also answered exactly; the
        id overlap feeds the running recall@k stat."""
        with self._stats_lock:
            self._last_rerank_k = rk
            due = self.measure_every and self._searches % self.measure_every == 0
        if not due:
            return
        _, exact_ids = v.exact.search(queries, k)
        ann2 = np.atleast_2d(np.asarray(ann_ids))
        exact2 = np.atleast_2d(exact_ids)
        hits = [
            len(set(a.tolist()) & set(e.tolist())) / max(len(e), 1)
            for a, e in zip(ann2, exact2)
        ]
        with self._stats_lock:
            self._recall_sum += float(np.mean(hits))
            self._recall_n += 1

    def __len__(self) -> int:
        v = self._current
        return v.size if v is not None else 0

    # -- ops surface ---------------------------------------------------------

    def stats(self) -> dict:
        """The router's registered stats fields (obs/metrics_schema.py SERVE
        registry) — merged into ``EmbeddingService.stats()``'s snapshot."""
        with self._stats_lock:
            swap_count = self._swap_count
            recall = (
                round(self._recall_sum / self._recall_n, 4)
                if self._recall_n
                else (1.0 if self.tier != "ann" else None)
            )
            rerank_k = self.rerank_k or self._last_rerank_k
        v = self._current
        snap = {
            "index_tier": self.tier,
            "index_version": v.version if v is not None else 0,
            "shard_count": v.sharded.shard_count
            if v is not None and v.sharded is not None
            else 1,
            "swap_count": swap_count,
            "swap_latency_ms": self._swap_window.percentiles_ms((50, 95, 99)),
            "recall_at_k": recall,
            "rerank_k": rerank_k,
            "search_stage_latency_ms": {
                s: w.percentiles_ms((50, 95, 99))
                for s, w in self._stage_windows.items()
                if w.count
            },
            "swap_in_flight": self.swap_in_flight,
        }
        return snap


class EmbeddingService:
    """`encode_text` / `encode_image` / `search` over a bucketed engine.

    ``tokenize(texts, length) -> (n, length) int ids`` enables raw-string
    requests (the CLI's byte/BPE tokenizers fit the signature); pre-tokenized
    rows and pixel arrays always work. ``cache=None`` disables caching,
    ``index`` defaults to an empty :class:`RetrievalIndex` that ``search``
    queries after you ``add`` corpus embeddings to it — or pass a
    :class:`RetrievalRouter` for tiered (sharded/ann) and hot-swappable
    retrieval; its registered stats fields then ride the :meth:`stats`
    snapshot.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        tokenize: Callable | None = None,
        cache: EmbeddingCache | None = None,
        index: RetrievalIndex | None = None,
        max_batch_size: int | None = None,
        max_wait_ms: float = 5.0,
        max_queue: int = 1024,
        default_timeout: float | None = 10.0,
        admission: AdmissionController | None = None,
        logger: MetricsLogger | None = None,
        spans=None,
    ):
        self.engine = engine
        self.tokenize = tokenize
        self.cache = cache
        self.index = index if index is not None else RetrievalIndex()
        self.default_timeout = default_timeout
        # Optional serve/admission.py front door: per-tenant token buckets,
        # bounded quotas, priority-ordered shedding. When wired, encode/search
        # accept tenant= and may raise ShedError BEFORE touching the batcher.
        self.admission = admission
        self.logger = logger
        # Optional obs/spans.py SpanRecorder: per-request spans on the caller
        # threads plus per-stage (queue-wait / assembly / device / reply)
        # spans on the batcher workers — one overlayable host timeline.
        self.spans = spans
        if max_batch_size is None:
            max_batch_size = engine.batch_buckets[-1]
        self._batchers = {
            "text": MicroBatcher(
                self._encode_rows_text, max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms, max_queue=max_queue, name="text",
                spans=spans,
            ),
            "image": MicroBatcher(
                self._encode_rows_image, max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms, max_queue=max_queue, name="image",
                spans=spans,
            ),
        }
        self._latency = LatencyWindow()
        self._lock = named_lock("serve.service.EmbeddingService._lock")
        self._requests = 0
        self._items = 0
        self._rejected = 0
        self._timeouts = 0
        self._shed = 0
        self._started = time.monotonic()
        self._exporter = None  # live /metrics endpoint (start_metrics_server)

    # -- engine-facing batch fns (worker thread only) ------------------------

    def _encode_rows_text(self, rows: list[np.ndarray]) -> list[np.ndarray]:
        # Coalesced rows may come from different callers with different
        # lengths; right-pad with id 0 (the training pad token) to the longest
        # so one flush is one engine call — the engine buckets from there.
        smax = max(r.shape[0] for r in rows)
        batch = np.zeros((len(rows), smax), dtype=self.engine.token_dtype)
        for i, r in enumerate(rows):
            batch[i, : r.shape[0]] = r
        return list(self.engine.encode_text(batch))

    def _encode_rows_image(self, rows: list[np.ndarray]) -> list[np.ndarray]:
        out = self.engine.encode_image(np.stack(rows))
        return list(out)

    # -- request paths -------------------------------------------------------

    def _normalize_text(self, texts) -> list[np.ndarray]:
        """str | (s,) ids | list of either | (n, s) ids → list of (s,) rows,
        padded to one common length so a coalesced batch stacks."""
        if isinstance(texts, str):
            texts = [texts]
        elif isinstance(texts, np.ndarray):
            if texts.ndim == 1:  # a single token row, not n scalar requests
                texts = [texts]
            elif texts.ndim == 2:
                texts = list(texts)
            else:
                raise ValueError(
                    f"token input must be (s,) or (n, s), got {texts.shape}"
                )
        rows: list = list(texts)
        str_pos = [i for i, t in enumerate(rows) if isinstance(t, str)]
        if str_pos:
            if self.tokenize is None:
                raise ValueError(
                    "string requests need a tokenize fn (construct the "
                    "service with tokenize=...)"
                )
            length = self.engine.text_len_buckets[-1]
            tokenized = self.tokenize([rows[i] for i in str_pos], length)
            for i, row in zip(str_pos, tokenized):
                rows[i] = row
        return [np.asarray(r, dtype=self.engine.token_dtype) for r in rows]

    def _admit(self, tenant, items: int, deadline_s):
        """Pass the admission front door (or raise the typed ShedError).
        Returns the ticket to release, or None when no admission is wired."""
        if self.admission is None:
            return None
        try:
            return self.admission.admit(
                tenant, items=items, deadline_s=deadline_s
            )
        except ShedError:
            with self._lock:
                self._shed += 1
            raise

    def _encode(
        self, kind: str, rows: list[np.ndarray], timeout, tenant=None
    ) -> np.ndarray:
        timeout = self.default_timeout if timeout is None else timeout
        # Admission covers the whole request (cache probe included): the
        # quota a tenant holds is its end-to-end concurrency, and the token
        # bucket meters offered rate, not just cache misses.
        ticket = self._admit(tenant, len(rows), timeout)
        ok = False
        try:
            out = self._encode_batched(kind, rows, timeout)
            ok = True
            return out
        finally:
            if ticket is not None:
                ticket.release(ok=ok)

    def _encode_batched(
        self, kind: str, rows: list[np.ndarray], timeout
    ) -> np.ndarray:
        t0 = time.monotonic()
        results: list[np.ndarray | None] = [None] * len(rows)
        pending: list[tuple[int, str | None, object]] = []
        try:
            for i, row in enumerate(rows):
                key = None
                if self.cache is not None:
                    key = content_key(row, kind)
                    hit = self.cache.get(key)
                    if hit is not None:
                        results[i] = hit
                        continue
                try:
                    fut = self._batchers[kind].submit(row)
                except QueueFullError:
                    with self._lock:
                        self._rejected += 1
                    raise
                pending.append((i, key, fut))
            for i, key, fut in pending:
                remaining = None
                if timeout is not None:
                    remaining = max(0.0, timeout - (time.monotonic() - t0))
                try:
                    emb = fut.result(timeout=remaining)
                except FutureTimeoutError:
                    with self._lock:
                        self._timeouts += 1
                    raise RequestTimeoutError(
                        f"{kind} request missed its {timeout}s deadline "
                        f"({len(pending)} item(s) in flight)"
                    ) from None
                results[i] = emb
                if self.cache is not None:
                    self.cache.put(key, emb)
        finally:
            with self._lock:
                self._requests += 1
                self._items += len(rows)
            t1 = time.monotonic()
            self._latency.record(t1 - t0)
            if self.spans is not None:
                self.spans.record(f"serve/request/{kind}", t0, t1)
        return np.stack(results)

    def encode_text(
        self, texts, *, timeout: float | None = None, tenant: str | None = None
    ) -> np.ndarray:
        """Texts (strings or token rows) → (n, embed_dim) embeddings."""
        return self._encode("text", self._normalize_text(texts), timeout, tenant)

    def encode_image(
        self, images, *, timeout: float | None = None, tenant: str | None = None
    ) -> np.ndarray:
        """(n, h, w, 3) or (h, w, 3) pixels → (n, embed_dim) embeddings."""
        arr = np.asarray(images, dtype=np.float32)
        if arr.ndim == 3:
            arr = arr[None]
        return self._encode("image", list(arr), timeout, tenant)

    def search(
        self,
        queries,
        k: int = 10,
        *,
        timeout: float | None = None,
        tenant: str | None = None,
        return_version: bool = False,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-k over the index. Queries: strings / int token rows (encoded
        through the text tower) or float rows (used as embeddings directly).
        Returns ``(scores, ids)`` — ordering contract of ``RetrievalIndex``.
        ``return_version=True`` (a :class:`RetrievalRouter` index only)
        additionally returns the index version that served the answer.
        """
        arr = queries if isinstance(queries, np.ndarray) else None
        if arr is not None and np.issubdtype(arr.dtype, np.floating):
            # Already embeddings: no encode path, so the admission check
            # (one per request) happens here instead of inside _encode.
            n = arr.shape[0] if arr.ndim > 1 else 1
            deadline = self.default_timeout if timeout is None else timeout
            ticket = self._admit(tenant, n, deadline)
            ok = False
            try:
                if return_version:
                    out = self.index.search(arr, k, return_version=True)
                else:
                    out = self.index.search(arr, k)
                ok = True
                return out
            finally:
                if ticket is not None:
                    ticket.release(ok=ok)
        emb = self.encode_text(queries, timeout=timeout, tenant=tenant)
        if return_version:
            return self.index.search(emb, k, return_version=True)
        return self.index.search(emb, k)

    # -- ops surface ---------------------------------------------------------

    def stats(self) -> dict:
        """One JSON-able snapshot of the service's operational state."""
        elapsed = max(1e-9, time.monotonic() - self._started)
        with self._lock:
            requests, items = self._requests, self._items
            rejected, timeouts = self._rejected, self._timeouts
            shed = self._shed
        snap = {
            "uptime_s": round(elapsed, 3),
            "requests": requests,
            "items": items,
            "qps": round(requests / elapsed, 2),
            "items_per_sec": round(items / elapsed, 2),
            "latency_ms": self._latency.percentiles_ms((50, 95, 99)),
            "batch_size_hist": {
                kind: b.batch_size_histogram()
                for kind, b in self._batchers.items()
            },
            # Per-stage tails (queue_wait / assembly / device / reply per
            # modality): the stage a p99 regression lives in, not just that
            # one exists.
            "stage_latency_ms": {
                kind: b.stage_latency_ms()
                for kind, b in self._batchers.items()
            },
            "rejected": rejected,
            "timeouts": timeouts,
            # Admission sheds are a SEPARATE stream from queue-full rejects:
            # shed = policy said no (tenant over rate/quota or shed by
            # priority), rejected = the whole stack was saturated.
            "shed": shed,
            "shed_rate": (
                round(self.admission.recent_shed_rate(), 4)
                if self.admission is not None
                else 0.0
            ),
            "compile_count": self.engine.compile_count,
            "bucket_space": self.engine.bucket_space,
            "index_size": len(self.index),
        }
        if self.cache is not None:
            snap["cache"] = self.cache.stats()
        if self.admission is not None:
            snap["admission"] = self.admission.stats()
        if isinstance(self.index, RetrievalRouter):
            # Tier/version/swap/recall fields — the router emits only keys
            # registered in the SERVE schema, so the merged snapshot stays
            # schema-valid end to end.
            snap.update(self.index.stats())
        return snap

    def health(self) -> dict:
        """The ``/healthz`` payload: ``degraded`` (still HTTP 200 — the
        process is up and answering) while admission is actively shedding
        or a hot swap is mid-flight, ``ok`` otherwise. ``reasons`` names
        each cause machine-readably — the fleet router drains a replica on
        ``"swap_in_flight"`` (the wave is taking it out on purpose) but
        keeps routing to one that is merely ``"shedding"`` (pulling an
        overloaded replica would concentrate load on its siblings)."""
        shed_rate = (
            self.admission.recent_shed_rate()
            if self.admission is not None
            else 0.0
        )
        swap = (
            self.index.swap_in_flight
            if isinstance(self.index, RetrievalRouter)
            else False
        )
        reasons = []
        if swap:
            reasons.append("swap_in_flight")
        if shed_rate > 0:
            reasons.append("shedding")
        return {
            "status": "degraded" if reasons else "ok",
            "shed_rate": round(shed_rate, 4),
            "swap_in_flight": bool(swap),
            "reasons": reasons,
        }

    def start_metrics_server(
        self,
        port: int = 0,
        *,
        host: str = "127.0.0.1",
        labels: dict | None = None,
        refresh_s: float = 0.25,
    ):
        """Mount the live OpenMetrics-style ``/metrics`` endpoint
        (obs/telemetry.py): a stdlib HTTP thread serving the :meth:`stats`
        snapshot as exposition text, with scrape-storm-bounded snapshot
        reuse. ``labels`` stamps a constant label set onto every series —
        the per-tenant scoping hook (one exporter per tenant scope).
        Returns the started :class:`~..obs.telemetry.TelemetryExporter`
        (``.port`` / ``.url``); :meth:`close` stops it."""
        from distributed_sigmoid_loss_tpu.obs.telemetry import (
            TelemetryExporter,
        )

        if self._exporter is not None:
            raise RuntimeError("metrics server already started")
        self._exporter = TelemetryExporter(
            self.stats, host=host, port=port, labels=labels,
            refresh_s=refresh_s, health_fn=self.health,
        )
        self._exporter.start()
        return self._exporter

    def log_stats(self) -> dict:
        """Emit :meth:`stats` through the wired MetricsLogger (validated
        against the declared serve-stats schema); returns it."""
        snap = self.stats()
        if self.logger is not None:
            from distributed_sigmoid_loss_tpu.obs.metrics_schema import (
                SERVE_STATS_FIELDS,
            )

            self.logger.write(
                {"metric": "serve_stats", **snap}, schema=SERVE_STATS_FIELDS
            )
        return snap

    def close(self) -> None:
        if self._exporter is not None:
            self._exporter.stop()
            self._exporter = None
        for b in self._batchers.values():
            b.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
