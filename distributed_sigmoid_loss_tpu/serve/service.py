"""EmbeddingService — the serving front end tying engine, batcher, cache and
index together.

One request flows: content hash → cache probe → (on miss) micro-batcher →
bucketed jitted engine → cache fill → caller, with the whole round trip
bounded by a per-request timeout. Text and image traffic get SEPARATE
batchers: their engine programs differ anyway (different buckets compile
apart), and coalescing them would make one modality's burst stall the other's
deadline.

``stats()`` is the operational contract: qps, p50/p95 latency, per-modality
batch-size histograms, cache hit rate, engine compile count vs bucket space,
and the backpressure/timeout reject counters — emitted as one JSON record via
``utils.logging.MetricsLogger.write`` (the `serve-bench` CLI prints exactly
this snapshot).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import TimeoutError as FutureTimeoutError
from typing import Callable, Sequence

import numpy as np

from distributed_sigmoid_loss_tpu.serve.batcher import MicroBatcher, QueueFullError
from distributed_sigmoid_loss_tpu.serve.cache import EmbeddingCache, content_key
from distributed_sigmoid_loss_tpu.serve.engine import InferenceEngine
from distributed_sigmoid_loss_tpu.serve.index import RetrievalIndex
from distributed_sigmoid_loss_tpu.utils.logging import LatencyWindow, MetricsLogger

__all__ = ["EmbeddingService", "RequestTimeoutError"]


class RequestTimeoutError(TimeoutError):
    """The request's deadline passed before its batch finished encoding."""


class EmbeddingService:
    """`encode_text` / `encode_image` / `search` over a bucketed engine.

    ``tokenize(texts, length) -> (n, length) int ids`` enables raw-string
    requests (the CLI's byte/BPE tokenizers fit the signature); pre-tokenized
    rows and pixel arrays always work. ``cache=None`` disables caching,
    ``index`` defaults to an empty :class:`RetrievalIndex` that ``search``
    queries after you ``add`` corpus embeddings to it.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        tokenize: Callable | None = None,
        cache: EmbeddingCache | None = None,
        index: RetrievalIndex | None = None,
        max_batch_size: int | None = None,
        max_wait_ms: float = 5.0,
        max_queue: int = 1024,
        default_timeout: float | None = 10.0,
        logger: MetricsLogger | None = None,
        spans=None,
    ):
        self.engine = engine
        self.tokenize = tokenize
        self.cache = cache
        self.index = index if index is not None else RetrievalIndex()
        self.default_timeout = default_timeout
        self.logger = logger
        # Optional obs/spans.py SpanRecorder: per-request spans on the caller
        # threads plus per-stage (queue-wait / assembly / device / reply)
        # spans on the batcher workers — one overlayable host timeline.
        self.spans = spans
        if max_batch_size is None:
            max_batch_size = engine.batch_buckets[-1]
        self._batchers = {
            "text": MicroBatcher(
                self._encode_rows_text, max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms, max_queue=max_queue, name="text",
                spans=spans,
            ),
            "image": MicroBatcher(
                self._encode_rows_image, max_batch_size=max_batch_size,
                max_wait_ms=max_wait_ms, max_queue=max_queue, name="image",
                spans=spans,
            ),
        }
        self._latency = LatencyWindow()
        self._lock = threading.Lock()
        self._requests = 0
        self._items = 0
        self._rejected = 0
        self._timeouts = 0
        self._started = time.monotonic()

    # -- engine-facing batch fns (worker thread only) ------------------------

    def _encode_rows_text(self, rows: list[np.ndarray]) -> list[np.ndarray]:
        # Coalesced rows may come from different callers with different
        # lengths; right-pad with id 0 (the training pad token) to the longest
        # so one flush is one engine call — the engine buckets from there.
        smax = max(r.shape[0] for r in rows)
        batch = np.zeros((len(rows), smax), dtype=self.engine.token_dtype)
        for i, r in enumerate(rows):
            batch[i, : r.shape[0]] = r
        return list(self.engine.encode_text(batch))

    def _encode_rows_image(self, rows: list[np.ndarray]) -> list[np.ndarray]:
        out = self.engine.encode_image(np.stack(rows))
        return list(out)

    # -- request paths -------------------------------------------------------

    def _normalize_text(self, texts) -> list[np.ndarray]:
        """str | (s,) ids | list of either | (n, s) ids → list of (s,) rows,
        padded to one common length so a coalesced batch stacks."""
        if isinstance(texts, str):
            texts = [texts]
        elif isinstance(texts, np.ndarray):
            if texts.ndim == 1:  # a single token row, not n scalar requests
                texts = [texts]
            elif texts.ndim == 2:
                texts = list(texts)
            else:
                raise ValueError(
                    f"token input must be (s,) or (n, s), got {texts.shape}"
                )
        rows: list = list(texts)
        str_pos = [i for i, t in enumerate(rows) if isinstance(t, str)]
        if str_pos:
            if self.tokenize is None:
                raise ValueError(
                    "string requests need a tokenize fn (construct the "
                    "service with tokenize=...)"
                )
            length = self.engine.text_len_buckets[-1]
            tokenized = self.tokenize([rows[i] for i in str_pos], length)
            for i, row in zip(str_pos, tokenized):
                rows[i] = row
        return [np.asarray(r, dtype=self.engine.token_dtype) for r in rows]

    def _encode(self, kind: str, rows: list[np.ndarray], timeout) -> np.ndarray:
        t0 = time.monotonic()
        timeout = self.default_timeout if timeout is None else timeout
        results: list[np.ndarray | None] = [None] * len(rows)
        pending: list[tuple[int, str | None, object]] = []
        try:
            for i, row in enumerate(rows):
                key = None
                if self.cache is not None:
                    key = content_key(row, kind)
                    hit = self.cache.get(key)
                    if hit is not None:
                        results[i] = hit
                        continue
                try:
                    fut = self._batchers[kind].submit(row)
                except QueueFullError:
                    with self._lock:
                        self._rejected += 1
                    raise
                pending.append((i, key, fut))
            for i, key, fut in pending:
                remaining = None
                if timeout is not None:
                    remaining = max(0.0, timeout - (time.monotonic() - t0))
                try:
                    emb = fut.result(timeout=remaining)
                except FutureTimeoutError:
                    with self._lock:
                        self._timeouts += 1
                    raise RequestTimeoutError(
                        f"{kind} request missed its {timeout}s deadline "
                        f"({len(pending)} item(s) in flight)"
                    ) from None
                results[i] = emb
                if self.cache is not None:
                    self.cache.put(key, emb)
        finally:
            with self._lock:
                self._requests += 1
                self._items += len(rows)
            t1 = time.monotonic()
            self._latency.record(t1 - t0)
            if self.spans is not None:
                self.spans.record(f"serve/request/{kind}", t0, t1)
        return np.stack(results)

    def encode_text(self, texts, *, timeout: float | None = None) -> np.ndarray:
        """Texts (strings or token rows) → (n, embed_dim) embeddings."""
        return self._encode("text", self._normalize_text(texts), timeout)

    def encode_image(self, images, *, timeout: float | None = None) -> np.ndarray:
        """(n, h, w, 3) or (h, w, 3) pixels → (n, embed_dim) embeddings."""
        arr = np.asarray(images, dtype=np.float32)
        if arr.ndim == 3:
            arr = arr[None]
        return self._encode("image", list(arr), timeout)

    def search(
        self, queries, k: int = 10, *, timeout: float | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Top-k over the index. Queries: strings / int token rows (encoded
        through the text tower) or float rows (used as embeddings directly).
        Returns ``(scores, ids)`` — ordering contract of ``RetrievalIndex``.
        """
        arr = queries if isinstance(queries, np.ndarray) else None
        if arr is not None and np.issubdtype(arr.dtype, np.floating):
            emb = arr  # already embeddings
        else:
            emb = self.encode_text(queries, timeout=timeout)
        return self.index.search(emb, k)

    # -- ops surface ---------------------------------------------------------

    def stats(self) -> dict:
        """One JSON-able snapshot of the service's operational state."""
        elapsed = max(1e-9, time.monotonic() - self._started)
        with self._lock:
            requests, items = self._requests, self._items
            rejected, timeouts = self._rejected, self._timeouts
        snap = {
            "uptime_s": round(elapsed, 3),
            "requests": requests,
            "items": items,
            "qps": round(requests / elapsed, 2),
            "items_per_sec": round(items / elapsed, 2),
            "latency_ms": self._latency.percentiles_ms((50, 95, 99)),
            "batch_size_hist": {
                kind: b.batch_size_histogram()
                for kind, b in self._batchers.items()
            },
            # Per-stage tails (queue_wait / assembly / device / reply per
            # modality): the stage a p99 regression lives in, not just that
            # one exists.
            "stage_latency_ms": {
                kind: b.stage_latency_ms()
                for kind, b in self._batchers.items()
            },
            "rejected": rejected,
            "timeouts": timeouts,
            "compile_count": self.engine.compile_count,
            "bucket_space": self.engine.bucket_space,
            "index_size": len(self.index),
        }
        if self.cache is not None:
            snap["cache"] = self.cache.stats()
        return snap

    def log_stats(self) -> dict:
        """Emit :meth:`stats` through the wired MetricsLogger (validated
        against the declared serve-stats schema); returns it."""
        snap = self.stats()
        if self.logger is not None:
            from distributed_sigmoid_loss_tpu.obs.metrics_schema import (
                SERVE_STATS_FIELDS,
            )

            self.logger.write(
                {"metric": "serve_stats", **snap}, schema=SERVE_STATS_FIELDS
            )
        return snap

    def close(self) -> None:
        for b in self._batchers.values():
            b.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
