"""Approximate retrieval tier: coarse quantized scoring, exact re-rank.

The exact scan streams ``N * d * 4`` bytes per query batch and the workload
is memory-bandwidth-bound (PAPERS.md, "Dissecting Embedding Bag Performance
in DLRM Inference") — so the second lever after sharding is shrinking the
bytes the candidate scan touches. This tier scores EVERY row with a cheap
quantized representation (the pruning pass), keeps the best ``rerank_k``
candidates, and re-ranks only those with exact f32 dot products:

- ``coarse="int8"`` (default): per-row symmetric int8 via the SAME
  ``ops.quant.quantize_int8`` recipe the serving/eval int8 towers use —
  4x fewer corpus bytes, int32 accumulation, per-row scales applied before
  selection (activation scales are per-query constants and cannot change a
  row's ordering). Quantization error is ~1e-2 of the score scale, so the
  coarse ORDER is nearly exact and modest ``rerank_k`` already recovers the
  exact top-k (measured recall@k is surfaced in stats, floor-enforced in
  tests).
- ``coarse="sign"``: 1-bit sign sketches (``ops.quant.sign_sketch``) — 32x
  fewer bytes; sign-agreement count is a monotone proxy good enough to
  prune, never to rank. Needs a larger ``rerank_k`` for the same recall
  (the recall/latency trade table lives in docs/SERVING.md).

The re-rank stage reuses :func:`eval.retrieval.merge_topk`, so WITHIN the
survivor set the returned ordering (including exact-tie order) is identical
to the exact path's — an ANN answer differs from the oracle only by
candidates the coarse pass pruned, which is exactly what recall@k measures.

Like ``ShardedIndex``, instances are immutable snapshots: refresh = build a
new one and publish it through the router/swap controller.
"""

from __future__ import annotations

import numpy as np

from distributed_sigmoid_loss_tpu.eval.retrieval import merge_topk
from distributed_sigmoid_loss_tpu.ops.quant import (
    quantize_int8,
    sign_sketch,
    sign_sketch_scores,
)

__all__ = ["AnnIndex", "default_rerank_k"]


def default_rerank_k(k: int, size: int) -> int:
    """The default pruning width: enough head-room over k that int8-grade
    coarse error stays above the 0.95 recall floor on realistic corpora
    (measured in tests/test_distindex.py), clamped to the corpus."""
    return min(max(8 * k, 64), size)


class AnnIndex:
    """Quantize-then-rerank approximate top-k over embedding rows.

    ``search(queries, k)`` routes coarse pruning → exact re-rank; the split
    methods (:meth:`coarse_positions` / :meth:`rerank`) let the router time
    and span the two stages separately.
    """

    def __init__(
        self,
        embeddings,
        ids=None,
        *,
        coarse: str = "int8",
        rerank_k: int | None = None,
    ):
        rows = np.ascontiguousarray(embeddings, dtype=np.float32)
        if rows.ndim != 2 or not len(rows):
            raise ValueError(
                f"embeddings must be a non-empty (n, d) array, got {rows.shape}"
            )
        if ids is None:
            ids = np.arange(len(rows), dtype=np.int64)
        else:
            ids = np.asarray(ids, dtype=np.int64)
            if ids.shape != (len(rows),):
                raise ValueError(f"ids shape {ids.shape} != ({len(rows)},)")
        if coarse not in ("int8", "sign"):
            raise ValueError(f"coarse must be 'int8' or 'sign', got {coarse!r}")
        self.coarse = coarse
        self.rerank_k = rerank_k  # None = per-search default_rerank_k(k)
        self._rows = rows
        self._ids = ids
        self.size = len(rows)
        self.dim = rows.shape[1]
        if coarse == "int8":
            q8, scale = quantize_int8(rows, axis=-1)  # the shared PTQ recipe
            self._q8 = np.asarray(q8)                 # (n, d) int8
            self._scale = np.asarray(scale)[:, 0]     # (n,) f32 per-row
        else:
            self._bits = sign_sketch(rows)            # (n, ceil(d/8)) uint8

    def __len__(self) -> int:
        return self.size

    def _resolve_rerank_k(self, k: int, rerank_k: int | None) -> int:
        rk = rerank_k if rerank_k is not None else self.rerank_k
        if rk is None or rk <= 0:
            rk = default_rerank_k(k, self.size)
        return min(max(int(rk), k), self.size)

    def coarse_positions(self, queries, rerank_k: int) -> np.ndarray:
        """The pruning pass: (q, rerank_k) corpus POSITIONS (not ids) of the
        best coarse-scored candidates, per query row (unordered)."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        if q.ndim == 1:
            q = q[None]
        if q.shape[1] != self.dim:
            raise ValueError(f"query dim {q.shape[1]} != index dim {self.dim}")
        if self.coarse == "int8":
            # Query-side quantization is a host-hot-path numpy mirror of the
            # quantize_int8 recipe (same abs-max scale, same round-half-even,
            # same clip) — an eager jnp round trip per search costs more than
            # the whole coarse scan. The per-row query scale is a positive
            # constant per score row, so it cannot change any row's ordering
            # and is dropped.
            scale = np.maximum(
                np.max(np.abs(q), axis=1, keepdims=True), 1e-12
            ) / 127.0
            qq = np.clip(np.rint(q / scale), -127, 127).astype(np.int8)
            # int32 queries x int8 corpus: numpy promotes the accumulator to
            # int32 while the BIG operand stays int8 in memory — the bytes
            # the scan streams are the point.
            acc = qq.astype(np.int32) @ self._q8.T  # (q, n)
            scores = acc.astype(np.float32) * self._scale[None, :]
        else:
            scores = sign_sketch_scores(sign_sketch(q), self._bits, self.dim)
        if rerank_k >= self.size:
            return np.broadcast_to(
                np.arange(self.size), (len(q), self.size)
            ).copy()
        part = np.argpartition(-scores, rerank_k - 1, axis=1)[:, :rerank_k]
        return part

    def rerank(
        self, queries, positions: np.ndarray, k: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """Exact f32 re-rank of the survivor ``positions``: top-k under the
        shared :func:`eval.retrieval.merge_topk` ordering contract."""
        q = np.ascontiguousarray(queries, dtype=np.float32)
        if q.ndim == 1:
            q = q[None]
        survivors = self._rows[positions]  # (q, rk, d)
        exact = np.einsum("qd,qrd->qr", q, survivors)
        return merge_topk(exact, self._ids[positions], min(k, self.size))

    def search(
        self, queries, k: int, *, rerank_k: int | None = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """(q, d) or (d,) queries → approximate top-k ``(scores, ids)``.
        Scores of returned candidates are EXACT (re-ranked); approximation
        only ever drops candidates, never mis-scores them."""
        arr = np.asarray(queries)
        squeeze = arr.ndim == 1
        k = min(int(k), self.size)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        rk = self._resolve_rerank_k(k, rerank_k)
        pos = self.coarse_positions(arr, rk)
        scores, ids = self.rerank(arr, pos, k)
        if squeeze:
            return scores[0], ids[0]
        return scores, ids

    def stats(self) -> dict:
        return {
            "size": self.size,
            "coarse": self.coarse,
            "rerank_k": self.rerank_k or 0,
        }
