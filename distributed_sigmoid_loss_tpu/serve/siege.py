"""graftsiege: fault injection + chaos scenarios for the serving stack.

The serving stack's failure semantics (typed shed/queue-full/shutdown
rejections, drain-on-close, swap-under-load, host loss) are contracts, and
contracts that are never exercised rot. This module makes them drillable:

- **chaos gate** — every fault-injection point is a ``maybe_inject(point)``
  call in production code that is DEAD unless the ``DSL_CHAOS`` environment
  hook is set AND a fault is armed. Points must be registered in
  :data:`CHAOS_POINTS` with a rationale; graftlint rule ``repo-chaos-gate``
  statically verifies both (gate present in ``maybe_inject``, every serve/
  call site registered, no stale registry rows), so an ungated injection
  can never reach a production path.
- **host-loss machinery** — :class:`EngineProcess` runs an engine worker in
  a separate OS process behind a pipe (the kill -9 / resume idiom from
  tests/test_multihost_process.py turned on the serving side); a SIGKILLed
  worker surfaces as a typed :class:`HostLostError` to every in-flight
  caller, never a hang, and ``restart()`` measures recovery.
- **scenario generator** — :func:`run_scenario` drives multi-tenant client
  load (burst / skew / slowloris / hostloss / swapstorm) through an
  :class:`~.admission.AdmissionController`-fronted submit callable and
  emits one schema-validated degradation record (p99 vs offered load,
  per-tenant shed_rate, recovery_time_s, silent_drops) for the
  ``serve-bench --scenario`` path to land in LEDGER.jsonl.

Module-level imports stay stdlib + admission + utils (``serve.batcher``
imports this module for its injection point, so importing service/engine
here would cycle through the partially-initialized package).
"""

from __future__ import annotations

import multiprocessing as mp
import os
import signal
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass

from distributed_sigmoid_loss_tpu.serve.admission import (
    AdmissionController,
    ShedError,
    TenantPolicy,
)
from distributed_sigmoid_loss_tpu.utils.logging import LatencyWindow

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = [
    "CHAOS_POINTS",
    "SCENARIOS",
    "EngineProcess",
    "FaultPlan",
    "HostLostError",
    "chaos_enabled",
    "clear_faults",
    "hostloss_drill",
    "inject",
    "install_fault",
    "maybe_inject",
    "run_scenario",
]

# Every fault-injection point in the serving stack, with the rationale for
# why that failure mode is worth drilling. graftlint (repo-chaos-gate)
# cross-checks this registry against the maybe_inject call sites in serve/:
# an unregistered call site, an empty rationale, or a stale row (registered
# but never called) each fail tier-1.
CHAOS_POINTS = {
    "engine.latency": (
        "slow accelerator step (thermal throttle, preempted donor VM): the "
        "deadline + shed path must degrade p99 gracefully, not queue-collapse"
    ),
    "engine.exception": (
        "engine call raises (OOM, XLA runtime fault): every future in the "
        "batch must fail typed; the worker must keep serving later batches"
    ),
    "batcher.stall": (
        "worker thread wedges before the engine call (lock contention, GC "
        "pause): queue fills, submits must hit typed backpressure, and "
        "close() must still drain"
    ),
    "swap.storm": (
        "hot swap under overload: swaps serialize, searches stay on their "
        "version, /healthz must show degraded while a swap is mid-flight"
    ),
    "fleet.partition": (
        "lease client partitioned from the coordinator (network split): the "
        "host must stop using its slices at the staleness bound and shed — "
        "bounded staleness means under-admit is the only legal failure mode"
    ),
}

# Armed fault plans, point -> FaultPlan. Mutable module state by design
# (allowlisted in analysis/repo_lint.py): tests and scenario drivers arm
# faults cross-thread, and the production read path must stay one dict probe.
_INJECTORS: dict = {}
_INJECT_LOCK = named_lock("serve.siege._INJECT_LOCK")


def chaos_enabled() -> bool:
    """The DSL_CHAOS hook: fault injection is dead unless this env var is
    exactly "1" (graftlint verifies maybe_inject is gated on this)."""
    return os.environ.get("DSL_CHAOS", "") == "1"


@dataclass
class FaultPlan:
    """One armed fault: sleep ``delay_s``, then raise ``exception`` (if
    any), at most ``count`` times (None = every pass through the point)."""

    delay_s: float = 0.0
    exception: BaseException | None = None
    count: int | None = None
    fired: int = 0

    def _take(self) -> bool:
        if self.count is not None and self.fired >= self.count:
            return False
        self.fired += 1
        return True


def install_fault(
    point: str,
    *,
    delay_s: float = 0.0,
    exception: BaseException | None = None,
    count: int | None = None,
) -> FaultPlan:
    """Arm a fault at a registered injection point (unregistered → KeyError).

    Arming does NOT flip the gate: nothing fires unless ``DSL_CHAOS=1`` is
    also set in the environment — the gate stays a deliberate, separate act.
    """
    if point not in CHAOS_POINTS:
        raise KeyError(
            f"unregistered chaos point {point!r}; register it in "
            f"serve/siege.py CHAOS_POINTS (known: {sorted(CHAOS_POINTS)})"
        )
    plan = FaultPlan(delay_s=delay_s, exception=exception, count=count)
    with _INJECT_LOCK:
        _INJECTORS[point] = plan
    return plan


def clear_faults(point: str | None = None) -> None:
    with _INJECT_LOCK:
        if point is None:
            _INJECTORS.clear()
        else:
            _INJECTORS.pop(point, None)


@contextmanager
def inject(point: str, **kwargs):
    """``with inject("engine.latency", delay_s=0.05): ...`` — arm for the
    block, disarm on exit (the env gate is still the caller's job)."""
    plan = install_fault(point, **kwargs)
    try:
        yield plan
    finally:
        clear_faults(point)


def maybe_inject(point: str) -> None:
    """The production-side injection point. Unregistered point → KeyError
    (a call site that drifts from the registry fails loudly, not silently);
    otherwise a no-op unless the DSL_CHAOS gate is up AND a fault is armed.
    """
    if point not in CHAOS_POINTS:
        raise KeyError(
            f"maybe_inject({point!r}): not a registered chaos point "
            f"(known: {sorted(CHAOS_POINTS)})"
        )
    if not chaos_enabled():
        return
    with _INJECT_LOCK:
        plan = _INJECTORS.get(point)
        live = plan is not None and plan._take()
    if not live:
        return
    if plan.delay_s > 0:
        time.sleep(plan.delay_s)
    if plan.exception is not None:
        raise plan.exception


# -- host-loss machinery ------------------------------------------------------


class HostLostError(RuntimeError):
    """The engine's host process died mid-request (kill -9, OOM-kill,
    preemption). Typed so admitted requests fail loudly instead of hanging —
    the zero-silent-drops contract."""


def _echo_worker(conn, latency_s: float) -> None:
    """Default engine surrogate for drills: echoes payloads after an
    optional simulated compute delay. Top-level so every mp start method
    can pickle it. Pure stdlib on purpose — the drill exercises the SERVING
    failure semantics (pipe loss, typed errors, recovery), not the model
    forward, so the child never imports jax."""
    while True:
        try:
            kind, payload = conn.recv()
        except (EOFError, OSError):
            return
        if kind == "stop":
            return
        if latency_s > 0:
            time.sleep(latency_s)
        try:
            conn.send(("ok", payload))
        except (BrokenPipeError, OSError):
            return


class EngineProcess:
    """An engine worker in a separate OS process, callable over a pipe.

    The serving-side half of the kill -9 / resume machinery: ``kill()``
    SIGKILLs the worker mid-traffic (no cleanup, like a lost host), after
    which every in-flight and subsequent ``call`` raises
    :class:`HostLostError` until ``restart()`` brings a fresh worker up.
    ``restarts`` counts recoveries.

    ``ctx`` picks the multiprocessing start method: "fork" is instant and
    right for drill workers that only touch stdlib; use "spawn" when the
    parent has initialized jax/XLA threads (fork-unsafe).
    """

    def __init__(self, worker=None, *, ctx: str = "fork", latency_s: float = 0.0):
        self._worker = worker or _echo_worker
        self._ctx_name = ctx
        self._latency_s = latency_s
        self._lock = named_lock("serve.siege.EngineProcess._lock")
        self.restarts = 0
        self._start()

    def _start(self) -> None:
        ctx = mp.get_context(self._ctx_name)
        parent_end, child_end = ctx.Pipe()
        self._proc = ctx.Process(
            target=self._worker,
            args=(child_end, self._latency_s),
            daemon=True,
        )
        self._proc.start()
        # Close the parent's copy of the child end: once the worker dies its
        # end is the LAST writer, so recv() raises EOFError instead of
        # blocking forever — the typed-loss path depends on this.
        child_end.close()
        self._conn = parent_end

    @property
    def pid(self) -> int:
        return self._proc.pid

    def alive(self) -> bool:
        return self._proc.is_alive()

    def call(self, payload, *, timeout_s: float = 30.0):
        """One round-trip through the worker; raises HostLostError when the
        worker is gone or unresponsive past ``timeout_s``."""
        with self._lock:
            try:
                self._conn.send(("req", payload))
                if not self._conn.poll(timeout_s):
                    raise HostLostError(
                        f"engine process pid={self._proc.pid} unresponsive "
                        f"after {timeout_s}s"
                    )
                kind, result = self._conn.recv()
            except (EOFError, BrokenPipeError, OSError) as e:
                raise HostLostError(
                    f"engine process pid={self._proc.pid} lost: "
                    f"{type(e).__name__}"
                ) from e
        if kind != "ok":
            raise HostLostError(f"engine process error: {result}")
        return result

    def kill(self) -> None:
        """SIGKILL the worker — no shutdown handshake, like a lost host."""
        if self._proc.is_alive():
            os.kill(self._proc.pid, signal.SIGKILL)
        self._proc.join(timeout=10.0)

    def restart(self) -> None:
        """Bring up a fresh worker (the resume half of the drill)."""
        try:
            self._conn.close()
        except OSError:
            pass
        if self._proc.is_alive():
            self.kill()
        self._start()
        self.restarts += 1

    def close(self) -> None:
        try:
            self._conn.send(("stop", None))
        except (BrokenPipeError, OSError):
            pass
        self._proc.join(timeout=5.0)
        if self._proc.is_alive():
            self.kill()
        try:
            self._conn.close()
        except OSError:
            pass


# -- scenario generator -------------------------------------------------------

SCENARIOS = (
    "burst",
    "skew",
    "slowloris",
    "hostloss",
    "swapstorm",
    # Fleet-tier drills (serve/fleet/scenarios.py wires the hooks): same
    # harness, same record contract, fleet fields merged in afterwards.
    "fleet-rolling-swap",
    "fleet-hostloss",
    "fleet-splitbrain",
)

# Scenarios that reuse the kill_fn/restart_fn slots (kill at 40% of the run,
# restart at 60%): for the fleet drills "kill" is replica kill -9 or a
# coordinator partition, and "restart" is restart+revive or heal.
_KILL_SCENARIOS = frozenset({
    "hostloss", "fleet-hostloss", "fleet-splitbrain",
})
# Scenarios that run the swap thread (swap_fn every 200ms).
_SWAP_SCENARIOS = frozenset({"swapstorm", "fleet-rolling-swap"})
# Scenarios with the square-wave (burst) load shape.
_BURST_SCENARIOS = frozenset({"burst", "fleet-rolling-swap"})

# Exception type names the harness counts as TYPED rejections: the contract
# is that every non-ok outcome is one of these (anything else is a silent
# drop — an outcome the client cannot act on). Matched by name so this
# module never imports service/batcher at module level.
_TYPED_REJECTIONS = frozenset({
    "ShedError",
    "QueueFullError",
    "BatcherClosedError",
    "ShutdownError",
    "RequestTimeoutError",
    "HostLostError",
    "NoReplicaError",
})


@dataclass
class _TenantTally:
    sent: int = 0
    ok: int = 0
    shed: int = 0
    typed_errors: int = 0
    silent_drops: int = 0


def _hog_and_victims(tenants):
    """The scenario's adversary is the lowest-priority tenant (ties: last
    declared); everyone else is a victim whose SLO must hold."""
    hog = min(tenants, key=lambda p: (p.priority, -tenants.index(p)))
    victims = [p for p in tenants if p is not hog] or [hog]
    return hog, victims


def run_scenario(
    scenario: str,
    *,
    submit,
    tenants,
    admission: AdmissionController | None,
    duration_s: float = 2.0,
    offered_load: float = 200.0,
    clients_per_tenant: int = 4,
    kill_fn=None,
    restart_fn=None,
    swap_fn=None,
    seed: int = 0,
) -> dict:
    """Drive one chaos scenario and return its degradation record.

    ``submit(tenant, i, items=1, fresh=False)`` performs ONE request end to
    end (admission included) and raises typed errors on rejection; ``i`` is
    a monotonically increasing per-client counter the harness varies so
    ``fresh=True`` traffic can be made cache-hostile by the caller.

    Scenario shapes (hog = lowest-priority tenant):

    - ``burst``    — square-wave load: 2.5x offered rate for half a second,
      near-idle the next; sheds must absorb the crest, not the trough.
    - ``skew``     — the hog sends 85% of the load, all cache-hostile
      (``fresh=True``): the memory-bandwidth-bound worst case.
    - ``slowloris``— the hog sends few, LARGE requests (items=16) that camp
      on in-flight quota; victims stay single-item and must stay in SLO.
    - ``hostloss`` — ``kill_fn()`` at 40% of the run, ``restart_fn()`` at
      60%; recovery_time_s = first post-kill success minus the kill time.
    - ``swapstorm``— ``swap_fn()`` every 200ms under full load.

    Every client obeys the rejection's ``retry_after_s`` guidance (capped),
    so the harness itself never retry-storms.
    """
    if scenario not in SCENARIOS:
        raise ValueError(f"unknown scenario {scenario!r}; pick from {SCENARIOS}")
    if scenario in _KILL_SCENARIOS and (kill_fn is None or restart_fn is None):
        raise ValueError(f"{scenario} scenario needs kill_fn and restart_fn")
    if scenario in _SWAP_SCENARIOS and swap_fn is None:
        raise ValueError(f"{scenario} scenario needs swap_fn")
    tenants = list(tenants)
    hog, _victims = _hog_and_victims(tenants)
    tallies = {p.name: _TenantTally() for p in tenants}
    windows = {p.name: LatencyWindow(8192) for p in tenants}
    overall_window = LatencyWindow(8192)
    tally_lock = named_lock("serve.siege.run_scenario.tally_lock")
    stop = threading.Event()
    t_start = time.monotonic()
    kill_at = {"t": None}
    first_ok_after_kill = {"t": None}

    # Per-tenant offered rate (requests/s across that tenant's clients).
    n = len(tenants)
    share = {p.name: offered_load / n for p in tenants}
    if scenario == "skew" and n > 1:
        share = {
            p.name: (
                offered_load * 0.85
                if p is hog
                else offered_load * 0.15 / (n - 1)
            )
            for p in tenants
        }
    if scenario == "slowloris":
        # Large requests: keep the hog's ITEM rate comparable while its
        # request rate drops 8x (items=16 below).
        share[hog.name] = share[hog.name] / 8.0

    def rate_mult(now_s: float) -> float:
        if scenario not in _BURST_SCENARIOS:
            return 1.0
        return 2.5 if (now_s % 1.0) < 0.5 else 0.1

    def client(policy: TenantPolicy, client_idx: int) -> None:
        rng_step = seed * 7919 + client_idx * 104729 + hash(policy.name) % 997
        i = client_idx
        tally = tallies[policy.name]
        window = windows[policy.name]
        items = 16 if (scenario == "slowloris" and policy is hog) else 1
        fresh = scenario == "skew" and policy is hog
        while not stop.is_set():
            now = time.monotonic() - t_start
            rate = share[policy.name] * rate_mult(now) / clients_per_tenant
            # Deterministically jittered interarrival around 1/rate.
            rng_step = (rng_step * 6364136223846793005 + 1442695040888963407) % (2**64)
            jitter = 0.5 + (rng_step >> 33) / (2**31)
            pause = jitter / max(rate, 1e-6)
            if stop.wait(min(pause, 0.25)):
                break
            i += clients_per_tenant
            t0 = time.monotonic()
            try:
                submit(policy.name, i, items=items, fresh=fresh)
            except ShedError as e:
                with tally_lock:
                    tally.sent += 1
                    tally.shed += 1
                # Obey the backoff guidance — the no-retry-storm contract.
                if e.retriable and e.retry_after_s > 0:
                    stop.wait(min(e.retry_after_s, 0.5))
                continue
            except Exception as e:  # noqa: BLE001 — classify the outcome
                typed = type(e).__name__ in _TYPED_REJECTIONS
                with tally_lock:
                    tally.sent += 1
                    if typed:
                        tally.typed_errors += 1
                    else:
                        tally.silent_drops += 1
                stop.wait(0.02)
                continue
            t_ok = time.monotonic()
            with tally_lock:
                tally.sent += 1
                tally.ok += 1
                if (
                    kill_at["t"] is not None
                    and first_ok_after_kill["t"] is None
                    and t_ok > kill_at["t"]
                ):
                    first_ok_after_kill["t"] = t_ok
            window.record(t_ok - t0)
            overall_window.record(t_ok - t0)

    threads = [
        threading.Thread(
            target=client, args=(p, c), daemon=True,
            name=f"siege-{p.name}-{c}",
        )
        for p in tenants
        for c in range(clients_per_tenant)
    ]
    for t in threads:
        t.start()

    swapper = None
    if scenario in _SWAP_SCENARIOS:
        def swap_loop():
            while not stop.wait(0.2):
                swap_fn()
        swapper = threading.Thread(target=swap_loop, daemon=True, name="siege-swap")
        swapper.start()

    deadline = t_start + duration_s
    killed = restarted = False
    while time.monotonic() < deadline:
        if scenario in _KILL_SCENARIOS:
            now = time.monotonic() - t_start
            if not killed and now >= 0.4 * duration_s:
                with tally_lock:
                    kill_at["t"] = time.monotonic()
                kill_fn()
                killed = True
            elif killed and not restarted and now >= 0.6 * duration_s:
                restart_fn()
                restarted = True
        time.sleep(0.01)
    stop.set()
    for t in threads:
        t.join(timeout=10.0)
    if swapper is not None:
        swapper.join(timeout=10.0)

    recovery_time_s = 0.0
    if kill_at["t"] is not None and first_ok_after_kill["t"] is not None:
        recovery_time_s = first_ok_after_kill["t"] - kill_at["t"]

    per_tenant = {}
    total_sent = total_shed = total_drops = 0
    for p in tenants:
        tally = tallies[p.name]
        pcts = windows[p.name].percentiles_ms((50, 99))
        total_sent += tally.sent
        total_shed += tally.shed
        total_drops += tally.silent_drops
        adm_row = (
            admission.stats()["per_tenant"].get(p.name, {})
            if admission is not None
            else {}
        )
        per_tenant[p.name] = {
            "sent": tally.sent,
            "ok": tally.ok,
            "shed": tally.shed,
            "shed_rate": round(tally.shed / tally.sent, 4) if tally.sent else 0.0,
            "typed_errors": tally.typed_errors,
            "silent_drops": tally.silent_drops,
            "p50_ms": pcts["p50_ms"],
            "p99_ms": pcts["p99_ms"],
            "slo_ms": p.slo_ms,
            "slo_violations": adm_row.get("slo_violations", 0),
        }
    overall_p99 = overall_window.percentiles_ms((99,))["p99_ms"]
    return {
        "metric": "serve_siege",
        "value": overall_p99,
        "unit": "ms",
        "scenario": scenario,
        "offered_load": offered_load,
        "duration_s": duration_s,
        "tenants": len(tenants),
        "shed_rate": round(total_shed / total_sent, 4) if total_sent else 0.0,
        "recovery_time_s": round(recovery_time_s, 4),
        "silent_drops": total_drops,
        "per_tenant": per_tenant,
    }


def hostloss_drill(
    *,
    tenants=None,
    duration_s: float = 2.0,
    offered_load: float = 120.0,
    capacity: int = 32,
    ctx: str = "fork",
    engine_latency_s: float = 0.002,
    seed: int = 0,
) -> dict:
    """Self-contained serving host-loss drill: admission → MicroBatcher →
    :class:`EngineProcess`, kill -9 mid-traffic, resume, and return the
    degradation record (used by tests and ``serve-bench --scenario
    hostloss``; the engine is the stdlib surrogate worker — the drill is
    about the serving stack's failure semantics, not the model forward)."""
    from distributed_sigmoid_loss_tpu.serve.batcher import MicroBatcher

    tenants = list(tenants) if tenants else [
        TenantPolicy("gold", priority=2, max_inflight=16, slo_ms=500.0),
        TenantPolicy("free", priority=1, rate=offered_load, max_inflight=8),
    ]
    admission = AdmissionController(tenants, capacity=capacity)
    proc = EngineProcess(ctx=ctx, latency_s=engine_latency_s)
    batcher = MicroBatcher(
        lambda rows: proc.call(rows, timeout_s=5.0),
        max_batch_size=8,
        max_wait_ms=2.0,
        max_queue=max(capacity * 2, 64),
        name="siege-drill",
    )

    def submit(tenant, i, *, items=1, fresh=False):
        del fresh
        with admission.admit(tenant, items=items, deadline_s=5.0):
            batcher.submit(i).result(timeout=5.0)

    try:
        record = run_scenario(
            "hostloss",
            submit=submit,
            tenants=tenants,
            admission=admission,
            duration_s=duration_s,
            offered_load=offered_load,
            kill_fn=proc.kill,
            restart_fn=proc.restart,
            seed=seed,
        )
    finally:
        batcher.close()
        proc.close()
    record["restarts"] = proc.restarts
    return record
