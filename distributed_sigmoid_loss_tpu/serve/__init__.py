"""serve — online embedding & retrieval serving over the trained two-tower model.

The layer that turns an exported/trained SigLIP into a request-serving
system (the ROADMAP's "heavy traffic" north star), runnable on CPU in tests:

- :mod:`.engine` — jitted encoders behind fixed padded shape buckets, so
  steady-state traffic never triggers a fresh XLA compile (compile-count
  introspection built in; optional dp-mesh sharded execution).
- :mod:`.batcher` — thread-safe micro-batcher: coalesces concurrent callers
  into one engine call under a ``max_wait_ms`` deadline, with bounded-queue
  backpressure (typed rejection, not unbounded growth).
- :mod:`.cache` — content-hash-keyed LRU embedding cache with
  hit/miss/eviction counters.
- :mod:`.index` — exact chunked dot-product top-k over L2-normalized rows,
  ranking-identical to ``eval.retrieval`` (shared tie-break contract).
- :mod:`.shard_index` — the same exact top-k partitioned over the dp mesh:
  per-shard candidates in a ``shard_map`` region, host-merged under the
  shared tie contract (ranking-identical to the one-matrix oracle).
- :mod:`.ann` — the approximate tier: int8 / sign-sketch coarse pruning
  (reusing ``ops.quant``) then exact re-rank, with measured recall@k.
- :mod:`.swap` — zero-downtime hot swap of weights + index segments
  (versioned, double-buffered, zero recompiles).
- :mod:`.service` — the façade: ``encode_text`` / ``encode_image`` /
  ``search`` with per-request timeouts and a ``stats()`` snapshot (qps,
  latency percentiles, batch histogram, cache hit rate, compile count) —
  plus ``RetrievalRouter``, the tiered/versioned index front end.
- :mod:`.fleet` — the multi-host tier: bounded-staleness token-lease
  distributed admission, the replica-group front door with session-affinity
  pinning and typed host-loss reroute, coordinated zero-downtime swap
  waves, and the fleet chaos scenarios.

Entry point: ``python -m distributed_sigmoid_loss_tpu serve-bench`` drives the
whole stack on synthetic data and prints the stats snapshot as JSON
(``--index-tier`` picks the retrieval tier, ``--swap-every`` adds hot-swap
churn).
"""

from distributed_sigmoid_loss_tpu.serve.admission import (  # noqa: F401
    AdmissionController,
    ShedError,
    TenantPolicy,
    parse_tenant_spec,
)
from distributed_sigmoid_loss_tpu.serve.ann import AnnIndex  # noqa: F401
from distributed_sigmoid_loss_tpu.serve.batcher import (  # noqa: F401
    BatcherClosedError,
    MicroBatcher,
    QueueFullError,
    ShutdownError,
)
from distributed_sigmoid_loss_tpu.serve.cache import (  # noqa: F401
    EmbeddingCache,
    content_key,
)
from distributed_sigmoid_loss_tpu.serve.engine import InferenceEngine  # noqa: F401
from distributed_sigmoid_loss_tpu.serve.fleet import (  # noqa: F401
    FLEET_SCENARIOS,
    FleetHost,
    FleetRouter,
    LeaseClient,
    LeaseCoordinator,
    LeasedAdmission,
    NoReplicaError,
    OverCommitError,
    ReplicaHandle,
    WaveController,
    build_fleet,
    run_fleet_scenario,
)
from distributed_sigmoid_loss_tpu.serve.index import RetrievalIndex  # noqa: F401
from distributed_sigmoid_loss_tpu.serve.service import (  # noqa: F401
    EmbeddingService,
    RequestTimeoutError,
    RetrievalRouter,
)
from distributed_sigmoid_loss_tpu.serve.shard_index import (  # noqa: F401
    ShardedIndex,
)
from distributed_sigmoid_loss_tpu.serve.siege import (  # noqa: F401
    CHAOS_POINTS,
    SCENARIOS,
    EngineProcess,
    HostLostError,
    chaos_enabled,
    hostloss_drill,
    inject,
    maybe_inject,
    run_scenario,
)
from distributed_sigmoid_loss_tpu.serve.swap import SwapController  # noqa: F401

__all__ = [
    "AdmissionController",
    "AnnIndex",
    "BatcherClosedError",
    "CHAOS_POINTS",
    "EmbeddingCache",
    "EmbeddingService",
    "EngineProcess",
    "FLEET_SCENARIOS",
    "FleetHost",
    "FleetRouter",
    "HostLostError",
    "InferenceEngine",
    "LeaseClient",
    "LeaseCoordinator",
    "LeasedAdmission",
    "MicroBatcher",
    "NoReplicaError",
    "OverCommitError",
    "QueueFullError",
    "ReplicaHandle",
    "RequestTimeoutError",
    "RetrievalIndex",
    "RetrievalRouter",
    "SCENARIOS",
    "ShardedIndex",
    "ShedError",
    "ShutdownError",
    "SwapController",
    "TenantPolicy",
    "WaveController",
    "build_fleet",
    "chaos_enabled",
    "content_key",
    "hostloss_drill",
    "inject",
    "maybe_inject",
    "parse_tenant_spec",
    "run_fleet_scenario",
    "run_scenario",
]
