"""graftfleet scenarios: the fleet-level chaos drills.

Three scenarios, run through the same :func:`~..siege.run_scenario`
closed-loop multi-tenant harness as the single-host drills (same typed
outcome taxonomy, same LEDGER.jsonl record contract, fleet fields added):

- ``fleet-rolling-swap`` — a coordinated swap wave every 200ms under the
  burst load shape: zero errors, per-session versions monotone, never two
  versions serving one session (the router+wave invariant), compile flat
  when the hosts are engine-backed.
- ``fleet-hostloss`` — kill -9 one replica mid-traffic: the router marks
  it lost on the first typed :class:`~..siege.HostLostError` and reroutes
  to siblings (zero silent drops); the dead host stops renewing, its
  lease slices expire at TTL, and the coordinator redistributes them so
  the surviving hosts' summed ceiling returns to full — no stranded quota.
- ``fleet-splitbrain`` — partition one host from the coordinator: its
  slices age out at USE_FRACTION·TTL (it sheds, reason ``"lease"``) while
  the coordinator re-grants them to reachable hosts only after the full
  TTL — both sides under-admit through the hand-off and the summed
  admitted rate never exceeds the global ceiling (the record's
  ``over_ceiling_samples`` is the per-sample proof, asserted zero).

Every record carries the admitted-rate evidence: per-host admit timestamps
are merged and swept with a sliding window against
``ceiling·window + global burst`` — the bound that holds because live lease
fractions sum ≤ 1.0 at every instant (see leases.py).

Stdlib-only: hosts are :class:`~..siege.EngineProcess` echo workers by
default, so ``serve-bench --fleet-scenario`` runs before jax ever loads
(the hostloss-drill convention).
"""

from __future__ import annotations

import bisect

from distributed_sigmoid_loss_tpu.serve.admission import TenantPolicy
from distributed_sigmoid_loss_tpu.serve.fleet.leases import (
    LeaseClient,
    LeaseCoordinator,
    LeasedAdmission,
)
from distributed_sigmoid_loss_tpu.serve.fleet.router import (
    FleetRouter,
    ReplicaHandle,
)
from distributed_sigmoid_loss_tpu.serve.fleet.waves import WaveController
from distributed_sigmoid_loss_tpu.serve.siege import (
    EngineProcess,
    run_scenario,
)

__all__ = [
    "FLEET_SCENARIOS",
    "Fleet",
    "FleetHost",
    "build_fleet",
    "run_fleet_scenario",
]

FLEET_SCENARIOS = (
    "fleet-rolling-swap",
    "fleet-hostloss",
    "fleet-splitbrain",
)


class FleetHost:
    """One serving host: leased admission in front of a compute backend
    (an :class:`~..siege.EngineProcess` for process-backed drills, or an
    in-process callable for engine-backed tests), plus the published index
    version the swap wave advances."""

    def __init__(
        self,
        name: str,
        *,
        admission: LeasedAdmission,
        client: LeaseClient,
        proc: EngineProcess | None = None,
        compute=None,
        swap_impl=None,
    ):
        self.name = name
        self.admission = admission
        self.client = client
        self.proc = proc
        self.compute = compute
        self.swap_impl = swap_impl
        self.version = 1

    def call(self, request):
        """One admitted request: ``request = (tenant, items, body)`` —
        admission from the leased slice, then the backend round-trip."""
        tenant, items, body = request
        with self.admission.admit(tenant, items=items, deadline_s=5.0):
            if self.proc is not None:
                return self.proc.call(body, timeout_s=5.0)
            if self.compute is not None:
                return self.compute(body)
            return body

    def health(self) -> dict:
        if self.proc is not None and not self.proc.alive():
            return {"status": "lost", "reasons": ["host_lost"]}
        return {"status": "ok", "reasons": []}

    def swap(self) -> None:
        """The per-replica swap step a wave runs while this host is
        drained and idle (engine-backed hosts swap weights here —
        zero-recompile — before the version advances)."""
        if self.swap_impl is not None:
            self.swap_impl()
        self.version += 1

    def kill(self) -> None:
        """kill -9 the backend; the lease client's alive_fn makes renewal
        stop with it, so the slices age out exactly like a lost host's."""
        if self.proc is not None:
            self.proc.kill()

    def restart(self) -> None:
        if self.proc is not None:
            self.proc.restart()

    def close(self) -> None:
        self.client.close()
        if self.proc is not None:
            self.proc.close()


class Fleet:
    """A built fleet: coordinator + hosts + router + wave controller."""

    def __init__(self, coordinator, hosts, router, waves):
        self.coordinator = coordinator
        self.hosts = hosts
        self.router = router
        self.waves = waves

    def close(self) -> None:
        for host in self.hosts:
            host.close()

    def admit_events(self) -> list:
        """All hosts' (timestamp, items) admits, time-sorted — the
        over-admission evidence trail."""
        events = []
        for host in self.hosts:
            events.extend(host.admission.admit_times())
        events.sort()
        return events


def build_fleet(
    *,
    replicas: int = 3,
    tenants,
    ttl_s: float = 0.5,
    renew_interval_s: float | None = None,
    ctx: str = "fork",
    engine_latency_s: float = 0.002,
    process_backed: bool = True,
    computes=None,
    swap_impls=None,
    drain_timeout_s: float = 10.0,
) -> Fleet:
    """Wire up a fleet: one coordinator, N hosts (each with its own lease
    client + leased admission), the router over their handles, and the
    wave controller. ``computes``/``swap_impls`` (per-replica lists) swap
    the process backend for in-process callables — the engine-backed path
    the compile-flat acceptance test uses."""
    if replicas < 2:
        raise ValueError(
            f"a fleet needs >= 2 replicas (got {replicas}); with one there "
            "is no sibling to reroute to and no wave to order"
        )
    tenants = list(tenants)
    coordinator = LeaseCoordinator(
        {p.name: p.rate for p in tenants}, ttl_s=ttl_s
    )
    hosts = []
    for k in range(replicas):
        name = f"replica-{k}"
        proc = None
        if process_backed:
            proc = EngineProcess(ctx=ctx, latency_s=engine_latency_s)
        client = LeaseClient(
            coordinator, name,
            renew_interval_s=renew_interval_s,
            alive_fn=proc.alive if proc is not None else None,
        )
        host = FleetHost(
            name,
            admission=LeasedAdmission(client, tenants),
            client=client,
            proc=proc,
            compute=computes[k] if computes else None,
            swap_impl=swap_impls[k] if swap_impls else None,
        )
        client.start()
        hosts.append(host)
    handles = [
        ReplicaHandle(
            h.name, h.call,
            health_fn=h.health,
            version_fn=(lambda h=h: h.version),
            swap_fn=h.swap,
        )
        for h in hosts
    ]
    router = FleetRouter(handles)
    waves = WaveController(router, drain_timeout_s=drain_timeout_s)
    return Fleet(coordinator, hosts, router, waves)


def _default_fleet_tenants(offered_load: float) -> list:
    # Rates sum to 0.75 × offered: the fleet runs with real admission
    # pressure, so lease hand-offs are visible as shed-rate movement.
    return [
        TenantPolicy(
            "gold", priority=2, rate=0.45 * offered_load,
            max_inflight=24, slo_ms=500.0,
        ),
        TenantPolicy(
            "free", priority=1, rate=0.30 * offered_load, max_inflight=12,
        ),
    ]


def _over_ceiling_sweep(
    events, ceiling: float, burst: float,
    *, window_s: float = 1.0, step_s: float = 0.05,
) -> tuple:
    """Slide a window over the merged admit trail; returns
    ``(over_ceiling_samples, peak_admitted_rate)``. The bound per window is
    ``ceiling·window + burst`` — the token-bucket inequality that holds
    when live fractions sum ≤ 1.0 (over_ceiling_samples > 0 means the
    lease invariant was violated at some instant)."""
    if not events:
        return (0, 0.0)
    times = [t for t, _items in events]
    prefix = [0]
    for _t, items in events:
        prefix.append(prefix[-1] + items)
    over = 0
    peak = 0.0
    t = times[0]
    t_end = times[-1]
    while t <= t_end:
        lo = bisect.bisect_left(times, t)
        hi = bisect.bisect_left(times, t + window_s)
        admitted = prefix[hi] - prefix[lo]
        peak = max(peak, admitted / window_s)
        if admitted > ceiling * window_s + burst + 1e-6:
            over += 1
        t += step_s
    return (over, peak)


def run_fleet_scenario(
    scenario: str,
    *,
    replicas: int = 3,
    tenants=None,
    duration_s: float = 2.0,
    offered_load: float = 160.0,
    clients_per_tenant: int = 4,
    lease_ttl_s: float = 0.5,
    ctx: str = "fork",
    engine_latency_s: float = 0.002,
    seed: int = 0,
) -> dict:
    """Run one fleet scenario end to end and return its degradation
    record (metric ``fleet_siege``; every field registered in
    analysis/bench_schema.py — the serve-bench ``--fleet-scenario`` path
    emits it through the same strict-zero-drops gate as the single-host
    drills)."""
    if scenario not in FLEET_SCENARIOS:
        raise ValueError(
            f"unknown fleet scenario {scenario!r}; pick from "
            f"{FLEET_SCENARIOS}"
        )
    tenants = (
        list(tenants) if tenants else _default_fleet_tenants(offered_load)
    )
    fleet = build_fleet(
        replicas=replicas, tenants=tenants, ttl_s=lease_ttl_s,
        ctx=ctx, engine_latency_s=engine_latency_s,
    )
    router, waves = fleet.router, fleet.waves
    victim = fleet.hosts[-1]

    def submit(tenant, i, *, items=1, fresh=False):
        del fresh
        session = f"{tenant}/{i % clients_per_tenant}"
        router.route((tenant, items, i), session=session)

    kill_fn = restart_fn = swap_fn = None
    if scenario == "fleet-hostloss":
        kill_fn = victim.kill

        def restart_fn():
            victim.restart()
            router.revive(victim.name)
    elif scenario == "fleet-splitbrain":
        kill_fn = victim.client.partition

        def restart_fn():
            victim.client.partition(False)
    elif scenario == "fleet-rolling-swap":
        swap_fn = waves.run_wave

    try:
        record = run_scenario(
            scenario,
            submit=submit,
            tenants=tenants,
            admission=None,
            duration_s=duration_s,
            offered_load=offered_load,
            clients_per_tenant=clients_per_tenant,
            kill_fn=kill_fn,
            restart_fn=restart_fn,
            swap_fn=swap_fn,
            seed=seed,
        )
        events = fleet.admit_events()
        ceiling = sum(p.rate for p in tenants if p.rate > 0)
        burst = sum(
            p.bucket_depth() for p in tenants if p.rate > 0
        )
        over, peak = _over_ceiling_sweep(events, ceiling, burst)
        record.update(router.stats())
        record.update(waves.stats())
        record.update(fleet.coordinator.stats())
        record["metric"] = "fleet_siege"
        record["fleet_replicas"] = replicas
        record["lease_ttl_s"] = lease_ttl_s
        record["ceiling_rate"] = round(ceiling, 2)
        record["peak_admitted_rate"] = round(peak, 2)
        record["over_ceiling_samples"] = over
        record["restarts"] = sum(
            h.proc.restarts for h in fleet.hosts if h.proc is not None
        )
    finally:
        fleet.close()
    return record
