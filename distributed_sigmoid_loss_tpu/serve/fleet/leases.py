"""graftfleet distributed admission: bounded-staleness token leases.

The single-host :class:`~..admission.AdmissionController` enforces a
tenant's rate/quota inside ONE process. A fleet of N replicas each running
that controller at full rate would admit N× the contract. This module
splits every tenant's GLOBAL ceiling into per-host slices via time-bounded
leases, with the classic lease-safety asymmetry making over-admission
structurally impossible rather than merely unobserved:

- the **coordinator** (:class:`LeaseCoordinator`) owns the grant table. A
  grant for tenant ``t`` to host ``h`` is a fraction of the tenant's global
  rate/quota, stamped with ``granted_at`` and the coordinator's ``ttl_s``.
  The table invariant — the sum of unexpired fractions per tenant never
  exceeds 1.0 — is enforced at grant time: a grant that would break it
  raises :class:`OverCommitError` instead of landing (the "pinned
  impossible" half of the contract; :func:`LeaseCoordinator.grant` is the
  low-level entry tests trip it through).
- each **host** (:class:`LeaseClient`) renews on a period well inside the
  TTL and stops USING a lease at ``granted_at + USE_FRACTION * ttl_s`` —
  strictly before the coordinator reclaims it at ``granted_at + ttl_s``.
  A host killed -9 (or partitioned from the coordinator) therefore goes
  quiet before its slice is re-granted to survivors: the two sides never
  overlap, so the summed in-use fraction stays ≤ 1.0 at every instant even
  across failures. Bounded staleness means shed-early is the safe failure
  mode — a partitioned host under-admits (sheds with reason ``"lease"``),
  never over-admits.
- :class:`LeasedAdmission` is the host-side front door: a per-tenant token
  bucket + in-flight quota scaled by the CURRENT lease fraction, raising
  the same typed :class:`~..admission.ShedError` contract as the
  single-host controller (so graftsiege clients obey the same backoff
  guidance) and recording admit timestamps so the fleet scenarios can
  prove the summed admitted rate stayed under the ceiling at every sample.

Stdlib-only on purpose: the coordinator "hop" is a direct method call on
one machine (the EngineProcess stand-in convention) — the protocol is the
contract, not the transport.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock
from distributed_sigmoid_loss_tpu.serve.admission import (
    _BACKOFF_BASE_S,
    _BACKOFF_CAP_S,
    _BACKOFF_MAX_DOUBLINGS,
    AdmissionTicket,
    ShedError,
    TenantPolicy,
)
from distributed_sigmoid_loss_tpu.serve.siege import maybe_inject

__all__ = [
    "USE_FRACTION",
    "Lease",
    "LeaseCoordinator",
    "LeaseClient",
    "LeasedAdmission",
    "OverCommitError",
]

# The staleness bound: a host stops using a lease at this fraction of the
# TTL, the coordinator reclaims only at the full TTL — the gap is the
# safety margin that keeps a dead host's slice and its re-grant from ever
# being in use simultaneously (clock skew would eat into it on a real
# multi-host deployment; on one machine time.monotonic is shared).
USE_FRACTION = 0.75

_EPS = 1e-9


class OverCommitError(RuntimeError):
    """A grant would push a tenant's summed live fractions past 1.0 — the
    over-admission path exists only as this raise."""


@dataclass(frozen=True)
class Lease:
    """One host's slice of one tenant's global ceiling."""

    tenant: str
    host: str
    fraction: float
    epoch: int
    granted_at: float
    ttl_s: float

    def expires_at(self) -> float:
        """When the COORDINATOR may reclaim (the host stops using earlier,
        at ``granted_at + USE_FRACTION * ttl_s``)."""
        return self.granted_at + self.ttl_s

    def usable_until(self) -> float:
        return self.granted_at + USE_FRACTION * self.ttl_s


class LeaseCoordinator:
    """The grant-table owner: equal-share target, availability-capped.

    ``ceilings`` maps tenant name → global rate (req/s; 0.0 = the tenant is
    quota-only — fractions still slice its in-flight quota). A renewing
    host is granted ``min(1/n_live, 1 - sum(other live fractions))`` per
    tenant: immediately after a host dies its slice is still counted live
    (until TTL), so survivors cannot absorb it early — the ceiling dips,
    never overshoots — and after the sweep reclaims it the next renewals
    converge back to full coverage within one renew period.
    """

    def __init__(self, ceilings: dict, *, ttl_s: float = 0.5):
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.ttl_s = float(ttl_s)
        self.ceilings = dict(ceilings)
        self._lock = named_lock(
            "serve.fleet.leases.LeaseCoordinator._lock"
        )
        self._grants: dict = {t: {} for t in self.ceilings}
        self._members: frozenset = frozenset()
        self._epoch = 0
        self._reclaims = 0

    # -- internals (lock held) ----------------------------------------------

    def _sweep_locked(self, now: float) -> None:
        expired = False
        for row in self._grants.values():
            for host, lease in list(row.items()):
                if now >= lease.expires_at():
                    del row[host]
                    self._reclaims += 1
                    expired = True
        if expired:
            self._epoch += 1

    def _grant_locked(
        self, tenant: str, host: str, fraction: float, now: float
    ) -> Lease:
        row = self._grants[tenant]
        others = sum(
            lease.fraction for h, lease in row.items() if h != host
        )
        if others + fraction > 1.0 + _EPS:
            raise OverCommitError(
                f"granting {fraction:.4f} of tenant {tenant!r} to host "
                f"{host!r} would commit {others + fraction:.4f} > 1.0 of "
                "the global ceiling — the grant-table invariant every "
                "admission bound rests on"
            )
        lease = Lease(
            tenant=tenant, host=host, fraction=fraction,
            epoch=self._epoch, granted_at=now, ttl_s=self.ttl_s,
        )
        row[host] = lease
        return lease

    def _renew_locked(self, host: str, now: float) -> dict:
        self._sweep_locked(now)
        live = {
            h for row in self._grants.values() for h in row
        } | {host}
        if frozenset(live) != self._members:
            self._members = frozenset(live)
            self._epoch += 1
        target = 1.0 / max(len(live), 1)
        out = {}
        for tenant in self._grants:
            row = self._grants[tenant]
            others = sum(
                lease.fraction
                for h, lease in row.items()
                if h != host
            )
            fraction = min(target, max(0.0, 1.0 - others))
            out[tenant] = self._grant_locked(tenant, host, fraction, now)
        return out

    # -- protocol surface ----------------------------------------------------

    def acquire(self, host: str) -> dict:
        """Grant/renew ``host``'s slice of every tenant: the one RPC of the
        protocol. Returns ``{tenant: Lease}``."""
        now = time.monotonic()
        with self._lock:
            return self._renew_locked(host, now)

    def grant(self, tenant: str, host: str, fraction: float) -> Lease:
        """Low-level single grant, invariant enforced — the entry the
        over-commit falsification test drives directly."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            return self._grant_locked(tenant, host, fraction, now)

    # -- ops surface ---------------------------------------------------------

    def granted_fraction(self, tenant: str) -> float:
        """Sum of live (unexpired) fractions for ``tenant`` — ≤ 1.0 by the
        grant invariant."""
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            return sum(
                lease.fraction
                for lease in self._grants.get(tenant, {}).values()
            )

    def stats(self) -> dict:
        with self._lock:
            snap = {
                "lease_epoch": self._epoch,
                "lease_reclaims": self._reclaims,
            }
        return snap


class LeaseClient:
    """One host's lease cache + renew loop.

    ``alive_fn`` ties renewal to the host's liveness (an EngineProcess's
    ``alive``): a kill -9'd host stops renewing exactly like a lost real
    host would, and its slice ages out at the coordinator. ``partition``
    simulates a coordinator partition deterministically (the
    ``fleet-splitbrain`` scenario's handle); the ``fleet.partition`` chaos
    point lets graftsiege arm the same failure through the DSL_CHAOS gate.
    """

    def __init__(
        self,
        coordinator: LeaseCoordinator,
        host: str,
        *,
        renew_interval_s: float | None = None,
        alive_fn=None,
    ):
        self.host = host
        self._coordinator = coordinator
        self._alive_fn = alive_fn
        self.renew_interval_s = (
            renew_interval_s
            if renew_interval_s is not None
            else coordinator.ttl_s / 4.0
        )
        self._lock = named_lock("serve.fleet.leases.LeaseClient._lock")
        self._leases: dict = {}
        self._partitioned = False
        self._stop = threading.Event()
        self._thread = None

    def start(self) -> "LeaseClient":
        """Synchronous first renew (a host serves nothing before it holds
        leases), then the background renew loop."""
        self.renew_once()
        self._thread = threading.Thread(
            target=self._renew_loop, daemon=True,
            name=f"lease-renew-{self.host}",
        )
        self._thread.start()
        return self

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _renew_loop(self) -> None:
        while not self._stop.wait(self.renew_interval_s):
            try:
                self.renew_once()
            except OverCommitError:
                # A refused grant is the coordinator protecting the
                # invariant; the host simply keeps aging toward shed-all.
                continue

    def renew_once(self) -> bool:
        """One renew attempt; False when skipped (partitioned/dead host).
        The coordinator call happens OUTSIDE the client lock — the lease
        snapshot swap is the only guarded write."""
        maybe_inject("fleet.partition")
        with self._lock:
            partitioned = self._partitioned
        if partitioned:
            return False
        if self._alive_fn is not None and not self._alive_fn():
            return False
        leases = self._coordinator.acquire(self.host)
        with self._lock:
            self._leases = leases
        return True

    def partition(self, on: bool = True) -> None:
        """Cut (or heal) this host's path to the coordinator. While cut,
        existing leases age out at USE_FRACTION·TTL and the host sheds —
        the bounded-staleness under-admission the splitbrain drill pins."""
        with self._lock:
            self._partitioned = on

    def fraction(self, tenant: str) -> float:
        """The fraction of ``tenant``'s global ceiling this host may use
        RIGHT NOW: 0.0 once the lease passes its usable window (strictly
        before the coordinator's reclaim point)."""
        now = time.monotonic()
        with self._lock:
            lease = self._leases.get(tenant)
        if lease is None or now >= lease.usable_until():
            return 0.0
        return lease.fraction

    def lease_epoch(self) -> int:
        with self._lock:
            leases = dict(self._leases)
        return max((l.epoch for l in leases.values()), default=0)


@dataclass
class _LeasedBucket:
    tokens: float
    refilled_at: float
    inflight: int = 0
    ok: int = 0
    shed: int = 0
    consecutive_sheds: int = 0


class LeasedAdmission:
    """Host-side admission front door over leased slices.

    Per-tenant token bucket at ``global_rate × fraction`` with depth
    ``global_depth × fraction`` (no floor: a sliver too small to hold one
    request admits nothing — under-admission is always the safe direction),
    plus an in-flight quota of ``floor(global_quota × fraction)``. The
    aggregate bound across hosts: since live fractions sum ≤ 1.0 at every
    instant, total admits over any window W ≤ ceiling·W + global burst —
    the inequality the fleet scenarios sample and assert.
    """

    def __init__(self, client: LeaseClient, policies):
        self._client = client
        self._policies = {p.name: p for p in policies}
        self._lock = named_lock(
            "serve.fleet.leases.LeasedAdmission._lock"
        )
        self._buckets: dict = {}
        # (monotonic timestamp, items) per admit — the scenario harness's
        # over-admission evidence; bounded so a soak can't grow it.
        self._admits: deque = deque(maxlen=262144)

    def policy(self, tenant: str) -> TenantPolicy:
        pol = self._policies.get(tenant)
        if pol is None:
            pol = TenantPolicy(tenant)
            self._policies[tenant] = pol
        return pol

    def admit(
        self,
        tenant: str,
        *,
        items: int = 1,
        deadline_s: float | None = None,
    ) -> AdmissionTicket:
        pol = self.policy(tenant)
        fraction = self._client.fraction(tenant)
        now = time.monotonic()
        with self._lock:
            st = self._buckets.get(tenant)
            if st is None:
                # Start full at the CURRENT scaled depth (single-host
                # semantics); fleet-safe because scaled depths sum ≤ the
                # global depth while live fractions sum ≤ 1.0.
                depth0 = (
                    pol.bucket_depth() * fraction if pol.rate > 0 else 0.0
                )
                st = _LeasedBucket(tokens=depth0, refilled_at=now)
                self._buckets[tenant] = st
            if pol.rate > 0 or pol.max_inflight:
                if fraction <= 0.0:
                    # No usable lease: expired, partitioned, or never
                    # granted — shed-early, the bounded-staleness contract.
                    raise self._shed(
                        st, tenant, "lease",
                        self._client.renew_interval_s, deadline_s,
                    )
            if pol.rate > 0:
                rate = pol.rate * fraction
                depth = pol.bucket_depth() * fraction
                st.tokens = min(
                    depth,
                    st.tokens + max(0.0, now - st.refilled_at) * rate,
                )
                st.refilled_at = now
                if st.tokens < items:
                    raise self._shed(
                        st, tenant, "rate",
                        (items - st.tokens) / max(rate, 1e-9), deadline_s,
                    )
            if pol.max_inflight:
                quota = int(pol.max_inflight * fraction)
                if st.inflight + items > quota:
                    raise self._shed(
                        st, tenant, "quota", _BACKOFF_BASE_S, deadline_s,
                    )
            if pol.rate > 0:
                st.tokens -= items
                # Only rate-limited admits join the evidence trail: the
                # over-admission sweep proves the summed RATE ceiling, and
                # unlimited tenants are outside it by policy.
                self._admits.append((now, items))
            st.inflight += items
            st.ok += 1
            st.consecutive_sheds = 0
        return AdmissionTicket(self, tenant, items)

    def _shed(
        self, st: _LeasedBucket, tenant: str, reason: str,
        base_s: float, deadline_s: float | None,
    ) -> ShedError:
        """Build the typed rejection (lock already held by admit). Same
        exponential + deterministically jittered backoff guidance as the
        single-host controller, so fleet clients never retry-storm."""
        st.shed += 1
        st.consecutive_sheds += 1
        doublings = min(st.consecutive_sheds - 1, _BACKOFF_MAX_DOUBLINGS)
        backoff = min(base_s * (2.0 ** doublings), _BACKOFF_CAP_S)
        frac = ((st.shed * 2654435761 + hash(tenant)) % 997) / 997.0
        retry_after = backoff * (0.75 + 0.5 * frac)
        retriable = deadline_s is None or retry_after <= deadline_s
        return ShedError(tenant, reason, retry_after, retriable=retriable)

    def _release(
        self, name: str, items: int, latency_s: float, *, ok: bool
    ) -> None:
        del latency_s, ok  # latency accounting lives with the router
        with self._lock:
            st = self._buckets.get(name)
            if st is not None:
                st.inflight = max(0, st.inflight - items)

    def admit_times(self) -> list:
        """Snapshot of (timestamp, items) admits — the over-admission
        evidence trail the scenarios aggregate across hosts."""
        with self._lock:
            return list(self._admits)

    def counts(self) -> dict:
        """Per-tenant {ok, shed} rows (merged into the scenario record's
        per_tenant map by the harness, not a schema surface itself)."""
        with self._lock:
            return {
                t: {"ok": st.ok, "shed": st.shed}
                for t, st in sorted(self._buckets.items())
            }
