"""graftfleet: the multi-host serving fleet tier.

Three pillars over the single-host serving stack (see SERVING.md "Fleet
tier"): bounded-staleness distributed admission via token leases
(:mod:`.leases`), a health-driven replica-group front door with
session-affinity pinning (:mod:`.router`), and coordinated zero-downtime
swap waves (:mod:`.waves`) — drilled end to end by the fleet scenarios
(:mod:`.scenarios`).
"""

from distributed_sigmoid_loss_tpu.serve.fleet.leases import (
    USE_FRACTION,
    Lease,
    LeaseClient,
    LeaseCoordinator,
    LeasedAdmission,
    OverCommitError,
)
from distributed_sigmoid_loss_tpu.serve.fleet.router import (
    FleetRouter,
    NoReplicaError,
    ReplicaHandle,
)
from distributed_sigmoid_loss_tpu.serve.fleet.scenarios import (
    FLEET_SCENARIOS,
    Fleet,
    FleetHost,
    build_fleet,
    run_fleet_scenario,
)
from distributed_sigmoid_loss_tpu.serve.fleet.waves import WaveController

__all__ = [
    "FLEET_SCENARIOS",
    "Fleet",
    "FleetHost",
    "FleetRouter",
    "Lease",
    "LeaseClient",
    "LeaseCoordinator",
    "LeasedAdmission",
    "NoReplicaError",
    "OverCommitError",
    "ReplicaHandle",
    "USE_FRACTION",
    "WaveController",
    "build_fleet",
    "run_fleet_scenario",
]
