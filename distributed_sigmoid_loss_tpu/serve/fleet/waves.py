"""graftfleet swap waves: SwapController generalized to coordinated
version fan-out across replicas.

A single-host :class:`~..swap.SwapController` publishes a new version with
zero downtime on ONE engine. Across a fleet the hard part is the window in
which replicas disagree about the current version: without coordination a
session could bounce between versions mid-conversation (embedding-space
incompatibility presented as "results got worse then better then worse").
The wave controller imposes the ordering that, combined with the router's
session-affinity pinning, makes that impossible:

1. waves are serialized (the controller lock — at most one wave in flight,
   the single-host swap-storm contract lifted to the fleet);
2. replicas swap in declared (wave) order, one at a time: **drain** (router
   stops new traffic; the replica's ``/healthz`` shows
   ``reasons=["swap_in_flight"]`` so the router can tell this drain from
   overload) → **wait idle** (zero in-flight — no request ever spans the
   version flip) → **swap** (the replica's own swap path: for a real
   engine, ``swap_params`` — zero recompiles, ``compile_count`` flat) →
   **undrain**;
3. sessions pinned to the old version keep landing on not-yet-swapped
   replicas; sessions created after a replica publishes the new version pin
   to it; once the last replica swaps, old-version sessions re-pin — only
   upward, only while idle (router invariant). At no instant do two
   versions serve one session.

A replica that is LOST when its turn comes is skipped (it picks the
version up on restart/revive — the rolling wave must not wedge behind a
dead host); the skip is visible in the wave result.
"""

from __future__ import annotations

import time

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock
from distributed_sigmoid_loss_tpu.utils.logging import LatencyWindow

__all__ = ["WaveController"]


class WaveController:
    """Wave-ordered version fan-out over a :class:`~.router.FleetRouter`.

    ``drain_timeout_s`` bounds the per-replica wait-idle barrier — a wedged
    replica fails the wave with a ``TimeoutError`` instead of wedging the
    controller forever.
    """

    def __init__(self, router, *, drain_timeout_s: float = 10.0):
        self.router = router
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = named_lock("serve.fleet.waves.WaveController._lock")
        self._wave_id = 0
        self._window = LatencyWindow(256)

    def _begin_wave_locked(self) -> int:
        self._wave_id += 1
        return self._wave_id

    def run_wave(self) -> dict:
        """Run one coordinated swap wave; returns ``{"wave_id", "swapped",
        "skipped", "duration_s"}``. Replica swap callables come from each
        :class:`~.router.ReplicaHandle`'s ``swap_fn`` (no-arg: the host
        closure knows what to publish — the double-buffered build is the
        host's job, exactly as in the single-host SwapController)."""
        t0 = time.monotonic()
        with self._lock:
            wave = self._begin_wave_locked()
            swapped, skipped = self._fan_out()
        duration = time.monotonic() - t0
        self._window.record(duration)
        return {
            "wave_id": wave,
            "swapped": swapped,
            "skipped": skipped,
            "duration_s": duration,
        }

    def _fan_out(self) -> tuple:
        """One replica at a time, wave order (controller lock held by
        run_wave — the lock IS the one-wave-at-a-time contract; the drain
        barrier polls via router.wait_idle, which sleeps without holding
        any router lock)."""
        swapped, skipped = [], []
        for replica in self.router.handles():
            status, _reasons = self.router._assess(replica)
            if status == "lost":
                skipped.append(replica.name)
                continue
            self.router.drain(replica.name)
            try:
                self.router.wait_idle(
                    replica.name, timeout_s=self.drain_timeout_s
                )
                if replica.swap_fn is not None:
                    replica.swap_fn()
                swapped.append(replica.name)
            finally:
                self.router.undrain(replica.name)
        return swapped, skipped

    def stats(self) -> dict:
        with self._lock:
            snap = {"wave_id": self._wave_id}
        return snap
