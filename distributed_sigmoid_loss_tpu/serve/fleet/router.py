"""graftfleet router: one logical front door over N serving replicas.

Health-driven routing with the failure vocabulary the single-host stack
already speaks:

- **healthy** replicas share traffic by smooth weighted round-robin
  (deficit credits: each pick adds every candidate's weight to its credit,
  the max-credit candidate wins and pays the round's total — deterministic,
  no RNG in the routing path).
- **degraded** replicas are kept or drained by CAUSE, which is why
  ``/healthz`` grew the structured ``reasons`` list: ``"swap_in_flight"``
  means the wave controller is draining the replica for a version swap (no
  new traffic), while ``"shedding"`` means overloaded-but-serving — pulling
  an overloaded replica out of rotation would concentrate load on its
  siblings and collapse the fleet, so it stays routable.
- **lost** replicas (health probe raised, or a call surfaced
  :class:`~..siege.HostLostError`) are marked and the request retries on a
  sibling — the typed-error + reroute contract; when no sibling remains the
  caller gets a typed :class:`NoReplicaError`, never a hang or a silent
  drop.

Session affinity: a session is pinned to the index VERSION that served its
first request. While pinned, requests route only to replicas publishing
that version (``affinity_hits`` counts them); when no routable replica
publishes it anymore (a swap wave retired it) the session re-pins — only
upward (monotone), and only while it has zero requests in flight, which
together give the wave invariant: no two versions ever serve one session
concurrently.
"""

from __future__ import annotations

import time

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock
from distributed_sigmoid_loss_tpu.serve.siege import HostLostError

__all__ = [
    "FleetRouter",
    "NoReplicaError",
    "ReplicaHandle",
]


class NoReplicaError(RuntimeError):
    """No routable replica can serve the request (all lost/draining, or a
    pinned session's version vanished mid-flight). Typed — clients back off
    and retry; the scenario harness counts it as a typed rejection, never a
    silent drop."""


class ReplicaHandle:
    """One replica as the router sees it: a submit callable plus optional
    health/version/swap probes (all host-local calls on one machine; the
    transport is not the contract)."""

    def __init__(
        self,
        name: str,
        call,
        *,
        health_fn=None,
        version_fn=None,
        swap_fn=None,
        weight: float = 1.0,
    ):
        if weight <= 0:
            raise ValueError(f"replica {name!r}: weight must be > 0")
        self.name = name
        self.call = call
        self.health_fn = health_fn
        self.version_fn = version_fn
        self.swap_fn = swap_fn
        self.weight = float(weight)

    def version(self) -> int:
        return int(self.version_fn()) if self.version_fn is not None else 0


class _Session:
    __slots__ = ("version", "inflight")

    def __init__(self):
        self.version = None
        self.inflight = 0


class FleetRouter:
    """The fleet front door (see module docstring)."""

    def __init__(self, replicas, *, drain_poll_s: float = 0.001):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self._replicas = {r.name: r for r in replicas}
        self._order = names
        self._drain_poll_s = drain_poll_s
        self._lock = named_lock("serve.fleet.router.FleetRouter._lock")
        self._credit = {n: 0.0 for n in names}
        self._inflight = {n: 0 for n in names}
        self._lost: set = set()
        self._draining: set = set()
        self._sessions: dict = {}
        self._reroutes = 0
        self._affinity_hits = 0
        self._routed = 0

    # -- health & membership -------------------------------------------------

    def handles(self) -> list:
        """Replicas in declared order — the wave order."""
        return [self._replicas[n] for n in self._order]

    def _assess(self, replica) -> tuple:
        """(status, reasons) from the replica's health probe; a probe that
        raises IS the lost signal (no probe = assumed ok)."""
        if replica.health_fn is None:
            return ("ok", [])
        try:
            payload = replica.health_fn()
        except Exception:  # noqa: BLE001 — any probe failure means lost
            return ("lost", ["probe_failed"])
        status = str(payload.get("status", "ok"))
        reasons = [str(r) for r in payload.get("reasons", ())]
        return (status, reasons)

    def drain(self, name: str) -> None:
        """Stop routing NEW requests to ``name`` (in-flight ones finish) —
        the wave controller's pre-swap step."""
        with self._lock:
            self._draining.add(name)

    def undrain(self, name: str) -> None:
        with self._lock:
            self._draining.discard(name)

    def mark_lost(self, name: str) -> None:
        with self._lock:
            self._lost.add(name)

    def revive(self, name: str) -> None:
        """Bring a restarted replica back into rotation."""
        with self._lock:
            self._lost.discard(name)

    def inflight(self, name: str) -> int:
        with self._lock:
            return self._inflight[name]

    def wait_idle(self, name: str, *, timeout_s: float = 10.0) -> None:
        """Block (poll, no lock held) until ``name`` has zero in-flight
        requests — the drain barrier a swap waits behind."""
        deadline = time.monotonic() + timeout_s
        while self.inflight(name) > 0:
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"replica {name!r} still has "
                    f"{self.inflight(name)} in-flight after {timeout_s}s"
                )
            time.sleep(self._drain_poll_s)

    # -- routing -------------------------------------------------------------

    def _pick(self, session_id, statuses, versions, tried) -> tuple:
        """(replica, version, session) under the router lock; raises
        NoReplicaError when nothing is routable. Increments in-flight
        counters for the pick — the caller MUST route exactly one call and
        then _finish/_fail it."""
        with self._lock:
            routable = [
                n for n in self._order
                if n not in tried
                and n not in self._lost
                and n not in self._draining
                and statuses[n][0] != "lost"
                and "swap_in_flight" not in statuses[n][1]
            ]
            if not routable:
                raise NoReplicaError(
                    f"no routable replica (lost={sorted(self._lost)}, "
                    f"draining={sorted(self._draining)}, "
                    f"tried={sorted(tried)})"
                )
            sess = None
            affinity = False
            candidates = routable
            if session_id is not None:
                sess = self._sessions.setdefault(session_id, _Session())
                if sess.version is not None:
                    on_pin = [
                        n for n in routable if versions[n] == sess.version
                    ]
                    if on_pin:
                        candidates = on_pin
                        affinity = True
                    else:
                        # The pinned version retired. Re-pin is legal only
                        # with nothing in flight (else two versions could
                        # serve the session concurrently) and only upward
                        # (versions monotone per session).
                        if sess.inflight > 0:
                            raise NoReplicaError(
                                f"session {session_id!r} pinned to retired "
                                f"version {sess.version} with "
                                f"{sess.inflight} in flight"
                            )
                        top = max(versions[n] for n in routable)
                        if top < sess.version:
                            raise NoReplicaError(
                                f"session {session_id!r} cannot re-pin "
                                f"downward ({sess.version} -> {top})"
                            )
                        sess.version = top
                        candidates = [
                            n for n in routable if versions[n] == top
                        ]
                else:
                    top = max(versions[n] for n in routable)
                    sess.version = top
                    candidates = [
                        n for n in routable if versions[n] == top
                    ]
            # Smooth weighted round-robin over the candidate set.
            total = 0.0
            for n in candidates:
                self._credit[n] += self._replicas[n].weight
                total += self._replicas[n].weight
            chosen = max(candidates, key=lambda n: (self._credit[n], n))
            self._credit[chosen] -= total
            self._inflight[chosen] += 1
            self._routed += 1
            if affinity:
                self._affinity_hits += 1
            if sess is not None:
                sess.inflight += 1
            return (self._replicas[chosen], versions[chosen], sess)

    def _finish(self, name: str, sess) -> None:
        with self._lock:
            self._inflight[name] = max(0, self._inflight[name] - 1)
            if sess is not None:
                sess.inflight = max(0, sess.inflight - 1)

    def _note_lost(self, name: str, sess) -> None:
        with self._lock:
            self._lost.add(name)
            self._reroutes += 1
            self._inflight[name] = max(0, self._inflight[name] - 1)
            if sess is not None:
                sess.inflight = max(0, sess.inflight - 1)

    def route(self, payload, *, session: str | None = None):
        """Route one request: pick → call → (on HostLostError) mark lost
        and retry on a sibling. Returns ``(result, replica_name, version)``.
        Raises typed errors only: the replica's own (ShedError & co. pass
        through untouched), :class:`~..siege.HostLostError` via
        :class:`NoReplicaError` once no sibling remains."""
        statuses = {
            n: self._assess(self._replicas[n]) for n in self._order
        }
        versions = {n: self._replicas[n].version() for n in self._order}
        tried: set = set()
        while True:
            replica, version, sess = self._pick(
                session, statuses, versions, tried
            )
            try:
                result = replica.call(payload)
            except HostLostError:
                self._note_lost(replica.name, sess)
                tried.add(replica.name)
                continue
            except BaseException:
                self._finish(replica.name, sess)
                raise
            self._finish(replica.name, sess)
            return (result, replica.name, version)

    # -- ops surface ---------------------------------------------------------

    def stats(self) -> dict:
        healthy = 0
        for n in self._order:
            status, reasons = self._assess(self._replicas[n])
            with self._lock:
                lost = n in self._lost
            if not lost and status != "lost":
                healthy += 1
        with self._lock:
            snap = {
                "replica_count": len(self._order),
                "healthy_replicas": healthy,
                "reroutes": self._reroutes,
                "affinity_hits": self._affinity_hits,
            }
        return snap
