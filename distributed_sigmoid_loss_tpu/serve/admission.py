"""Per-tenant SLO-aware admission control: rate limits, quotas, and
priority-ordered load shedding in front of the MicroBatcher.

The bounded queue (PR 1) gave the serving stack backpressure, but it is
tenant-blind: under overload every caller degrades equally, so one
over-quota tenant's burst blows the p99 of every in-SLO tenant behind it.
This module is the missing front door. Every request is classified by
tenant and admitted through three checks, cheapest first:

1. **token bucket** — per-tenant sustained rate + burst allowance; the
   classic leaky-bucket refill arithmetic, no background thread.
2. **bounded quota** — per-tenant in-flight cap (submitted but not yet
   released), so a slow-consuming tenant (slowloris) saturates its OWN
   allowance and nothing else.
3. **priority-tiered capacity** — the global in-flight budget is tiered by
   tenant priority: rank r of K distinct priorities may fill
   ``capacity * r / K`` slots, the top rank the whole budget. Under
   overload low-priority traffic hits its (lower) watermark first — shed
   low first, never the other way around.

A rejected request raises :class:`ShedError` — typed, DISTINCT from the
batcher's ``QueueFullError`` (shed = policy said no, queue-full = the
whole stack is saturated) — carrying ``retry_after_s`` backoff guidance:
exponential in the tenant's consecutive sheds, deterministically jittered
(so a thundering herd decorrelates instead of re-synchronizing), capped,
and deadline-aware — ``retriable=False`` when the suggested wait would
blow the caller's remaining deadline, which is the signal to fail over
instead of retry-storming.

``stats()`` is schema-registered (obs/metrics_schema.py SERVE registry)
and rides ``EmbeddingService.stats()`` / the ``/metrics`` exporter; the
``per_tenant`` map flattens with a ``tenant=`` label (the PR 9 labels
hook, now populated from inside one exporter too).
"""

from __future__ import annotations

import math
import threading
import time
from collections import Counter, deque
from dataclasses import dataclass, field

from distributed_sigmoid_loss_tpu.utils.logging import LatencyWindow

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = [
    "AdmissionController",
    "AdmissionTicket",
    "ShedError",
    "TenantPolicy",
    "parse_tenant_spec",
]

DEFAULT_TENANT = "default"

# Backoff guidance bounds: the first shed suggests ~base, consecutive sheds
# double it (capped) — a well-behaved client backs off instead of storming.
_BACKOFF_BASE_S = 0.05
_BACKOFF_CAP_S = 30.0
_BACKOFF_MAX_DOUBLINGS = 8


@dataclass(frozen=True)
class TenantPolicy:
    """One tenant's admission contract.

    ``rate`` — sustained admits/s through the token bucket (0 = unlimited).
    ``burst`` — bucket depth (0 = auto: one second of ``rate``, min 1).
    ``max_inflight`` — bounded quota: requests admitted but not yet released
    (0 = unlimited). ``priority`` — higher sheds LATER under overload.
    ``slo_ms`` — advisory latency target; violations are counted in stats
    (the per-tenant p99-vs-SLO signal), never enforced.
    """

    name: str
    priority: int = 1
    rate: float = 0.0
    burst: int = 0
    max_inflight: int = 0
    slo_ms: float | None = None

    def bucket_depth(self) -> float:
        if self.rate <= 0:
            return math.inf
        return float(self.burst) if self.burst > 0 else max(self.rate, 1.0)


class ShedError(RuntimeError):
    """Admission rejected the request (policy, not saturation).

    ``reason`` ∈ {"rate", "quota", "overload"}; ``retry_after_s`` is the
    backoff guidance (exponential + jittered, see module docstring) and
    ``retriable`` is False when that wait would exceed the caller's stated
    deadline — retrying is then guaranteed-wasted load.
    """

    def __init__(
        self,
        tenant: str,
        reason: str,
        retry_after_s: float,
        *,
        retriable: bool = True,
    ):
        self.tenant = tenant
        self.reason = reason
        self.retry_after_s = round(float(retry_after_s), 4)
        self.retriable = retriable
        advice = (
            f"retry after {self.retry_after_s}s"
            if retriable
            else "do not retry (guidance exceeds your deadline)"
        )
        super().__init__(
            f"tenant {tenant!r} shed ({reason}); {advice}"
        )


@dataclass
class _TenantState:
    tokens: float = math.inf
    refilled_at: float = field(default_factory=time.monotonic)
    inflight: int = 0
    admitted: int = 0
    shed: Counter = field(default_factory=Counter)
    consecutive_sheds: int = 0
    slo_violations: int = 0
    latency: LatencyWindow = field(default_factory=lambda: LatencyWindow(4096))


class AdmissionTicket:
    """One admitted request's handle: ``release()`` returns the in-flight
    slots and records the observed latency (idempotent; usable as a
    context manager so an exception path can never leak quota)."""

    def __init__(self, controller: "AdmissionController", tenant: str, items: int):
        self._controller = controller
        self.tenant = tenant
        self.items = items
        self._t0 = time.monotonic()
        self._released = False

    def release(self, *, ok: bool = True) -> None:
        if self._released:
            return
        self._released = True
        self._controller._release(
            self.tenant, self.items, time.monotonic() - self._t0, ok=ok
        )

    def __enter__(self):
        return self

    def __exit__(self, exc_type, *exc):
        self.release(ok=exc_type is None)


class AdmissionController:
    """Thread-safe per-tenant admission front end (see module docstring).

    ``capacity`` is the global in-flight budget the priority tiers split;
    size it to what the engine sustains inside the SLO (≈ largest batch
    bucket × acceptable queue depth). Unknown tenants share
    ``default_policy`` (each still gets its own bucket/quota state).
    """

    def __init__(
        self,
        policies=(),
        *,
        capacity: int = 64,
        default_policy: TenantPolicy | None = None,
        shed_window_s: float = 5.0,
    ):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.shed_window_s = float(shed_window_s)
        self._policies = {p.name: p for p in policies}
        self._default = default_policy or TenantPolicy(DEFAULT_TENANT)
        self._lock = named_lock("serve.admission.AdmissionController._lock")
        self._states: dict[str, _TenantState] = {}
        self._total_inflight = 0
        self._decisions: deque = deque(maxlen=65536)  # (ts, was_shed)
        # Priority rank table over the declared policy set (+ default):
        # rank r of K distinct priorities owns capacity*r/K slots.
        self._rebuild_thresholds()

    # -- policy surface ------------------------------------------------------

    def _rebuild_thresholds(self) -> None:
        prios = sorted({p.priority for p in self._policies.values()}
                       | {self._default.priority})
        k = len(prios)
        self._thresholds = {
            p: max(1, math.ceil(self.capacity * (i + 1) / k))
            for i, p in enumerate(prios)
        }

    def policy(self, tenant: str | None) -> TenantPolicy:
        name = tenant or DEFAULT_TENANT
        pol = self._policies.get(name)
        if pol is None:
            pol = (
                self._default
                if name == self._default.name
                else TenantPolicy(
                    name,
                    priority=self._default.priority,
                    rate=self._default.rate,
                    burst=self._default.burst,
                    max_inflight=self._default.max_inflight,
                    slo_ms=self._default.slo_ms,
                )
            )
        return pol

    def _state(self, name: str, pol: TenantPolicy) -> _TenantState:
        st = self._states.get(name)
        if st is None:
            st = _TenantState(tokens=pol.bucket_depth())
            self._states[name] = st
        return st

    # -- admission -----------------------------------------------------------

    def admit(
        self,
        tenant: str | None = None,
        *,
        items: int = 1,
        deadline_s: float | None = None,
    ) -> AdmissionTicket:
        """Admit ``items`` request slots for ``tenant`` or raise
        :class:`ShedError`. ``deadline_s`` = the caller's remaining budget,
        used only to mark hopeless retry guidance ``retriable=False``."""
        pol = self.policy(tenant)
        name = pol.name
        now = time.monotonic()
        with self._lock:
            st = self._state(name, pol)
            # 1) token bucket.
            if pol.rate > 0:
                depth = pol.bucket_depth()
                # max(0, ...): a freshly created state stamps refilled_at
                # AFTER `now` was read, and a negative delta must not drain
                # the bucket below its starting depth.
                st.tokens = min(
                    depth,
                    st.tokens + max(0.0, now - st.refilled_at) * pol.rate,
                )
                st.refilled_at = now
                if st.tokens < items:
                    raise self._shed(
                        st, name, "rate",
                        (items - st.tokens) / pol.rate, deadline_s, now,
                    )
            # 2) bounded per-tenant quota.
            if pol.max_inflight and st.inflight + items > pol.max_inflight:
                p50 = st.latency.percentiles_ms((50,))["p50_ms"] / 1000.0
                raise self._shed(
                    st, name, "quota", max(p50, _BACKOFF_BASE_S),
                    deadline_s, now,
                )
            # 3) priority-tiered global capacity: shed low priority first.
            threshold = self._thresholds.get(
                pol.priority,
                max(1, math.ceil(
                    self.capacity
                    * self._rank_of(pol.priority)
                    / max(len(self._thresholds), 1)
                )),
            )
            if self._total_inflight + items > threshold:
                raise self._shed(
                    st, name, "overload", _BACKOFF_BASE_S, deadline_s, now
                )
            if pol.rate > 0:
                st.tokens -= items
            st.inflight += items
            st.admitted += 1
            st.consecutive_sheds = 0
            self._total_inflight += items
            self._decisions.append((now, False))
        return AdmissionTicket(self, name, items)

    def _rank_of(self, priority: int) -> int:
        below = sum(1 for p in self._thresholds if p <= priority)
        return max(below, 1)

    def _shed(
        self, st: _TenantState, name: str, reason: str,
        base_s: float, deadline_s: float | None, now: float,
    ) -> ShedError:
        """Build the typed rejection (caller raises it; lock already held)."""
        st.shed[reason] += 1
        st.consecutive_sheds += 1
        self._decisions.append((now, True))
        doublings = min(st.consecutive_sheds - 1, _BACKOFF_MAX_DOUBLINGS)
        backoff = min(base_s * (2.0 ** doublings), _BACKOFF_CAP_S)
        # Deterministic per-tenant jitter in [0.75, 1.25): Knuth hash of the
        # tenant's shed count — clients backing off together spread out
        # instead of re-arriving in the same wave (no retry storm).
        total_shed = sum(st.shed.values())
        frac = ((total_shed * 2654435761 + hash(name)) % 997) / 997.0
        retry_after = backoff * (0.75 + 0.5 * frac)
        retriable = deadline_s is None or retry_after <= deadline_s
        return ShedError(name, reason, retry_after, retriable=retriable)

    def _release(
        self, name: str, items: int, latency_s: float, *, ok: bool
    ) -> None:
        pol = self.policy(name)
        with self._lock:
            st = self._states.get(name)
            if st is None:
                return
            st.inflight = max(0, st.inflight - items)
            self._total_inflight = max(0, self._total_inflight - items)
            if ok:
                st.latency.record(latency_s)
                if pol.slo_ms is not None and latency_s * 1000.0 > pol.slo_ms:
                    st.slo_violations += 1

    # -- ops surface ---------------------------------------------------------

    def recent_shed_rate(self, window_s: float | None = None) -> float:
        """Fraction of admission decisions in the trailing window that were
        sheds (0.0 when idle) — the ``/healthz`` degraded signal."""
        window = self.shed_window_s if window_s is None else window_s
        cutoff = time.monotonic() - window
        with self._lock:
            recent = [shed for ts, shed in self._decisions if ts >= cutoff]
        if not recent:
            return 0.0
        return sum(recent) / len(recent)

    def stats(self) -> dict:
        """Schema-registered snapshot: global budget + one row per tenant
        (flattened with a ``tenant=`` label by the /metrics exporter)."""
        with self._lock:
            names = sorted(self._states)
            total_inflight = self._total_inflight
            per_tenant = {}
            for name in names:
                st = self._states[name]
                pol = self.policy(name)
                shed = sum(st.shed.values())
                seen = st.admitted + shed
                per_tenant[name] = {
                    "priority": pol.priority,
                    "admitted": st.admitted,
                    "shed": shed,
                    "shed_rate": round(shed / seen, 4) if seen else 0.0,
                    "inflight": st.inflight,
                    "slo_ms": pol.slo_ms,
                    "slo_violations": st.slo_violations,
                    "latency_ms": st.latency.percentiles_ms((50, 95, 99)),
                }
        snap = {
            "capacity": self.capacity,
            "inflight": total_inflight,
            "shed_rate": round(self.recent_shed_rate(), 4),
            "per_tenant": per_tenant,
        }
        return snap


def parse_tenant_spec(spec: str) -> list[TenantPolicy]:
    """Parse the CLI tenant grammar into policies.

    ``"gold:prio=2,quota=16,slo=250;free:prio=1,rate=40,quota=4"`` —
    semicolon-separated tenants, each ``name:key=value,...`` with keys
    ``prio``/``priority``, ``rate`` (req/s, 0 = unlimited), ``burst``,
    ``quota`` (max in-flight, 0 = unlimited), ``slo`` (ms).
    """
    policies = []
    for chunk in filter(None, (c.strip() for c in spec.split(";"))):
        name, _, body = chunk.partition(":")
        name = name.strip()
        if not name:
            raise ValueError(f"tenant spec chunk {chunk!r} has no name")
        kw: dict = {}
        for pair in filter(None, (p.strip() for p in body.split(","))):
            key, sep, value = pair.partition("=")
            if not sep:
                raise ValueError(
                    f"tenant {name!r}: expected key=value, got {pair!r}"
                )
            key = key.strip().lower()
            try:
                num = float(value)
            except ValueError:
                raise ValueError(
                    f"tenant {name!r}: {key}={value!r} is not a number"
                ) from None
            if key in ("prio", "priority"):
                kw["priority"] = int(num)
            elif key == "rate":
                kw["rate"] = num
            elif key == "burst":
                kw["burst"] = int(num)
            elif key == "quota":
                kw["max_inflight"] = int(num)
            elif key == "slo":
                kw["slo_ms"] = num
            else:
                raise ValueError(
                    f"tenant {name!r}: unknown key {key!r} (use prio/rate/"
                    "burst/quota/slo)"
                )
        policies.append(TenantPolicy(name, **kw))
    if not policies:
        raise ValueError(f"empty tenant spec {spec!r}")
    return policies
