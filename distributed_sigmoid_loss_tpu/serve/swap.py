"""Zero-downtime hot swap: versioned weight + index-segment publication.

A training job keeps producing better checkpoints while the serving stack is
under live traffic; this module is the piece that moves them into production
without a restart, a dropped request, or a fresh XLA compile:

- **weights** — the bucketed engine's jitted encoders take the param pytree
  as an ARGUMENT, so ``InferenceEngine.swap_params`` replaces the tree (same
  treedef/shapes/dtypes, validated) and every warmed bucket's compiled
  program keeps serving: ``compile_count`` is asserted unchanged by the swap
  tests — the zero-recompile contract the bucketed engine was built for.
  New params typically come from ``train.restore_checkpoint`` or are served
  through a ``train.load_forward`` artifact engine — either way they are
  just a pytree by the time they reach the swap.
- **index segments** — ``RetrievalRouter.build`` constructs the new tier
  indexes DOUBLE-BUFFERED (the old version keeps answering during the
  build, which is the expensive part), then ``publish_built`` swaps one
  reference atomically. A search reads the current version once at entry
  and keeps it: in-flight requests finish on the version they started on,
  and the version each response observes is monotonically non-decreasing.

Ordering: segments are built first (old traffic unaffected), then params
and the version reference flip back-to-back — the window where new params
serve the old segments is two attribute assignments wide. Cross-request
consistency (an encode followed by a search landing on different versions)
is inherently eventual in any rolling deploy; PER-SEARCH consistency is
what the version object guarantees.
"""

from __future__ import annotations

import threading
import time

from distributed_sigmoid_loss_tpu.serve.engine import InferenceEngine
from distributed_sigmoid_loss_tpu.serve.service import RetrievalRouter
from distributed_sigmoid_loss_tpu.serve.siege import maybe_inject

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["SwapController"]


class SwapController:
    """Orchestrates one hot swap: build segments → swap params → publish.

    Swaps serialize on an internal lock (a second swap waits, never
    interleaves); the search path takes no lock at all. ``swap_count`` and
    swap-latency percentiles land in the router's :meth:`stats` (and from
    there in ``serve-bench`` records); each swap also emits a
    ``serve/swap`` span when the router carries a SpanRecorder.
    """

    def __init__(self, engine: InferenceEngine, router: RetrievalRouter):
        self.engine = engine
        self.router = router
        self._lock = named_lock("serve.swap.SwapController._lock")

    def swap(self, *, params=None, embeddings=None, ids=None) -> int:
        """Publish a new serving version; returns its version number.

        ``params`` — new weight pytree for the engine (None keeps the
        current weights). ``embeddings``/``ids`` — new corpus for fresh
        index segments (None re-publishes the current segments, a
        params-only swap). At least one of the two must be given.
        """
        if params is None and embeddings is None:
            raise ValueError("swap() needs params and/or embeddings")
        t0 = time.perf_counter()
        with self._lock:
            # Mark the swap mid-flight for the whole build+publish window:
            # /healthz reports degraded until end_swap (the swapstorm drill
            # asserts the window is visible, and that it always closes).
            self.router.begin_swap()
            try:
                # Chaos point: stretch/fault the swap window under load
                # (dead unless DSL_CHAOS=1 — serve/siege.py).
                maybe_inject("swap.storm")
                # Double-buffered build: the expensive half happens while the
                # old version keeps serving every request.
                built = (
                    self.router.build(embeddings, ids)
                    if embeddings is not None
                    else None
                )
                if params is not None:
                    self.engine.swap_params(params)  # zero recompiles
                version = self.router.publish_built(built)
            finally:
                self.router.end_swap()
        t1 = time.perf_counter()
        self.router.record_swap(t1 - t0)
        if self.router.spans is not None:
            self.router.spans.record("serve/swap", t0, t1)
        return version
