"""LRU embedding cache keyed by content hash.

Serving embeddings is read-heavy and repetitive — the same captions and the
same catalog images arrive over and over (the workload class where caching
dominates cost, ISSUE: arXiv:2512.05831). An embedding is a pure function of
the request content and the deployed params, so a content-addressed cache is
exact: key = blake2b of the raw token/pixel bytes (plus a caller-supplied
namespace for the model/params generation), value = the host-side embedding
row. Hits skip tokenize→pad→device→encode entirely.

Thread-safe: ``get``/``put`` run under one lock (the service's batcher workers
and client threads share the cache). Counters (hits/misses/evictions) feed the
service's ``stats()`` snapshot.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict

import numpy as np

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["EmbeddingCache", "content_key"]


def content_key(content, namespace: str = "") -> str:
    """Content hash of a request payload: str, bytes, or ndarray.

    Arrays hash their dtype+shape+bytes (two token rows of different length
    must never collide); ``namespace`` distinguishes model/params generations
    and modalities sharing one cache (e.g. ``"text"`` vs ``"image"``).
    """
    h = hashlib.blake2b(digest_size=16)
    if namespace:
        h.update(namespace.encode())
        h.update(b"\x00")
    if isinstance(content, str):
        content = content.encode()
    if isinstance(content, (bytes, bytearray)):
        h.update(b"raw")
        h.update(content)
    else:
        arr = np.ascontiguousarray(content)
        h.update(str(arr.dtype).encode())
        h.update(repr(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


class EmbeddingCache:
    """Bounded LRU mapping content keys → embedding rows (host numpy).

    ``capacity`` is an entry count, not bytes: embedding rows are fixed-size
    (embed_dim floats), so entries are the natural budget unit and the byte
    footprint is ``capacity * embed_dim * 4``.
    """

    def __init__(self, capacity: int = 65536):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._data: OrderedDict[str, np.ndarray] = OrderedDict()
        self._lock = named_lock("serve.cache.EmbeddingCache._lock")
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._data)

    def get(self, key: str) -> np.ndarray | None:
        with self._lock:
            value = self._data.get(key)
            if value is None:
                self.misses += 1
                return None
            self._data.move_to_end(key)
            self.hits += 1
            return value

    def put(self, key: str, value: np.ndarray) -> None:
        with self._lock:
            if key in self._data:
                # Refresh recency; the value is content-addressed so any
                # overwrite is byte-identical by construction.
                self._data.move_to_end(key)
                return
            self._data[key] = value
            if len(self._data) > self.capacity:
                self._data.popitem(last=False)
                self.evictions += 1

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {
                "size": len(self._data),
                "capacity": self.capacity,
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "hit_rate": round(self.hits / total, 4) if total else 0.0,
            }
