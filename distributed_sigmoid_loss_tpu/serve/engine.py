"""Jitted inference engine with fixed padded shape buckets — recompile-free
steady-state serving.

XLA compiles one program per input SHAPE. Online traffic has arbitrary batch
sizes and text lengths, so feeding raw request shapes to a jitted encoder
means a fresh multi-second compile whenever a new size first appears — the
classic serving latency cliff. The engine applies the same shape discipline
the training stack uses (static per-bucket shapes, one compiled program each):
every call is padded UP to a fixed (batch_bucket, len_bucket) grid point, run
through the jitted tower, and sliced back down. After :meth:`warmup` the
compile count is exactly ``bucket_space`` — the number of grid points — and
never grows again, no matter how many requests arrive.

Rows are independent through both towers (attention mixes within a row only),
so batch padding never perturbs real rows. Text LENGTH padding uses token id 0
up to the bucket — identical to the training tokenizer's padding to
``context_length`` — so the default single len-bucket (= context_length)
reproduces training-time embeddings bit-for-bit; extra shorter buckets are an
opt-in latency/recall trade for models trained with length buckets.

Optionally shards the padded batch over an existing ``parallel.mesh`` mesh
(``mesh=``): the batch axis is placed on ``dp`` and XLA partitions the tower
forward — the same data-parallel layout eval uses. Bucket sizes must then
divide the dp axis so every device holds whole rows.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis
from distributed_sigmoid_loss_tpu.serve.siege import maybe_inject

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["InferenceEngine"]


def _validated_buckets(buckets: Sequence[int], what: str) -> tuple[int, ...]:
    out = tuple(sorted(set(int(b) for b in buckets)))
    if not out or out[0] < 1:
        raise ValueError(f"{what} must be positive, got {buckets!r}")
    return out


class InferenceEngine:
    """Bucketed, jitted two-tower encoder: ``encode_image`` / ``encode_text``.

    ``encode_image_fn(params, images)`` / ``encode_text_fn(params, tokens)``
    are pure functions returning L2-normalized embedding rows (the model's
    ``SigLIP.encode_image`` / ``encode_text`` methods, or a loaded exported
    forward — anything traceable). They are jitted here, once each; bucket
    shapes do the rest of the compile hygiene.
    """

    def __init__(
        self,
        encode_image_fn: Callable,
        encode_text_fn: Callable,
        params: Any,
        *,
        batch_buckets: Sequence[int] = (1, 8, 32, 128),
        text_len_buckets: Sequence[int] = (64,),
        image_shape: tuple[int, int, int] = (224, 224, 3),
        token_dtype=np.int32,
        mesh=None,
        batch_axis: str = data_axis,
    ):
        self.batch_buckets = _validated_buckets(batch_buckets, "batch_buckets")
        self.text_len_buckets = _validated_buckets(
            text_len_buckets, "text_len_buckets"
        )
        self.image_shape = tuple(image_shape)
        self.token_dtype = np.dtype(token_dtype)
        self.params = params
        self.mesh = mesh
        self.batch_axis = batch_axis
        if mesh is not None:
            dp = mesh.shape[batch_axis]
            bad = [b for b in self.batch_buckets if b % dp]
            if bad:
                raise ValueError(
                    f"batch buckets {bad} do not divide the mesh's "
                    f"{batch_axis}={dp} axis; every device must hold whole rows"
                )
        self._jit = {
            "image": jax.jit(encode_image_fn),
            "text": jax.jit(encode_text_fn),
        }
        self._compiled: set[tuple] = set()
        self._lock = named_lock("serve.engine.InferenceEngine._lock")

    @classmethod
    def from_model(cls, model, params, **kw):
        """Engine over a live ``models.SigLIP`` — buckets default from its
        config (text len bucket = context_length: training-identical padding)."""
        cfg = model.cfg
        kw.setdefault("text_len_buckets", (cfg.text.context_length,))
        kw.setdefault(
            "image_shape", (cfg.vision.image_size, cfg.vision.image_size, 3)
        )

        def img_fn(p, images):
            return model.apply({"params": p}, images, method=type(model).encode_image)

        def txt_fn(p, tokens):
            return model.apply({"params": p}, tokens, method=type(model).encode_text)

        return cls(img_fn, txt_fn, params, **kw)

    # -- live refresh --------------------------------------------------------

    def swap_params(self, new_params) -> None:
        """Replace the parameter pytree WITHOUT recompiling anything.

        The jitted encoders take params as an ARGUMENT, so a new tree with
        the same treedef and leaf shapes/dtypes hits every warmed bucket's
        compiled program — ``compile_count`` stays exactly where warmup left
        it (the zero-downtime hot-swap contract, asserted by the swap tests).
        A mismatched tree would silently change the programs' signatures and
        trigger fresh compiles mid-traffic, so it is refused here instead.

        Publication is atomic (one attribute assignment); an engine call
        already in flight keeps the params it read at call start — requests
        finish on the version they started on.
        """
        old_leaves, old_tree = jax.tree.flatten(self.params)
        new_leaves, new_tree = jax.tree.flatten(new_params)
        if old_tree != new_tree:
            raise ValueError(
                "swap_params: new param tree structure differs from the "
                "serving tree — a structural change is a new engine, not a "
                "hot swap"
            )
        for i, (o, n) in enumerate(zip(old_leaves, new_leaves)):
            o_spec = (tuple(getattr(o, "shape", ())), str(getattr(o, "dtype", "")))
            n_spec = (tuple(getattr(n, "shape", ())), str(getattr(n, "dtype", "")))
            if o_spec != n_spec:
                raise ValueError(
                    f"swap_params: leaf {i} spec {n_spec} != serving spec "
                    f"{o_spec} — shape/dtype changes would recompile every "
                    "bucket mid-traffic"
                )
        self.params = new_params

    # -- introspection -------------------------------------------------------

    @property
    def compile_count(self) -> int:
        """Distinct (kind, padded shape) programs built so far. Steady state:
        equal to the warmed bucket count, NEVER the request count."""
        with self._lock:
            return len(self._compiled)

    @property
    def bucket_space(self) -> int:
        """Total grid points: image batch buckets + text (batch × len) buckets."""
        return len(self.batch_buckets) * (1 + len(self.text_len_buckets))

    def jit_cache_size(self) -> int | None:
        """The jit layer's own entry count (cross-check for tests); None when
        the running jax build doesn't expose it."""
        sizes = []
        for fn in self._jit.values():
            if hasattr(fn, "_cache_size"):
                sizes.append(fn._cache_size())
        return sum(sizes) if sizes else None

    # -- encode paths --------------------------------------------------------

    def _bucket_for(self, n: int, buckets: tuple[int, ...], what: str) -> int:
        for b in buckets:
            if n <= b:
                return b
        raise ValueError(
            f"{what} {n} exceeds the largest bucket {buckets[-1]}; "
            "split the request or extend the bucket grid"
        )

    def _run(self, kind: str, padded: np.ndarray) -> np.ndarray:
        # Chaos points (serve/siege.py): a slow or faulting accelerator step.
        # Dead unless DSL_CHAOS=1 AND a fault is armed; a raise here fans out
        # typed through the batcher's futures, never a hang.
        maybe_inject("engine.latency")
        maybe_inject("engine.exception")
        if self.mesh is not None:
            spec = P(self.batch_axis, *([None] * (padded.ndim - 1)))
            padded = jax.device_put(padded, NamedSharding(self.mesh, spec))
        key = (kind, padded.shape)
        with self._lock:
            self._compiled.add(key)
        return np.asarray(self._jit[kind](self.params, padded))

    def encode_text(self, tokens) -> np.ndarray:
        """(n, s) or (s,) int token ids → (n, embed_dim) float32 rows.

        Pads n up to a batch bucket and s up to a len bucket (id 0 — the
        training pad token), then slices the real rows back out.
        """
        arr = np.asarray(tokens, dtype=self.token_dtype)
        if arr.ndim == 1:
            arr = arr[None, :]
        n, s = arr.shape
        nb = self._bucket_for(n, self.batch_buckets, "batch size")
        sb = self._bucket_for(s, self.text_len_buckets, "text length")
        padded = np.zeros((nb, sb), dtype=self.token_dtype)
        padded[:n, :s] = arr
        return self._run("text", padded)[:n]

    def encode_image(self, images) -> np.ndarray:
        """(n, h, w, 3) or (h, w, 3) float pixels → (n, embed_dim) rows."""
        arr = np.asarray(images, dtype=np.float32)
        if arr.ndim == 3:
            arr = arr[None]
        if arr.shape[1:] != self.image_shape:
            raise ValueError(
                f"image shape {arr.shape[1:]} != engine's {self.image_shape}; "
                "resize upstream (the compiled towers are shape-fixed)"
            )
        n = arr.shape[0]
        nb = self._bucket_for(n, self.batch_buckets, "batch size")
        padded = np.zeros((nb, *self.image_shape), dtype=np.float32)
        padded[:n] = arr
        return self._run("image", padded)[:n]

    def warmup(self) -> int:
        """Compile every bucket combination up front (zeros input) so the
        first real request never pays a compile. Returns the compile count —
        after this, equal to :attr:`bucket_space` and constant."""
        for nb in self.batch_buckets:
            self.encode_image(np.zeros((nb, *self.image_shape), np.float32))
            for sb in self.text_len_buckets:
                self.encode_text(np.zeros((nb, sb), self.token_dtype))
        return self.compile_count
