"""In-memory exact retrieval index: dot-product top-k over L2-normalized rows.

Exact, not approximate: at the embedding dims this stack serves (512-1152) a
blocked matmul scan saturates memory bandwidth, so brute force is both the
correctness oracle AND a competitive baseline — an ANN layer (IVF/HNSW) is a
later PR that must reproduce these rankings on its recall ceiling.

The scan is CHUNKED over index rows: per query block only a
(queries × chunk_size) score panel is live, so memory stays bounded by the
chunk knob while the index itself can hold millions of rows. The running
top-k is merged per chunk with a STABLE sort, which pins the tie order to
insertion position — the same deterministic contract as
:func:`eval.retrieval.topk_ids`, and tested identical to it (chunked or not).

Ranking parity with the offline eval: ``eval.retrieval.retrieval_ranks``
counts strictly-greater similarities, so on a tie-free fixture the positive's
position in :meth:`search` output equals its ``retrieval_ranks`` rank exactly.
"""

from __future__ import annotations

import threading

import numpy as np

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

__all__ = ["RetrievalIndex"]


class RetrievalIndex:
    """Append-only exact top-k index over embedding rows.

    ``add`` stacks rows (with optional integer ids; default = insertion
    order); ``search`` returns ``(scores, ids)`` of the top-k by dot product,
    descending, ties broken by insertion order (earlier row wins). Thread-safe
    for concurrent add/search (snapshot semantics: a search sees the rows
    present when it started — an ``add`` landing MID-scan is invisible to
    that search, never a torn chunk; pinned by the gated-interleaving test in
    tests/test_serve.py).
    """

    def __init__(self, *, chunk_size: int = 4096, dtype=np.float32):
        if chunk_size < 1:
            raise ValueError(f"chunk_size must be >= 1, got {chunk_size}")
        self.chunk_size = chunk_size
        self.dtype = np.dtype(dtype)
        self._blocks: list[np.ndarray] = []
        self._ids: list[np.ndarray] = []
        self._size = 0
        self._lock = named_lock("serve.index.RetrievalIndex._lock")

    def __len__(self) -> int:
        with self._lock:
            return self._size

    @property
    def dim(self) -> int | None:
        with self._lock:
            return self._blocks[0].shape[1] if self._blocks else None

    def add(self, embeddings, ids=None) -> np.ndarray:
        """Append (n, d) rows; returns the assigned ids (n,)."""
        emb = np.ascontiguousarray(embeddings, dtype=self.dtype)
        if emb.ndim == 1:
            emb = emb[None]
        if emb.ndim != 2:
            raise ValueError(f"embeddings must be (n, d), got {emb.shape}")
        with self._lock:
            if self._blocks and emb.shape[1] != self._blocks[0].shape[1]:
                raise ValueError(
                    f"dim {emb.shape[1]} != index dim {self._blocks[0].shape[1]}"
                )
            if ids is None:
                ids = np.arange(self._size, self._size + len(emb), dtype=np.int64)
            else:
                ids = np.asarray(ids, dtype=np.int64)
                if ids.shape != (len(emb),):
                    raise ValueError(
                        f"ids shape {ids.shape} != ({len(emb)},)"
                    )
            self._blocks.append(emb)
            self._ids.append(ids)
            self._size += len(emb)
            return ids

    def _snapshot(self) -> tuple[list[np.ndarray], list[np.ndarray], int]:
        with self._lock:
            return list(self._blocks), list(self._ids), self._size

    def search(self, queries, k: int) -> tuple[np.ndarray, np.ndarray]:
        """(q, d) or (d,) queries → (scores (q, k), ids (q, k)), score-descending,
        ties by insertion order. k is clamped to the index size."""
        blocks, id_blocks, size = self._snapshot()
        if size == 0:
            raise ValueError("search on an empty index")
        q = np.ascontiguousarray(queries, dtype=self.dtype)
        squeeze = q.ndim == 1
        if squeeze:
            q = q[None]
        k = min(int(k), size)
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")

        best_scores = np.full((len(q), 0), -np.inf, dtype=self.dtype)
        best_ids = np.zeros((len(q), 0), dtype=np.int64)
        # Iterate fixed-size chunks across block boundaries, in insertion
        # order: within each merge, retained rows (earlier positions) precede
        # chunk rows (later positions), and the STABLE argsort therefore
        # resolves every tie to the earlier insertion — chunk size never
        # changes the result.
        for chunk, chunk_ids in self._chunks(blocks, id_blocks):
            sims = q @ chunk.T  # (q, chunk)
            cand_scores = np.concatenate([best_scores, sims], axis=1)
            cand_ids = np.concatenate(
                [best_ids, np.broadcast_to(chunk_ids, (len(q), len(chunk_ids)))],
                axis=1,
            )
            order = np.argsort(-cand_scores, axis=1, kind="stable")[:, :k]
            best_scores = np.take_along_axis(cand_scores, order, axis=1)
            best_ids = np.take_along_axis(cand_ids, order, axis=1)
        if squeeze:
            return best_scores[0], best_ids[0]
        return best_scores, best_ids

    def _chunks(self, blocks, id_blocks):
        """Yield (rows, ids) panels of at most chunk_size, splitting and
        coalescing add()-blocks as needed."""
        pend_rows: list[np.ndarray] = []
        pend_ids: list[np.ndarray] = []
        pending = 0
        for block, ids in zip(blocks, id_blocks):
            start = 0
            while start < len(block):
                take = min(self.chunk_size - pending, len(block) - start)
                pend_rows.append(block[start : start + take])
                pend_ids.append(ids[start : start + take])
                pending += take
                start += take
                if pending == self.chunk_size:
                    yield np.concatenate(pend_rows), np.concatenate(pend_ids)
                    pend_rows, pend_ids, pending = [], [], 0
        if pending:
            yield np.concatenate(pend_rows), np.concatenate(pend_ids)
