"""Thread-safe dynamic micro-batcher: coalesce concurrent requests into one
engine call.

Online traffic arrives one request at a time, but the engine's throughput
comes from batched MXU matmuls — the classic serving trade (batch for
throughput, deadline for latency). This batcher is the piece in between: a
bounded queue of single-item requests, a worker that drains it into batches of
at most ``max_batch_size``, waiting at most ``max_wait_ms`` past the FIRST
queued item's arrival before flushing a partial batch, and futures fanning the
results back to the callers.

Backpressure is explicit: when the queue is full, ``submit`` raises
:class:`QueueFullError` immediately instead of growing without bound — the
caller (or its load balancer) sheds the request while the tail latency of
queued work stays bounded by ``max_queue / throughput``.

The batch function runs on the worker thread only, one call at a time, so a
non-thread-safe engine path is safe behind a batcher.

Per-stage observability (graftscope): every request's life splits into
queue-wait (enqueue → its batch starts assembling... strictly: → assembly
done), batch-assembly (deadline coalescing after the first item), device
(the ``run_batch`` engine call) and reply (future fan-out). Each stage feeds
a bounded :class:`~distributed_sigmoid_loss_tpu.utils.logging.LatencyWindow`
(surfaced as ``stage_latency_ms`` in ``EmbeddingService.stats()``) and,
when a ``SpanRecorder`` is attached, a host span on the worker's timeline —
so a p99 regression names its stage instead of an opaque end-to-end number.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import Counter
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

from distributed_sigmoid_loss_tpu.serve.siege import maybe_inject
from distributed_sigmoid_loss_tpu.utils.logging import LatencyWindow

from distributed_sigmoid_loss_tpu.obs.lockwatch import named_lock

BATCH_STAGES = ("queue_wait", "assembly", "device", "reply")

__all__ = [
    "MicroBatcher",
    "QueueFullError",
    "BatcherClosedError",
    "ShutdownError",
    "BATCH_STAGES",
]


class QueueFullError(RuntimeError):
    """The batcher's bounded queue is full — request rejected (backpressure)."""


class BatcherClosedError(RuntimeError):
    """submit() after close(): the worker is draining/stopped."""


class ShutdownError(RuntimeError):
    """The batcher shut down with this request still queued: a typed
    rejection, never a hung future — the close() drain guarantee."""


@dataclass
class _Request:
    item: Any
    future: Future = field(default_factory=Future)
    enqueued_at: float = field(default_factory=time.monotonic)


_SENTINEL = object()


def _resolve(req: "_Request", result) -> None:
    """Set a result, tolerating a future already failed by the close-side
    drain sweep (the worker and the sweep may race; exactly one wins)."""
    if req.future.cancelled():
        return
    try:
        req.future.set_result(result)
    except InvalidStateError:
        pass


def _fail(req: "_Request", exc: BaseException) -> None:
    if req.future.cancelled():
        return
    try:
        req.future.set_exception(exc)
    except InvalidStateError:
        pass


class MicroBatcher:
    """Coalesce single-item submissions into batched ``run_batch`` calls.

    ``run_batch(items) -> results`` receives a list of 1..max_batch_size items
    and must return one result per item, in order. A raised exception fails
    every future of that batch (callers see the error; the worker keeps
    serving subsequent batches).
    """

    def __init__(
        self,
        run_batch: Callable[[list], Sequence],
        *,
        max_batch_size: int = 32,
        max_wait_ms: float = 5.0,
        max_queue: int = 1024,
        name: str = "batcher",
        spans=None,
    ):
        if max_batch_size < 1:
            raise ValueError(f"max_batch_size must be >= 1, got {max_batch_size}")
        if max_wait_ms < 0:
            raise ValueError(f"max_wait_ms must be >= 0, got {max_wait_ms}")
        self._run_batch = run_batch
        self.max_batch_size = max_batch_size
        self.max_wait = max_wait_ms / 1000.0
        self.name = name
        self._spans = spans  # SpanRecorder or None (obs/spans.py)
        self._queue: queue.Queue = queue.Queue(maxsize=max_queue)
        self._closed = False
        self._hist_lock = named_lock("serve.batcher.MicroBatcher._hist_lock")
        self._batch_sizes: Counter[int] = Counter()
        # Small windows: a batcher's stage stats cover recent traffic, and
        # four windows per batcher must stay cheap.
        self._stage_windows = {s: LatencyWindow(2048) for s in BATCH_STAGES}
        self._worker = threading.Thread(
            target=self._loop, name=f"{name}-worker", daemon=True
        )
        self._worker.start()

    # -- client side ---------------------------------------------------------

    def submit(self, item) -> Future:
        """Enqueue one item; returns the Future of its result.

        Raises :class:`QueueFullError` when the bounded queue is full and
        :class:`BatcherClosedError` after :meth:`close`.
        """
        if self._closed:
            raise BatcherClosedError("submit() on a closed MicroBatcher")
        req = _Request(item)
        try:
            self._queue.put_nowait(req)
        except queue.Full:
            raise QueueFullError(
                f"batcher queue full ({self._queue.maxsize} pending); "
                "retry later or raise max_queue"
            ) from None
        if self._closed:
            # close() raced our enqueue: the worker may already be past its
            # final drain, which would leave this future hung forever. Fail
            # it typed; if the worker DOES still serve it, the safe setters
            # let exactly one side win.
            _fail(req, ShutdownError("batcher shut down while request queued"))
        return req.future

    def close(self, *, wait: bool = True) -> None:
        """Stop accepting work; the worker drains what is already queued.

        Drain guarantee: every request that made it into the queue is either
        answered by the worker or failed with :class:`ShutdownError` — a
        ``fut.result()`` can never hang on a closed batcher.
        """
        if self._closed:
            return
        self._closed = True
        # The sentinel is the wake-up/stop signal; put() (blocking) because a
        # full queue still needs the worker stopped after it drains.
        self._queue.put(_SENTINEL)
        if wait:
            self._worker.join()
            # Final sweep: anything enqueued after the worker's own drain
            # (submit racing close) gets the typed rejection here.
            self._drain_reject()

    def _drain_reject(self) -> None:
        """Fail everything still queued with ShutdownError (sentinels skipped)."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if req is _SENTINEL:
                continue
            _fail(req, ShutdownError("batcher shut down while request queued"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def batch_size_histogram(self) -> dict[int, int]:
        """{batch_size: count of engine calls at that size}."""
        with self._hist_lock:
            return dict(sorted(self._batch_sizes.items()))

    def stage_latency_ms(self) -> dict[str, dict[str, float]]:
        """{stage: {p50_ms, p95_ms, p99_ms}} per batching stage — queue_wait
        and reply are per REQUEST, assembly and device per engine CALL."""
        return {
            stage: w.percentiles_ms((50, 95, 99))
            for stage, w in self._stage_windows.items()
        }

    def _stage(self, stage: str, t0: float, t1: float) -> None:
        self._stage_windows[stage].record(t1 - t0)
        if self._spans is not None:
            self._spans.record(f"serve/{self.name}/{stage}", t0, t1)

    # -- worker side ---------------------------------------------------------

    def _collect(self) -> tuple[list[_Request], float] | None:
        """Block for the first request, then fill the batch until size or the
        first request's deadline. None = sentinel seen with nothing pending.
        Returns ``(batch, t_assembly_start)`` — assembly starts when the
        worker picks the first item up (queue wait before that belongs to the
        queue_wait stage, not assembly)."""
        first = self._queue.get()
        if first is _SENTINEL:
            return None
        t_assembly = time.monotonic()
        batch = [first]
        deadline = first.enqueued_at + self.max_wait
        while len(batch) < self.max_batch_size:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            if nxt is _SENTINEL:
                # Re-queue so the outer loop terminates after this batch.
                self._queue.put(_SENTINEL)
                break
            batch.append(nxt)
        return batch, t_assembly

    def _loop(self) -> None:
        while True:
            collected = self._collect()
            if collected is None:
                # Sentinel: reject anything that slipped in behind it before
                # the worker exits (the drain guarantee's worker-side half).
                self._drain_reject()
                return
            batch, t_assembly = collected
            t_run = time.monotonic()
            # Per-request queue wait: enqueue → assembly done (the moment its
            # engine call starts); per-call assembly: the coalescing window.
            for r in batch:
                self._stage("queue_wait", r.enqueued_at, t_run)
            self._stage("assembly", t_assembly, t_run)
            with self._hist_lock:
                self._batch_sizes[len(batch)] += 1
            try:
                # Chaos point: a wedged worker (stall) or a pre-engine fault;
                # dead unless DSL_CHAOS=1 AND a fault is armed (serve/siege).
                maybe_inject("batcher.stall")
                results = self._run_batch([r.item for r in batch])
            except Exception as e:  # noqa: BLE001 — fan the failure out
                self._stage("device", t_run, time.monotonic())
                for r in batch:
                    _fail(r, e)
                continue
            t_reply = time.monotonic()
            self._stage("device", t_run, t_reply)
            if len(results) != len(batch):
                err = RuntimeError(
                    f"run_batch returned {len(results)} results for "
                    f"{len(batch)} items"
                )
                for r in batch:
                    _fail(r, err)
                continue
            for r, res in zip(batch, results):
                _resolve(r, res)
            self._stage("reply", t_reply, time.monotonic())
