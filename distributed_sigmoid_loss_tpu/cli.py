"""Command-line entry point: ``python -m distributed_sigmoid_loss_tpu <cmd>``.

The reference has no CLI (its entry points are test-file ``__main__`` blocks,
/root/reference/test_distributed_sigmoid_loss.py:144-148); a framework needs one.
The subcommands tie the subsystems together:

- ``train`` — end-to-end SigLIP training on synthetic data: mesh, towers,
  distributed sigmoid loss (all-gather or ring), optax, metrics logging,
  preemption-safe checkpointing (``--ckpt-dir``).
- ``eval``  — zero-shot retrieval + classification of a (random-init or
  checkpointed) model on held-out synthetic data.
- ``export`` — AOT-export a lowered train/forward step to a StableHLO artifact
  (``jax.export``): deployable without model code, replayable on a matching
  topology.
- ``bench`` — the headline throughput benchmark (delegates to bench.py when run
  from a repo checkout; the measured JSON contract is documented there).
- ``serve-bench`` — online-serving micro-bench: concurrent client threads
  through the batched/cached/bucketed ``serve/`` stack (engine + micro-batcher
  + LRU cache + retrieval index) on synthetic data; prints the ``stats()``
  snapshot (qps, latency percentiles, batch histogram, cache hit rate, compile
  count) as one JSON record. CPU-runnable — docs/SERVING.md.
- ``data-bench`` — input-pipeline stage bench: shard read / decode / tokenize
  / augment / host→device commit in isolation, plus the composed real-data
  pipeline (read-ahead + fused batcher + prefetch) vs the synthetic loader,
  as schema-validated JSON records with the ``synthetic_ratio`` acceptance
  figure and a decode worker-scaling curve. CPU-runnable —
  docs/PERF.md "Feeding the headline".
- ``lint`` — graftlint: the repo-invariant AST linter, the graftprove
  config-space drift check (declarative solver vs the real imperative
  refusals), and the jaxpr collective/dtype/dataflow auditor traced over the
  sampled step-config product on an emulated CPU mesh (exit 1 on findings,
  ``--json``, per-rule ``--disable``, ``--full-product``, ``--baseline``).
  The same analyzers run in tier-1 (tests/test_analysis.py,
  tests/test_config_space.py) and the dryrun — docs/ANALYSIS.md.
- ``obs`` — graftscope offline reports: ``obs summarize DIR`` merges the
  host spans a ``train --obs-dir`` run recorded with any device trace
  capture under DIR into one where-the-time-goes report, optionally writing
  a single merged Chrome-trace JSON (``--merged-out``) —
  docs/OBSERVABILITY.md.

``train`` and ``eval`` accept ``--cpu-devices N`` to emulate an N-chip mesh on
CPU — the TPU-native analogue of the reference's ``mp.spawn`` + Gloo localhost
harness. ``bench`` runs on the real chip only (its numbers are the measured
contract; an emulated mesh would record meaningless throughput).
"""

from __future__ import annotations

import argparse
import os
import sys

__all__ = ["main"]


def _bootstrap_devices(args) -> None:
    """Force an emulated N-device CPU platform BEFORE jax initializes."""
    if args.cpu_devices:
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={args.cpu_devices}"
        )
        import jax

        jax.config.update("jax_platforms", "cpu")


def _model_config(args):
    from distributed_sigmoid_loss_tpu.utils.config import SigLIPConfig

    if getattr(args, "tiny", False) and args.model != "b16":
        # --tiny is an alias for --model tiny; silently overriding an explicit
        # non-default --model would run a different config than the user asked for.
        raise SystemExit(
            f"--tiny conflicts with --model {args.model}; pass one or the other"
        )
    name = "tiny" if getattr(args, "tiny", False) else args.model
    cfg = {
        "tiny": SigLIPConfig.tiny_test,
        "l14": SigLIPConfig.l14,
        "so400m": SigLIPConfig.so400m,
        "b16": SigLIPConfig.b16,
    }[name]()
    moe = getattr(args, "moe_experts", 0)
    if moe:
        # Shared by train AND eval: a checkpoint trained with --moe-experts can
        # only be restored into an identically-shaped (MoE) model.
        if moe < 2:
            raise SystemExit(f"--moe-experts must be >= 2, got {moe}")
        import dataclasses

        group = getattr(args, "moe_group_size", 0)
        tower_kw = {"moe_experts": moe}
        if group:
            if group < 1:
                raise SystemExit(f"--moe-group-size must be >= 1, got {group}")
            tower_kw["moe_group_size"] = group
        cfg = dataclasses.replace(
            cfg,
            vision=dataclasses.replace(cfg.vision, **tower_kw),
            text=dataclasses.replace(cfg.text, **tower_kw),
        )
    elif getattr(args, "moe_group_size", 0):
        raise SystemExit("--moe-group-size without --moe-experts is a no-op")
    if getattr(args, "quant", ""):
        # Eval/export-only (make_train_step rejects quantized configs): dynamic
        # int8 projection matmuls — the v5e's 2x-bf16 inference gear.
        import dataclasses

        cfg = dataclasses.replace(
            cfg,
            vision=dataclasses.replace(cfg.vision, quant=args.quant),
            text=dataclasses.replace(cfg.text, quant=args.quant),
        )
    if getattr(args, "quant_train", ""):
        # Trainable int8 (train subcommand): same dynamic int8 forward through
        # the straight-through estimator — backward stays full-precision
        # (ops/quant.py int8_dot_general_ste), so the step trains normally.
        import dataclasses

        cfg = dataclasses.replace(
            cfg,
            vision=dataclasses.replace(
                cfg.vision, quant_train=args.quant_train
            ),
            text=dataclasses.replace(cfg.text, quant_train=args.quant_train),
        )
    if getattr(args, "remat_policy", ""):
        # Same override bench.py carries: the measured-best policies are
        # per-model AND per-batch (docs/PERF.md round-4 sweep), so the train
        # CLI exposes the knob rather than hard-coding one winner.
        if not (cfg.vision.remat or cfg.text.remat):
            # tiny_test() disables remat entirely — the policy would be
            # silently ignored (Encoder applies it only under remat=True).
            raise SystemExit(
                f"--remat-policy {args.remat_policy} is a no-op for "
                f"{name!r}: its towers run without rematerialization"
            )
        import dataclasses

        cfg = dataclasses.replace(
            cfg,
            vision=dataclasses.replace(
                cfg.vision, remat_policy=args.remat_policy
            ),
            text=dataclasses.replace(cfg.text, remat_policy=args.remat_policy),
        )
    return cfg


def _make_training_mesh(args):
    """The (dp[, ep|pp]) mesh for ``--ep`` / ``--pp`` topologies — ONE set of
    rules shared by train and export (an artifact validated under different
    rules than the job it deploys to is exactly the drift this helper prevents).

    Returns ``(mesh, None)`` or ``(None, error_message)``.
    """
    import jax

    from distributed_sigmoid_loss_tpu.parallel.mesh import make_mesh

    dcn = getattr(args, "dcn_slices", 1)
    if dcn > 1:
        import numpy as np
        from jax.sharding import Mesh

        from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis
        from distributed_sigmoid_loss_tpu.parallel.multihost import (
            _hybrid_device_array,
        )

        devices = jax.devices()
        n_dev = len(devices)
        pp = getattr(args, "pp", 1)
        if args.ep > 1:
            return None, "--dcn-slices composes with dp/pp only (no --ep)"
        if n_dev % (dcn * pp):
            return None, (
                f"--dcn-slices {dcn} x --pp {pp} must divide device count "
                f"{n_dev}"
            )
        # dcn outermost, and GROUPED BY REAL SLICE on multi-slice hardware
        # (mesh_utils.create_hybrid_device_mesh via _hybrid_device_array) —
        # a raw enumeration-order reshape could put devices of different
        # slices in one "dp" row, sending the f32 psum over DCN and the int8
        # hop over ICI: the exact inversion of the feature. CPU emulation and
        # single-slice devices carry no slice metadata; plain reshape there.
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        if len(slice_ids) > 1:
            if len(slice_ids) != dcn:
                return None, (
                    f"--dcn-slices {dcn} != actual slice count "
                    f"{len(slice_ids)} — the dcn axis must follow real "
                    f"slice boundaries for the compression split to match "
                    f"the link topology"
                )
            # pp rides the innermost ICI factor (stage hops are ppermute
            # neighbor traffic); _hybrid_device_array groups by real slice.
            arr = _hybrid_device_array(dcn, n_dev // (dcn * pp), pp, devices)
        else:
            if devices and devices[0].platform == "tpu":
                # On real single-slice TPU hardware the 'dcn' axis lands on
                # ICI neighbors: the int8/top-k hop pays quantization loss on
                # a fast link with zero bandwidth win. A stderr warning is
                # easy to lose in multi-host logs (advisor, round 4), so a
                # production run REFUSES unless the override flag makes the
                # emulation intent explicit. The silent plain-reshape path
                # exists for CPU emulation, where virtual devices carry no
                # slice metadata.
                if not getattr(args, "force_dcn_emulation", False):
                    return None, (
                        f"--dcn-slices {dcn} on single-slice TPU hardware: "
                        "the 'dcn' axis maps onto ICI neighbors, so "
                        "compressed gradient sync pays quantization loss on "
                        "a fast link with no bandwidth win; pass "
                        "--force-dcn-emulation to run it anyway (perf "
                        "experiments emulating a multi-slice topology)"
                    )
                print(
                    f"WARNING: --dcn-slices {dcn} on single-slice TPU "
                    "hardware (--force-dcn-emulation) — compressed sync "
                    "pays quantization loss on ICI with no bandwidth win",
                    file=sys.stderr,
                )
            arr = np.array(devices)
        if pp > 1:
            from distributed_sigmoid_loss_tpu.parallel.pipeline import (
                pipeline_axis,
            )

            return (
                Mesh(
                    arr.reshape(dcn, n_dev // (dcn * pp), pp),
                    ("dcn", data_axis, pipeline_axis),
                ),
                None,
            )
        return (
            Mesh(arr.reshape(dcn, n_dev // dcn), ("dcn", data_axis)),
            None,
        )
    pp = getattr(args, "pp", 1)
    if pp > 1:
        from distributed_sigmoid_loss_tpu.parallel.mesh import (
            data_axis,
            make_2d_mesh,
        )
        from distributed_sigmoid_loss_tpu.parallel.pipeline import pipeline_axis

        n_dev = len(jax.devices())
        if args.ep > 1:
            return None, "--pp with --ep is not supported (pp towers are dense)"
        if n_dev % pp:
            return None, f"--pp {pp} must divide device count {n_dev}"
        return (
            make_2d_mesh(n_dev // pp, pp, axis_names=(data_axis, pipeline_axis)),
            None,
        )
    if args.ep <= 1:
        return make_mesh(), None
    from distributed_sigmoid_loss_tpu.models.moe import EP_AXIS
    from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis, make_2d_mesh

    n_dev = len(jax.devices())
    if not args.moe_experts:
        return None, (
            "--ep > 1 without --moe-experts would only shrink data "
            "parallelism (a dense model has no ep-sharded params)"
        )
    if n_dev % args.ep:
        return None, f"--ep {args.ep} must divide device count {n_dev}"
    if args.moe_experts % args.ep:
        return None, (
            f"--ep {args.ep} must divide --moe-experts {args.moe_experts} "
            f"(expert kernels are stacked (E, ...) and sharded over ep)"
        )
    return make_2d_mesh(n_dev // args.ep, args.ep, axis_names=(data_axis, EP_AXIS)), None


def _byte_tokenize_for(cfg, vocab_path: str = ""):
    """Tokenizer folded into the config's vocab when it's smaller (tiny test
    configs): modulo keeps distinct texts distinct, where clamping would
    collapse them onto the max id. Shared by train (real-data loaders) and eval
    (zero-shot prompts). ``vocab_path``: a trained BPE vocab (``tokenizer``
    subcommand) instead of the byte-level default."""
    from distributed_sigmoid_loss_tpu.data import BpeTokenizer, ByteTokenizer

    tok = BpeTokenizer.load(vocab_path) if vocab_path else ByteTokenizer()

    def tokenize(texts, length):
        import numpy as np

        ids = np.asarray(tok(texts, length))
        if cfg.text.vocab_size < tok.vocab_size:
            ids = ids % cfg.text.vocab_size
        return ids

    return tokenize


def _resolve_eval_data(path: str):
    """Resolve --eval-data to ("dir", path) / ("shards", [tars]) / (None, error).

    ONE resolution helper shared by cmd_train's early usage check and the
    source build, so the two can never disagree on what a valid path is.
    """
    import glob as globmod
    import os

    if os.path.isdir(path):
        return "dir", path
    shards = globmod.glob(path)
    if shards:
        return "shards", shards
    return None, f"--eval-data matched nothing: {path!r}"


def _eval_holdout_source(args, cfg, tokenize, native_decode: bool):
    """Build the --eval-data holdout source (directory or tar-shard glob).

    Yields GLOBAL batches of ``args.batch`` rows on every host (place_global
    slices process-wise) — the eval batch is one fixed batch, so the striped
    multi-host read path is deliberately not used here. ``native_decode``
    must match the training stream's decoder: PIL and the native libjpeg
    engine produce numerically different pixels, and a decode-skewed eval
    batch would measure the wrong distribution.
    """
    from distributed_sigmoid_loss_tpu.data import ImageTextFolder, ImageTextShards

    kind, resolved = _resolve_eval_data(args.eval_data)
    if kind == "dir":
        return ImageTextFolder(
            resolved, cfg, args.batch, tokenize, native_decode=native_decode,
        )
    if kind == "shards":
        return ImageTextShards(
            resolved, cfg, args.batch, tokenize, native_decode=native_decode,
        )
    # Same exit-2 usage-error channel as '--data-shards matched nothing'
    # (cmd_train pre-validates; this is the non-train-caller backstop).
    print(resolved, file=sys.stderr)
    raise SystemExit(2)


def _train_config_conflicts(args) -> str | None:
    """The ``train`` command's config-compatibility refusals, as a pure
    predicate: the first conflict message, or None when the flag set is
    coherent.

    Extracted from cmd_train so graftprove (analysis/config_space.py) can
    probe the CLI layer with a synthesized namespace: every refusal here is
    config-space (flag compatibility) and must agree with the declarative
    constraint table — a disagreement is a ``config-space-drift`` finding.
    Environment checks (paths, coordinators, device counts) stay in
    cmd_train.
    """
    if args.ep < 1:
        return f"--ep must be >= 1, got {args.ep}"
    if args.moe_aux_weight is not None and not args.moe_experts:
        return ("--moe-aux-weight without --moe-experts would be a silent "
                "no-op (a dense model has no routers to balance)")
    if args.pp > 1 and args.moe_experts:
        return "--pp with --moe-experts is not supported (pp towers are dense)"
    # graftshard mode resolution: --update-sharding supersedes --zero1 (the
    # deprecated alias). Mirrors parallel/update_shard.resolve_update_sharding
    # without the jax import this predicate must stay free of.
    update_mode = getattr(args, "update_sharding", "") or ""
    if args.zero1 and update_mode not in ("", "zero1"):
        return (f"--zero1 is the deprecated alias for --update-sharding "
                f"zero1 and contradicts --update-sharding {update_mode}; "
                "drop one of them")
    if args.zero1 and not update_mode:
        update_mode = "zero1"
    if update_mode == "off":
        update_mode = ""
    if args.pp > 1 and update_mode:
        return (f"--pp with --update-sharding {update_mode} is not supported "
                "(the sharded update — zero1's constrain and full's "
                "reduce-scatter alike — would re-shard the stage-local "
                "moments dp-wise every step)")
    if args.pp_microbatches and args.pp <= 1:
        return "--pp-microbatches without --pp > 1 would be a silent no-op"
    if args.pp_microbatches < 0:
        return f"--pp-microbatches must be >= 1, got {args.pp_microbatches}"
    if args.accum_bf16 and args.accum == 1:
        # Same check exists in make_train_step; exit-2 here beats a deep raise.
        return ("--accum-bf16 requires --accum > 1 (the unaccumulated step "
                "has no accumulator)")
    if args.pp > 1 and args.accum > 1 and args.accum_negatives == "global":
        # Same check exists in make_train_step; repeat it HERE so the exit-2
        # message lands before the minutes-long create_train_state.
        return ("--accum-negatives global with --pp is not supported (the pp "
                "forward is already whole-batch per accumulation step)")
    if args.gradcache_bf16 and (
        args.accum == 1 or args.accum_negatives != "global"
    ):
        return ("--gradcache-bf16 requires --accum > 1 with "
                "--accum-negatives global (only the GradCache path stashes "
                "embedding tables)")
    if args.loss_impl == "chunked":
        # Refuse, don't drop: a run claiming the streamed-negatives memory
        # shape while silently running the ring would invalidate any HBM A/B.
        if args.variant == "ring":
            return ("--loss-impl chunked applies to the all_gather variant "
                    "only (the ring already streams negatives one chunk per "
                    "hop); drop --variant ring or pass --variant all_gather")
        if args.ring_overlap:
            return ("--loss-impl chunked (all_gather) and --ring-overlap "
                    "(ring) select different comm variants; pick one")
    if args.ring_overlap and args.variant == "all_gather":
        return ("--ring-overlap applies to the ring variant only (the "
                "all-gather loss has no hop loop to overlap)")
    if args.loss_family == "softmax" and (
        args.loss_impl != "fused" or args.ring_overlap
    ):
        return ("--loss-impl chunked / --ring-overlap apply to the sigmoid "
                "family only (the softmax ring already streams its logsumexp)")
    if args.use_pallas and args.loss_family != "sigmoid":
        # The streaming kernel computes the sigmoid family's block math; a
        # softmax run claiming --use-pallas would silently run plain XLA.
        return "--use-pallas applies to the sigmoid family only"
    if args.watchdog == "skip" and not args.ckpt_dir:
        # The jitted step DONATES its input state, so a poisoned update can
        # only be undone by restoring a checkpoint — skip without --ckpt-dir
        # would silently train on from the poisoned params.
        return ("--watchdog skip requires --ckpt-dir (skipping rolls back to "
                "the last good checkpoint; without one there is nothing to "
                "roll back to)")
    if args.dcn_slices > 1 and not args.grad_compression:
        return ("--dcn-slices without --grad-compression is a silent no-op: "
                "the regular step already spans slices when the dp axis is "
                "built dcn-outermost (parallel/multihost.py make_hybrid_mesh); "
                "the separate dcn axis exists to compress its gradient hop")
    if args.grad_compression:
        reasons = []
        if args.dcn_slices < 2:
            reasons.append("--dcn-slices >= 2 (the dcn axis being compressed)")
        if args.variant == "ring":
            reasons.append("--variant all_gather or unset (ring ppermute has "
                           "no joint-(dcn,dp) axis form)")
        if args.ep > 1:
            # --pp and --moe-experts (experts replicated, ep == 1) compose
            # since round 5; expert PARALLELISM stays with the regular step
            # (no GSPMD all-to-alls inside the manual region).
            reasons.append("no --ep (expert parallelism needs the regular step)")
        if args.ring_overlap:
            reasons.append("no --ring-overlap (compressed sync is "
                           "all_gather-only; there is no ring hop loop)")
        if args.ema_decay is not None:
            reasons.append("no --ema-decay")
        if args.grad_compression in ("topk", "adaptive", "learned") and not (
            0 < args.topk_frac <= 1
        ):
            reasons.append(
                f"--topk-frac in (0, 1], got {args.topk_frac} (it is the "
                f"fraction of gradient entries kept per tensor)"
            )
        if args.grad_compression in ("adaptive", "learned") and args.pp > 1:
            reasons.append(
                "no --pp (the adaptive controller's scheme table is per "
                "GLOBAL tensor; pp shards block grads stage-locally — use "
                "int8/topk under pp)"
            )
        if reasons:
            return "--grad-compression requires: " + "; ".join(reasons)
    if args.topk_frac != 0.01 and args.grad_compression not in (
        "topk", "adaptive", "learned"
    ):
        return "--topk-frac without --grad-compression topk is a silent no-op"
    if args.topk_exact and args.grad_compression not in (
        "topk", "adaptive", "learned"
    ):
        return "--topk-exact without --grad-compression topk is a silent no-op"
    if args.dcn_budget_mbps is not None and args.grad_compression not in (
        "adaptive", "learned"
    ):
        return ("--dcn-budget-mbps without --grad-compression adaptive is a "
                "silent no-op: only the adaptive bit controller consumes the "
                "bandwidth budget")
    if getattr(args, "controller", None) and args.grad_compression not in (
        "adaptive", "learned"
    ):
        return ("--controller without --grad-compression adaptive/learned is "
                "a silent no-op: the bit controller only exists inside the "
                "adaptive step wrapper (a fixed scheme has no per-round "
                "policy to select)")
    if getattr(args, "emu_dcn_mbps", None) is not None and args.dcn_slices < 2:
        return ("--emu-dcn-mbps without --dcn-slices >= 2 is a silent no-op: "
                "the emulated pipe carries the dcn hop's payload, and there "
                "is no dcn mesh axis (or compressed sync round) to emulate")
    return None


def cmd_train(args) -> int:
    _bootstrap_devices(args)
    import jax

    if args.async_checkpoint and not args.ckpt_dir:
        print("--async-checkpoint without --ckpt-dir would be a silent no-op "
              "(there is nothing to save)", file=sys.stderr)
        return 2
    if args.eval_data and not args.eval_every:
        print("--eval-data without --eval-every would be a silent no-op "
              "(nothing ever evaluates it)", file=sys.stderr)
        return 2
    if args.eval_data:
        # Validate the path NOW — the eval hook is built after the
        # minutes-long state init, far too late for a typo'd glob.
        kind, resolved = _resolve_eval_data(args.eval_data)
        if kind is None:
            print(resolved, file=sys.stderr)
            return 2
    if args.coordinator:
        if args.num_processes < 1 or args.process_id < 0:
            print(
                "--coordinator requires --num-processes >= 1 and --process-id >= 0 "
                "(every process runs the same command with its own --process-id)",
                file=sys.stderr,
            )
            return 2
        if args.batch % args.num_processes:
            print(
                f"--batch {args.batch} must be divisible by --num-processes "
                f"{args.num_processes} (batch is GLOBAL; each process contributes "
                f"batch/num_processes rows)",
                file=sys.stderr,
            )
            return 2
        # Multi-process run: rendezvous BEFORE any other jax use so every host
        # sees the same global device list (the pjit single-controller model).
        from distributed_sigmoid_loss_tpu.parallel.multihost import (
            initialize_multihost,
        )

        try:
            initialize_multihost(
                coordinator_address=args.coordinator,
                num_processes=args.num_processes,
                process_id=args.process_id,
            )
        except Exception as e:
            # Environmental (ports/sandbox): a distinct exit code lets harnesses
            # skip rather than fail — same contract as tests/_multihost_worker.py.
            print(f"INIT_FAILED: {type(e).__name__}: {e}", file=sys.stderr)
            return 3

    from distributed_sigmoid_loss_tpu.data import (
        SyntheticImageText,
        global_batch_from_local,
    )
    from distributed_sigmoid_loss_tpu.models import SigLIP
    from distributed_sigmoid_loss_tpu.train import (
        PreemptionGuard,
        RestoreRequiredError,
        create_train_state,
        latest_step,
        make_optimizer,
        make_train_step,
        train_resilient,
    )
    from distributed_sigmoid_loss_tpu.utils.config import LossConfig, TrainConfig
    from distributed_sigmoid_loss_tpu.utils.logging import MetricsLogger

    cfg = _model_config(args)
    conflict = _train_config_conflicts(args)
    if conflict is not None:
        print(conflict, file=sys.stderr)
        return 2
    mesh, mesh_err = _make_training_mesh(args)
    if mesh_err:
        print(mesh_err, file=sys.stderr)
        return 2
    pidx, pcnt = jax.process_index(), jax.process_count()
    print(
        f"mesh: {dict(mesh.shape)} devices={len(jax.devices())}"
        + (f" process {pidx}/{pcnt}" if pcnt > 1 else ""),
        file=sys.stderr,
    )
    if pcnt > 1 and args.batch % pcnt:
        # --coordinator runs checked this already; a pre-initialized runtime
        # (TPU pod auto-init) reaches here without that gate. batch is GLOBAL;
        # an indivisible value would silently train at batch//pcnt*pcnt.
        print(
            f"--batch {args.batch} must be divisible by process count {pcnt}",
            file=sys.stderr,
        )
        return 2
    # Resolved graftshard mode ("off" | "zero1" | "full") — the conflict
    # predicate above already refused contradictory flag pairs.
    update_mode = args.update_sharding or ("zero1" if args.zero1 else "off")
    if update_mode == "full":
        from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis as _dax

        if dict(mesh.shape).get(_dax, 1) < 2:
            # Environment refusal (a mesh-instance property, not flag
            # compatibility — same split as the builders'): nothing to
            # reduce-scatter over on a 1-wide data axis.
            print(
                "--update-sharding full requires a data-parallel axis of "
                f"size > 1, got mesh {dict(mesh.shape)}",
                file=sys.stderr,
            )
            return 2

    if args.loss_family != "sigmoid":
        import dataclasses

        # The model's t_prime init is family-dependent (CLIP: log(1/0.07));
        # the loss config lives on the model config so init sees it.
        cfg = dataclasses.replace(cfg, loss=LossConfig(family=args.loss_family))
    if args.pp > 1:
        import dataclasses

        # pp stages are the nn.scan-stacked block params; force scanned towers
        # (the production configs already are — this covers --tiny, whose
        # test default is unrolled).
        cfg = dataclasses.replace(
            cfg,
            vision=dataclasses.replace(cfg.vision, scan_layers=True),
            text=dataclasses.replace(cfg.text, scan_layers=True),
        )
        # Validate BEFORE create_train_state: a full b16-class param init costs
        # minutes, and every other bad flag combination exits 2 with a message.
        from distributed_sigmoid_loss_tpu.parallel.pp_towers import (
            validate_pp_tower,
        )

        try:
            validate_pp_tower(cfg.vision, args.pp, "vision")
            validate_pp_tower(cfg.text, args.pp, "text")
        except ValueError as e:
            print(f"--pp {args.pp}: {e}", file=sys.stderr)
            return 2
    model = SigLIP(cfg)
    tx = make_optimizer(
        TrainConfig(
            learning_rate=args.lr, warmup_steps=5, total_steps=max(args.steps, 10),
            optimizer=args.optimizer,
        )
    )
    source = None
    if sum(map(bool, (args.data_dir, args.data_shards, args.native_data))) > 1:
        print(
            "--data-dir, --data-shards and --native-data are mutually "
            "exclusive data sources",
            file=sys.stderr,
        )
        return 2
    if args.data_dir and pcnt > 1:
        # A plain folder has no shard structure to stripe across hosts; the
        # multi-host real-data path is --data-shards (tar shards stripe
        # process-wise, the reference's per-rank slicing scaled to files —
        # test_distributed_sigmoid_loss.py:57-68).
        print(
            "--data-dir is a single-process flag; for multi-host real-data "
            "training pack the data as tar shards and use --data-shards "
            "(shards stripe across processes)",
            file=sys.stderr,
        )
        return 2
    if args.shuffle_buffer and not args.data_shards:
        print("--shuffle-buffer applies to --data-shards streams only "
              "(--data-dir already shuffles whole epochs)", file=sys.stderr)
        return 2
    if args.native_decode and not (args.data_dir or args.data_shards):
        print("--native-decode without --data-dir/--data-shards would be a "
              "silent no-op (synthetic data is not decoded)", file=sys.stderr)
        return 2
    # 0 = auto (cpu_count minus the prefetch/main threads); the host worker
    # pool for decode (file sources) / generation (native engine).
    from distributed_sigmoid_loss_tpu.data.workers import resolve_data_workers

    try:
        data_workers = resolve_data_workers(args.data_workers)
    except ValueError as e:
        print(f"--data-workers: {e}", file=sys.stderr)
        return 2
    # Resolved by the file-stream branch; read by the --eval-data holdout so
    # eval decode/tokenization matches training exactly.
    native_decode = False
    tokenize = None
    if args.data_dir or args.data_shards:
        from distributed_sigmoid_loss_tpu.data import (
            ImageTextFolder,
            ImageTextShards,
        )

        tokenize = _byte_tokenize_for(cfg, args.tokenizer)
        if args.native_decode:
            from distributed_sigmoid_loss_tpu.data.native_decode import (
                native_decode_available,
            )

            native_decode = native_decode_available()
            if not native_decode:
                print("--native-decode: libjpeg engine unavailable, "
                      "falling back to PIL decode", file=sys.stderr)
        if args.data_dir:
            source = ImageTextFolder(
                args.data_dir, cfg, args.batch, tokenize,
                native_decode=native_decode,
                data_workers=data_workers,
            )
        else:
            import glob as globmod

            shards = globmod.glob(args.data_shards)
            if not shards:
                print(f"--data-shards matched nothing: {args.data_shards!r}",
                      file=sys.stderr)
                return 2
            if pcnt > 1 and len(shards) < pcnt:
                print(
                    f"--data-shards matched {len(shards)} tar(s) for {pcnt} "
                    "processes; every process needs at least one shard in its "
                    "stripe",
                    file=sys.stderr,
                )
                return 2
            # Multi-process: each host reads its own shard stripe (i, i+N, ...)
            # and contributes batch/num_processes LOCAL rows per step; place()
            # assembles them into the global array with zero cross-host data
            # movement (global_batch_from_local).
            source = ImageTextShards(
                shards, cfg, args.batch // pcnt, tokenize,
                shard_index=pidx, num_shards=pcnt,
                native_decode=native_decode,
                shuffle_buffer=args.shuffle_buffer,
                data_workers=data_workers,
            )
    elif args.native_data:
        from distributed_sigmoid_loss_tpu.data import (
            NativeSyntheticImageText,
            native_available,
        )

        reason = "no C++ toolchain or prebuilt library"
        if native_available():
            try:
                source = NativeSyntheticImageText(
                    cfg, args.batch, num_threads=data_workers
                )
            except (RuntimeError, OSError) as e:
                # available() can't foresee every build failure (old compiler,
                # read-only install dir); the flag promises a fallback either way.
                reason = f"engine unusable: {e}"
        if source is None:
            print(
                f"--native-data: {reason}; falling back to the numpy pipeline",
                file=sys.stderr,
            )
    if source is None:
        source = SyntheticImageText(cfg, args.batch)
    data = iter(source)
    first = next(data)

    # When resuming, the freshly-created state is only train_resilient's
    # restore target — zeros=True skips the (minutes-long on b16-class towers)
    # random init that the checkpoint would immediately overwrite.
    resuming = bool(args.ckpt_dir) and latest_step(args.ckpt_dir) is not None
    pp_micro = 0
    if args.pp > 1:
        # Default microbatch count 2x stages: enough to keep the bubble
        # fraction (S-1)/(S+M-1) under a third without shrinking per-call work.
        pp_micro = args.pp_microbatches or 2 * args.pp
    if args.grad_compression and pp_micro:
        # Fail the batch-split arithmetic HERE (exit 2), not as a traceback
        # inside the first step trace after the minutes-long state init: the
        # compressed+pp step needs global batch = (dcn*dp) x accum x
        # pp-microbatch rows.
        from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis as _dax

        groups = mesh.shape["dcn"] * mesh.shape[_dax]
        ok = args.batch % groups == 0
        local = args.batch // groups if ok else 0
        ok = ok and local % args.accum == 0
        micro_rows = local // args.accum if ok else 0
        if not ok or micro_rows % pp_micro:
            print(
                f"--grad-compression with --pp: global batch {args.batch} "
                f"must divide as (dcn*dp = {groups}) x accum = {args.accum} "
                f"x pp-microbatches = {pp_micro}; "
                f"need batch % {groups * args.accum * pp_micro} == 0",
                file=sys.stderr,
            )
            return 2
    state = create_train_state(
        jax.random.key(0), model, tx, first, mesh,
        update_sharding=update_mode,
        ema=args.ema_decay is not None, zeros=resuming,
        pp_axis="pp" if args.pp > 1 else None,
    )
    # ONE resolution of the step kwargs shared by the compressed and regular
    # branches — a default (e.g. the 0.01 router-aux weight) edited in only
    # one branch would silently train a different objective per mode.
    moe_aux_w = (
        (0.01 if args.moe_aux_weight is None else args.moe_aux_weight)
        if args.moe_experts
        else None
    )
    gradcache_dt = "bfloat16" if args.gradcache_bf16 else None
    if args.grad_compression:
        from distributed_sigmoid_loss_tpu.train import (
            make_compressed_train_step,
            with_adaptive_compression,
            with_error_feedback,
        )

        # ef (and the adaptive carry) ride the live state only; checkpoints never include them (checkpoint._strip_ef), so compressed and plain runs share one checkpoint structure.
        if args.grad_compression in ("adaptive", "learned"):
            state = with_adaptive_compression(
                state, mesh, update_sharding=update_mode,
                learned=args.grad_compression == "learned",
            )
        else:
            state = with_error_feedback(
                state, mesh, pp_axis="pp" if args.pp > 1 else None,
                update_sharding=update_mode,
            )
        try:
            step_fn, shardings = make_compressed_train_step(
                model,
                mesh,
                LossConfig(variant="all_gather", family=args.loss_family,
                           precision="default", loss_impl=args.loss_impl,
                           use_pallas=args.use_pallas),
                update_sharding=update_mode,
                compression=args.grad_compression,
                topk_frac=args.topk_frac,
                topk_approximate=not args.topk_exact,
                accum_steps=args.accum,
                accum_dtype="bfloat16" if args.accum_bf16 else None,
                accum_negatives=args.accum_negatives,
                gradcache_embed_dtype=gradcache_dt,
                pp_microbatches=pp_micro,
                moe_aux_weight=moe_aux_w,
            )
        except ValueError as e:
            # Tower/pp constraints (scan_layers, depth % stages, ...) surface
            # as exit-2 config errors, not tracebacks — same contract as the
            # regular --pp path's validate_pp_tower handling.
            print(f"--grad-compression with --pp {args.pp}: {e}",
                  file=sys.stderr)
            return 2
        if args.grad_compression in ("adaptive", "learned"):
            # Host-side bit controller around the jitted step: stage the
            # scheme table (a value change of a donated replicated operand —
            # never a recompile), time the step, fold (duration, reported
            # wire bytes) into the bandwidth EWMA, and re-decide from the
            # step's per-tensor stats. Without emulation the step duration
            # upper-bounds the sync duration, so the EWMA UNDER-estimates
            # bandwidth — conservative narrowing, never optimistic widening;
            # under --emu-dcn-mbps the payload actually crosses the throttled
            # pipe and the EWMA tracks MEASURED transfer time. Wrapping
            # step_fn keeps one wiring for both the resilient and plain
            # loops below.
            import atexit as _atexit
            import time as _time

            import numpy as _np

            from distributed_sigmoid_loss_tpu.parallel.adaptive_compression import (
                BitController,
                CodecTrainer,
                leaf_sizes,
            )
            from distributed_sigmoid_loss_tpu.train import (
                stage_codec,
                stage_scheme,
            )

            if update_mode == "full":
                # The wire carries the dp reduce-scattered 1/W shard per
                # tensor, so the controller's payload tables (its bandwidth
                # arithmetic) must be sized to the shard, not the tensor.
                from distributed_sigmoid_loss_tpu.parallel.mesh import (
                    data_axis as _dax,
                )
                from distributed_sigmoid_loss_tpu.parallel.update_shard import (
                    shard_leaf_sizes,
                )

                controller_sizes = shard_leaf_sizes(
                    state.params, dict(mesh.shape)[_dax]
                )
            else:
                controller_sizes = leaf_sizes(state.params)
            learned_mode = args.grad_compression == "learned"
            n_dcn = dict(mesh.shape)["dcn"]
            controller = BitController(
                controller_sizes,
                n_dcn=n_dcn,
                topk_frac=args.topk_frac,
                dcn_budget_mbps=args.dcn_budget_mbps,
                controller=args.controller or "greedy",
                learned=learned_mode,
            )
            codec_trainer = CodecTrainer() if learned_mode else None
            emulator = None
            bf16_ref_dt = None
            if args.emu_dcn_mbps is not None:
                from distributed_sigmoid_loss_tpu.parallel.dcn_emu import (
                    DCNEmulator,
                )

                emulator = DCNEmulator(args.emu_dcn_mbps).start()
                _atexit.register(emulator.close)
                # The fixed-bf16 reference payload the wall-clock ratio
                # compares against: the same (n_dcn-1)-hop egress at 2
                # bytes/param, measured through the SAME pipe so the ratio is
                # wire time vs wire time, not model vs measurement.
                bf16_ref_bytes = (n_dcn - 1) * 2 * int(sum(controller_sizes))
            compiled_step = step_fn

            def step_fn(st, batch):
                nonlocal bf16_ref_dt
                st = stage_scheme(st, controller.scheme, mesh)
                t0 = _time.perf_counter()
                st, metrics = compiled_step(st, batch)
                wire = float(metrics["dcn_wire_bytes"])  # blocks on the step
                step_dt = _time.perf_counter() - t0
                metrics = dict(metrics)
                if emulator is None:
                    controller.observe(step_dt, wire)
                else:
                    transfer_dt = emulator.transfer(wire)
                    controller.observe(transfer_dt, wire)
                    # Re-measure the bf16 reference occasionally (every
                    # transfer for the first few, then EWMA holds) so the
                    # ratio tracks the live pipe, not a stale calibration.
                    if bf16_ref_dt is None or emulator.transfers <= 8:
                        ref = emulator.transfer(bf16_ref_bytes)
                        bf16_ref_dt = ref if bf16_ref_dt is None else (
                            0.5 * ref + 0.5 * bf16_ref_dt
                        )
                    metrics["dcn_measured_mbps"] = (
                        emulator.measured_mbps or 0.0
                    )
                    metrics["wire_savings_wallclock_ratio"] = (
                        (step_dt + bf16_ref_dt) / (step_dt + transfer_dt)
                    )
                controller.decide(
                    _np.asarray(st.comp["ef_ratio"]),
                    gnorm=_np.asarray(st.comp["gnorm"]),
                    gvar=_np.asarray(st.comp["gvar"]),
                )
                if codec_trainer is not None:
                    # Host-side codec training from the step's block second
                    # moments; staging new codec weights is a value change of
                    # a replicated operand — never a recompile.
                    new_codec = codec_trainer.update(
                        _np.asarray(st.comp["blockmoment"])
                    )
                    if codec_trainer.rounds >= codec_trainer.warmup_rounds:
                        st = stage_codec(st, new_codec, mesh)
                metrics["dcn_bw_est_mbps"] = controller.bw_est_mbps or 0.0
                metrics["controller_mode"] = controller.mode
                metrics["error_budget"] = float(controller.last_error_budget)
                return st, metrics
    else:
        # --loss-impl chunked is an all_gather memory shape; an unset --variant
        # follows it (same convention as --grad-compression selecting
        # all_gather) — an EXPLICIT ring was already refused above.
        variant = args.variant or (
            "all_gather" if args.loss_impl == "chunked" else "ring"
        )
        step_fn, shardings = make_train_step(
            model,
            mesh,
            LossConfig(variant=variant,
                       family=args.loss_family, precision="default",
                       loss_impl=args.loss_impl,
                       ring_overlap=args.ring_overlap,
                       use_pallas=args.use_pallas),
            accum_steps=args.accum,
            accum_negatives=args.accum_negatives,
            accum_dtype="bfloat16" if args.accum_bf16 else None,
            gradcache_embed_dtype=gradcache_dt,
            update_sharding=update_mode,
            ema_decay=args.ema_decay,
            moe_aux_weight=moe_aux_w,
            pp_microbatches=pp_micro,
        )

    # graftscope wiring: schema-validated metrics lines, host spans (enabled
    # only under --obs-dir — disabled spans are the allocation-free no-op),
    # the health watchdog, and the always-on flight recorder.
    from distributed_sigmoid_loss_tpu.obs import (
        FlightRecorder,
        HealthWatchdog,
        SpanRecorder,
    )
    from distributed_sigmoid_loss_tpu.obs.metrics_schema import (
        HEALTH_EVENT_FIELDS,
        TRAIN_METRICS_FIELDS,
        TRAIN_METRICS_PREFIXES,
    )

    logger = MetricsLogger(
        every=args.log_every,
        schema=TRAIN_METRICS_FIELDS,
        schema_prefixes=TRAIN_METRICS_PREFIXES,
    )
    if args.obs_dir:
        os.makedirs(args.obs_dir, exist_ok=True)
    spans = SpanRecorder(enabled=bool(args.obs_dir))
    flight = FlightRecorder(
        path=os.path.join(args.obs_dir, "flight.json") if args.obs_dir
        else None
    )
    watchdog = (
        None if args.watchdog == "off"
        else HealthWatchdog(policy="warn" if args.watchdog == "warn" else "skip")
    )

    # Static attribution of THE step that will run (obs/attribution.py):
    # trace-only — seconds, no compile, chip-free — so every metrics line
    # carries mfu_est + comm_bytes_total even when no chip ever materializes.
    att_fields = {}
    try:
        from distributed_sigmoid_loss_tpu.obs.attribution import (
            metrics_line_fields,
            static_attribution,
        )

        abstract_batch = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), first
        )
        att_fields = metrics_line_fields(
            static_attribution(step_fn, state, abstract_batch),
            device_kind=jax.devices()[0].device_kind,
        )
        print(
            "obs attribution: "
            + " ".join(f"{k}={v}" for k, v in sorted(att_fields.items())),
            file=sys.stderr,
        )
    except Exception as e:  # noqa: BLE001 — attribution must never kill a run
        print(f"WARNING: static attribution failed ({type(e).__name__}: {e}); "
              "metrics lines will not carry mfu_est/comm_bytes_total",
              file=sys.stderr)

    # graftshard placement fields on every metrics line: the mode plus the
    # measured at-rest optimizer bytes per replica (compiler accounting, the
    # same figure bench records) — so a training-run JSONL alone shows the
    # W× shard saving without a separate bench invocation.
    upd_fields = {}
    if update_mode != "off":
        from distributed_sigmoid_loss_tpu.parallel.update_shard import (
            opt_mem_bytes_per_replica,
        )

        upd_fields["update_sharding"] = update_mode
        _opt_mem = opt_mem_bytes_per_replica(state.opt_state)
        if _opt_mem is not None:
            upd_fields["opt_mem_bytes_per_replica"] = _opt_mem

    # Striped-shard sources already yield this host's LOCAL rows (batch/pcnt
    # each); synthetic sources yield the same deterministic GLOBAL batch on
    # every host, which place() slices process-wise.
    rows_are_local = pcnt > 1 and bool(args.data_shards)

    # The batch dim's mesh axes: ("dcn", dp) under --dcn-slices (the
    # compressed step shards rows over BOTH; P("dp") alone would declare the
    # dp blocks replicated over dcn and mis-assemble multi-host stripes).
    from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis as _da

    batch_axes = ("dcn", _da) if args.dcn_slices > 1 else _da

    def place_global(b):
        # Reference-style full-batch-then-slice (test_distributed_sigmoid_loss.py:
        # 57-68): every host holds the same global batch and contributes the
        # process-order slice its own devices hold.
        if pcnt == 1:
            return jax.device_put(b, shardings)
        import numpy as np

        local = jax.tree.map(
            lambda x: np.asarray(x).reshape(
                pcnt, x.shape[0] // pcnt, *x.shape[1:]
            )[pidx],
            b,
        )
        return global_batch_from_local(local, mesh, axis_name=batch_axes)

    def place(b):
        if pcnt > 1 and rows_are_local:
            return global_batch_from_local(b, mesh, axis_name=batch_axes)
        return place_global(b)

    def host_batches(skip: int = 0):
        # The synthetic pipeline is deterministic per position: on resume, skip
        # the batches the checkpointed steps already consumed so the resumed run
        # sees the same stream an uninterrupted run would.
        if skip == 0:
            yield first
        for i, b in enumerate(data, start=1):
            if i >= skip:
                yield b

    # Device feeding goes through data.prefetch: a worker thread keeps host
    # fetch + decode + host->device commit one batch ahead of the step, and
    # the stats object turns device starvation into a NUMBER — every train
    # log line carries input_wait_frac (~0 = the host keeps up; positive =
    # the fraction of wall time the device sat waiting on input).
    from distributed_sigmoid_loss_tpu.data import PrefetchStats, prefetch as _prefetch

    input_stats = PrefetchStats()

    def place_spanned(b):
        # h2d-commit runs on the prefetch worker thread; the span lands on
        # its own track of the host timeline (SpanRecorder is thread-safe).
        with spans.span("h2d_commit"):
            return place(b)

    def device_batches(skip: int = 0):
        return _prefetch(
            host_batches(skip), mesh, size=2,
            put=lambda b, m, a: place_spanned(b), stats=input_stats,
        )

    # Soak-run telemetry (graftledger): under --obs-dir the latest metrics
    # line is ALSO mirrored into DIR/telemetry.json via atomic rename each
    # log interval — tail the run's live state without parsing (or racing)
    # the metrics log stream.
    telemetry_env = None
    if args.obs_dir:
        from distributed_sigmoid_loss_tpu.obs.ledger import (
            environment_fingerprint,
        )

        telemetry_env = environment_fingerprint()

    def write_telemetry(step_i, line):
        if not args.obs_dir or step_i % args.log_every:
            return
        import time as _time

        from distributed_sigmoid_loss_tpu.obs.telemetry import (
            write_telemetry_file,
        )

        try:
            write_telemetry_file(
                os.path.join(args.obs_dir, "telemetry.json"),
                {"step": step_i, "ts": round(_time.time(), 3),
                 "metrics": line, "env": telemetry_env},
            )
        except OSError as e:  # telemetry must never kill a training run
            print(f"WARNING: telemetry write failed: {e}", file=sys.stderr)

    def log_metrics(step_i, m):
        # Most metrics are device scalars; compression_scheme_hist is a small
        # per-scheme count vector — serialized as a list so the JSONL line
        # stays one self-describing record.
        def as_jsonable(v):
            try:
                return float(v)
            except TypeError:
                return [float(x) for x in v]

        line = {
            **{k: as_jsonable(v) for k, v in m.items()},
            "input_wait_frac": input_stats.input_wait_frac(),
            **att_fields,
            **upd_fields,
        }
        if watchdog is not None:
            for ev in watchdog.observe(step_i, line):
                flight.note_event(ev)
                logger.write(ev.record(), schema=HEALTH_EVENT_FIELDS)
        flight.note_metrics(step_i, line)
        logger.log(step_i, line)
        write_telemetry(step_i, line)

    eval_hook = None
    if args.eval_every:
        from distributed_sigmoid_loss_tpu.eval import retrieval_metrics as _rm

        # ONE fixed batch for every in-training eval: the curve then measures
        # the model, not data drift. It must NOT be drawn from the live
        # training iterator: that would shift every subsequent stream
        # position, so a resume with a different --eval-every would silently
        # train on a different stream than the original run (breaking
        # device_batches' skip arithmetic). Synthetic runs get a genuinely
        # held-out source (shifted seeds); file/native streams use the
        # --eval-data holdout when given and otherwise fall back to the
        # already-drawn position-0 batch (disclosed: that curve partially
        # measures train-set fit).
        if args.eval_data:
            try:
                # A too-small holdout surfaces as a loader ValueError — at
                # construction for the directory source, at first draw for
                # shards: usage error, not a traceback. place_global stays
                # OUTSIDE the try — its sharding errors are batch/topology
                # mistakes, not --eval-data's fault.
                holdout = _eval_holdout_source(
                    args, cfg,
                    tokenize or _byte_tokenize_for(cfg, args.tokenizer),
                    native_decode=native_decode,
                )
                eval_first = next(iter(holdout))
            except ValueError as e:
                print(f"--eval-data: {e}", file=sys.stderr)
                return 2
            eval_batch = place_global(eval_first)
        elif isinstance(source, SyntheticImageText):
            eval_batch = place(
                next(iter(SyntheticImageText(
                    cfg, args.batch, image_seed=43, text_seed=41
                )))
            )
        else:
            print(
                "--eval-every without --eval-data on a file/native stream: "
                "the fixed eval batch is the position-0 TRAINING batch, so "
                "the curve partially measures train-set fit — pass "
                "--eval-data with held-out shards or a directory for a true "
                "validation curve",
                file=sys.stderr,
            )
            eval_batch = place(first)
        # Jitted once: the hook runs repeatedly inside the train loop, where
        # an eager per-op forward would dominate wall time on real models.
        eval_fwd = jax.jit(
            lambda p, im, tk: model.apply({"params": p}, im, tk)[:2]
        )

        def eval_hook(step_i, st):
            zi, zt = eval_fwd(
                st.params, eval_batch["images"], eval_batch["tokens"]
            )
            rm = _rm(zi, zt, mesh=mesh, ks=(1, 5))
            # force: eval steps are out-of-band of --log-every (and must not
            # touch the steps/sec clock).
            logger.log(
                step_i, {f"eval/{k}": float(v) for k, v in rm.items()},
                force=True,
            )

    if args.ckpt_dir and args.tokenizer:
        # Stash the vocab with the checkpoints: eval auto-loads it, so restored
        # models never silently tokenize with a different vocab than training.
        import shutil

        os.makedirs(args.ckpt_dir, exist_ok=True)
        stash = os.path.join(args.ckpt_dir, "tokenizer.json")
        if os.path.abspath(args.tokenizer) != os.path.abspath(stash):
            shutil.copyfile(args.tokenizer, stash)
    if args.ckpt_dir:
        # Preemption-safe resilient loop: resumes from the newest checkpoint in
        # --ckpt-dir, saves every --ckpt-every steps and on SIGTERM, rolls back
        # on a non-finite loss.
        skip = latest_step(args.ckpt_dir) or 0
        import contextlib

        from distributed_sigmoid_loss_tpu.train import AsyncSaver

        saver_ctx = AsyncSaver() if args.async_checkpoint else contextlib.nullcontext()
        stream = device_batches(skip)
        with PreemptionGuard() as guard, saver_ctx as saver:
            try:
                state, report = train_resilient(
                    state,
                    step_fn,
                    stream,
                    total_steps=args.steps,
                    ckpt_dir=args.ckpt_dir,
                    ckpt_every=args.ckpt_every,
                    guard=guard,
                    saver=saver,
                    # The state was built with zeros=True on the promise that
                    # train_resilient's restore overwrites it; if the
                    # checkpoint vanished between latest_step() and restore,
                    # refuse (BEFORE any step runs) to train from all-zero
                    # params and overwrite --ckpt-dir with garbage.
                    require_restore=resuming,
                    on_metrics=log_metrics,
                    eval_every=args.eval_every,
                    on_eval=eval_hook,
                    # --watchdog skip routes a non-finite loss into the
                    # rollback-and-skip path instead of the halting raise;
                    # either way the flight recorder dumps the trajectory.
                    on_divergence="skip" if args.watchdog == "skip" else "halt",
                    spans=spans,
                    flight=flight,
                )
            except RestoreRequiredError as e:
                print(f"--ckpt-dir {args.ckpt_dir}: {e}", file=sys.stderr)
                return 1
            finally:
                # Join the prefetch worker BEFORE anything else reads `data`:
                # after close the source iterator has no concurrent reader.
                stream.close()
        print(
            f"resilient loop: steps {report.start_step}->{report.final_step}, "
            f"checkpoints at {report.checkpoints}"
            + (" (preempted)" if report.preempted else ""),
            file=sys.stderr,
        )
    else:
        # 1-based step numbers, matching train_resilient's on_metrics contract.
        stream = device_batches()
        i = 0  # the crash dump below must name a step even if fetch 1 dies
        try:
            for i, batch in zip(range(1, args.steps + 1), stream):
                with spans.span("step"):
                    state, metrics = step_fn(state, batch)
                log_metrics(i, metrics)
                if eval_hook is not None and i % args.eval_every == 0:
                    with spans.span("eval"):
                        eval_hook(i, state)
        except BaseException as e:
            # Same black-box contract as the resilient loop: a crash leaves
            # the last-N trajectory behind, not just a traceback.
            flight.dump(f"crash at step {i}: {type(e).__name__}: {e}")
            raise
        finally:
            stream.close()  # joins the worker; `data` is single-reader again

    if args.obs_dir:
        spans_path = os.path.join(args.obs_dir, "host_spans.trace.json")
        spans.export(spans_path)
        print(f"obs: host spans -> {spans_path} "
              f"({len(spans.spans())} spans retained; summarize with "
              f"`python -m distributed_sigmoid_loss_tpu obs summarize "
              f"{args.obs_dir}`)", file=sys.stderr)

    # Zero-shot retrieval on a held-out synthetic batch (the model normalizes
    # its embeddings already).
    from distributed_sigmoid_loss_tpu.eval import retrieval_metrics

    held_out = place(next(iter(data)))
    zimg, ztxt, _ = model.apply(
        {"params": state.params}, held_out["images"], held_out["tokens"]
    )
    rm = retrieval_metrics(zimg, ztxt, mesh=mesh, ks=(1, 5))
    print({k: round(float(v), 4) for k, v in rm.items()}, file=sys.stderr)
    return 0


def cmd_eval(args) -> int:
    _bootstrap_devices(args)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_sigmoid_loss_tpu.data import SyntheticImageText, put_batch
    from distributed_sigmoid_loss_tpu.eval import (
        retrieval_metrics,
        zeroshot_metrics,
    )
    from distributed_sigmoid_loss_tpu.models import SigLIP
    from distributed_sigmoid_loss_tpu.parallel.mesh import make_mesh
    from distributed_sigmoid_loss_tpu.train import init_params

    if args.ema and not args.ckpt_dir:
        print(
            "--ema requires --ckpt-dir (EMA weights live in a train checkpoint; "
            "a fresh model has none)",
            file=sys.stderr,
        )
        return 2
    cfg = _model_config(args)
    if args.ckpt_dir:
        # Use the vocab stashed by `train --tokenizer` unless the user overrode
        # it — silently tokenizing with a different vocab than training makes
        # the metrics garbage with no error.
        stashed = os.path.join(args.ckpt_dir, "tokenizer.json")
        if os.path.exists(stashed):
            if not args.tokenizer:
                args.tokenizer = stashed
                print(f"using checkpoint tokenizer {stashed}", file=sys.stderr)
            elif os.path.abspath(args.tokenizer) != os.path.abspath(stashed):
                import json as jsonmod

                with open(args.tokenizer) as f1, open(stashed) as f2:
                    if jsonmod.load(f1) != jsonmod.load(f2):
                        print(
                            f"WARNING: --tokenizer {args.tokenizer} differs "
                            f"from the checkpoint's stashed vocab {stashed}; "
                            "token ids will not match training",
                            file=sys.stderr,
                        )
    mesh = make_mesh()
    model = SigLIP(cfg)

    captions = None
    if args.data_dir and args.data_shards:
        print("--data-dir and --data-shards are mutually exclusive",
              file=sys.stderr)
        return 2
    if args.data_dir or args.data_shards:
        # Real pairs through the SAME loaders train uses; captions ride along
        # as the zero-shot class names (see below).
        from distributed_sigmoid_loss_tpu.data import (
            ImageTextFolder,
            ImageTextShards,
        )

        tokenize = _byte_tokenize_for(cfg, args.tokenizer)
        if args.data_dir:
            source = ImageTextFolder(
                args.data_dir, cfg, args.batch, tokenize, keep_captions=True
            )
        else:
            import glob as globmod

            shards = globmod.glob(args.data_shards)
            if not shards:
                print(f"--data-shards matched nothing: {args.data_shards!r}",
                      file=sys.stderr)
                return 2
            source = ImageTextShards(
                shards, cfg, args.batch, tokenize, keep_captions=True
            )
        batch = next(iter(source))
        captions = batch.pop("captions")
    else:
        batch = next(
            iter(SyntheticImageText(cfg, args.batch, image_seed=7, text_seed=9))
        )
    if args.ckpt_dir:
        # Train writes step-numbered checkpoints of the FULL train state; restore
        # the newest one into a matching structure (optimizer slots are needed
        # only as the restore target) and keep the params. Checkpoints written
        # with --ema-decay carry an extra `ema` subtree — the restore target must
        # match, so retry with an EMA-shaped state when the bare one mismatches.
        from distributed_sigmoid_loss_tpu.train import (
            create_train_state,
            make_optimizer,
            restore_latest,
        )
        from distributed_sigmoid_loss_tpu.utils.config import TrainConfig

        # The restore target's opt_state tree must match the checkpoint's
        # optimizer family — lion has one momentum slot, adafactor factored
        # moments (orbax restore is structure-strict).
        tx = make_optimizer(TrainConfig(optimizer=args.optimizer))
        # zeros=True: the state is only a restore TARGET (structure + shapes +
        # shardings); running the real random init here costs minutes of host
        # RNG on b16-class towers before the checkpoint overwrites every leaf.
        state = create_train_state(
            jax.random.key(0), model, tx, batch, mesh, ema=args.ema, zeros=True
        )
        try:
            restored = restore_latest(args.ckpt_dir, state)
        except Exception as first_err:
            # The checkpoint's EMA-shapedness may differ from the request; retry
            # with the other target shape. If that fails too, the problem is NOT
            # EMA (wrong --model, corrupt checkpoint, ...) — surface the
            # ORIGINAL error rather than guessing from message text.
            try:
                alt = create_train_state(
                    jax.random.key(0), model, tx, batch, mesh,
                    ema=not args.ema, zeros=True,
                )
                restored = restore_latest(args.ckpt_dir, alt)
            except Exception:
                raise first_err
            if args.ema:
                # The bare-shaped retry succeeded: the checkpoint has no EMA.
                print(
                    f"--ema requested but the checkpoint at {args.ckpt_dir} has "
                    f"no EMA weights (train with --ema-decay)",
                    file=sys.stderr,
                )
                return 2
        if restored is None:
            print(f"no checkpoint found under {args.ckpt_dir}", file=sys.stderr)
            return 2
        state, step = restored
        which = "ema" if args.ema else "params"
        print(f"restored step {step} ({which}) from {args.ckpt_dir}", file=sys.stderr)
        params = state.ema if args.ema else state.params
    else:
        # Forward-only eval of a fresh model: params only, no optimizer slots.
        params = init_params(jax.random.key(0), model, batch, mesh)

    batch = put_batch(batch, mesh)
    zimg, ztxt, _ = model.apply({"params": params}, batch["images"], batch["tokens"])
    out = {
        k: round(float(v), 4)
        for k, v in retrieval_metrics(zimg, ztxt, mesh=mesh, ks=(1, 5)).items()
    }

    # Zero-shot classification demo: class prompts through the byte tokenizer and
    # text tower -> prompt-ensembled classifier; synthetic integer labels.
    from functools import partial

    from distributed_sigmoid_loss_tpu.eval import build_classifier

    tokenize = _byte_tokenize_for(cfg, args.tokenizer)
    if captions is not None:
        # Real data: the batch's distinct captions ARE the label space — each
        # image's true class is its own caption (caption-matching zero-shot, the
        # standard retrieval-as-classification eval when no label set exists).
        class_names = sorted(set(captions))
        n_classes = len(class_names)
        class_index = {c: i for i, c in enumerate(class_names)}
        label_values = np.asarray([class_index[c] for c in captions], np.int32)
    else:
        n_classes = args.classes
        class_names = [f"c{c}" for c in range(n_classes)]
        # Class name first: short context lengths (tiny config: 8 tokens) would
        # truncate a trailing class name out of every prompt, collapsing all
        # classes onto identical token rows.
        rng = np.random.default_rng(0)
        label_values = rng.integers(0, n_classes, zimg.shape[0]).astype(np.int32)

    classifier = build_classifier(
        partial(model.apply, {"params": params}, method=SigLIP.encode_text),
        class_names,
        tokenize,
        cfg.text.context_length,
        templates=("{} photo.", "{} image."),
    )
    labels = put_batch(jnp.asarray(label_values), mesh)
    ks = tuple(k for k in (1, 5) if k <= n_classes)
    zs = zeroshot_metrics(zimg, classifier, labels, mesh=mesh, ks=ks)
    out.update({f"zeroshot_{k}": round(float(v), 4) for k, v in zs.items()})
    print(out)
    return 0


def cmd_export(args) -> int:
    """AOT-export a lowered step (train or forward) to a StableHLO artifact.

    The artifact replays with ``jax.export.deserialize(...).call(...)`` on a
    matching device topology — no model code needed at load time. ``--check``
    reloads the written file and replays one step against the live jitted step.
    """
    _bootstrap_devices(args)
    import jax
    import jax.numpy as jnp
    import numpy as np

    from distributed_sigmoid_loss_tpu.data import SyntheticImageText
    from distributed_sigmoid_loss_tpu.models import SigLIP
    from distributed_sigmoid_loss_tpu.train import (
        create_train_state,
        export_step,
        load_exported,
        make_optimizer,
        make_train_step,
        save_exported,
    )
    from distributed_sigmoid_loss_tpu.utils.config import LossConfig, TrainConfig

    if args.quant and args.what == "train_step":
        print(
            "--quant is inference-only (zero gradients through round); "
            "use it with --what forward",
            file=sys.stderr,
        )
        return 2
    cfg = _model_config(args)
    if args.loss_family != "sigmoid":
        import dataclasses

        # Same family wiring as train: the model's t_prime init follows it.
        cfg = dataclasses.replace(cfg, loss=LossConfig(family=args.loss_family))
    model = SigLIP(cfg)
    n_dev = len(jax.devices())
    if args.what == "forward" and args.ep > 1:
        # The forward export takes freshly-init'd (unsharded) params and never
        # touches the mesh; silently accepting --ep would emit a 1-device
        # program while the flags promise an expert-parallel one.
        print("--ep applies to --what train_step only (the forward export is "
              "a single-device inference program)", file=sys.stderr)
        return 2
    mesh, mesh_err = _make_training_mesh(args)  # same topology rules as train
    if mesh_err:
        print(mesh_err, file=sys.stderr)
        return 2

    b = args.batch
    batch = next(iter(SyntheticImageText(cfg, b)))

    if args.what == "train_step":
        # The schedule + aux weight are baked into the artifact — export the
        # values the deployed job will actually train with (--lr etc.).
        tx = make_optimizer(
            TrainConfig(
                learning_rate=args.lr,
                warmup_steps=args.warmup_steps,
                total_steps=args.total_steps,
            )
        )
        state = create_train_state(jax.random.key(0), model, tx, batch, mesh)
        moe_aux = args.moe_aux_weight if args.moe_experts else None
        step, shardings = make_train_step(
            model, mesh,
            LossConfig(variant=args.variant, family=args.loss_family),
            moe_aux_weight=moe_aux,
        )
        batch = jax.device_put(batch, shardings)
        example = (state, batch)
        fn = step
    else:  # forward
        from flax import linen as nn

        params = nn.meta.unbox(
            model.init(jax.random.key(0), batch["images"], batch["tokens"])[
                "params"
            ]
        )

        def fn(params, images, tokens):
            zimg, ztxt, _ = model.apply({"params": params}, images, tokens)
            return zimg, ztxt

        example = (params, batch["images"], batch["tokens"])

    platforms = (args.platform,) if args.platform else None
    exported = export_step(fn, example, platforms=platforms)
    save_exported(args.out, exported)
    size = os.path.getsize(args.out)
    model_name = "tiny" if args.tiny else args.model
    print(
        f"exported {args.what} ({model_name}, batch {b}, {n_dev} device(s)) "
        f"-> {args.out} ({size} bytes)"
    )

    if args.check:
        if args.platform and args.platform != jax.default_backend():
            print(
                f"--check skipped: artifact targets {args.platform!r}, current "
                f"backend is {jax.default_backend()!r}",
                file=sys.stderr,
            )
            return 0
        loaded = load_exported(args.out)
        # Flat calling convention (see train/export.py); the live train step
        # donates its state argument, so replay the artifact on copies first.
        got = loaded.call(*jax.tree.leaves(jax.tree.map(jnp.copy, example)))
        want = fn(*example)
        want_leaves = jax.tree.leaves(want)
        assert len(want_leaves) == len(got)
        for w, g in zip(want_leaves, got):
            np.testing.assert_allclose(
                np.asarray(w), np.asarray(g), rtol=1e-5, atol=1e-6
            )
        print("check ok: reloaded artifact replays identically")
    return 0


def cmd_bench(extra: list[str]) -> int:
    if any(a == "--cpu-devices" or a.startswith("--cpu-devices=") for a in extra):
        print(
            "bench runs on the real chip only (emulated-mesh throughput would be "
            "meaningless); use `train --cpu-devices N` for CPU-mesh smoke runs",
            file=sys.stderr,
        )
        return 2
    # bench.py lives at the repo root (it is the driver's measured contract, not
    # package code); delegate when available.
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    bench = os.path.join(repo_root, "bench.py")
    if not os.path.exists(bench):
        print("bench.py not found (requires a repo checkout)", file=sys.stderr)
        return 2
    os.execv(sys.executable, [sys.executable, bench] + extra)


def _emit_serve_record(record: dict, *, strict_zero_drops: bool = False) -> int:
    """The serve-bench emit contract (shared by the snapshot and scenario
    paths): validate against the declared record schema, warn on stderr,
    never lose the measurement, append to the run ledger. With
    ``strict_zero_drops`` a non-zero ``silent_drops`` count fails the run —
    the chaos scenarios' every-outcome-is-typed acceptance gate."""
    import json

    from distributed_sigmoid_loss_tpu.analysis.bench_schema import (
        validate_record,
    )
    from distributed_sigmoid_loss_tpu.obs.ledger import append_record

    problems = validate_record(record)
    if problems:
        print("WARNING: serve-bench record schema violation: "
              + "; ".join(problems), file=sys.stderr)
    print(json.dumps(record))
    # graftledger: serve-bench/siege records join the same append-only
    # trajectory as the train headline (obs/ledger.py; never fatal).
    append_record(record, source="serve-bench", problems=problems)
    if strict_zero_drops and record.get("silent_drops"):
        print(
            f"WARNING: {record['silent_drops']} silent drop(s) — a request "
            "ended with neither a result nor a typed rejection; the "
            "degradation contract is broken",
            file=sys.stderr,
        )
        return 1
    return 0


def cmd_serve_bench(args) -> int:
    """Drive the serve/ stack on synthetic data with concurrent clients and
    print the ``stats()`` snapshot as one JSON record (bench.py style).

    The operational proof of the serving layer: with warmed buckets the
    printed ``compile_count`` equals ``bucket_space`` (the number of shape
    buckets) — NOT the request count — while concurrent clients coalesce into
    batched engine calls (see ``batch_size_hist``) and repeated content hits
    the cache (``cache.hit_rate``).
    """
    _bootstrap_devices(args)
    import concurrent.futures
    import threading
    import time

    import numpy as np

    from distributed_sigmoid_loss_tpu.data import SyntheticImageText
    from distributed_sigmoid_loss_tpu.models import SigLIP
    from distributed_sigmoid_loss_tpu.serve import (
        EmbeddingCache,
        EmbeddingService,
        InferenceEngine,
        QueueFullError,
        RequestTimeoutError,
        RetrievalRouter,
        SwapController,
    )
    from distributed_sigmoid_loss_tpu.utils.logging import MetricsLogger

    if args.requests < 1 or args.clients < 1:
        print("--requests and --clients must be >= 1", file=sys.stderr)
        return 2
    if args.swap_every < 0 or args.rerank_k < 0:
        print("--swap-every and --rerank-k must be >= 0", file=sys.stderr)
        return 2
    if args.index_tier == "sharded" and not args.mesh:
        print(
            "--index-tier sharded needs --mesh (the dp axis the corpus "
            "partitions over; pair with --cpu-devices N off-chip)",
            file=sys.stderr,
        )
        return 2
    try:
        buckets = tuple(int(b) for b in args.batch_buckets.split(","))
    except ValueError:
        print(f"--batch-buckets must be comma-separated ints, got "
              f"{args.batch_buckets!r}", file=sys.stderr)
        return 2

    if args.fleet_scenario and args.scenario:
        print("--fleet-scenario and --scenario are mutually exclusive (one "
              "drill per run)", file=sys.stderr)
        return 2
    if not args.fleet_scenario and (args.fleet_replicas or args.lease_ttl_s):
        print("--fleet-replicas/--lease-ttl-s only make sense with "
              "--fleet-scenario", file=sys.stderr)
        return 2
    if args.fleet_scenario and args.fleet_replicas and args.fleet_replicas < 2:
        print("--fleet-replicas must be >= 2 (with one replica there is no "
              "sibling to reroute to and no wave to order)", file=sys.stderr)
        return 2

    scenario_tenants = None
    if args.scenario or args.fleet_scenario:
        from distributed_sigmoid_loss_tpu.serve import parse_tenant_spec

        if args.duration_s <= 0 or args.offered_load <= 0 or args.capacity < 1:
            print("--duration-s/--offered-load must be > 0 and --capacity "
                  ">= 1", file=sys.stderr)
            return 2
        try:
            scenario_tenants = parse_tenant_spec(args.tenants)
        except ValueError as e:
            print(f"--tenants: {e}", file=sys.stderr)
            return 2

    if args.fleet_scenario:
        # Like the hostloss drill below: the fleet drill runs the leased
        # admission → router → EngineProcess stack with stdlib surrogate
        # workers, so it exercises the fleet-tier failure semantics (lease
        # reclaim, typed reroute, swap waves) without spinning up the
        # jitted stack. Over-admission is a hard failure: the split-brain
        # ceiling proof is only as good as its enforcement.
        from distributed_sigmoid_loss_tpu.serve import run_fleet_scenario

        record = run_fleet_scenario(
            args.fleet_scenario,
            replicas=args.fleet_replicas or 3,
            tenants=scenario_tenants,
            duration_s=args.duration_s,
            offered_load=args.offered_load,
            lease_ttl_s=args.lease_ttl_s or 0.5,
            seed=args.seed,
        )
        rc = _emit_serve_record(record, strict_zero_drops=True)
        if record.get("over_ceiling_samples"):
            print(
                f"WARNING: {record['over_ceiling_samples']} window sample(s) "
                "exceeded the global admission ceiling — the bounded-"
                "staleness lease invariant is broken",
                file=sys.stderr,
            )
            return 1
        return rc

    if args.scenario == "hostloss":
        # The host-loss drill runs the admission → batcher → EngineProcess
        # stack with the stdlib surrogate worker: it drills the SERVING
        # failure semantics (kill -9 mid-traffic, typed HostLostError to
        # every in-flight caller, measured recovery), not the model forward
        # — so it runs before the jitted stack spins up and the drill's
        # child process never imports jax.
        from distributed_sigmoid_loss_tpu.serve import hostloss_drill

        record = hostloss_drill(
            tenants=scenario_tenants,
            duration_s=args.duration_s,
            offered_load=args.offered_load,
            capacity=args.capacity,
            seed=args.seed,
        )
        return _emit_serve_record(record, strict_zero_drops=True)

    import jax
    from flax import linen as nn

    cfg = _model_config(args)
    model = SigLIP(cfg)
    mesh = None
    if args.mesh:
        from distributed_sigmoid_loss_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
        n_dev = len(jax.devices())
        if any(b % n_dev for b in buckets):
            print(
                f"--mesh: batch buckets {buckets} must all divide the device "
                f"count {n_dev} (every device holds whole rows)",
                file=sys.stderr,
            )
            return 2

    pool = max(args.pool, 1)
    source = iter(SyntheticImageText(cfg, pool, image_seed=args.seed + 1,
                                     text_seed=args.seed + 2))
    batch = next(source)
    pool_tokens = np.asarray(batch["tokens"])
    pool_images = np.asarray(batch["images"])

    params = nn.meta.unbox(
        model.init(jax.random.key(args.seed), pool_images[:1],
                   pool_tokens[:1])["params"]
    )
    engine = InferenceEngine.from_model(
        model, params, batch_buckets=buckets, mesh=mesh
    )
    t0 = time.perf_counter()
    warmed = engine.warmup()
    warmup_s = time.perf_counter() - t0
    print(
        f"warmed {warmed} shape buckets in {warmup_s:.1f}s "
        f"({args.model} model, {len(buckets)} batch buckets)",
        file=sys.stderr,
    )

    # Corpus embeddings straight through the engine (the service clock should
    # measure client traffic, not index build); chunked to the largest bucket.
    step = buckets[-1]
    corpus_rows = [
        engine.encode_image(pool_images[i : i + step])
        for i in range(0, min(args.index_size, pool), step)
    ]
    corpus_emb = np.concatenate(corpus_rows)
    router = RetrievalRouter(
        tier=args.index_tier,
        mesh=mesh if args.index_tier == "sharded" else None,
        rerank_k=args.rerank_k or None,
    )
    router.publish(corpus_emb)
    if args.index_tier == "sharded":
        # Warm the fan-out program off the clock — same discipline as the
        # engine's bucket warmup (the shard_map compiles once per query
        # bucket; client searches are single-query).
        router.search(corpus_emb[:1], k=args.topk)

    admission = None
    if args.scenario:
        from distributed_sigmoid_loss_tpu.serve import AdmissionController

        admission = AdmissionController(
            scenario_tenants, capacity=args.capacity
        )
    service = EmbeddingService(
        engine,
        cache=EmbeddingCache(args.cache_size),
        index=router,
        max_wait_ms=args.max_wait_ms,
        max_queue=args.max_queue,
        default_timeout=60.0,
        logger=MetricsLogger(),
        admission=admission,
    )
    if args.metrics_port >= 0:
        # Live pull-based telemetry DURING the bench: the OpenMetrics-style
        # /metrics endpoint (obs/telemetry.py) on a stdlib HTTP thread —
        # scrape it mid-run instead of waiting for the final JSON record.
        exporter = service.start_metrics_server(port=args.metrics_port)
        print(f"serve-bench: live /metrics at {exporter.url}",
              file=sys.stderr)

    if args.scenario:
        # Scenario soak: graftsiege's generator replaces the fixed-request
        # client loop — open-loop offered load shaped per scenario, real
        # engine underneath, admission at the front door. The degradation
        # record (p99 vs offered load, per-tenant shed_rate, recovery_time_s,
        # silent_drops) merges with the stats() snapshot; any silent drop
        # fails the run.
        from distributed_sigmoid_loss_tpu.serve import run_scenario

        swap_fn = None
        if args.scenario == "swapstorm":
            storm_controller = SwapController(engine, router)

            def swap_fn() -> None:
                storm_controller.swap(params=params, embeddings=corpus_emb)

        def submit(tenant: str, i: int, *, items: int = 1,
                   fresh: bool = False) -> None:
            if fresh:
                # Deterministic per-i cache-hostile row: always misses the
                # cache, so every admit reaches the batcher/engine.
                rng = np.random.default_rng(args.seed * 100003 + i)
                row = rng.integers(0, cfg.text.vocab_size,
                                   cfg.text.context_length, dtype=np.int32)
                service.encode_text(row, tenant=tenant, timeout=5.0)
            elif items > 1:
                rows = np.stack(
                    [pool_tokens[(i + j) % pool] for j in range(items)]
                )
                service.encode_text(rows, tenant=tenant, timeout=5.0)
            else:
                service.encode_text(pool_tokens[i % pool], tenant=tenant,
                                    timeout=5.0)

        scen = run_scenario(
            args.scenario,
            submit=submit,
            tenants=scenario_tenants,
            admission=admission,
            duration_s=args.duration_s,
            offered_load=args.offered_load,
            clients_per_tenant=args.clients,
            swap_fn=swap_fn,
            seed=args.seed,
        )
        snap = service.stats()
        service.close()
        record = {
            "model": args.model,
            "clients": args.clients,
            "batch_buckets": list(buckets),
            "max_wait_ms": args.max_wait_ms,
            "sharded": bool(mesh),
            "index_tier": args.index_tier,
            "swap_every": args.swap_every,
            "warmup_s": round(warmup_s, 2),
            **snap,
            **scen,
        }
        rc = _emit_serve_record(record, strict_zero_drops=True)
        # The steady-state compile gate holds under chaos too: shedding and
        # swap churn must not push any request off the warmed bucket grid.
        if snap["compile_count"] != warmed:
            print(
                f"WARNING: compile_count {snap['compile_count']} != warmed "
                f"buckets {warmed} — a request triggered a fresh compile",
                file=sys.stderr,
            )
            return 1
        return rc

    # --swap-every N churn: a swapper thread republishes the weights and
    # freshly built index segments after every N completed client ops —
    # the zero-downtime/zero-recompile contract exercised UNDER the same
    # traffic the bench measures (swap_count / swap_latency_ms land in the
    # record; the compile_count gate below still applies).
    ops_done = [0]
    swap_done = threading.Event()
    swap_thread = None
    if args.swap_every:
        controller = SwapController(engine, router)

        def swapper():
            next_at = args.swap_every
            while not swap_done.is_set():
                if ops_done[0] >= next_at:
                    controller.swap(params=params, embeddings=corpus_emb)
                    next_at += args.swap_every
                else:
                    swap_done.wait(0.002)

        swap_thread = threading.Thread(
            target=swapper, name="serve-bench-swapper", daemon=True
        )
        swap_thread.start()

    def client(cid: int, n_ops: int) -> None:
        rng = np.random.default_rng(args.seed * 1000 + cid)
        for _ in range(n_ops):
            op = rng.random()
            try:
                if op < 0.2:  # image encode from the shared pool (cacheable)
                    service.encode_image(pool_images[rng.integers(pool)])
                elif op < 0.4:  # retrieval query
                    service.search(pool_tokens[rng.integers(pool)], k=args.topk)
                elif op < 0.7:  # repeated text from the pool (cacheable)
                    service.encode_text(pool_tokens[rng.integers(pool)])
                else:  # fresh text (guaranteed cache miss → batcher/engine)
                    row = rng.integers(
                        0, cfg.text.vocab_size,
                        cfg.text.context_length, dtype=np.int32,
                    )
                    service.encode_text(row)
            except (QueueFullError, RequestTimeoutError):
                pass  # shed/missed requests are counted in service.stats()
            ops_done[0] += 1

    per_client = [args.requests // args.clients] * args.clients
    for i in range(args.requests % args.clients):
        per_client[i] += 1
    with concurrent.futures.ThreadPoolExecutor(args.clients) as pool_ex:
        list(pool_ex.map(client, range(args.clients), per_client))
    if swap_thread is not None:
        swap_done.set()
        swap_thread.join(timeout=60)

    snap = service.stats()
    service.close()
    record = {
        "metric": "serve_bench",
        "value": snap["qps"],
        "unit": "req/s",
        "model": args.model,
        "clients": args.clients,
        "requests_sent": args.requests,
        "batch_buckets": list(buckets),
        "max_wait_ms": args.max_wait_ms,
        "sharded": bool(mesh),
        "index_tier": args.index_tier,
        "swap_every": args.swap_every,
        "warmup_s": round(warmup_s, 2),
        **snap,
    }
    rc = _emit_serve_record(record)
    # Steady-state contract: every compile happened at warmup — one per shape
    # bucket. A violation means a request escaped the bucket grid.
    if snap["compile_count"] != warmed:
        print(
            f"WARNING: compile_count {snap['compile_count']} != warmed "
            f"buckets {warmed} — a request triggered a fresh compile",
            file=sys.stderr,
        )
        return 1
    return rc


def cmd_data_bench(args) -> int:
    """Run the input-pipeline stage bench (data/data_bench.py) — the
    CPU-runnable surface; ``bench.py --data-bench`` queues the same runner on
    the chip host."""
    _bootstrap_devices(args)
    from distributed_sigmoid_loss_tpu.data.data_bench import run_data_bench

    return run_data_bench(args)


def _load_host_spans(root: str):
    """(host_trace, spans) aggregated from every host_spans.trace.json under
    ``root`` — shared by `obs summarize` and the span half of `obs diff`."""
    import glob as globmod
    import json as jsonmod

    from distributed_sigmoid_loss_tpu.obs.spans import Span

    host_trace = None
    host_paths = sorted(
        globmod.glob(os.path.join(root, "**", "host_spans.trace.json"),
                     recursive=True)
    )
    spans: list = []
    if host_paths:
        host_trace = {"traceEvents": []}
        for path in host_paths:
            with open(path, encoding="utf-8") as f:
                trace = jsonmod.load(f)
            host_trace["traceEvents"].extend(trace.get("traceEvents", []))
        for ev in host_trace["traceEvents"]:
            if ev.get("ph") == "X" and "dur" in ev:
                t0 = ev["ts"] / 1e6
                spans.append(Span(ev["name"], t0, t0 + ev["dur"] / 1e6,
                                  ev.get("tid", 0)))
    return host_trace, host_paths, spans


def _add_obs_args(p) -> None:
    """Register the `obs` arguments on ``p`` — used for both the subparser in
    ``main`` (so `obs` shows up in --help) and the standalone intermixed
    parser the obs short-circuit builds, keeping the two in lockstep."""
    p.add_argument("action",
                   choices=["summarize", "ledger", "diff", "regress"],
                   help="summarize: aggregate host spans + device op time "
                        "under DIR; ledger: per-metric trajectory summary; "
                        "diff: field-level diff of two records or two run "
                        "dirs' span summaries; regress: proxy metrics vs "
                        "the committed baseline (exit 1 on regression)")
    p.add_argument("paths", nargs="*",
                   help="summarize: DIR; diff: two operands (metric@N "
                        "ledger selector, entry index, record-JSON path, "
                        "or run dir); ledger/regress: none")
    p.add_argument("--top", type=int, default=12,
                   help="rows per device-op table (obs summarize)")
    p.add_argument("--merged-out", default="", metavar="PATH",
                   help="also write one merged Chrome-trace JSON (host + "
                        "device events; open in ui.perfetto.dev)")
    p.add_argument("--ledger", default="", metavar="PATH",
                   help="ledger file for `obs ledger`/`obs diff` (default: "
                        "DSL_LEDGER_PATH or LEDGER.jsonl at the repo root)")
    p.add_argument("--metric", default="", metavar="NAME",
                   help="restrict `obs ledger` to one metric stream")
    p.add_argument("--backfill", action="store_true",
                   help="before summarizing, seed the ledger from the "
                        "committed BENCH_r*/MULTICHIP_r* round files "
                        "(idempotent; rounds whose backend was down land "
                        "as status=no-backend)")
    p.add_argument("--baseline", default="", metavar="PATH",
                   help="`obs regress`: baseline file (default: the "
                        "committed obs/regress_baseline.json)")
    p.add_argument("--update", action="store_true",
                   help="`obs regress`: regenerate the baseline from the "
                        "current tree instead of comparing (commit the "
                        "result with the change that moved it)")
    p.add_argument("--cpu-devices", type=int, default=0,
                   help="`obs regress`: virtual CPU mesh size (default 8 — "
                        "the same deterministic mesh the committed "
                        "baseline was generated on)")


def cmd_obs(args) -> int:
    """The graftscope/graftledger offline surface:

    - ``obs summarize DIR`` — merged host-span + device-trace report.
    - ``obs ledger`` — the per-metric perf trajectory from the append-only
      run ledger (no-backend/deferred/error rounds listed but excluded from
      the baseline stats); ``--backfill`` seeds it from the committed
      BENCH_r*/MULTICHIP_r* round files.
    - ``obs diff A B`` — field-level diff of two records (ledger selectors
      like ``metric@-1``, entry indices, or record-JSON paths) or of two
      run directories' span summaries.
    - ``obs regress`` — the chip-free proxy regression gate
      (obs/regress.py) against the committed baseline; ``--update``
      regenerates the baseline on the 8-virtual-device CPU mesh.
    """
    if args.action == "ledger":
        return _obs_ledger(args)
    if args.action == "diff":
        return _obs_diff(args)
    if args.action == "regress":
        return _obs_regress(args)
    return _obs_summarize(args)


def _obs_ledger(args) -> int:
    from distributed_sigmoid_loss_tpu.obs.ledger import (
        backfill_round_files,
        ledger_path,
        read_ledger,
        trajectory,
        trajectory_summary,
    )

    path = args.ledger or None
    if args.backfill:
        added = backfill_round_files(path=path)
        print(f"backfilled {len(added)} entr(y/ies) from the committed "
              f"round files -> {ledger_path(path)}", file=sys.stderr)
    entries = read_ledger(path)
    if not entries:
        print(f"ledger {ledger_path(path)!r} is empty (bench runs append "
              "automatically; seed history with `obs ledger --backfill`)",
              file=sys.stderr)
        return 2
    traj = trajectory(entries, metric=args.metric or None)
    if not traj:
        print(f"no entries for metric {args.metric!r}", file=sys.stderr)
        return 2
    for metric in sorted(traj):
        points = traj[metric]
        print(f"== {metric} ({len(points)} entr(y/ies))")
        for p in points:
            rnd = f"r{p['round']:02d}" if p.get("round") is not None else "  -"
            val = p.get("value")
            val_s = f"{val:>12.2f}" if isinstance(val, (int, float)) else (
                f"{val!r:>12}"
            )
            extra = p.get("device_kind", "")
            print(f"  {rnd:>4} {val_s} {p.get('unit', ''):<13}"
                  f"{p['status']:<12}{p['source']:<28}{extra}")
        s = trajectory_summary(points)
        if s["n"]:
            last = s["last"]
            print(f"  -> baseline over {s['n']} measured "
                  f"(excluded {s['excluded']} non-measurement): "
                  f"last {last['value']} ({last.get('status')}), "
                  f"best {s['best']}, mean {round(s['mean'], 2)}")
        else:
            print(f"  -> no measured entries ({s['excluded']} excluded: "
                  "outages/deferrals are not baselines)")
    return 0


def _resolve_diff_operand(op: str, entries):
    """One `obs diff` operand -> ("record", dict) | ("spans", dir).

    Accepts: a run directory (span summaries), a JSON file (a raw record, a
    ledger entry, or a driver round file whose ``tail`` holds record lines),
    ``metric@N`` (the N-th ledger entry of that metric, negatives from the
    end), or a bare integer (global ledger entry index).
    """
    import json as jsonmod

    from distributed_sigmoid_loss_tpu.obs.ledger import _records_in_tail

    if os.path.isdir(op):
        return "spans", op
    if os.path.exists(op):
        with open(op, encoding="utf-8") as f:
            data = jsonmod.load(f)
        if not isinstance(data, dict):
            raise ValueError(f"{op}: not a JSON object")
        if "metric" in data:
            return "record", data
        if isinstance(data.get("record"), dict):
            return "record", data["record"]
        if "tail" in data:
            recs = _records_in_tail(data.get("tail", ""))
            if recs:
                return "record", recs[-1]
        raise ValueError(f"{op}: no bench record found in the file")
    if "@" in op:
        metric, _, idx_s = op.rpartition("@")
        matching = [e for e in entries
                    if e.get("record", {}).get("metric") == metric]
        if not matching:
            raise ValueError(f"no ledger entries for metric {metric!r}")
        try:
            return "record", matching[int(idx_s)]["record"]
        except (ValueError, IndexError):
            raise ValueError(
                f"{op}: index {idx_s!r} out of range "
                f"({len(matching)} entr(y/ies) for {metric!r})"
            ) from None
    try:
        return "record", entries[int(op)]["record"]
    except ValueError:
        raise ValueError(
            f"{op}: not a path, metric@N selector, or entry index"
        ) from None
    except IndexError:
        raise ValueError(
            f"{op}: ledger has {len(entries)} entr(y/ies)"
        ) from None


def _obs_diff(args) -> int:
    from distributed_sigmoid_loss_tpu.obs.ledger import (
        diff_records,
        read_ledger,
    )

    if len(args.paths) != 2:
        print("obs diff needs exactly two operands (ledger selector "
              "metric@N, entry index, record-JSON path, or run dir)",
              file=sys.stderr)
        return 2
    entries = read_ledger(args.ledger or None)
    try:
        (kind_a, a), (kind_b, b) = (
            _resolve_diff_operand(op, entries) for op in args.paths
        )
    except ValueError as e:
        print(f"obs diff: {e}", file=sys.stderr)
        return 2
    if {kind_a, kind_b} == {"spans"}:
        from distributed_sigmoid_loss_tpu.obs.spans import summarize_spans

        rows_a = summarize_spans(_load_host_spans(a)[2])
        rows_b = summarize_spans(_load_host_spans(b)[2])
        if not rows_a or not rows_b:
            print("obs diff: one of the run dirs has no host spans "
                  "(train with --obs-dir)", file=sys.stderr)
            return 2
        print(f"== span summary diff (A={a} B={b})")
        print(f"  {'span':<28}{'A mean ms':>11}{'B mean ms':>11}{'delta':>9}")
        for name in sorted(set(rows_a) | set(rows_b)):
            ma = rows_a.get(name, {}).get("mean_ms")
            mb = rows_b.get(name, {}).get("mean_ms")
            if ma is None or mb is None:
                only = "A" if mb is None else "B"
                print(f"  {name:<28}{'(only in ' + only + ')':>31}")
                continue
            print(f"  {name:<28}{ma:>11.2f}{mb:>11.2f}{mb - ma:>+9.2f}")
        return 0
    if kind_a != "record" or kind_b != "record":
        print("obs diff: cannot diff a run dir against a record — pass two "
              "of the same kind", file=sys.stderr)
        return 2
    d = diff_records(a, b)
    print(f"== record diff (A={args.paths[0]} B={args.paths[1]})")
    for k, entry in d["changed"].items():
        delta = ""
        if "rel" in entry:
            delta = f"  ({entry['delta']:+g}, {entry['rel']:+.1%})"
        elif "delta" in entry:
            delta = f"  ({entry['delta']:+g})"
        print(f"  {k:<28}{entry['a']!r} -> {entry['b']!r}{delta}")
    if d["added"]:
        print(f"  only in B: {', '.join(d['added'])}")
    if d["removed"]:
        print(f"  only in A: {', '.join(d['removed'])}")
    if not (d["changed"] or d["added"] or d["removed"]):
        print("  records are identical")
    return 0


def _obs_regress(args) -> int:
    # Same bootstrap discipline as `lint`: the lattice traces shard_map'd
    # steps, which needs the multi-device virtual mesh.
    if not args.cpu_devices:
        args.cpu_devices = 8
    _bootstrap_devices(args)
    from distributed_sigmoid_loss_tpu.obs.regress import run_regress

    return run_regress(
        baseline_path=args.baseline or None,
        update=args.update,
    )


def _obs_summarize(args) -> int:
    """``obs summarize DIR``: one merged offline report of a run's host spans
    (``host_spans.trace.json`` written by ``train --obs-dir``) and any device
    trace capture (``*.trace.json.gz`` from ``utils.profiling.trace`` /
    ``bench --profile``) found under DIR — the unified graftscope timeline,
    no TensorBoard needed. ``--merged-out`` additionally writes one combined
    Chrome-trace JSON that opens in ui.perfetto.dev with host and device
    tracks side by side.
    """
    import glob as globmod
    import json as jsonmod

    if len(args.paths) != 1:
        print("obs summarize needs exactly one DIR operand", file=sys.stderr)
        return 2
    root = args.paths[0]
    from distributed_sigmoid_loss_tpu.obs.spans import (
        merge_chrome_traces,
        summarize_spans,
    )

    host_trace, host_paths, spans = _load_host_spans(root)

    device_files = globmod.glob(
        os.path.join(root, "**", "*.trace.json.gz"), recursive=True
    )

    if not spans and not device_files:
        print(f"no host_spans.trace.json or *.trace.json.gz under "
              f"{root!r} (train with --obs-dir and/or capture a device "
              "trace with utils.profiling.trace / bench --profile)",
              file=sys.stderr)
        return 2

    if spans:
        print(f"== host spans ({len(spans)} retained, "
              f"{len(host_paths)} file(s))")
        print(f"  {'span':<28}{'count':>7}{'total ms':>11}{'mean ms':>9}"
              f"{'p50':>8}{'p95':>8}{'max':>9}")
        for name, row in summarize_spans(spans).items():
            print(f"  {name:<28}{row['count']:>7}{row['total_ms']:>11.1f}"
                  f"{row['mean_ms']:>9.2f}{row['p50_ms']:>8.2f}"
                  f"{row['p95_ms']:>8.2f}{row['max_ms']:>9.2f}")

    if device_files:
        from distributed_sigmoid_loss_tpu.utils.profiling import (
            summarize_device_ops,
        )

        dev = summarize_device_ops(root, top=args.top)
        if dev["categories"]:
            print("\n== device ops by hlo_category "
                  "(achieved rates over span time)")
            print(f"  {'category':<28}{'ms':>10}{'share':>8}{'TFLOP/s':>9}"
                  f"{'GB/s':>8}")
            for name, ms, share, tf, gb in dev["categories"]:
                print(f"  {name:<28}{ms:>10.1f}{share:>8.1%}{tf:>9.1f}"
                      f"{gb:>8.0f}")
            print("\n== top device ops")
            for name, ms, n, tf, gb in dev["top_ops"]:
                print(f"  {name:<42}{ms:>9.1f} ms  n={n:<5}"
                      f"{tf:>7.1f} TF/s{gb:>7.0f} GB/s")
        else:
            print("\n(device trace files found but no 'XLA Ops' track — "
                  "host-only capture?)")

    if args.merged_out:
        from distributed_sigmoid_loss_tpu.utils.profiling import (
            _read_trace_files,
        )

        device_events = _read_trace_files(root) if device_files else ()
        merged = merge_chrome_traces(host_trace or {"traceEvents": []},
                                     device_events)
        with open(args.merged_out, "w", encoding="utf-8") as f:
            jsonmod.dump(merged, f)
        print(f"\nmerged chrome trace -> {args.merged_out} "
              f"({len(merged['traceEvents'])} events; open in "
              "ui.perfetto.dev)")
    return 0


def cmd_lint(args) -> int:
    """Run graftlint: the repo-invariant AST linter, the graftguard
    lock-discipline analyzer (guarded-by + lock-order + lockwatch gate),
    plus (default) the config-space drift check and the jaxpr
    collective/dtype/dataflow auditor over the sampled step-config product
    on an emulated CPU mesh. Exit 0 = clean, 1 = findings, 2 = usage error.

    Rule catalog + allowlist policy: docs/ANALYSIS.md. The same entry points
    run inside tests/test_analysis.py and the __graft_entry__ dryrun, so a
    finding here is a tier-1 failure — `lint` is the local preview.
    """
    # The auditor traces shard_map'd steps, which needs a multi-device mesh;
    # default to the 8-virtual-device CPU bootstrap the tests use.
    if not args.no_jaxpr and not args.cpu_devices:
        args.cpu_devices = 8
    _bootstrap_devices(args)
    import json as jsonmod

    from distributed_sigmoid_loss_tpu.analysis import (
        ALL_RULES,
        apply_lint_baseline,
        load_lint_baseline,
        run_lint,
    )

    unknown = [r for r in args.disable if r not in ALL_RULES]
    if unknown:
        print(
            f"--disable: unknown rule(s) {unknown}; known rules: "
            + ", ".join(ALL_RULES),
            file=sys.stderr,
        )
        return 2
    baseline_keys = None
    if args.baseline:
        try:
            baseline_keys = load_lint_baseline(args.baseline)
        except (OSError, ValueError) as e:
            print(f"--baseline: {e}", file=sys.stderr)
            return 2
    findings = run_lint(
        disabled=set(args.disable),
        jaxpr=not args.no_jaxpr,
        full_product=args.full_product,
    )
    if baseline_keys is not None:
        findings = apply_lint_baseline(findings, baseline_keys)
    checked = [r for r in ALL_RULES if r not in args.disable]
    if args.no_jaxpr:
        checked = [
            r for r in checked
            if not r.startswith("jaxpr-") and r != "config-space-drift"
        ]
    if baseline_keys is None:
        checked = [r for r in checked if r != "lint-stale-suppression"]
    if args.json:
        print(jsonmod.dumps({
            "rules_checked": checked,
            "disabled": sorted(args.disable),
            "findings": [f.as_dict() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f)
    print(
        f"graftlint: {len(checked)} rules checked, {len(findings)} "
        f"finding(s)" + (f", {len(args.disable)} disabled" if args.disable
                         else ""),
        file=sys.stderr,
    )
    return 1 if findings else 0


def cmd_tokenizer(args) -> int:
    """Train a BPE vocab from captions and write it as json."""
    import glob as globmod

    from distributed_sigmoid_loss_tpu.data import BpeTokenizer

    if bool(args.data_dir) == bool(args.text_file):
        print("pass exactly one of --data-dir or --text-file", file=sys.stderr)
        return 2
    if args.data_dir:
        paths = sorted(globmod.glob(os.path.join(args.data_dir, "*.txt")))
        if not paths:
            print(f"no *.txt captions under {args.data_dir!r}", file=sys.stderr)
            return 2
        texts = []
        for path in paths:
            with open(path, encoding="utf-8") as f:
                texts.append(f.read().strip())
    else:
        with open(args.text_file, encoding="utf-8") as f:
            texts = [line.strip() for line in f if line.strip()]
    if not texts:
        print("corpus is empty (no non-blank captions)", file=sys.stderr)
        return 2
    tok = BpeTokenizer.train(texts, args.vocab_size)
    tok.save(args.out)
    n_merges = len(tok.merges)
    sample = texts[0][:60]
    ratio = len(sample.encode("utf-8")) / max(1, len(tok.encode(sample)) - 2)
    print(
        f"trained {n_merges} merges (vocab {tok.vocab_size}) from "
        f"{len(texts)} captions -> {args.out}; "
        f"~{ratio:.2f} bytes/token on a sample"
    )
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="distributed_sigmoid_loss_tpu", description=__doc__
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    tr = sub.add_parser("train", help="end-to-end SigLIP training (synthetic data)")
    tr.add_argument("--steps", type=int, default=20)
    tr.add_argument("--tokenizer", default="",
                    help="trained BPE vocab json (see the `tokenizer` "
                         "subcommand); default = byte-level tokenizer")

    tr.add_argument("--batch", type=int, default=64, help="global batch size")
    tr.add_argument("--variant", choices=["all_gather", "ring"], default=None,
                    help="loss comm pattern (default ring; --grad-compression "
                         "and --loss-impl chunked select all_gather)")
    tr.add_argument("--loss-impl", choices=["fused", "chunked"],
                    default="fused",
                    help="all_gather loss memory shape: 'fused' computes the "
                         "whole (local_b, W*local_b) logits in one matmul; "
                         "'chunked' streams the gathered negatives through a "
                         "scan over W chunk-blocks — the full logits matrix "
                         "is never materialized (~W* lower peak loss HBM, "
                         "unlocking larger per-chip batches)")
    tr.add_argument("--ring-overlap", action="store_true",
                    help="double-buffer the ring loss's hop loop: hop k+1's "
                         "ppermute is issued before hop k's block matmuls so "
                         "XLA hides ICI latency behind the MXU (ring variant "
                         "only; bitwise-same accumulation order)")
    tr.add_argument("--use-pallas", action="store_true",
                    help="streaming 2-D Pallas loss kernel: every logits "
                         "block (fused gather, chunked scan body, ring hop) "
                         "computes tile-by-tile in VMEM with a fused-backward "
                         "recompute VJP — composes with --loss-impl chunked "
                         "and --ring-overlap; with --quant-train int8 the "
                         "block products run the int8 MXU path (STE "
                         "semantics); falls back to XLA per block for "
                         "non-tileable shapes (recorded, never silent)")
    tr.add_argument("--loss-family", choices=["sigmoid", "softmax"],
                    default="sigmoid",
                    help="sigmoid = SigLIP (reference); softmax = CLIP/InfoNCE "
                         "over the same comm variants")
    tr.add_argument("--lr", type=float, default=1e-3)
    tr.add_argument("--optimizer", choices=["adamw", "lion", "adafactor"],
                    default="adamw",
                    help="optimizer family: adamw (default), lion (half the "
                         "optimizer state; use ~3-10x smaller --lr), adafactor "
                         "(factored second moments, biggest-model memory)")
    tr.add_argument("--model", choices=["b16", "l14", "so400m", "tiny"], default="b16")
    tr.add_argument("--tiny", action="store_true", help="alias for --model tiny")
    tr.add_argument("--accum", type=int, default=1, help="grad-accumulation microsteps")
    tr.add_argument("--accum-bf16", action="store_true",
                    help="bf16 gradient accumulator under --accum (adds stay "
                         "f32; halves the accumulator's HBM footprint and "
                         "per-microstep read+write traffic)")
    tr.add_argument("--remat-policy", default="",
                    choices=["", "nothing", "save_hot", "save_all_hot",
                             "save_mlp"],
                    help="override both towers' remat policy (default: the "
                         "model config's own; measured winners per shape in "
                         "docs/PERF.md — e.g. save_hot for b16/l14 "
                         "microbatch-128 recipes, save_mlp for so400m)")
    tr.add_argument("--quant-train", choices=["", "int8"], default="",
                    help="trainable int8: block projection matmuls run the "
                         "dynamic symmetric int8 recipe FORWARD (v5e int8 "
                         "MXU = 2x bf16 peak) with the full-precision VJP "
                         "BACKWARD (straight-through estimator) — the int8 "
                         "training track (docs/PERF.md roofline rationale)")
    tr.add_argument("--accum-negatives", choices=["local", "global"],
                    default="local",
                    help="with --accum > 1: 'local' contrasts each microbatch "
                         "against its own texts only (cheap, smaller negative "
                         "set); 'global' computes the EXACT full-batch loss "
                         "GradCache-style (embed pass + loss island + "
                         "surrogate re-forward; ~30%% slower, bitwise-faithful "
                         "negatives)")
    tr.add_argument("--gradcache-bf16", action="store_true",
                    help="with --accum-negatives global: store the GradCache "
                         "embedding stash in bf16 (island matmuls read bf16 "
                         "operands, stash HBM halves; ~2^-9 rounding on the "
                         "island loss/cotangents)")
    tr.add_argument("--moe-experts", type=int, default=0,
                    help="swap tower MLPs for this many experts per block "
                         "(mixture-of-experts; shards over an ep mesh axis)")
    tr.add_argument("--moe-aux-weight", type=float, default=None,
                    help="router load-balancing loss weight (requires "
                         "--moe-experts; default 0.01 when MoE is on)")
    tr.add_argument("--moe-group-size", type=int, default=0,
                    help="GShard routing group size (with --moe-experts): "
                         "capacity is per-group, so smaller groups shrink the "
                         "dispatch tensors for tight HBM budgets (default 512)")
    tr.add_argument("--pp", type=int, default=1,
                    help="pipeline-parallel stages: split each tower's block "
                         "stack into this many gpipe stages over a pp mesh "
                         "axis (device count must divide; towers must be "
                         "scanned + dense)")
    tr.add_argument("--pp-microbatches", type=int, default=0,
                    help="microbatches per pipelined step (default 2*pp); "
                         "global batch must divide by dp*pp_microbatches")
    tr.add_argument("--ep", type=int, default=1,
                    help="expert-parallel mesh factor (with --moe-experts): mesh "
                         "becomes (dp = devices/ep, ep); 1 = replicated experts")
    tr.add_argument("--data-dir", default="",
                    help="train on a directory of name.jpg + name.txt pairs "
                         "(real data; single-process)")
    tr.add_argument("--data-shards", default="",
                    help="train on webdataset-style tar shards matching this "
                         "glob (real data; single-process)")
    tr.add_argument("--shuffle-buffer", type=int, default=0,
                    help="sample-shuffle reservoir size for --data-shards "
                         "(webdataset-style; 0 = stream in tar order)")
    tr.add_argument("--native-decode", action="store_true",
                    help="decode real-data images with the native libjpeg "
                         "engine (threaded, off-GIL; with --data-dir or "
                         "--data-shards); falls back to PIL with a notice")
    tr.add_argument("--native-data", action="store_true",
                    help="use the C++ input-pipeline engine (native/dataloader.cc) "
                         "instead of the numpy pipeline; falls back with a notice "
                         "when no toolchain is available")
    tr.add_argument("--data-workers", type=int, default=0, metavar="N",
                    help="host worker threads for image decode / native "
                         "generation (0 = auto: cpu_count minus the "
                         "prefetch/main threads)")
    tr.add_argument("--update-sharding", choices=["off", "zero1", "full"],
                    default="",
                    help="cross-replica update sharding (graftshard, "
                         "parallel/update_shard.py): 'zero1' re-pins "
                         "optimizer state over dp (the classic layout); "
                         "'full' reduce-scatters gradients into a 1/W shard, "
                         "runs the optax update + state on the shard, and "
                         "all-gathers params once per step — ~W x less "
                         "optimizer HBM, and with --grad-compression the "
                         "dcn wire compresses the shard (another ~W x fewer "
                         "bytes); requires a dp axis > 1, excludes --pp")
    tr.add_argument("--zero1", action="store_true",
                    help="deprecated alias for --update-sharding zero1 — "
                         "shard optimizer state over dp (ZeRO-1); fits "
                         "so400m-class towers in v5e HBM")
    tr.add_argument("--dcn-slices", type=int, default=1, metavar="N",
                    help="multi-slice topology: a separate dcn mesh axis of "
                         "size N outermost (cross-slice DCN links), dp inside "
                         "(ICI) — pair with --grad-compression")
    tr.add_argument("--force-dcn-emulation", action="store_true",
                    help="allow --dcn-slices on single-slice TPU hardware "
                         "(quantization loss on ICI, no bandwidth win — for "
                         "perf experiments emulating a multi-slice topology)")
    tr.add_argument("--grad-compression", "--compression",
                    choices=["int8", "topk", "adaptive", "learned"],
                    default="",
                    help="compress the gradient sync over the dcn axis: f32 "
                         "psum on ICI; on DCN either int8 all-gather (~4x "
                         "fewer bytes), top-k sparsification (~50x at the "
                         "default 1%%), adaptive — a per-tensor "
                         "int8/int4/sign1/top-k scheme chosen each round by "
                         "the bandwidth-aware bit controller "
                         "(parallel/adaptive_compression.py) — or learned: "
                         "the adaptive ladder plus graftcodec's rung 6, a "
                         "per-tensor-group linear autoencoder (~0.26 "
                         "bytes/param) trained online on the host from the "
                         "step's block moments; all with error feedback "
                         "(train/compressed_step.py)")
    tr.add_argument("--dcn-budget-mbps", type=float, default=None,
                    metavar="MBPS",
                    help="per-device DCN egress budget for --grad-compression "
                         "adaptive: the bit controller narrows per-tensor "
                         "schemes until min(measured-bandwidth EWMA, this "
                         "budget) fits the sync round (unset: measured "
                         "bandwidth alone)")
    tr.add_argument("--controller", choices=["greedy", "budgeted"],
                    default=None,
                    help="bit-controller policy for --grad-compression "
                         "adaptive/learned (default greedy): greedy narrows "
                         "the lowest-EF-ratio tensors first; budgeted "
                         "allocates a global loss-impact budget — per-rung "
                         "error-per-byte-saved knapsack descent over "
                         "ef_ratio/gvar/gnorm (docs/PERF.md graftcodec)")
    tr.add_argument("--emu-dcn-mbps", type=float, default=None,
                    metavar="MBPS",
                    help="honest DCN emulation (parallel/dcn_emu.py): ship "
                         "each round's dcn payload across a throttled "
                         "two-process localhost pipe at this bandwidth, so "
                         "dcn_bw_est_mbps reacts to MEASURED transfer time "
                         "and metrics carry dcn_measured_mbps + "
                         "wire_savings_wallclock_ratio vs the fixed-bf16 "
                         "reference; requires --dcn-slices >= 2")
    tr.add_argument("--topk-frac", type=float, default=0.01, metavar="F",
                    help="fraction of entries kept per tensor under "
                         "--grad-compression topk (adaptive: its top-k "
                         "rung; the narrow rung keeps F/4)")
    tr.add_argument("--topk-exact", action="store_true",
                    help="exact lax.top_k selection instead of the default "
                         "approx_max_k (4x slower on TPU at gradient scale "
                         "-- docs/PERF.md; use for bit-reproducibility)")
    tr.add_argument("--ema-decay", type=float, default=None,
                    help="maintain an EMA of the params in the train state "
                         "(e.g. 0.9999, warmed up)")
    tr.add_argument("--cpu-devices", type=int, default=0, help="emulate N CPU devices")
    tr.add_argument("--ckpt-dir", default="",
                    help="checkpoint/resume directory: resumes from the newest "
                         "step-numbered checkpoint, saves every --ckpt-every steps "
                         "and on SIGTERM (preemption)")
    tr.add_argument("--async-checkpoint", action="store_true",
                    help="non-blocking checkpoint writes (orbax async): the "
                         "step loop overlaps the save IO instead of stalling "
                         "for it (seconds per save at so400m scale)")
    tr.add_argument("--ckpt-every", type=int, default=50)
    tr.add_argument("--eval-every", type=int, default=0, metavar="N",
                    help="every N steps, log zero-shot retrieval metrics "
                         "(eval/i2t_recall@K ...) on one fixed batch — the "
                         "in-training validation curve. Synthetic runs use a "
                         "genuinely held-out batch (shifted seeds); file/"
                         "native streams use --eval-data when given, else "
                         "fall back (with a warning) to the first training "
                         "batch, so the curve there includes train-set fit")
    tr.add_argument("--eval-data", default="", metavar="PATH_OR_GLOB",
                    help="held-out eval source for --eval-every: a directory "
                         "(ImageTextFolder layout) or a tar-shard glob kept "
                         "OUT of --data-dir/--data-shards — makes the "
                         "in-training curve a true validation curve")
    tr.add_argument("--log-every", type=int, default=1)
    tr.add_argument("--obs-dir", default="", metavar="DIR",
                    help="enable graftscope host-span recording: the train "
                         "loop's fetch/h2d-commit/step/eval/checkpoint spans "
                         "are written to DIR/host_spans.trace.json "
                         "(Chrome-trace JSON — overlays a device capture in "
                         "ui.perfetto.dev; merge offline with `obs summarize "
                         "DIR`), and the flight recorder dumps to "
                         "DIR/flight.json on crash/SIGTERM instead of stderr")
    tr.add_argument("--watchdog", choices=["off", "warn", "skip"],
                    default="warn",
                    help="training health watchdog (obs/health.py): 'warn' "
                         "(default) emits structured health_event records on "
                         "NaN/Inf metrics and loss spikes vs the rolling "
                         "median; 'skip' additionally routes a non-finite "
                         "loss into the resilient loop's rollback-and-skip "
                         "path (requires --ckpt-dir); 'off' disables "
                         "detection (the grad_norm/param_norm/update_ratio "
                         "scalars stay on every metrics line regardless)")
    tr.add_argument("--coordinator", default="",
                    help="multi-process rendezvous address host:port — every "
                         "process runs this same command with its own --process-id; "
                         "--batch stays GLOBAL and must be divisible by "
                         "--num-processes")
    tr.add_argument("--num-processes", type=int, default=0,
                    help="total process count (required with --coordinator)")
    tr.add_argument("--process-id", type=int, default=-1,
                    help="this process's 0-based rank (required with --coordinator)")

    ev = sub.add_parser("eval", help="zero-shot retrieval + classification")
    ev.add_argument("--tokenizer", default="",
                    help="trained BPE vocab json (see the `tokenizer` "
                         "subcommand); default = byte-level tokenizer")
    ev.add_argument("--batch", type=int, default=64)
    ev.add_argument("--classes", type=int, default=10)
    ev.add_argument("--model", choices=["b16", "l14", "so400m", "tiny"], default="b16")
    ev.add_argument("--tiny", action="store_true", help="alias for --model tiny")
    ev.add_argument("--moe-experts", type=int, default=0,
                    help="match a checkpoint trained with --moe-experts")
    ev.add_argument("--optimizer", choices=["adamw", "lion", "adafactor"],
                    default="adamw",
                    help="optimizer family the checkpoint was trained with "
                         "(shapes the restore target's optimizer state)")
    ev.add_argument("--data-dir", default="",
                    help="directory of name.jpg + name.txt pairs: score REAL "
                         "pairs (retrieval + caption-matching zero-shot) "
                         "instead of synthetic data")
    ev.add_argument("--data-shards", default="",
                    help="glob of webdataset-style tar shards (same loaders as "
                         "train); mutually exclusive with --data-dir")
    ev.add_argument("--cpu-devices", type=int, default=0)
    ev.add_argument("--ckpt-dir", default="", help="restore params from this checkpoint")
    ev.add_argument("--quant", choices=["", "int8"], default="",
                    help="run the towers' projection matmuls in dynamic int8 "
                         "(v5e int8 MXU = 2x bf16 peak; inference-only)")
    ev.add_argument("--ema", action="store_true",
                    help="evaluate the checkpoint's EMA weights (train --ema-decay)")

    tk = sub.add_parser(
        "tokenizer",
        help="train a byte-level BPE vocab on a caption corpus (data/tokenizer.py)",
    )
    tk.add_argument("out", help="output vocab json path")
    tk.add_argument("--vocab-size", type=int, default=4096)
    tk.add_argument("--data-dir", default="",
                    help="directory of name.txt caption files (the "
                         "ImageTextFolder layout)")
    tk.add_argument("--text-file", default="",
                    help="plain text file, one caption per line")

    ex = sub.add_parser(
        "export",
        help="AOT-export a lowered step to a StableHLO artifact (jax.export)",
    )
    ex.add_argument("out", help="output artifact path")
    ex.add_argument("--quant", choices=["", "int8"], default="",
                    help="quantize the towers for --what forward artifacts "
                         "(int8 projection matmuls; rejected for train_step)")
    ex.add_argument("--what", choices=["train_step", "forward"],
                    default="train_step")
    ex.add_argument("--model", choices=["b16", "l14", "so400m", "tiny"],
                    default="b16")
    ex.add_argument("--tiny", action="store_true", help="alias for --model tiny")
    ex.add_argument("--moe-experts", type=int, default=0,
                    help="export the MoE variant (matches train --moe-experts)")
    ex.add_argument("--ep", type=int, default=1,
                    help="expert-parallel mesh factor (with --moe-experts): the "
                         "artifact is lowered for a (dp = devices/ep, ep) mesh, "
                         "matching train --ep (train_step only)")
    ex.add_argument("--moe-aux-weight", type=float, default=0.01,
                    help="router load-balancing loss weight baked into the "
                         "train_step artifact (match the train job's value)")
    ex.add_argument("--moe-group-size", type=int, default=0,
                    help="GShard routing group size baked into the artifact "
                         "(match the train job's value; default 512)")
    ex.add_argument("--batch", type=int, default=64,
                    help="global batch the artifact is shaped for")
    ex.add_argument("--variant", choices=["all_gather", "ring"], default="ring")
    ex.add_argument("--loss-family", choices=["sigmoid", "softmax"],
                    default="sigmoid",
                    help="loss family baked into the train_step artifact "
                         "(match the train job's --loss-family)")
    ex.add_argument("--lr", type=float, default=1e-3,
                    help="learning rate baked into the train_step artifact")
    ex.add_argument("--warmup-steps", type=int, default=2000,
                    help="LR warmup steps baked into the train_step artifact")
    ex.add_argument("--total-steps", type=int, default=100_000,
                    help="LR schedule horizon baked into the train_step artifact")
    ex.add_argument("--platform", default="",
                    help="lowering target (e.g. tpu) when exporting from a "
                         "different host backend; default: current backend")
    ex.add_argument("--check", action="store_true",
                    help="reload the written artifact and replay one step "
                         "against the live jitted step")
    ex.add_argument("--cpu-devices", type=int, default=0,
                    help="emulate N CPU devices (export for an N-device mesh)")

    bn = sub.add_parser(
        "bench", help="headline throughput benchmark (extra args pass through)"
    )
    bn.add_argument("rest", nargs=argparse.REMAINDER)

    sb = sub.add_parser(
        "serve-bench",
        help="online serving micro-bench: concurrent clients through the "
             "batched/cached/bucketed serve/ stack; prints the stats "
             "snapshot as JSON (CPU-runnable)",
    )
    sb.add_argument("--requests", type=int, default=512,
                    help="total client requests across all clients")
    sb.add_argument("--clients", type=int, default=8,
                    help="concurrent client threads")
    sb.add_argument("--model", choices=["b16", "l14", "so400m", "tiny"],
                    default="tiny",
                    help="tower config (default tiny: the CPU-runnable "
                         "smoke/bench shape; big models need a real chip)")
    sb.add_argument("--batch-buckets", default="1,8,32", metavar="N,N,...",
                    help="padded batch-size buckets the engine compiles "
                         "(steady state never compiles outside the grid)")
    sb.add_argument("--max-wait-ms", type=float, default=5.0,
                    help="micro-batcher deadline: max ms a queued request "
                         "waits for coalescing before a partial flush")
    sb.add_argument("--max-queue", type=int, default=1024,
                    help="bounded request queue per modality (full queue "
                         "rejects with backpressure)")
    sb.add_argument("--cache-size", type=int, default=4096,
                    help="LRU embedding cache capacity (entries)")
    sb.add_argument("--pool", type=int, default=64,
                    help="distinct synthetic items clients draw from "
                         "(repeats exercise the cache)")
    sb.add_argument("--index-size", type=int, default=64,
                    help="corpus rows indexed for the search requests")
    sb.add_argument("--index-tier", choices=["exact", "sharded", "ann"],
                    default="exact",
                    help="retrieval tier answering search requests: exact = "
                         "single-host chunked scan (the oracle), sharded = "
                         "dp-mesh per-shard top-k + merged candidates "
                         "(requires --mesh), ann = int8 quantize-then-rerank "
                         "with measured recall@k in the record "
                         "(docs/SERVING.md)")
    sb.add_argument("--swap-every", type=int, default=0, metavar="N",
                    help="churn mode: hot-swap the weights + freshly built "
                         "index segments after every N completed client ops "
                         "(0 = off); swap_count / swap_latency_ms land in "
                         "the record and the zero-recompile gate still "
                         "applies")
    sb.add_argument("--rerank-k", type=int, default=0, metavar="K",
                    help="ann tier: coarse candidates kept for the exact "
                         "re-rank (0 = auto: max(8·topk, 64)) — the "
                         "recall/latency knob")
    sb.add_argument("--topk", type=int, default=5)
    sb.add_argument("--metrics-port", type=int, default=-1, metavar="PORT",
                    help="expose the live OpenMetrics-style /metrics "
                         "endpoint during the bench on this port (0 = an "
                         "ephemeral port, printed on stderr; -1 = off) — "
                         "scrape qps/latency/compile_count mid-run "
                         "(docs/OBSERVABILITY.md 'graftledger')")
    sb.add_argument("--scenario", default="",
                    choices=["", "burst", "skew", "slowloris", "hostloss",
                             "swapstorm"],
                    help="graftsiege soak: replace the fixed-request client "
                         "loop with a shaped overload scenario (open-loop "
                         "offered load, multi-tenant admission at the front "
                         "door) and emit the degradation record — p99 vs "
                         "offered load, per-tenant shed_rate, "
                         "recovery_time_s, silent_drops "
                         "(docs/SERVING.md 'Overload & SLO semantics')")
    sb.add_argument("--tenants",
                    default="gold:prio=2,quota=24,slo=500;"
                            "free:prio=1,rate=80,quota=8",
                    metavar="SPEC",
                    help="scenario tenant policies, ';'-separated "
                         "name:key=value[,key=value...] rows (keys: prio, "
                         "rate req/s, burst, quota in-flight items, slo ms)")
    sb.add_argument("--duration-s", type=float, default=4.0,
                    help="scenario soak duration (wall seconds of offered "
                         "load; recovery measurement may extend past it)")
    sb.add_argument("--offered-load", type=float, default=200.0,
                    help="aggregate offered load across tenants (req/s) the "
                         "scenario shapes — set ≥2x sustained capacity for "
                         "the overload drill")
    sb.add_argument("--capacity", type=int, default=64,
                    help="AdmissionController global in-flight item budget "
                         "(priority tiers partition it under overload)")
    sb.add_argument("--fleet-scenario", default="",
                    choices=["", "fleet-rolling-swap", "fleet-hostloss",
                             "fleet-splitbrain"],
                    help="graftfleet drill: N EngineProcess-backed replicas "
                         "behind the fleet router with token-lease "
                         "distributed admission — rolling swap wave under "
                         "burst, replica kill -9 with lease reclaim, or "
                         "coordinator split-brain (must under-admit, never "
                         "over-admit); emits the fleet_siege degradation "
                         "record (docs/SERVING.md 'Fleet tier')")
    sb.add_argument("--fleet-replicas", type=int, default=0, metavar="N",
                    help="replica count for --fleet-scenario (>= 2; 0 = "
                         "unset, defaults to 3 when a fleet scenario runs)")
    sb.add_argument("--lease-ttl-s", type=float, default=0.0, metavar="S",
                    help="fleet lease TTL: a dead host's quota slices "
                         "expire and redistribute within this bound (0 = "
                         "unset, defaults to 0.5 when a fleet scenario "
                         "runs)")
    sb.add_argument("--seed", type=int, default=0)
    sb.add_argument("--mesh", action="store_true",
                    help="shard engine batches over the dp mesh (batch "
                         "buckets must divide the device count)")
    sb.add_argument("--cpu-devices", type=int, default=0,
                    help="emulate N CPU devices (pair with --mesh)")

    db = sub.add_parser(
        "data-bench",
        help="input-pipeline stage bench: shard read / decode / tokenize / "
             "augment / h2d commit in isolation + the composed real-data "
             "pipeline vs the synthetic loader (schema-validated JSON "
             "records; CPU-runnable) — docs/PERF.md 'Feeding the headline'",
    )
    from distributed_sigmoid_loss_tpu.data.data_bench import (
        add_data_bench_args,
    )

    add_data_bench_args(db)
    db.add_argument("--cpu-devices", type=int, default=0,
                    help="emulate N CPU devices (the h2d/composed stages "
                         "commit onto this mesh)")

    ob = sub.add_parser(
        "obs",
        help="graftscope/graftledger reports: `obs summarize DIR` (merged "
             "host+device timeline), `obs ledger` (the perf trajectory from "
             "the append-only run ledger), `obs diff A B` (record or span "
             "diffs), `obs regress` (chip-free proxy regression gate vs the "
             "committed baseline) — docs/OBSERVABILITY.md",
    )
    _add_obs_args(ob)

    ln = sub.add_parser(
        "lint",
        help="graftlint: repo-invariant linter + config-space drift check + "
             "jaxpr collective/dtype/dataflow auditor over the sampled "
             "step-config product (exit 1 on findings); rule catalog in "
             "docs/ANALYSIS.md",
    )
    ln.add_argument("--json", action="store_true",
                    help="machine-readable report (rules checked + findings, "
                         "each with a stable rule_id + location) instead of "
                         "one text line per finding")
    ln.add_argument("--disable", action="append", default=[], metavar="RULE",
                    help="skip this rule id (repeatable); see docs/ANALYSIS.md "
                         "for the catalog — prefer fixing or allowlisting "
                         "with a rationale over disabling")
    ln.add_argument("--no-jaxpr", action="store_true",
                    help="AST rules only (skip the config-space probe and "
                         "the step-config traces; sub-second, for "
                         "pre-commit-style hooks)")
    ln.add_argument("--full-product", action="store_true",
                    help="audit the pairwise-covering sample of the FULL "
                         "legal config product from the solver, not just "
                         "the tier-1 sample (~30 s of extra traces; what "
                         "the dryrun's graftprove token runs)")
    ln.add_argument("--baseline", default="", metavar="FILE",
                    help="ratchet mode: suppress findings recorded in FILE "
                         "(a saved `lint --json` report or a JSON list of "
                         "{rule, subject}); entries that no longer fire "
                         "become lint-stale-suppression findings")
    ln.add_argument("--cpu-devices", type=int, default=0,
                    help="virtual CPU mesh size for the jaxpr auditor "
                         "(default 8 — the same emulated mesh the tests use)")

    argv = sys.argv[1:] if argv is None else list(argv)
    # bench forwards its arguments to bench.py untouched; argparse REMAINDER
    # cannot capture a LEADING option (`bench --use-pallas` errors), so bench is
    # routed before parsing. The subparser stays registered for --help and as a
    # fallback if this short-circuit is ever bypassed.
    if argv[:1] == ["bench"]:
        return cmd_bench(argv[1:])
    # obs mixes nargs="*" positionals (diff's two operands) with options;
    # plain parse_args consumes positionals greedily, so flags were only
    # accepted trailing (`obs diff A B --ledger P` worked, `obs diff
    # --ledger P A B` errored). parse_intermixed_args fixes that but cannot
    # traverse subparsers, so obs is routed through a standalone parser
    # built from the same _add_obs_args. The subparser stays registered for
    # --help and as a fallback.
    if argv[:1] == ["obs"]:
        obs_ap = argparse.ArgumentParser(
            prog="distributed_sigmoid_loss_tpu obs"
        )
        _add_obs_args(obs_ap)
        return cmd_obs(obs_ap.parse_intermixed_args(argv[1:]))
    args = ap.parse_args(argv)
    dispatch = {
        "train": cmd_train,
        "eval": cmd_eval,
        "export": cmd_export,
        "tokenizer": cmd_tokenizer,
        "bench": lambda a: cmd_bench(a.rest),
        "serve-bench": cmd_serve_bench,
        "data-bench": cmd_data_bench,
        "lint": cmd_lint,
        "obs": cmd_obs,
    }
    return dispatch[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
