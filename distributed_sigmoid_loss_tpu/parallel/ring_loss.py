"""Ring (neighbor-exchange) distributed sigmoid loss — TPU-native rebuild of the
reference ``SigLipLoss`` (/root/reference/rwightman_sigmoid_loss.py:12-124).

Reference semantics: compute the positive block locally (rwightman_sigmoid_loss.py:69),
then shift text shards around the ring ``W-1`` times, accumulating negative-only blocks.
With ``bidir=True`` (default) shards travel both directions in ``(W-1)//2`` paired
exchanges plus one unidirectional remainder hop when ``W`` is even
(rwightman_sigmoid_loss.py:75-107); otherwise ``W-1`` single rightward hops (:108-122).
Memory stays O(local_b²) per step instead of the all-gather variant's O(W·local_b²) —
this is the batch-dimension analogue of ring attention and the scalable path for global
batch 32k.

TPU-first redesign:

- The Python hop loop becomes ``lax.scan`` over ``ppermute`` steps so XLA can overlap
  each ICI transfer with the previous block's MXU matmul (the reference relies on
  ``batch_isend_irecv`` + compute interleaving for the same effect).
- Gradients ride the ring in reverse automatically: ``ppermute``'s transpose is the
  inverse permutation — exactly the hand-written ``NeighbourExchange[Bidir].backward``
  (distributed_utils.py:74-77, 94-98).
- ``t_prime``/``bias`` are plain arguments, mirroring the reference variant's API split
  (``logit_scale``/``logit_bias`` passed into ``forward``, not module state,
  rwightman_sigmoid_loss.py:68; ``logit_scale ≡ t_prime`` — both are log-temperature,
  exp'd inside, rwightman_sigmoid_loss.py:50).
"""

from __future__ import annotations

import jax
from jax import lax

from distributed_sigmoid_loss_tpu.ops.sigmoid_loss import sigmoid_loss_block
from distributed_sigmoid_loss_tpu.parallel.collectives import (
    double_buffered_scan,
    neighbour_exchange,
    neighbour_exchange_bidir,
)

__all__ = ["ring_sigmoid_loss"]


def ring_sigmoid_loss(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    bias: jax.Array,
    *,
    axis_name: str = "dp",
    bidir: bool = True,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool = False,
    overlap: bool = False,
    quant: str = "",
) -> jax.Array:
    """Per-shard loss of the ring variant; call inside ``shard_map``.

    Mathematically equal to :func:`allgather_sigmoid_loss` (the reference proves this
    with its variant-parity test, test_sigmoid_loss_variants.py:93-113) with a different
    communication pattern: ``W-1`` neighbor hops instead of one all-gather.

    ``overlap=True`` restructures the hop loop double-buffered (hop k+1's
    ``ppermute`` issued before hop k's block-loss matmuls — see
    :func:`~distributed_sigmoid_loss_tpu.parallel.collectives.double_buffered_scan`)
    so XLA can hide the ICI transfer behind the MXU. The accumulation order is
    UNCHANGED, so the overlapped ring is bitwise-comparable to the serial one.

    ``use_pallas=True`` makes the streaming 2-D Pallas kernel the per-hop
    block body (serial AND overlapped hop loops — both route through
    ``block``); ``quant="int8"`` additionally runs each block product on the
    int8 MXU path (STE semantics, ops/quant.py).
    """
    def block(ztxt_chunk, negative_only):
        if use_pallas:
            import jax.numpy as jnp

            from distributed_sigmoid_loss_tpu.ops.pallas_sigmoid_loss import (
                NEGATIVE_ONLY_OFFSET,
                streaming_block_loss_or_none,
            )

            offset = jnp.float32(NEGATIVE_ONLY_OFFSET if negative_only else 0.0)
            fused = streaming_block_loss_or_none(
                zimg, ztxt_chunk, t_prime, bias, offset, quant=quant
            )
            if fused is not None:
                return fused
        return sigmoid_loss_block(
            zimg,
            ztxt_chunk,
            t_prime,
            bias,
            negative_only=negative_only,
            precision=precision,
        )

    w = lax.axis_size(axis_name)
    if overlap and w > 1:
        return _ring_sigmoid_loss_overlapped(block, ztxt, axis_name, w, bidir)

    # Positive (own-shard) block: rwightman_sigmoid_loss.py:69.
    loss = block(ztxt, False)

    if w == 1:
        return loss

    if bidir:
        num_bidir, remainder = divmod(w - 1, 2)

        def step(carry, _):
            to_left, to_right, acc = carry
            from_right, from_left = neighbour_exchange_bidir(
                to_left, to_right, axis_name
            )
            # Accumulation order (from_right then from_left) matches the reference's
            # `for f in text_features_recv` loop, rwightman_sigmoid_loss.py:86-93.
            acc = acc + block(from_right, True) + block(from_left, True)
            return (from_right, from_left, acc), None

        carry = (ztxt, ztxt, loss)
        if num_bidir:
            carry, _ = lax.scan(step, carry, None, length=num_bidir)
        _, to_right, loss = carry

        if remainder:
            # Even W: one extra unidirectional hop, rwightman_sigmoid_loss.py:96-107.
            from_left = neighbour_exchange(to_right, axis_name, to_right=True)
            loss = loss + block(from_left, True)
    else:
        # Unidirectional ring: W-1 rightward hops, rwightman_sigmoid_loss.py:108-122.
        def step(carry, _):
            to_right, acc = carry
            from_left = neighbour_exchange(to_right, axis_name, to_right=True)
            acc = acc + block(from_left, True)
            return (from_left, acc), None

        (_, loss), _ = lax.scan(step, (ztxt, loss), None, length=w - 1)

    return loss


def _ring_sigmoid_loss_overlapped(block, ztxt, axis_name: str, w: int, bidir: bool):
    """Double-buffered hop loop: every exchange is issued BEFORE the compute it
    could overlap with — hop 1 before the positive block, hop k+1 before hop
    k's negative blocks, the even-W remainder hop before the last pair's
    blocks. Hop order and accumulation order match the serial ring exactly
    (same reference semantics, same float add sequence), so the two are
    bitwise-comparable; only the comm/compute interleaving differs.
    """
    if bidir:
        num_bidir, remainder = divmod(w - 1, 2)
        if num_bidir == 0:
            # w == 2: the lone unidirectional remainder hop, issued before the
            # positive block (rwightman_sigmoid_loss.py:96-107 semantics).
            from_left = neighbour_exchange(ztxt, axis_name, to_right=True)
            return block(ztxt, False) + block(from_left, True)

        # Pair 1 on the wire while the positive block runs.
        first = neighbour_exchange_bidir(ztxt, ztxt, axis_name)
        loss = block(ztxt, False)
        (from_right, from_left), loss = double_buffered_scan(
            lambda pair: neighbour_exchange_bidir(pair[0], pair[1], axis_name),
            # Same accumulation order as the serial ring (from_right then
            # from_left — the reference's recv loop, rwightman:86-93).
            lambda pair, acc: acc + block(pair[0], True) + block(pair[1], True),
            first,
            loss,
            num_bidir,
        )
        if remainder:
            # Even W: issue the remainder hop BEFORE the last pair's blocks.
            # The serial ring sends its post-scan `to_right` (= the last
            # pair's from_left) — identical payload here.
            last = neighbour_exchange(from_left, axis_name, to_right=True)
        loss = loss + block(from_right, True) + block(from_left, True)
        if remainder:
            loss = loss + block(last, True)
        return loss

    # Unidirectional: W-1 rightward hops, hop 1 issued before the positive
    # block (rwightman_sigmoid_loss.py:108-122 semantics).
    first = neighbour_exchange(ztxt, axis_name, to_right=True)
    loss = block(ztxt, False)
    last, loss = double_buffered_scan(
        lambda cur: neighbour_exchange(cur, axis_name, to_right=True),
        lambda cur, acc: acc + block(cur, True),
        first,
        loss,
        w - 1,
    )
    return loss + block(last, True)
