"""Multi-host / multi-slice runtime — the TPU-native replacement for the reference's
process-group bring-up (``dist.init_process_group("gloo", ...)`` + MASTER_ADDR
rendezvous, /root/reference/test_distributed_sigmoid_loss.py:35-51).

On TPU pods there is no hand-rolled rendezvous: ``jax.distributed.initialize()``
discovers peers from the TPU runtime (or coordinator env vars on CPU/GPU), after which
every host sees the same global device list and the single-controller pjit model works
unchanged — the same meshes, the same collectives, zero changes to loss code. Across
slices, the outer mesh axis rides DCN while inner axes ride ICI; the helpers below
build meshes with that layout so the bandwidth-hungry axes (tp/sp) stay on ICI and only
the dp grad-sync crosses DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis, model_axis

__all__ = ["initialize_multihost", "make_hybrid_mesh", "global_batch_for"]


def initialize_multihost(**kwargs) -> tuple[int, int]:
    """Bring up the multi-host runtime; returns ``(process_index, process_count)``.

    On a TPU pod slice this needs no arguments (peers come from the TPU metadata
    service); elsewhere pass ``coordinator_address``/``num_processes``/``process_id``.
    Safe to call when already initialized or single-process (no-op).
    """
    if kwargs:
        # Explicit coordinator config: let failures propagate — silently degrading to
        # single-process would strand the other hosts at the rendezvous.
        jax.distributed.initialize(**kwargs)
    else:
        try:
            jax.distributed.initialize()
        except (RuntimeError, ValueError):
            # Already initialized, or single-process run with no coordinator.
            pass
    return jax.process_index(), jax.process_count()


def make_hybrid_mesh(
    dp_dcn: int | None = None,
    dp_ici: int = 1,
    tp_ici: int = 1,
    *,
    axis_names: tuple[str, str] = (data_axis, model_axis),
) -> Mesh:
    """(dp, tp) mesh spanning slices: dp's slow (DCN) factor outermost, tp on ICI.

    ``dp_dcn=None`` infers the DCN factor as ``device_count / (dp_ici * tp_ici)``.
    The returned mesh's dp axis has size ``dp_dcn * dp_ici``; collectives over tp
    never leave a slice.
    """
    n_dev = len(jax.devices())
    if dp_dcn is None:
        inner = dp_ici * tp_ici
        if n_dev % inner:
            raise ValueError(
                f"device count {n_dev} not divisible by dp_ici*tp_ici={inner}"
            )
        dp_dcn = n_dev // inner
    if dp_dcn * dp_ici * tp_ici != n_dev:
        raise ValueError(
            f"dp_dcn*dp_ici*tp_ici = {dp_dcn * dp_ici * tp_ici} != device count {n_dev}"
        )
    if dp_dcn > 1:
        devices = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(dp_ici, tp_ici),
            dcn_mesh_shape=(dp_dcn, 1),
        )
    else:
        devices = mesh_utils.create_device_mesh((dp_dcn * dp_ici, tp_ici))
    devices = np.asarray(devices).reshape(dp_dcn * dp_ici, tp_ici)
    return Mesh(devices, axis_names)


def global_batch_for(per_chip_batch: int, mesh: Mesh, axis_name: str = data_axis) -> int:
    """Global batch that puts ``per_chip_batch`` examples on each dp shard."""
    return per_chip_batch * mesh.shape[axis_name]
