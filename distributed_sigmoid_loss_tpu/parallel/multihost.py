"""Multi-host / multi-slice runtime — the TPU-native replacement for the reference's
process-group bring-up (``dist.init_process_group("gloo", ...)`` + MASTER_ADDR
rendezvous, /root/reference/test_distributed_sigmoid_loss.py:35-51).

On TPU pods there is no hand-rolled rendezvous: ``jax.distributed.initialize()``
discovers peers from the TPU runtime (or coordinator env vars on CPU/GPU), after which
every host sees the same global device list and the single-controller pjit model works
unchanged — the same meshes, the same collectives, zero changes to loss code. Across
slices, the outer mesh axis rides DCN while inner axes ride ICI; the helpers below
build meshes with that layout so the bandwidth-hungry axes (tp/sp) stay on ICI and only
the dp grad-sync crosses DCN.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.experimental import mesh_utils
from jax.sharding import Mesh

from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis, model_axis

__all__ = ["initialize_multihost", "make_hybrid_mesh", "global_batch_for"]

# Environment markers that a multi-host job context exists. When any is set, a failed
# bring-up must NEVER degrade to single-process: every host runs this same code, so the
# degradation would silently turn an N-host job into N independent trainings.
_MULTIHOST_ENV_VARS = (
    "COORDINATOR_ADDRESS",
    "JAX_COORDINATOR_ADDRESS",
    "JAX_COORDINATOR_IP",
    "MEGASCALE_COORDINATOR_ADDRESS",
    "TPU_WORKER_HOSTNAMES",
    "CLOUD_TPU_TASK_ID",
)


def _multihost_env_marker() -> str | None:
    import os

    for var in _MULTIHOST_ENV_VARS:
        value = os.environ.get(var)
        if not value:
            continue
        if var == "TPU_WORKER_HOSTNAMES" and "," not in value:
            # A single hostname is a 1-host job (some TPU runtimes set this even
            # for one host); only a multi-entry list implies peers exist.
            continue
        return var
    return None


def initialize_multihost(**kwargs) -> tuple[int, int]:
    """Bring up the multi-host runtime; returns ``(process_index, process_count)``.

    On a TPU pod slice this needs no arguments (peers come from the TPU metadata
    service); elsewhere pass ``coordinator_address``/``num_processes``/``process_id``.
    Safe to call when already initialized or single-process (no-op).
    """
    if kwargs:
        # Explicit coordinator config: let failures propagate — silently degrading to
        # single-process would strand the other hosts at the rendezvous, and a
        # conflicting re-init on a live runtime must raise (jax enforces it), not
        # silently keep the previous identity.
        jax.distributed.initialize(**kwargs)
    elif jax.distributed.is_initialized():
        # State check, not message matching: an argument-less call on a live runtime
        # (e.g. a pod run invoking this helper from two entry points) is the benign
        # no-op.
        return jax.process_index(), jax.process_count()
    else:
        try:
            jax.distributed.initialize()
        except RuntimeError as e:
            # A transient coordinator failure must propagate — swallowing it would
            # strand every other host at the rendezvous while this one trains alone.
            # (The already-initialized case is handled by the state check above;
            # message matching below covers only the no-distributed-context cases,
            # each pinned by tests/test_multihost_process.py.)
            msg = str(e).lower()
            benign = (
                # Backend started without a distributed client: benign single-
                # process, UNLESS a multi-host env marker says peers exist.
                "must be called before" in msg
                # No coordinator to auto-detect — plain single-process run.
                or "unable to detect" in msg
                or "could not detect" in msg
            )
            if benign and (marker := _multihost_env_marker()):
                raise RuntimeError(
                    f"initialize_multihost: jax.distributed.initialize() failed "
                    f"({e}) but {marker} is set, so this looks like one host of a "
                    f"multi-host job. Refusing to degrade to single-process "
                    f"training; call initialize_multihost() before any other jax "
                    f"use, or pass coordinator_address/num_processes/process_id."
                ) from e
            if not benign:
                raise
        except ValueError as e:
            # "coordinator_address should be defined" = nothing to auto-detect, the
            # plain single-process no-op. Any other ValueError (e.g. a coordinator
            # address present but process count missing) is a partial multi-host
            # config — propagate rather than silently train alone.
            if "coordinator_address" not in str(e):
                raise
            if marker := _multihost_env_marker():
                raise RuntimeError(
                    f"initialize_multihost: nothing to auto-detect ({e}) but "
                    f"{marker} is set — one host of a multi-host job would train "
                    f"alone. Pass coordinator_address/num_processes/process_id."
                ) from e
    return jax.process_index(), jax.process_count()


def make_hybrid_mesh(
    dp_dcn: int | None = None,
    dp_ici: int | None = None,
    tp_ici: int = 1,
    *,
    axis_names: tuple[str, str] = (data_axis, model_axis),
) -> Mesh:
    """(dp, tp) mesh spanning slices: dp's slow (DCN) factor outermost, tp on ICI.

    ``dp_dcn=None`` infers the DCN factor from the actual slice topology (number of
    distinct ``slice_index`` values, falling back to 1 when devices carry no slice
    attribute — single-slice or CPU emulation). ``dp_ici=None`` absorbs whatever
    device factor remains; an explicit ``dp_ici`` that doesn't fill the device count
    raises. The returned mesh's dp axis has size ``dp_dcn * dp_ici``; collectives
    over tp never leave a slice.
    """
    devices = _hybrid_device_array(dp_dcn, dp_ici, tp_ici, jax.devices())
    return Mesh(devices, axis_names)


def _hybrid_device_array(dp_dcn, dp_ici, tp_ici, devices) -> np.ndarray:
    """The (dp_dcn*dp_ici, tp_ici) device arrangement behind
    :func:`make_hybrid_mesh` — split out so the multi-slice (``dp_dcn > 1``)
    branch is testable with fake multi-slice device objects (real multi-slice
    metadata never exists in the CI environment)."""
    n_dev = len(devices)
    if dp_dcn is None:
        # The DCN factor is the real slice count, NOT the leftover device factor:
        # on a single slice (or CPU emulation, where devices carry no slice_index)
        # the leftover belongs to dp_ici.
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        dp_dcn = len(slice_ids)
    if dp_ici is None:
        if n_dev % (dp_dcn * tp_ici) != 0:
            raise ValueError(
                f"dp_dcn*tp_ici = {dp_dcn * tp_ici} does not divide "
                f"device count {n_dev}"
            )
        dp_ici = n_dev // (dp_dcn * tp_ici)
    if dp_dcn * dp_ici * tp_ici != n_dev:
        raise ValueError(
            f"dp_dcn*dp_ici*tp_ici = {dp_dcn * dp_ici * tp_ici} != device count {n_dev}"
        )
    if dp_dcn > 1:
        arr = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=(dp_ici, tp_ici),
            dcn_mesh_shape=(dp_dcn, 1),
            devices=devices,
        )
    else:
        arr = mesh_utils.create_device_mesh(
            (dp_dcn * dp_ici, tp_ici), devices=devices
        )
    return np.asarray(arr).reshape(dp_dcn * dp_ici, tp_ici)


def global_batch_for(per_chip_batch: int, mesh: Mesh, axis_name: str = data_axis) -> int:
    """Global batch that puts ``per_chip_batch`` examples on each dp shard."""
    return per_chip_batch * mesh.shape[axis_name]
