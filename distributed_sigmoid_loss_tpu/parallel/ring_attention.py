"""Ring attention: sequence-parallel exact attention over a ``ppermute`` ring.

The reference's ring variant streams the *batch* dimension of contrastive negatives
around a ring (rwightman_sigmoid_loss.py:71-122) — SURVEY.md §5 identifies this as the
blockwise/ring-attention communication topology. This module applies the same topology
to the *sequence* dimension, making long-context towers first-class: each shard holds a
sequence block of Q/K/V; K/V blocks ride the ring ``W-1`` hops while the local Q block
accumulates exact attention via online (flash-style) softmax. Memory per chip stays
O(s_local²) and the ppermute transfer overlaps the block matmul — the standard TPU
recipe for million-token contexts.

Gradients flow through ``lax.scan`` + ``ppermute`` automatically (the VJP re-runs the
ring in reverse), mirroring how the reference's hand-written ``NeighbourExchange``
backward shifts grads the opposite way (distributed_utils.py:74-77).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from distributed_sigmoid_loss_tpu.parallel.collectives import pvary, ring_shift_right

__all__ = ["ring_self_attention", "dense_attention"]

_NEG_INF = -1e30


def dense_attention(q, k, v, *, causal=False, scale=None):
    """Reference single-device attention. q/k/v: (b, s, h, dh) → (b, s, h, dh)."""
    dh = q.shape[-1]
    scale = (dh ** -0.5) if scale is None else scale
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = q.shape[1], k.shape[1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool), k=s_k - s_q)
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def ring_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    scale: float | None = None,
    checkpoint_steps: bool = True,
) -> jax.Array:
    """Exact sequence-parallel attention; call inside ``shard_map``.

    Args:
      q, k, v: (b, s_local, h, dh) — this shard's sequence block, where the global
        sequence is the axis-index-ordered concatenation of shards.
      causal: mask using *global* positions (shard offset = axis_index · s_local).
      checkpoint_steps: rematerialize each ring step in the backward pass instead of
        storing per-step logits (the long-context memory trade).

    Returns (b, s_local, h, dh) — this shard's block of the exact attention output.
    """
    w = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    b, s, h, dh = q.shape
    scale = (dh ** -0.5) if scale is None else scale

    q32 = q.astype(jnp.float32)

    def block_update(carry_o, carry_m, carry_l, k_blk, v_blk, src_idx):
        """One online-softmax accumulation of q against a (k,v) block from shard
        ``src_idx``."""
        logits = jnp.einsum(
            "bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)
        ) * scale
        if causal:
            q_pos = idx * s + lax.broadcasted_iota(jnp.int32, (s, s), 0)
            k_pos = src_idx * s + lax.broadcasted_iota(jnp.int32, (s, s), 1)
            mask = q_pos >= k_pos
            logits = jnp.where(mask[None, None], logits, _NEG_INF)

        m_blk = logits.max(axis=-1)  # (b, h, q)
        m_new = jnp.maximum(carry_m, m_blk)
        # Guard fully-masked rows: keep exp arguments finite.
        corr = jnp.exp(carry_m - m_new)
        p = jnp.exp(logits - m_new[..., None])  # (b, h, q, k)
        l_new = carry_l * corr + p.sum(axis=-1)
        o_new = carry_o * corr[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return o_new, m_new, l_new

    if checkpoint_steps:
        block_update = jax.checkpoint(block_update, static_argnums=())

    def step(carry, i):
        o, m, l, k_blk, v_blk = carry
        src_idx = (idx - i) % w  # block i hops ago originated at shard idx - i
        o, m, l = block_update(o, m, l, k_blk, v_blk, src_idx)
        # Shift K/V one hop right for the next iteration (last shift is unused but
        # keeps the scan uniform; XLA overlaps it with the block math above).
        k_blk = ring_shift_right(k_blk, axis_name)
        v_blk = ring_shift_right(v_blk, axis_name)
        return (o, m, l, k_blk, v_blk), None

    # Freshly-created constants are "unvarying" under shard_map's varying-axis typing;
    # mark them as varying over the ring axis so the scan carry types line up.
    o0 = pvary(jnp.zeros((b, h, s, dh), jnp.float32), axis_name)
    m0 = pvary(jnp.full((b, h, s), _NEG_INF, jnp.float32), axis_name)
    l0 = pvary(jnp.zeros((b, h, s), jnp.float32), axis_name)

    (o, m, l, _, _), _ = lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(w), length=w
    )

    out = o / jnp.maximum(l[..., None], 1e-38)
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def make_ring_attention(mesh, axis_name: str = "sp", **kw):
    """Convenience wrapper: global (b, S, h, dh) arrays in, sequence sharded over
    ``axis_name``."""
    from jax.sharding import PartitionSpec as P

    fn = functools.partial(ring_self_attention, axis_name=axis_name, **kw)
    spec = P(None, axis_name)
    return jax.jit(
        jax.shard_map(
            fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec
        )
    )
