"""Cross-replica update sharding: ONE placement rule for grads, optimizer
state, and the param publish (graftshard).

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (Xu et al., arXiv:2004.13336, PAPERS.md) shows the whole
gradient -> optimizer -> new-param path can run on 1/W of each tensor per
replica: reduce-scatter the gradient sum, update the shard, all-gather the
new params once. The XLA paper does this as a compiler pass; the JAX-native
spelling is sharding *constraints* placed where the dataflow forks —
GSPMD then emits exactly that reduce-scatter / shard-compute / all-gather
program. This module is the one home of that placement logic; before it,
``zero1_constrain`` (train_step.py) re-pinned the optimizer tree after the
fact per-builder, and the compressed step compressed the *whole* gradient
instead of the 1/W shard.

Three modes (``UPDATE_SHARDING_MODES``), CLI ``--update-sharding``:

- ``"off"``   — replicated update, the plain data-parallel step.
- ``"zero1"`` — the historical ZeRO-1 placement: optimizer state sharded
  over the data axis, but only leaves whose leading dim divides the axis
  size exactly (``shape[0] % W == 0``); grads and params stay replicated.
  Kept bit-compatible with the ``--zero1`` era so existing checkpoints
  restore onto identical layouts.
- ``"full"``  — the 2004.13336 scheme: grads are constrained to the shard
  spec *before* the optax update (XLA turns the dp all-reduce into a
  reduce-scatter), optimizer state lives sharded, and the updated params
  are constrained back to their model shardings (one all-gather publishes
  the weights). The leading-dim rule is permissive: any leaf with
  ``shape[0] >= W`` shards. Ragged tails (``shape[0] % W != 0``) are
  zero-padded explicitly in the manual compressed path
  (:func:`psum_scatter_shard` / :func:`ef_slot_shape`), so their wire and
  EF residuals genuinely shard; in the constraint-based path jax (0.4.x)
  cannot represent uneven shardings and ``with_sharding_constraint``
  silently degrades those leaves to replicated — numerics are unchanged,
  only their at-rest moment bytes stay un-sharded.
  zero1 checkpoints stay loadable — orbax restores by value into the
  target's shardings, and full shards a superset of zero1's leaves.

The compressed step (train/compressed_step.py) cannot lean on GSPMD inside
its fully-manual shard_map region, so it uses the explicit collective
helpers here: :func:`psum_scatter_shard` (zero-pad the leading dim to a
multiple of W, then a tiled ``lax.psum_scatter``) produces the same
shard the constraint-based path owns, the per-rung compressor then sees
1/W of every tensor on the DCN wire, and the error-feedback residual is
shard-local (:func:`ef_slot_shape`).
"""

from __future__ import annotations

import math
from typing import Any

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P, Sharding

__all__ = [
    "UPDATE_SHARDING_MODES",
    "resolve_update_sharding",
    "shardable",
    "padded_rows",
    "update_shard_spec",
    "constrain_update_sharding",
    "capture_shardings",
    "apply_sharded_update",
    "psum_scatter_shard",
    "unpad_like",
    "ef_slot_shape",
    "shard_leaf_sizes",
    "opt_mem_bytes_per_replica",
]

UPDATE_SHARDING_MODES = ("off", "zero1", "full")

# Sentinel for "no captured sharding — leave this leaf to the compiler";
# distinct from None so pytrees of shardings keep their leaf structure.
KEEP = object()


def resolve_update_sharding(update_sharding: str = "", zero1: bool = False) -> str:
    """Resolve the mode from the new flag + the deprecated ``zero1`` alias.

    ``update_sharding=""`` (unset) defers to the legacy flag: ``zero1=True``
    means ``"zero1"``, else ``"off"``. An explicit mode wins — except the
    contradiction ``zero1=True`` with ``update_sharding="off"``, which is
    refused rather than silently dropping either flag.
    """
    if update_sharding in ("", None):
        return "zero1" if zero1 else "off"
    if update_sharding not in UPDATE_SHARDING_MODES:
        raise ValueError(
            f"update_sharding must be one of {UPDATE_SHARDING_MODES}, "
            f"got {update_sharding!r}"
        )
    if zero1 and update_sharding == "off":
        raise ValueError(
            "zero1=True contradicts update_sharding='off' — drop the "
            "deprecated zero1 flag (it is the same lever as "
            "update_sharding='zero1')"
        )
    return update_sharding


def shardable(shape, w: int, mode: str = "full") -> bool:
    """Does a leaf of ``shape`` shard its leading dim over a size-``w`` axis?

    THE placement predicate — both step builders, the EF layout, the wire
    accounting, and the tests ask this one function, so the rule cannot
    drift per call site. zero1 keeps the historical exact-divisibility rule
    (layout-identical to the ``--zero1`` era); full shards every leaf with
    at least one row per replica and pads the ragged tail.
    """
    if mode == "off" or w <= 1 or not shape:
        return False
    if mode == "zero1":
        return shape[0] >= w and shape[0] % w == 0
    if mode == "full":
        return shape[0] >= w
    raise ValueError(f"unknown update_sharding mode {mode!r}")


def padded_rows(dim0: int, w: int) -> int:
    """``dim0`` rounded up to a multiple of ``w`` (the padded shard layout)."""
    return -(-dim0 // w) * w


def update_shard_spec(shape, w: int, axis_name: str = "dp", mode: str = "full") -> P:
    """PartitionSpec for one update-path leaf: ``P(axis)`` iff shardable."""
    return P(axis_name) if shardable(shape, w, mode) else P()


def constrain_update_sharding(
    tree: Any, mesh: Mesh, axis_name: str = "dp", mode: str = "full"
) -> Any:
    """Constrain every array leaf of ``tree`` to its update-shard placement.

    Inside jit this is where GSPMD learns the intent: constraining the
    *gradients* makes the dp sync a reduce-scatter, constraining the
    *optimizer state* keeps the optax math on shards. ``mode="off"`` (or a
    trivial axis) is the identity.
    """
    if mode == "off":
        return tree
    w = dict(mesh.shape).get(axis_name, 1)
    if w <= 1:
        return tree

    def con(x):
        if not hasattr(x, "shape"):
            return x
        return lax.with_sharding_constraint(
            x, NamedSharding(mesh, update_shard_spec(x.shape, w, axis_name, mode))
        )

    return jax.tree.map(con, tree)


def capture_shardings(tree: Any) -> Any:
    """Concrete leaf shardings of ``tree`` (``KEEP`` where unavailable).

    Used by the full-mode step builders to record the model's at-rest param
    placements from the first concrete state they see — the all-gather
    publish target. Tracers and abstract leaves (the jaxpr-audit path traces
    steps on ``eval_shape`` states) capture as ``KEEP``, which
    :func:`apply_sharded_update` treats as "compiler's choice".
    """

    def of(x):
        if isinstance(x, jax.core.Tracer):
            return KEEP
        s = getattr(x, "sharding", None)
        return s if isinstance(s, Sharding) else KEEP

    return jax.tree.map(of, tree)


def apply_sharded_update(
    state: Any,
    grads: Any,
    *,
    mesh: Mesh,
    axis_name: str = "dp",
    mode: str = "off",
    param_shardings: Any = None,
):
    """``state.apply_gradients`` with the update path placed per ``mode``.

    The one shared optimizer-application recipe of both step builders
    (regular + compressed), replacing their per-builder ``zero1_constrain``
    re-pin branches:

    - ``off``: plain ``apply_gradients``.
    - ``zero1``: ``apply_gradients`` then the optimizer tree constrained to
      the zero1 spec — byte-identical to the historical behavior.
    - ``full``: grads constrained to the shard spec *first* (the
      reduce-scatter), the optimizer tree constrained sharded, and —
      when ``param_shardings`` is given — the updated params constrained
      back to their at-rest placements (the single all-gather publish;
      without it GSPMD may propagate the shard layout into the returned
      params and the next donated call recompiles on the new layout).
    """
    w = dict(mesh.shape).get(axis_name, 1)
    if mode == "off" or w <= 1:
        return state.apply_gradients(grads=grads)
    if mode == "full":
        grads = constrain_update_sharding(grads, mesh, axis_name, mode)
    state = state.apply_gradients(grads=grads)
    state = state.replace(
        opt_state=constrain_update_sharding(state.opt_state, mesh, axis_name, mode)
    )
    if mode == "full" and param_shardings is not None:
        def publish(p, s):
            if not isinstance(s, Sharding):
                return p
            return lax.with_sharding_constraint(p, s)

        state = state.replace(
            params=jax.tree.map(publish, state.params, param_shardings)
        )
    return state


def psum_scatter_shard(x: jax.Array, axis_name: str, w: int) -> jax.Array:
    """Reduce-scatter one gradient leaf inside a manual (shard_map) region.

    Zero-pads the leading dim to a multiple of ``w`` then runs a tiled
    ``lax.psum_scatter``: member i of ``axis_name`` receives the SUM of row
    block i — exactly the rows :func:`update_shard_spec` assigns it, so the
    shard that leaves the region under an ``out_specs=P(axis)`` lands where
    the constraint-based optimizer path expects it, no reshard. Returns the
    (padded_rows/w, ...) shard of the SUM — callers divide for the mean.
    """
    pad = padded_rows(x.shape[0], w) - x.shape[0]
    if pad:
        x = jnp.concatenate([x, jnp.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
    return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)


def unpad_like(tree: Any, ref: Any) -> Any:
    """Slice padded leading dims back to the reference tree's shapes.

    The inverse of :func:`psum_scatter_shard`'s padding, applied OUTSIDE the
    manual region where shapes are global again: slicing a dp-sharded array
    along its sharded dim is a local mask under GSPMD (uneven sharding), not
    a gather.
    """
    return jax.tree.map(
        lambda x, r: x[: r.shape[0]] if x.shape != r.shape else x, tree, ref
    )


def ef_slot_shape(shape, n_slices: int, w: int, mode: str = "off") -> tuple:
    """Error-feedback slot shape for one param leaf.

    ``(n_slices, *shape)`` replicated-grad layout, except under full update
    sharding where the residual is SHARD-LOCAL: ``(n_slices,
    padded_rows(shape[0], w), *shape[1:])``, sharded ``(dcn, dp)`` — each
    replica carries only the residual of the shard it quantizes.
    """
    if shardable(shape, w, mode):
        return (n_slices, padded_rows(shape[0], w)) + tuple(shape[1:])
    return (n_slices,) + tuple(shape)


def shard_leaf_sizes(params: Any, w: int, mode: str = "full") -> list:
    """Per-leaf element counts of the update-path operand each replica owns.

    Under full sharding the compressor (and the BitController's payload
    table) sees the padded 1/W shard, not the whole tensor; other modes see
    full tensors. Matches ``adaptive_compression.leaf_sizes`` ordering.
    """
    sizes = []
    for p in jax.tree.leaves(params):
        shape = tuple(p.shape)
        if shardable(shape, w, mode):
            sizes.append(
                (padded_rows(shape[0], w) // w) * int(math.prod(shape[1:]))
            )
        else:
            sizes.append(int(math.prod(shape)))
    return sizes


def opt_mem_bytes_per_replica(opt_state: Any) -> int | None:
    """Measured per-replica bytes of the optimizer tree, for the bench
    record / LEDGER field of the same name.

    Primary: ``compiled_memory_stats`` of an identity-shaped jit over the
    tree — the compiler's own per-device output allocation, the figure the
    ≥0.6·W× regression pin asserts. Fallback (backends without memory
    stats): sum of addressable shard bytes. None when neither is available.
    """
    from distributed_sigmoid_loss_tpu.utils.profiling import (
        memory_stats_of_compiled,
    )

    try:
        compiled = jax.jit(lambda o: jax.tree.map(jnp.copy, o)).lower(
            opt_state
        ).compile()
        stats = memory_stats_of_compiled(compiled)
    except Exception:
        stats = None
    if stats is not None and stats.get("output_size_in_bytes") is not None:
        return int(stats["output_size_in_bytes"])
    total = 0
    for leaf in jax.tree.leaves(opt_state):
        sharding = getattr(leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(leaf.shape)
        else:
            shape = getattr(leaf, "shape", ())
        total += int(math.prod(shape)) * int(
            getattr(getattr(leaf, "dtype", None), "itemsize", 4)
        )
    return total
