"""Pipeline parallelism: GPipe microbatch scheduling over a ``pp`` mesh axis.

The reference has no pipeline layer (its towers are toy Linears,
/root/reference/test_distributed_sigmoid_loss.py:71-76); this module is part of the
beyond-reference scale story, alongside tensor (tp), sequence (sp), and data (dp)
parallelism: deep towers whose layers don't fit one chip are split into S *stages*
laid out along a ``pp`` mesh axis, and M microbatches stream through the stages in
the classic GPipe schedule (S + M - 1 ticks, bubble fraction (S-1)/(S+M-1)).

TPU-native design, not a port of torch.distributed.pipelining:

- **One jitted SPMD program.** Every stage runs the same code under ``shard_map``;
  "which stage am I" is ``lax.axis_index("pp")``, and stage-to-stage activation
  transfer is a single ``ppermute`` ring hop per tick — the ICI-neighbour pattern
  the fabric is built for. There are no per-stage processes, queues, or schedules.
- **Stage-stacked parameters.** Stage s owns ``params[s]`` of a (S, ...)-stacked
  pytree sharded over ``pp`` — with ``depth//S`` transformer layers per stage this
  is exactly the ``nn.scan`` layer-stacked layout reshaped to (S, depth//S, ...),
  so pipeline placement is a pure sharding annotation on the existing tree.
- **Autodiff = the reverse schedule.** The backward pipeline (cotangents flowing
  last-stage → first-stage) is the transpose of ``lax.scan`` + ``ppermute`` — jax
  derives it; nothing hand-written, mirroring how the framework gets the
  reference's ``NeighbourExchange.backward`` for free (collectives.py).
- **Static shapes.** Warmup/drain bubbles run the stage on don't-care data and
  mask the writes (``jnp.where``), keeping every tick identical for XLA.

Composability: ``gpipe`` is manual over ``pp`` only (``axis_names={"pp"}``), so
dp/tp axes of the same mesh keep working through GSPMD — batch stays dp-sharded,
stage weights stay tp-sharded, and the pipeline only moves activations.

Memory: by default microbatch inputs/outputs are replicated over ``pp`` (each
stage holds the (M, ...) buffer — M·|x| HBM per chip). ``gpipe(stream_io=True)``
removes that: the buffers block-shard over ``pp`` and a ppermute conveyor
delivers each microbatch to stage 0 exactly when the schedule consumes it (and
ships outputs back to their home shard), cutting the buffer cost S-fold at zero
extra ticks. The pp towers use it whenever S | M (parallel/pp_towers.py);
``one_f_one_b(stream_inputs=True)`` applies the same input conveyor to the
1F1B schedule (whose outputs are already O(params)).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.collectives import (
    pvary,
    ring_shift_left,
    ring_shift_right,
)

__all__ = [
    "pipeline_axis",
    "gpipe",
    "one_f_one_b",
    "stack_stage_params",
    "make_layer_stage_fn",
]

pipeline_axis = "pp"


def stack_stage_params(layer_params: Any, num_stages: int) -> Any:
    """Reshape layer-stacked params (leaves ``(depth, ...)``) to stage-major
    ``(num_stages, depth // num_stages, ...)`` — the layout :func:`gpipe` shards
    over the ``pp`` axis. ``depth`` must divide evenly into stages."""

    def reshape(leaf):
        depth = leaf.shape[0]
        if depth % num_stages:
            raise ValueError(
                f"depth {depth} does not divide into {num_stages} pipeline stages"
            )
        return leaf.reshape((num_stages, depth // num_stages) + leaf.shape[1:])

    return jax.tree.map(reshape, layer_params)


def make_layer_stage_fn(layer_apply: Callable[[Any, jax.Array], jax.Array]) -> Callable:
    """Stage function applying a stack of identical layers sequentially.

    ``layer_apply(layer_params, x) -> x`` is one layer (e.g.
    ``lambda p, x: block.apply({"params": p}, x)``); the returned stage function
    takes the stage's ``(layers_per_stage, ...)``-stacked params and scans the
    layers — the inner-depth analogue of ``Encoder(scan_layers=True)``.
    """

    def stage_fn(stage_params, x):
        def body(carry, p):
            return layer_apply(p, carry), None

        x, _ = lax.scan(body, x, stage_params)
        return x

    return stage_fn


def _psum_replicate(x: jax.Array, axis_name: str) -> jax.Array:
    """``lax.psum`` whose backward is identity — for ``check_vma=False`` regions.

    The masked output collect (``psum(where(stage == last, out, 0))``) relies
    on the vma-TYPED transpose of a variant→invariant psum, which is identity
    per device (each device's cotangent flows to its own operand). Under an
    enclosing ``check_vma=False`` shard_map the unchecked transpose re-psums
    the cotangent instead — an S-fold overcount, since every pp plane's
    identical downstream loss copy would then contribute once per plane
    (measured: exactly 2x block grads at S=2). The custom VJP pins the
    per-plane semantics.
    """

    @jax.custom_vjp
    def f(v):
        return lax.psum(v, axis_name)

    f.defvjp(lambda v: (lax.psum(v, axis_name), None), lambda _, ct: (ct,))
    return f(x)


def _input_conveyor(xs_home, stage, axis_name, num_stages, num_micro):
    """The just-in-time input conveyor shared by ``gpipe(stream_io=True)`` and
    ``one_f_one_b(stream_inputs=True)`` (both consume microbatch ``t`` at
    stage 0 on tick ``t``).

    ``xs_home``: this stage's pp-sharded ``(M/S, ...)`` home block. Returns
    ``(conv0, advance)`` where ``conv0`` is the conveyor slot before tick 0
    and ``advance(conv, t)`` produces the slot for tick ``t+1``: inject from
    home storage when the next microbatch's transit starts here
    (``stage == home(t+1+stage)``, home(m) = ⌊mS/M⌋), else receive one hop
    from the stage above (``ring_shift_left``). Invariant: before tick t,
    stage p holds microbatch ``t+p`` iff ``p <= home(t+p)`` (in transit
    toward stage 0, one hop per tick). At t=0 that is microbatch ``p`` iff
    ``p`` IS its home — only stage 0 for M > S, every stage when M == S.
    """
    per = num_micro // num_stages

    def home(m):
        return jnp.clip(m * num_stages // num_micro, 0, num_stages - 1)

    conv0 = jnp.where(
        stage == home(stage), xs_home[0], jnp.zeros_like(xs_home[0])
    )

    def advance(conv, t):
        m_next = t + 1 + stage
        j_in = jnp.clip(m_next - stage * per, 0, per - 1)
        return jnp.where(
            stage == home(m_next),
            lax.dynamic_index_in_dim(xs_home, j_in, 0, keepdims=False),
            ring_shift_left(conv, axis_name),
        )

    return conv0, advance


def gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = pipeline_axis,
    checkpoint_stages: bool = False,
    stream_io: bool = False,
    enclosing_manual: bool = False,
) -> jax.Array:
    """Run ``microbatches`` through ``num_stages`` pipelined stages; returns outputs.

    Args:
      stage_fn: ``(per_stage_params, x) -> y`` with ``y.shape == x.shape`` (a
        residual-block stack; the equal-shape constraint is what lets one ring
        buffer carry every stage boundary).
      stage_params: pytree with leading stage axis ``S == mesh.shape[axis_name]``
        on every leaf, sharded over ``axis_name`` (see :func:`stack_stage_params`).
      microbatches: ``(M, mb, ...)`` array of M microbatches. Any M ≥ 1 works;
        throughput-wise M ≫ S amortizes the (S-1)-tick bubble.
      checkpoint_stages: rematerialize each stage call in the backward pipeline
        (GPipe's standard activation-memory trade).
      stream_io: shard the microbatch buffers over ``pp`` instead of
        replicating them (requires ``S | M``) — per-stage HBM for inputs AND
        outputs drops S-fold, from ``2·M·|x|`` to ``2·(M/S)·|x|`` plus two
        in-flight slots. Mechanism: the M dim's natural block sharding makes
        stage ``p`` the HOME of microbatches ``[p·M/S, (p+1)·M/S)``; an input
        conveyor moves each microbatch one ``ppermute`` hop per tick toward
        stage 0, timed to arrive exactly when the schedule consumes it
        (microbatch ``m`` departs home ``p=⌊mS/M⌋`` at tick ``m-p``), and a
        mirrored output conveyor carries finished microbatches from the last
        stage back to their home shard (``y_m`` arrives at tick
        ``m+2(S-1)-p`` — the last arrival lands on the existing final tick,
        so streaming costs ZERO extra ticks, just 2 activation-sized hops per
        tick riding the same ICI links as the stage boundary).

      enclosing_manual: the caller is ALREADY inside a ``shard_map`` manual
        over ``axis_name`` (e.g. the compressed train step's fully-manual
        ``(dcn, dp, pp)`` region — nested shard_maps over disjoint axis sets
        are not supported, so the device-level schedule is entered directly).
        ``stage_params`` leaves must then be this device's LOCAL stage slice
        (``(layers_per_stage, ...)``, no leading stage dim) and
        ``microbatches`` the local ``(M, mb_local, ...)`` block, replicated
        over ``axis_name``; outputs come back replicated the same way.

    Returns:
      ``(M, mb, ...)`` outputs of the full S-stage stack — replicated over
      ``pp`` normally, sharded over ``pp`` on the M dim under ``stream_io``.
    """
    num_stages = mesh.shape[axis_name]
    num_micro = microbatches.shape[0]
    if stream_io and num_micro % num_stages:
        raise ValueError(
            f"stream_io requires stages | microbatches, got S={num_stages}, "
            f"M={num_micro} (the M dim block-shards over pp as the home "
            f"layout; pad M or use stream_io=False)"
        )
    if checkpoint_stages:
        stage_fn = jax.checkpoint(stage_fn)

    def device_fn(params, xs):
        # params: this stage's LOCAL (layers_per_stage, ...) slice.
        stage = lax.axis_index(axis_name)
        if not enclosing_manual:
            # Under an enclosing check_vma=False region the vma machinery is
            # off and pcast's typed transpose would reject the untyped
            # cotangents; the wrapped path needs the varying mark for scan.
            xs = pvary(xs, axis_name)
        # Ring buffer carrying the stage boundary + the output accumulator
        # (zeros_like the varying xs, so both are varying too).
        act0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)

        def tick(carry, t):
            act, out = carry
            # Stage boundary hop: every stage sends its last activation right and
            # receives its predecessor's. Stage 0's "received" slot is ignored in
            # favor of the next microbatch feed.
            received = ring_shift_right(act, axis_name)
            feed = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
            )
            x_in = jnp.where(stage == 0, feed, received)
            y = stage_fn(params, x_in)
            # The last stage finishes microbatch t-(S-1) at tick t; warmup ticks
            # (t < S-1) write nowhere. Stage-0 re-feeds past M need no guard:
            # they would reach the last stage only at tick M+S-1, past the scan.
            out_idx = jnp.clip(t - (num_stages - 1), 0, num_micro - 1)
            is_ready = (stage == num_stages - 1) & (t >= num_stages - 1)
            out = jnp.where(
                is_ready,
                lax.dynamic_update_index_in_dim(out, y.astype(out.dtype), out_idx, 0),
                out,
            )
            return (y, out), None

        (_, out), _ = lax.scan(
            tick, (act0, out0), jnp.arange(num_micro + num_stages - 1)
        )
        # Only the last stage holds real outputs; the masked psum replicates them
        # to every stage (its transpose feeds cotangents back to the last stage).
        collect = _psum_replicate if enclosing_manual else lax.psum
        return collect(
            jnp.where(stage == num_stages - 1, out, jnp.zeros_like(out)), axis_name
        )

    def device_fn_streamed(params, xs_home):
        # xs_home: (M/S, mb, ...) — this stage's home block of microbatches.
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        stage = lax.axis_index(axis_name)
        s, per = num_stages, num_micro // num_stages
        act0 = jnp.zeros_like(xs_home[0])
        conv0, advance_conv = _input_conveyor(
            xs_home, stage, axis_name, num_stages, num_micro
        )
        oconv0 = jnp.zeros_like(xs_home[0])
        out0 = jnp.zeros_like(xs_home)

        def tick(carry, t):
            act, conv, oconv, out_local = carry
            received = ring_shift_right(act, axis_name)
            x_in = jnp.where(stage == 0, conv, received)
            y = stage_fn(params, x_in)

            conv = advance_conv(conv, t)

            # Output conveyor: the last stage inserts the microbatch it just
            # finished; everyone else passes their slot one hop toward its
            # home. After this tick, stage p holds y of m = t - 2(S-1) + p.
            fresh = (stage == s - 1) & (t >= s - 1)
            oconv = jnp.where(
                fresh, y.astype(oconv.dtype), ring_shift_left(oconv, axis_name)
            )
            m_here = t - 2 * (s - 1) + stage
            arrived = (
                (m_here >= 0)
                & (m_here < num_micro)
                & (stage == jnp.clip(m_here * s // num_micro, 0, s - 1))
            )
            j_out = jnp.clip(m_here - stage * per, 0, per - 1)
            out_local = jnp.where(
                arrived,
                lax.dynamic_update_index_in_dim(out_local, oconv, j_out, 0),
                out_local,
            )
            return (y, conv, oconv, out_local), None

        (_, _, _, out_local), _ = lax.scan(
            tick,
            (act0, conv0, oconv0, out0),
            jnp.arange(num_micro + num_stages - 1),
        )
        return out_local

    if enclosing_manual:
        if stream_io:
            raise ValueError(
                "enclosing_manual with stream_io is not supported (the "
                "streamed buffers' pp sharding would have to be expressed in "
                "the ENCLOSING shard_map's specs); use stream_io=False"
            )
        return device_fn(stage_params, microbatches)
    if stream_io:
        return jax.shard_map(
            device_fn_streamed,
            mesh=mesh,
            in_specs=(P(axis_name), P(axis_name)),
            out_specs=P(axis_name),
            axis_names={axis_name},
        )(stage_params, microbatches)

    def device_fn_sliced(params, xs):
        # shard_map's P(axis_name) in_spec delivers a leading size-1 stage dim.
        return device_fn(jax.tree.map(lambda p: jnp.squeeze(p, 0), params), xs)

    return jax.shard_map(
        device_fn_sliced,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        axis_names={axis_name},
    )(stage_params, microbatches)


def one_f_one_b(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    loss_fn: Callable[[jax.Array], jax.Array],
    *,
    mesh: Mesh,
    axis_name: str = pipeline_axis,
    stream_inputs: bool = False,
) -> tuple[jax.Array, Any]:
    """1F1B pipeline training step: ``(mean loss, stage-param grads)``.

    :func:`gpipe` + autodiff is GPipe also in *memory*: the forward scan saves
    every microbatch's stage boundary, so activation memory grows O(M). This
    schedule hand-orchestrates the backward instead — each global tick runs ONE
    forward and ONE backward sub-tick on every stage (the 1F1B steady state),
    and a stage keeps a forward input stashed only until its own backward
    consumes it. The stash is a ring buffer of static depth ``2S-1``:
    activation memory is O(S), independent of M — the property that lets
    M ≫ S shrink the bubble without growing HBM.

    Schedule (stage s, microbatch m, S stages, global tick u):

    - forward of m at s:   u = m + s
    - backward of m at s:  u = m + 2(S-1) - s  (uniform S-1-tick backward
      delay; at the LAST stage forward and backward of a microbatch share a
      tick, so the loss cotangent seeds the backward stream with no stash)
    - stash residence at s: 2(S-1-s) ticks  →  depth 2S-1 covers every stage
    - total ticks: M + 2(S-1); per-tick work = 1 fwd + 1 bwd (the backward
      sub-tick re-runs the stage forward under ``jax.vjp`` — same recompute
      trade as ``gpipe(checkpoint_stages=True)``)

    Cotangents ride the reverse ring (``ppermute`` left) exactly like the
    reference's backward neighbour exchange (distributed_utils.py:74-77);
    here it is explicit because the schedule, not autodiff, owns the backward.

    Args:
      stage_fn: ``(per_stage_params, x) -> y``, ``y.shape == x.shape``.
      stage_params: (S, ...)-leading pytree sharded over ``axis_name``.
      microbatches: ``(M, mb, ...)``; every microbatch must be full-shape.
      loss_fn: ``y -> scalar`` applied to each LAST-stage output; the returned
        loss (and grads) are the mean over the M microbatches.

    ``stream_inputs=True`` shards the microbatch buffer over ``pp`` instead
    of replicating it (requires ``S | M``), using the same just-in-time
    ppermute conveyor as ``gpipe(stream_io=True)`` — the forward sub-tick's
    stage-0 feed timing is identical (microbatch ``u`` consumed at tick
    ``u``). Outputs need no conveyor here: they are already the O(1) loss
    accumulator and O(params) grads.

    Returns:
      ``(loss, grads)``: scalar mean loss (replicated) and a grads pytree
      shaped/sharded like ``stage_params``.
    """
    num_stages = mesh.shape[axis_name]
    num_micro = microbatches.shape[0]
    stash_depth = 2 * num_stages - 1
    total_ticks = num_micro + 2 * (num_stages - 1)
    if stream_inputs and num_micro % num_stages:
        raise ValueError(
            f"stream_inputs requires stages | microbatches, got "
            f"S={num_stages}, M={num_micro}"
        )

    def device_fn(params, xs):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        stage = lax.axis_index(axis_name)
        if not stream_inputs:
            xs = pvary(xs, axis_name)
        mb_shape = xs.shape[1:]

        # Every carry starts device-varying (pvary): the body mixes in
        # stage-dependent data, and scan requires carry-in/out vma types match.
        act0 = pvary(jnp.zeros(mb_shape, xs.dtype), axis_name)
        cot0 = pvary(jnp.zeros(mb_shape, xs.dtype), axis_name)
        stash0 = pvary(jnp.zeros((stash_depth,) + mb_shape, xs.dtype), axis_name)
        # (zeros_like params is already varying — params arrive pp-sharded.)
        gacc0 = jax.tree.map(jnp.zeros_like, params)
        loss0 = pvary(jnp.zeros((), jnp.float32), axis_name)
        # Input conveyor (stream_inputs): shared with gpipe's streamed path.
        if stream_inputs:
            conv0, advance_conv = _input_conveyor(
                xs, stage, axis_name, num_stages, num_micro
            )
        else:
            conv0 = jnp.zeros((), xs.dtype)  # placeholder carry, never read

        def tick(carry, u):
            act, cot, stash, conv, gacc, loss_acc = carry

            # ---- forward sub-tick: mb m_f = u - stage ----------------------
            m_f = u - stage
            f_valid = (m_f >= 0) & (m_f < num_micro)
            received = ring_shift_right(act, axis_name)
            if stream_inputs:
                feed = conv
                conv = advance_conv(conv, u)
            else:
                feed = lax.dynamic_index_in_dim(
                    xs, jnp.clip(m_f, 0, num_micro - 1), 0, keepdims=False
                )
            x_in = jnp.where(stage == 0, feed, received)
            y = stage_fn(params, x_in)
            act_next = y
            # Stash this tick's stage input for our own backward sub-tick
            # (possibly THIS tick, at the last stage). Drain ticks (m_f >= M)
            # must not disturb slot (M-1) % depth, whose backward may still be
            # pending — keep the old slice unless f_valid, so correctness never
            # depends on the drain path bitwise-recomputing mb M-1's boundary
            # (it would stop doing so if stage_fn gained dropout/rng).
            slot = jnp.clip(m_f, 0, num_micro - 1) % stash_depth
            old_slice = lax.dynamic_index_in_dim(stash, slot, 0, keepdims=False)
            stash = lax.dynamic_update_index_in_dim(
                stash, jnp.where(f_valid, x_in, old_slice), slot, 0
            )
            # Last stage only: loss + cotangent seed for the same microbatch.
            # lax.cond so the S-1 other stages skip the loss fwd+bwd entirely
            # (loss_fn is collective-free by contract, so a device-varying
            # predicate is safe under shard_map).
            is_last = stage == num_stages - 1

            def _seed(yy):
                l, g = jax.value_and_grad(loss_fn)(yy)
                return l.astype(jnp.float32), g.astype(yy.dtype)

            loss_u, dy_seed = lax.cond(
                is_last,
                _seed,
                lambda yy: (
                    pvary(jnp.zeros((), jnp.float32), axis_name),
                    jnp.zeros_like(yy),
                ),
                y,
            )
            loss_acc = loss_acc + jnp.where(is_last & f_valid, loss_u, 0.0)

            # ---- backward sub-tick: mb m_b = u - 2(S-1) + stage ------------
            m_b = u - 2 * (num_stages - 1) + stage
            b_valid = (m_b >= 0) & (m_b < num_micro)
            received_cot = ring_shift_left(cot, axis_name)
            dy = jnp.where(is_last, dy_seed, received_cot)
            x_saved = lax.dynamic_index_in_dim(
                stash, jnp.clip(m_b, 0, num_micro - 1) % stash_depth, 0,
                keepdims=False,
            )
            _, f_vjp = jax.vjp(stage_fn, params, x_saved)
            gparams, dx = f_vjp(dy)
            gacc = jax.tree.map(
                lambda a, g: a + jnp.where(b_valid, g, jnp.zeros_like(g)),
                gacc, gparams,
            )
            cot_next = jnp.where(b_valid, dx, jnp.zeros_like(dx))
            return (act_next, cot_next, stash, conv, gacc, loss_acc), None

        (_, _, _, _, gacc, loss_acc), _ = lax.scan(
            tick, (act0, cot0, stash0, conv0, gacc0, loss0),
            jnp.arange(total_ticks),
        )
        # Mean over microbatches; the loss lives on the last stage only — the
        # masked psum replicates it (same pattern as gpipe's output collect).
        loss = (
            lax.psum(
                jnp.where(stage == num_stages - 1, loss_acc, 0.0), axis_name
            )
            / num_micro
        )
        grads = jax.tree.map(lambda g: jnp.expand_dims(g / num_micro, 0), gacc)
        return loss, grads

    return jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name) if stream_inputs else P()),
        out_specs=(P(), P(axis_name)),
        axis_names={axis_name},
    )(stage_params, microbatches)
