"""Pipeline parallelism: GPipe microbatch scheduling over a ``pp`` mesh axis.

The reference has no pipeline layer (its towers are toy Linears,
/root/reference/test_distributed_sigmoid_loss.py:71-76); this module is part of the
beyond-reference scale story, alongside tensor (tp), sequence (sp), and data (dp)
parallelism: deep towers whose layers don't fit one chip are split into S *stages*
laid out along a ``pp`` mesh axis, and M microbatches stream through the stages in
the classic GPipe schedule (S + M - 1 ticks, bubble fraction (S-1)/(S+M-1)).

TPU-native design, not a port of torch.distributed.pipelining:

- **One jitted SPMD program.** Every stage runs the same code under ``shard_map``;
  "which stage am I" is ``lax.axis_index("pp")``, and stage-to-stage activation
  transfer is a single ``ppermute`` ring hop per tick — the ICI-neighbour pattern
  the fabric is built for. There are no per-stage processes, queues, or schedules.
- **Stage-stacked parameters.** Stage s owns ``params[s]`` of a (S, ...)-stacked
  pytree sharded over ``pp`` — with ``depth//S`` transformer layers per stage this
  is exactly the ``nn.scan`` layer-stacked layout reshaped to (S, depth//S, ...),
  so pipeline placement is a pure sharding annotation on the existing tree.
- **Autodiff = the reverse schedule.** The backward pipeline (cotangents flowing
  last-stage → first-stage) is the transpose of ``lax.scan`` + ``ppermute`` — jax
  derives it; nothing hand-written, mirroring how the framework gets the
  reference's ``NeighbourExchange.backward`` for free (collectives.py).
- **Static shapes.** Warmup/drain bubbles run the stage on don't-care data and
  mask the writes (``jnp.where``), keeping every tick identical for XLA.

Composability: ``gpipe`` is manual over ``pp`` only (``axis_names={"pp"}``), so
dp/tp axes of the same mesh keep working through GSPMD — batch stays dp-sharded,
stage weights stay tp-sharded, and the pipeline only moves activations.

Scope note: microbatch inputs/outputs are replicated over ``pp`` (each stage holds
the (M, ...) buffer); at tower-activation sizes this costs M·|x| HBM per chip and
keeps the schedule a pure scan. Streaming stage-0-resident inputs is a further
memory optimization, not a semantics change.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.collectives import pvary, ring_shift_right

__all__ = [
    "pipeline_axis",
    "gpipe",
    "stack_stage_params",
    "make_layer_stage_fn",
]

pipeline_axis = "pp"


def stack_stage_params(layer_params: Any, num_stages: int) -> Any:
    """Reshape layer-stacked params (leaves ``(depth, ...)``) to stage-major
    ``(num_stages, depth // num_stages, ...)`` — the layout :func:`gpipe` shards
    over the ``pp`` axis. ``depth`` must divide evenly into stages."""

    def reshape(leaf):
        depth = leaf.shape[0]
        if depth % num_stages:
            raise ValueError(
                f"depth {depth} does not divide into {num_stages} pipeline stages"
            )
        return leaf.reshape((num_stages, depth // num_stages) + leaf.shape[1:])

    return jax.tree.map(reshape, layer_params)


def make_layer_stage_fn(layer_apply: Callable[[Any, jax.Array], jax.Array]) -> Callable:
    """Stage function applying a stack of identical layers sequentially.

    ``layer_apply(layer_params, x) -> x`` is one layer (e.g.
    ``lambda p, x: block.apply({"params": p}, x)``); the returned stage function
    takes the stage's ``(layers_per_stage, ...)``-stacked params and scans the
    layers — the inner-depth analogue of ``Encoder(scan_layers=True)``.
    """

    def stage_fn(stage_params, x):
        def body(carry, p):
            return layer_apply(p, carry), None

        x, _ = lax.scan(body, x, stage_params)
        return x

    return stage_fn


def gpipe(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    microbatches: jax.Array,
    *,
    mesh: Mesh,
    axis_name: str = pipeline_axis,
    checkpoint_stages: bool = False,
) -> jax.Array:
    """Run ``microbatches`` through ``num_stages`` pipelined stages; returns outputs.

    Args:
      stage_fn: ``(per_stage_params, x) -> y`` with ``y.shape == x.shape`` (a
        residual-block stack; the equal-shape constraint is what lets one ring
        buffer carry every stage boundary).
      stage_params: pytree with leading stage axis ``S == mesh.shape[axis_name]``
        on every leaf, sharded over ``axis_name`` (see :func:`stack_stage_params`).
      microbatches: ``(M, mb, ...)`` array of M microbatches. Any M ≥ 1 works;
        throughput-wise M ≫ S amortizes the (S-1)-tick bubble.
      checkpoint_stages: rematerialize each stage call in the backward pipeline
        (GPipe's standard activation-memory trade).

    Returns:
      ``(M, mb, ...)`` outputs of the full S-stage stack, replicated over ``pp``.
    """
    num_stages = mesh.shape[axis_name]
    num_micro = microbatches.shape[0]
    if checkpoint_stages:
        stage_fn = jax.checkpoint(stage_fn)

    def device_fn(params, xs):
        params = jax.tree.map(lambda p: jnp.squeeze(p, 0), params)
        stage = lax.axis_index(axis_name)
        xs = pvary(xs, axis_name)
        # Ring buffer carrying the stage boundary + the output accumulator
        # (zeros_like the varying xs, so both are varying too).
        act0 = jnp.zeros_like(xs[0])
        out0 = jnp.zeros_like(xs)

        def tick(carry, t):
            act, out = carry
            # Stage boundary hop: every stage sends its last activation right and
            # receives its predecessor's. Stage 0's "received" slot is ignored in
            # favor of the next microbatch feed.
            received = ring_shift_right(act, axis_name)
            feed = lax.dynamic_index_in_dim(
                xs, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
            )
            x_in = jnp.where(stage == 0, feed, received)
            y = stage_fn(params, x_in)
            # The last stage finishes microbatch t-(S-1) at tick t; warmup ticks
            # (t < S-1) write nowhere. Stage-0 re-feeds past M need no guard:
            # they would reach the last stage only at tick M+S-1, past the scan.
            out_idx = jnp.clip(t - (num_stages - 1), 0, num_micro - 1)
            is_ready = (stage == num_stages - 1) & (t >= num_stages - 1)
            out = jnp.where(
                is_ready,
                lax.dynamic_update_index_in_dim(out, y.astype(out.dtype), out_idx, 0),
                out,
            )
            return (y, out), None

        (_, out), _ = lax.scan(
            tick, (act0, out0), jnp.arange(num_micro + num_stages - 1)
        )
        # Only the last stage holds real outputs; the masked psum replicates them
        # to every stage (its transpose feeds cotangents back to the last stage).
        return lax.psum(
            jnp.where(stage == num_stages - 1, out, jnp.zeros_like(out)), axis_name
        )

    return jax.shard_map(
        device_fn,
        mesh=mesh,
        in_specs=(P(axis_name), P()),
        out_specs=P(),
        axis_names={axis_name},
    )(stage_params, microbatches)
