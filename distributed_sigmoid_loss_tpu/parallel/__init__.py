from distributed_sigmoid_loss_tpu.parallel.mesh import (  # noqa: F401
    make_mesh,
    data_axis,
)
from distributed_sigmoid_loss_tpu.parallel.collectives import (  # noqa: F401
    ring_shift_right,
    ring_shift_left,
    neighbour_exchange,
    neighbour_exchange_bidir,
)
from distributed_sigmoid_loss_tpu.parallel.allgather_loss import (  # noqa: F401
    allgather_sigmoid_loss,
)
from distributed_sigmoid_loss_tpu.parallel.ring_loss import (  # noqa: F401
    ring_sigmoid_loss,
)
from distributed_sigmoid_loss_tpu.parallel.contrastive import (  # noqa: F401
    allgather_contrastive_loss,
    ring_contrastive_loss,
)
from distributed_sigmoid_loss_tpu.parallel.api import (  # noqa: F401
    make_sharded_loss_fn,
)
from distributed_sigmoid_loss_tpu.parallel.ring_attention import (  # noqa: F401
    ring_self_attention,
    make_ring_attention,
)
from distributed_sigmoid_loss_tpu.parallel.ulysses_attention import (  # noqa: F401
    ulysses_self_attention,
    make_ulysses_attention,
)
from distributed_sigmoid_loss_tpu.parallel.pipeline import (  # noqa: F401
    gpipe,
    one_f_one_b,
    make_layer_stage_fn,
    stack_stage_params,
)
from distributed_sigmoid_loss_tpu.parallel.compression import (  # noqa: F401
    compressed_axis_mean,
    init_error_feedback,
    quantize_tensor_int8,
    dequantize_tensor_int8,
)
