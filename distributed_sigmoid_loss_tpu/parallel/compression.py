"""Compressed gradient synchronization for the slow (DCN) mesh axis.

Multi-slice data parallelism syncs gradients over two very different links:
ICI within a slice (~100s of GB/s per chip) and DCN between slices (~GB/s per
host). The reference's world does the whole sync in one NCCL all-reduce at
f32 (its test harness's ``average_gradients`` = ``all_reduce(SUM)/W``,
/root/reference/test_distributed_sigmoid_loss.py:79-83); production DLRM/LLM
systems compress the slow hop (Zhang et al., "Dual-Level Adaptive Lossy
Compression", arXiv:2407.04272; Abrahamyan et al., "Learned Gradient
Compression", arXiv:2103.08870 — PAPERS.md). This module is the TPU-native
split of that all-reduce by link speed:

- **ICI hop**: plain f32 ``psum`` over the ``dp`` axis — bandwidth is ample,
  precision is free.
- **DCN hop**: per-tensor symmetric **int8** quantization + ``all_gather`` of
  the int8 payloads (+ one f32 scale per tensor) over the ``dcn`` axis, then
  a local dequantized mean — 4x fewer bytes on the slow wire than f32
  all-reduce at dcn=2 (the common 2-slice case), with **error feedback**
  (Seide et al. 1-bit SGD; Karimireddy et al. EF-SGD) carrying each slice's
  quantization residual into its next step so the bias does not accumulate.

Used inside a fully-manual ``shard_map`` over ``(dcn, dp)`` — see
``train/compressed_step.py``. All functions here are pure and collective-free
except :func:`compressed_axis_mean`, which all-gathers over ``axis_name``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "quantize_tensor_int8",
    "dequantize_tensor_int8",
    "sparsify_topk",
    "densify_topk",
    "compressed_axis_mean",
    "init_error_feedback",
]

_QMAX = 127.0
_EPS = 1e-12


def quantize_tensor_int8(t: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-tensor int8: ``(q, scale)`` with ``q * scale ~= t``.

    Per-tensor (not per-row) scales: gradient tensors are well-conditioned
    after the ICI psum averages ``dp`` microbatches, and error feedback
    absorbs what the coarse scale loses — while the wire format stays ONE
    f32 per tensor.
    """
    scale = jnp.maximum(jnp.max(jnp.abs(t.astype(jnp.float32))), _EPS) / _QMAX
    q = jnp.clip(
        jnp.round(t.astype(jnp.float32) / scale), -_QMAX, _QMAX
    ).astype(jnp.int8)
    return q, scale


def dequantize_tensor_int8(q: jax.Array, scale: jax.Array) -> jax.Array:
    return q.astype(jnp.float32) * scale


def sparsify_topk(
    t: jax.Array, k: int, approximate: bool = True
) -> tuple[jax.Array, jax.Array]:
    """Top-``k``-by-magnitude sparsification: ``(values, flat_indices)``.

    The OTHER standard wire format for gradient compression (deep gradient
    compression / EF-SGD with sparsification): keep the k largest-|.| entries,
    error feedback carries the rest. Wire cost 8 bytes/kept entry (f32 value +
    int32 index) vs 4 bytes/entry dense — a win for k/size < ~1/2, typically
    run at 1%.

    ``approximate=True`` (default) selects via ``lax.approx_max_k`` — the
    TPU-optimized bucketed top-k. Measured on chip at b16 gradient scale
    (docs/PERF.md): exact ``lax.top_k`` costs 227 ms/step (61% of a train
    step — compute-prohibitive), approx 55 ms at 98.5% recall. Bucketed
    selection can occasionally miss entries ABOVE the k-th magnitude (bucket
    collisions keep only the bucket max), so approximation is only sound
    together with error feedback: whatever is missed — large or small —
    rides the residual into the next step. Use it with EF (the compressed
    train step already requires EF for topk).
    """
    flat = t.astype(jnp.float32).ravel()
    if approximate:
        _, idx = lax.approx_max_k(jnp.abs(flat), k)
    else:
        _, idx = lax.top_k(jnp.abs(flat), k)
    idx = idx.astype(jnp.int32)
    return flat[idx], idx


def densify_topk(values: jax.Array, idx: jax.Array, size: int) -> jax.Array:
    """Scatter ``values`` back to a flat zeros(size) (inverse of sparsify)."""
    return jnp.zeros((size,), jnp.float32).at[idx].add(values)


def init_error_feedback(params, n_slices: int):
    """Zero error-feedback state: one f32 residual tree per DCN slice.

    Leaves are ``(n_slices, *param.shape)`` so the global state shards over
    the ``dcn`` axis (each slice holds only ITS residual — one param-sized
    f32 tree per device group, the same budget as one adam moment).
    """
    return jax.tree.map(
        lambda p: jnp.zeros((n_slices,) + p.shape, jnp.float32), params
    )


def compressed_axis_mean(tree, axis_name: str, ef=None, method: str = "int8",
                         topk_frac: float = 0.01,
                         topk_approximate: bool = True):
    """Mean of ``tree`` over the (slow) ``axis_name`` with a compressed wire.

    Must run inside ``shard_map`` manual over ``axis_name``. ``tree`` holds
    this member's local contribution (already averaged over any fast axes).
    ``ef`` is this member's error-feedback tree (same structure, leaves with
    a leading size-1 slice dim from the ``P(axis_name)`` in_spec) or None.

    ``method``: ``"int8"`` (per-tensor symmetric quantization, 4x fewer
    bytes) or ``"topk"`` (top-``topk_frac``-by-magnitude sparsification,
    8 bytes/kept entry — ~50x fewer at the standard 1%; run it WITH error
    feedback, the dropped 99% is pure bias otherwise).
    ``topk_approximate=False`` switches the topk selection to exact
    ``lax.top_k`` (4x slower on TPU at gradient scale, docs/PERF.md).

    Returns ``(mean_tree, new_ef)`` — ``mean_tree`` replicated over the axis,
    ``new_ef`` the residual ``(t + ef) - decompress(compress(t + ef))`` to
    carry into the next step (None if ``ef`` is None).
    """
    if method not in ("int8", "topk"):
        raise ValueError(f"unknown compression method: {method!r}")
    n = lax.axis_size(axis_name)

    def one(t, e):
        target = t if e is None else t + jnp.squeeze(e, 0).astype(t.dtype)
        if method == "int8":
            q, s = quantize_tensor_int8(target)
            sent = dequantize_tensor_int8(q, s)
            qs = lax.all_gather(q, axis_name)    # int8 on the wire
            ss = lax.all_gather(s, axis_name)    # one f32 scale per member
            mean = jnp.sum(
                qs.astype(jnp.float32)
                * ss.reshape((n,) + (1,) * t.ndim), axis=0
            ) / n
        else:
            k = max(1, int(round(topk_frac * t.size)))
            vals, idx = sparsify_topk(target, k, approximate=topk_approximate)
            sent = densify_topk(vals, idx, t.size).reshape(t.shape)
            all_vals = lax.all_gather(vals, axis_name)   # (n, k) f32
            all_idx = lax.all_gather(idx, axis_name)     # (n, k) int32
            mean = (
                jnp.zeros((t.size,), jnp.float32)
                .at[all_idx.ravel()]
                .add(all_vals.ravel())
                .reshape(t.shape)
            ) / n
        new_e = None
        if e is not None:
            new_e = (target.astype(jnp.float32) - sent)[None]
        return mean.astype(t.dtype), new_e

    if ef is None:
        mean = jax.tree.map(lambda t: one(t, None)[0], tree)
        return mean, None
    flat_t, treedef = jax.tree.flatten(tree)
    flat_e = treedef.flatten_up_to(ef)
    out = [one(t, e) for t, e in zip(flat_t, flat_e)]
    mean = treedef.unflatten([m for m, _ in out])
    new_ef = treedef.unflatten([e for _, e in out])
    return mean, new_ef
