"""Differentiable ring communication primitives built on ``jax.lax.ppermute``.

TPU-native equivalent of the reference's hand-rolled autograd P2P layer
(/root/reference/distributed_utils.py): there, ``neighbour_exchange`` batches an
``isend`` to one neighbor with an ``irecv`` from the other (distributed_utils.py:10-27),
and custom ``autograd.Function``s re-run the exchange in the *reverse* direction for the
backward pass (``NeighbourExchange.backward``, distributed_utils.py:74-77;
``NeighbourExchangeBidir.backward``, :94-98).

On TPU none of that machinery is needed: ``jax.lax.ppermute`` IS a batched homogeneous
send/recv over the ICI ring, and its autodiff transpose is the inverse permutation — the
exact semantics the reference hand-writes. These wrappers only fix the ring topology
(left/right neighbors on a named mesh axis) so the loss code reads like the reference's
comm pattern.

All functions must be called inside ``shard_map`` (they take a mesh ``axis_name``).
"""

from __future__ import annotations

import jax
from jax import lax

__all__ = [
    "ring_shift_right",
    "ring_shift_left",
    "neighbour_exchange",
    "neighbour_exchange_bidir",
    "double_buffered_scan",
    "pvary",
    "ring_perm_problems",
    "validate_ring_perm",
]


def ring_perm_problems(perm, axis_size: int) -> list:
    """Why ``perm`` is NOT a total bijection on an axis of ``axis_size``.

    THE shared bijection check: the trace-time guard below and the jaxpr
    auditor (analysis/jaxpr_audit.py, rule ``jaxpr-ppermute-bijection``) both
    call it, so the runtime error and the static finding can never disagree
    about what a valid ring permutation is. A non-bijective perm silently
    zero-fills the shards nobody sends to (``ppermute`` semantics) — the
    broken-ring class: the loss simply loses negative blocks, with no error.

    Returns a list of human-readable problem strings; empty = bijection.
    """
    problems = []
    try:
        pairs = [(int(s), int(d)) for s, d in perm]
    except (TypeError, ValueError):
        return [f"perm is not a sequence of (src, dst) pairs: {perm!r}"]
    oob = [p for p in pairs if not (0 <= p[0] < axis_size and 0 <= p[1] < axis_size)]
    if oob:
        problems.append(f"pairs out of range [0, {axis_size}): {oob}")
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    dup_src = sorted({s for s in srcs if srcs.count(s) > 1})
    dup_dst = sorted({d for d in dsts if dsts.count(d) > 1})
    if dup_src:
        problems.append(f"duplicate source shard(s) {dup_src} (send twice)")
    if dup_dst:
        problems.append(
            f"duplicate destination shard(s) {dup_dst} (collide; the shards "
            "nobody sends to receive ZEROS)"
        )
    if not problems and len(pairs) != axis_size:
        missing = sorted(set(range(axis_size)) - set(srcs))
        problems.append(
            f"partial permutation: only {len(pairs)}/{axis_size} shards "
            f"send (shard(s) {missing} drop their payload and their "
            "neighbors receive zeros)"
        )
    return problems


def validate_ring_perm(perm, axis_size: int, axis_name) -> None:
    """Trace-time twin of the auditor's bijection rule: raise a clear error
    naming the axis and size when ``perm`` is not a total bijection."""
    problems = ring_perm_problems(perm, axis_size)
    if problems:
        raise ValueError(
            f"ppermute permutation over axis {axis_name!r} (size {axis_size}) "
            "is not a bijection: " + "; ".join(problems)
        )


def pvary(x: jax.Array, axis_name):
    """Mark ``x`` as varying over ``axis_name`` under shard_map's replication typing.

    Compat shim: ``lax.pvary`` is deprecated in favor of ``lax.pcast(..,
    to='varying')``; use whichever this jax version provides.
    """
    if hasattr(lax, "pcast"):
        return lax.pcast(x, axis_name, to="varying")
    return lax.pvary(x, axis_name)


def _ring_perm(world_size: int, shift: int) -> list[tuple[int, int]]:
    return [(i, (i + shift) % world_size) for i in range(world_size)]


def ring_shift_right(x: jax.Array, axis_name: str) -> jax.Array:
    """Every shard sends ``x`` to its right neighbor ``(i+1) % W``; returns the shard
    received from the *left* neighbor.

    Equivalent to the reference's ``neighbour_exchange(from=left, to=right, tensor)``
    (distributed_utils.py:10-27) executed simultaneously on all ranks. Differentiable:
    the VJP is a left-shift — identical to ``NeighbourExchange.backward`` swapping
    from_rank/to_rank (distributed_utils.py:74-77).
    """
    w = lax.axis_size(axis_name)
    perm = _ring_perm(w, +1)
    validate_ring_perm(perm, w, axis_name)
    return lax.ppermute(x, axis_name, perm=perm)


def ring_shift_left(x: jax.Array, axis_name: str) -> jax.Array:
    """Mirror of :func:`ring_shift_right`: send to ``(i-1) % W``, receive from the
    right neighbor."""
    w = lax.axis_size(axis_name)
    perm = _ring_perm(w, -1)
    validate_ring_perm(perm, w, axis_name)
    return lax.ppermute(x, axis_name, perm=perm)


def neighbour_exchange(x: jax.Array, axis_name: str, *, to_right: bool = True):
    """One unidirectional ring hop (reference ``neighbour_exchange_with_grad``,
    distributed_utils.py:80-81). ``to_right=True`` matches the reference's default
    call pattern ``neighbour_exchange(left_rank, right_rank, tensor_to_right)``
    (rwightman_sigmoid_loss.py:97-99, 110-112)."""
    return ring_shift_right(x, axis_name) if to_right else ring_shift_left(x, axis_name)


def double_buffered_scan(issue, consume, first, acc, n_hops: int):
    """Comm/compute-overlapped ring loop: issue hop ``k+1`` BEFORE consuming
    hop ``k``.

    The serial ring (``exchange → compute → exchange → ...``) leaves every ICI
    transfer exposed: the MXU idles while the wire moves the next chunk. This
    carry restructure puts each iteration's ``ppermute`` and the PREVIOUS
    hop's block matmuls in the same scan body with no data dependency between
    them, so XLA's scheduler can run the DMA behind the matmul — the standard
    double-buffering cure for exposed exchange latency (the reference gets the
    same overlap from ``batch_isend_irecv`` + interleaved compute).

    Args:
      issue: ``payload -> next_payload`` — the exchange (any pytree payload;
        the bidir ring passes the ``(from_right, from_left)`` pair).
      consume: ``(payload, acc) -> acc`` — hop k's compute.
      first: hop 1's payload, ALREADY issued by the caller (before its own
        local compute, so hop 1 also overlaps).
      n_hops: total hops to consume.

    Returns ``(last_payload, acc)`` where ``last_payload`` is hop
    ``n_hops``'s payload, NOT yet consumed — the caller folds it in the
    epilogue, optionally issuing a final remainder exchange first. Identical
    accumulation order to the serial loop (the adds are merely interleaved
    with comm issue, never reordered), so results stay bitwise-comparable.
    """
    if n_hops < 1:
        raise ValueError(f"n_hops must be >= 1, got {n_hops}")
    if n_hops == 1:
        return first, acc

    def step(carry, _):
        cur, a = carry
        nxt = issue(cur)  # hop k+1 on the wire ...
        a = consume(cur, a)  # ... while hop k feeds the MXU
        return (nxt, a), None

    (last, acc), _ = lax.scan(step, (first, acc), None, length=n_hops - 1)
    return last, acc


def neighbour_exchange_bidir(
    to_left: jax.Array, to_right: jax.Array, axis_name: str
) -> tuple[jax.Array, jax.Array]:
    """Simultaneous exchange with both neighbors; returns ``(from_right, from_left)``.

    Matches the reference's ``neighbour_exchange_bidir_with_grad(left_rank, right_rank,
    tensor_to_left, tensor_to_right) -> (tensor_from_right, tensor_from_left)``
    (distributed_utils.py:30-62, 101-106): two ``ppermute``s — one leftward, one
    rightward — which XLA issues as a single fused bidirectional ICI transfer. The VJP
    is the mirrored pair of permutes, exactly ``NeighbourExchangeBidir.backward``
    (distributed_utils.py:94-98).
    """
    from_left = ring_shift_right(to_right, axis_name)
    from_right = ring_shift_left(to_left, axis_name)
    return from_right, from_left
