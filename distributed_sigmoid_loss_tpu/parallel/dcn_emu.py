"""graftcodec's honest DCN emulation: a throttled two-process localhost pipe.

Every adaptive-vs-fixed number before this module carried the single-slice
caveat: on one host the "dcn" axis is virtual, the all_gather is a memcpy,
and ``dcn_bw_est_mbps`` measured compute price + controller reactivity — not
wire savings. This module closes that gap WITHOUT pretending to be a real
DCN: after each step, the host ships the step's actual ``dcn_wire_bytes``
payload across a localhost socket to a peer *process* that drains it through
a token bucket sized by ``--emu-dcn-mbps``. The measured send→ack time is

- added to the step's wall clock (so adaptive-vs-fixed A/Bs report actual
  wall-clock wire savings at that bandwidth), and
- fed to :class:`~.adaptive_compression.BitController.observe` (so the
  bandwidth EWMA reacts to MEASURED transfer time, exactly as it would to a
  congested inter-slice link).

Topology: one emulator per host process, one sink subprocess (spawned from
this file as a plain script — stdlib-only, no jax import), one long-lived
TCP connection. Each transfer is ``[int64 length][payload]`` down,
``[int64 bytes_drained]`` back; the sink counts every byte and echoes the
count, so a short read is a loud :class:`RuntimeError` ("zero silent drops"
— the dryrun token's contract), never a silently-faster round. A length of
-1 is the shutdown handshake.

The receiver throttles (not the sender): after each chunk it sleeps until
``bytes_so_far * 8 / mbps`` of wall clock has passed, so the measured
transfer time converges to the serialization delay of a ``mbps`` link for
payloads ≫ one chunk, while tiny payloads see mostly the ~RTT floor — the
same shape real links have.

Stdlib-only on both sides; the parent API is :class:`DCNEmulator`.
"""

from __future__ import annotations

import argparse
import os
import socket
import struct
import subprocess
import sys
import time

__all__ = ["DCNEmulator", "serve"]

_HDR = struct.Struct("<q")
_CHUNK = 64 * 1024
_SHUTDOWN = -1


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    parts = []
    while n:
        buf = conn.recv(n)
        if not buf:
            raise ConnectionError("peer closed mid-message")
        parts.append(buf)
        n -= len(buf)
    return b"".join(parts)


def _throttled_drain(conn: socket.socket, nbytes: int, mbps: float) -> int:
    """Read up to ``nbytes`` from ``conn``, pacing reads so the drain rate is
    ``mbps``. Returns the byte count actually read (== nbytes unless the
    peer died — the ack makes any shortfall loud on the other side)."""
    start = time.monotonic()
    got = 0
    while got < nbytes:
        buf = conn.recv(min(_CHUNK, nbytes - got))
        if not buf:
            break
        got += len(buf)
        lag = got * 8.0 / (mbps * 1e6) - (time.monotonic() - start)
        if lag > 0:
            time.sleep(lag)
    return got


def serve(port: int, mbps: float, *, announce=None) -> None:
    """Sink half (runs in the subprocess): accept ONE connection, drain
    length-prefixed payloads through the token bucket, ack each with the
    drained byte count, exit on the shutdown header."""
    if mbps <= 0:
        raise ValueError(f"emulated bandwidth must be > 0 Mbps, got {mbps}")
    srv = socket.create_server(("127.0.0.1", port))
    print(f"DCN_EMU_PORT {srv.getsockname()[1]}", flush=True,
          file=announce or sys.stdout)
    conn, _ = srv.accept()
    srv.close()
    try:
        while True:
            (length,) = _HDR.unpack(_recv_exact(conn, _HDR.size))
            if length == _SHUTDOWN:
                return
            got = _throttled_drain(conn, length, mbps)
            conn.sendall(_HDR.pack(got))
    except ConnectionError:
        return
    finally:
        conn.close()


class DCNEmulator:
    """Parent half: spawn the sink, own the connection, time transfers.

    >>> with DCNEmulator(mbps=200.0) as emu:
    ...     dt = emu.transfer(wire_bytes)     # measured seconds
    ...     controller.observe(dt, wire_bytes)

    ``measured_mbps`` is the EWMA of ``bytes * 8 / dt`` over completed
    transfers — the figure the ``dcn_measured_mbps`` metric stamps; for
    payloads well above one 64 KiB chunk it lands within ~2x of the
    configured throttle (the dryrun token's pin). No locks, no threads: one
    blocking socket used from the training loop's thread only.
    """

    def __init__(self, mbps: float, *, alpha: float = 0.5,
                 connect_timeout_s: float = 30.0):
        if mbps <= 0:
            raise ValueError(
                f"emulated bandwidth must be > 0 Mbps, got {mbps}"
            )
        self.mbps = float(mbps)
        self.alpha = float(alpha)
        self.connect_timeout_s = float(connect_timeout_s)
        self.transfers = 0
        self.bytes_total = 0
        self.measured_mbps: float | None = None
        self._proc: subprocess.Popen | None = None
        self._sock: socket.socket | None = None
        # One reusable zeros block; transfers loop over it so a multi-MB
        # payload never allocates its own buffer.
        self._block = memoryview(bytes(_CHUNK * 16))

    def start(self) -> "DCNEmulator":
        if self._sock is not None:
            return self
        env = dict(os.environ)
        self._proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--serve", "--mbps", str(self.mbps), "--port", "0"],
            stdout=subprocess.PIPE, text=True, env=env,
        )
        line = self._proc.stdout.readline()
        if not line.startswith("DCN_EMU_PORT "):
            raise RuntimeError(f"dcn_emu sink failed to start: {line!r}")
        port = int(line.split()[1])
        self._sock = socket.create_connection(
            ("127.0.0.1", port), timeout=self.connect_timeout_s
        )
        self._sock.settimeout(None)
        return self

    def transfer(self, nbytes) -> float:
        """Ship ``nbytes`` through the throttled pipe; return measured
        seconds (send start → ack). Raises if the sink drained a different
        byte count — a dropped byte must never read as a faster link."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return 0.0
        if self._sock is None:
            self.start()
        sock = self._sock
        t0 = time.monotonic()
        sock.sendall(_HDR.pack(nbytes))
        left = nbytes
        while left:
            take = min(left, len(self._block))
            sock.sendall(self._block[:take])
            left -= take
        (drained,) = _HDR.unpack(_recv_exact(sock, _HDR.size))
        dt = time.monotonic() - t0
        if drained != nbytes:
            raise RuntimeError(
                f"dcn_emu dropped bytes: sent {nbytes}, sink drained "
                f"{drained} — emulated measurements would be silently wrong"
            )
        self.transfers += 1
        self.bytes_total += nbytes
        if dt > 0:
            inst = nbytes * 8.0 / dt / 1e6
            self.measured_mbps = (
                inst if self.measured_mbps is None
                else self.alpha * inst + (1 - self.alpha) * self.measured_mbps
            )
        return dt

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.sendall(_HDR.pack(_SHUTDOWN))
            except OSError:
                pass
            self._sock.close()
            self._sock = None
        if self._proc is not None:
            try:
                self._proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self._proc.kill()
                self._proc.wait()
            if self._proc.stdout is not None:
                self._proc.stdout.close()
            self._proc = None

    def __enter__(self) -> "DCNEmulator":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def _main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--serve", action="store_true", required=True)
    ap.add_argument("--mbps", type=float, required=True)
    ap.add_argument("--port", type=int, default=0)
    args = ap.parse_args(argv)
    serve(args.port, args.mbps)
    return 0


if __name__ == "__main__":
    sys.exit(_main())
