"""Interleaved microbatch split/merge for dp-sharded global batches.

``(B, ...) -> (m, B/m, ...)`` where microbatch i takes the i-th chunk of every
device's RESIDENT rows, so the reshuffle is layout-only — a contiguous global
split would all-to-all the raw batch across the dp axis every step. Shared by
gradient accumulation (train/train_step.py) and the pipeline-parallel towers
(parallel/pp_towers.py): one copy of layout-sensitive sharding logic.

``microbatch_merge`` is the exact inverse, so callers that need row order
preserved end-to-end (the pp towers: the contrastive loss's positive-pair
diagonal) can split, process, and merge without permuting the batch. Gradient
accumulation never merges — microbatch composition is semantically free there.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.mesh import data_axis

__all__ = ["microbatch_split", "microbatch_merge"]


def microbatch_split(
    x: jax.Array, m: int, mesh: Mesh, axis_name: str = data_axis,
    what: str = "microbatches",
) -> jax.Array:
    """``(B, ...) -> (m, B/m, ...)``, per-device-chunk interleaved over ``axis_name``.

    ``what`` names the knob in the divisibility error (callers pass their flag
    name, e.g. "accum_steps" or "pp_microbatches").
    """
    has_axis = axis_name in mesh.axis_names
    d = dict(mesh.shape).get(axis_name, 1)
    b = x.shape[0]
    if b % (d * m):
        raise ValueError(
            f"batch {b} must divide by mesh {axis_name}={d} x {what}={m}"
        )
    c = b // (d * m)
    y = x.reshape(d, m, c, *x.shape[1:])
    if has_axis:
        y = jax.lax.with_sharding_constraint(y, NamedSharding(mesh, P(axis_name)))
    y = jnp.swapaxes(y, 0, 1)
    if has_axis:
        # Pin the transposed layout BEFORE the flattening reshape so GSPMD
        # keeps the swap local to each device's resident chunk.
        y = jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(None, axis_name))
        )
    y = y.reshape(m, d * c, *x.shape[1:])
    if has_axis:
        y = jax.lax.with_sharding_constraint(
            y, NamedSharding(mesh, P(None, axis_name))
        )
    return y


def microbatch_merge(
    y: jax.Array, mesh: Mesh, axis_name: str = data_axis
) -> jax.Array:
    """Exact inverse of :func:`microbatch_split`."""
    has_axis = axis_name in mesh.axis_names
    d = dict(mesh.shape).get(axis_name, 1)
    m, dc = y.shape[0], y.shape[1]
    c = dc // d
    x = y.reshape(m, d, c, *y.shape[2:])
    if has_axis:
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(mesh, P(None, axis_name))
        )
    x = jnp.swapaxes(x, 0, 1)
    x = x.reshape(d * m * c, *y.shape[2:])
    if has_axis:
        x = jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P(axis_name)))
    return x
