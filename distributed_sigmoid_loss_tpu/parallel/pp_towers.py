"""Pipeline-parallel SigLIP tower forwards: the block stack as gpipe stages.

Round-2 left :mod:`parallel.pipeline` a library (oracle-tested on toy stacks);
this module makes it a *capability*: the real ViT / text towers run their
encoder blocks through the GPipe schedule over a ``pp`` mesh axis, composing
with data parallelism (batch stays ``dp``-sharded through GSPMD — gpipe's
``shard_map`` manualizes only ``pp``).

Design: a scanned tower already stores its blocks stage-ready — ``nn.scan``
stacks every block param with a leading ``depth`` axis
(models/transformer.py:326-332), and :func:`pipeline.stack_stage_params` just
reshapes ``(depth, ...) -> (S, depth/S, ...)``, so pipeline placement is a
sharding annotation, not a new param layout. The pre-block (patch/token embed)
and post-block (final LN, pooling, projection) pieces are tiny; they run
replicated-over-``pp`` via the same flax submodules the towers use, applied as
pure functions over the extracted param subtrees. Exactness vs the plain tower
forward is pinned in tests/test_pp_towers.py.

The reference has no model layer at all (its towers are toy Linears,
/root/reference/test_distributed_sigmoid_loss.py:71-76); pipeline parallelism
is part of the beyond-reference scale story alongside dp/tp/sp/ep.

Constraints (validated): towers must be ``scan_layers=True`` (stage-major
params), ``depth % pp == 0``, no sequence parallelism inside a pipelined tower
(nested manual ``shard_map`` axes), and no MoE (the router's sown aux losses
cannot ride ``Block.apply`` under the schedule).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from distributed_sigmoid_loss_tpu.models.transformer import (
    Block,
    MapHead,
    _dtype,
    _remat_policy,
)
from distributed_sigmoid_loss_tpu.models.vit import PatchEmbed
from distributed_sigmoid_loss_tpu.ops.sigmoid_loss import l2_normalize
from distributed_sigmoid_loss_tpu.parallel.microbatch import (
    microbatch_merge,
    microbatch_split,
)
from distributed_sigmoid_loss_tpu.parallel.pipeline import (
    gpipe,
    make_layer_stage_fn,
    pipeline_axis,
    stack_stage_params,
)
from distributed_sigmoid_loss_tpu.utils.config import (
    SigLIPConfig,
    TextConfig,
    ViTConfig,
    tower_quant_mode,
)

__all__ = [
    "siglip_forward_pp",
    "text_forward_pp",
    "validate_pp_tower",
    "vision_forward_pp",
]


def validate_pp_tower(cfg: ViTConfig | TextConfig, num_stages: int, name: str) -> None:
    """Raise with an actionable message when a tower can't be pipelined."""
    if not cfg.scan_layers:
        raise ValueError(
            f"{name}: pipeline parallelism needs scan_layers=True (stage params "
            "are the nn.scan-stacked block leaves)"
        )
    if cfg.depth % num_stages:
        raise ValueError(
            f"{name}: depth {cfg.depth} must divide into {num_stages} pipeline "
            "stages"
        )
    if cfg.sequence_parallel_axis is not None:
        raise ValueError(
            f"{name}: sequence parallelism inside a pipelined tower would nest "
            "manual shard_maps; run sp XOR pp per tower"
        )
    if cfg.moe_experts:
        raise ValueError(
            f"{name}: MoE blocks sow router aux losses, which Block.apply under "
            "the pipeline schedule would silently drop; pp towers must be dense"
        )


def _pipelined_blocks(
    cfg: ViTConfig | TextConfig,
    block_params,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    causal: bool = False,
    axis_name: str = pipeline_axis,
    enclosing_manual: bool = False,
) -> jax.Array:
    """Run the (depth,)-stacked block params over ``x`` via the gpipe schedule.

    ``enclosing_manual``: caller is already inside a shard_map manual over
    ``axis_name`` (and possibly data axes — the compressed step's
    ``(dcn, dp, pp)`` region). ``block_params`` leaves are then the LOCAL
    stage slice ``(depth/S, ...)`` and ``x`` the local batch rows; the
    microbatch split is a plain contiguous reshape (rows are already
    device-local, so the GSPMD-interleaved split is unnecessary) and gpipe
    runs its device-level schedule directly.
    """
    num_stages = mesh.shape[axis_name]
    dtype = _dtype(cfg.dtype)
    block = Block(
        cfg.width, cfg.num_heads, cfg.mlp_ratio, dtype,
        attn_impl=cfg.attn_impl, causal=causal,
        # Same dot injection as the scanned tower (incl. the trainable STE
        # mode) — without this a quantized config would silently run its
        # pipelined blocks full-precision, and the exactness oracle vs the
        # plain tower forward would mask nothing else.
        quant=tower_quant_mode(cfg),
    )

    def layer_apply(p, xx):
        return block.apply({"params": p}, xx)

    if cfg.remat:
        # Per-layer remat with the tower's policy — same granularity the
        # non-pp scan path uses, so the HBM/recompute trade carries over.
        layer_apply = jax.checkpoint(
            layer_apply, policy=_remat_policy(cfg.remat_policy),
            prevent_cse=False,
        )
    stage_fn = make_layer_stage_fn(layer_apply)
    if enclosing_manual:
        # Local stage slice arrives pre-sliced by the enclosing shard_map's
        # P(pp) in_spec; sanity-check it is one stage's worth of layers.
        local_depth = jax.tree.leaves(block_params)[0].shape[0]
        if local_depth * num_stages != cfg.depth:
            raise ValueError(
                f"enclosing_manual expects per-stage block params "
                f"(depth/S = {cfg.depth // num_stages} layers), got leading "
                f"dim {local_depth}"
            )
        if x.shape[0] % num_microbatches:
            raise ValueError(
                f"local batch {x.shape[0]} must divide into "
                f"{num_microbatches} pp microbatches"
            )
        xs = x.reshape((num_microbatches, -1) + x.shape[1:])
        ys = gpipe(
            stage_fn, block_params, xs, mesh=mesh, axis_name=axis_name,
            stream_io=False, enclosing_manual=True,
        )
        return ys.reshape((-1,) + x.shape[1:])
    stage_params = stack_stage_params(block_params, num_stages)
    # Row order is preserved: split -> pipeline -> exact-inverse merge, so the
    # loss's positive-pair diagonal survives the microbatching.
    xs = microbatch_split(x, num_microbatches, mesh, what="pp_microbatches")
    # stream_io whenever the schedule allows (S | M — true for the default
    # M = 2S): the (M, ...) in/out buffers shard over pp instead of
    # replicating, cutting per-stage activation-buffer HBM S-fold.
    ys = gpipe(
        stage_fn, stage_params, xs, mesh=mesh, axis_name=axis_name,
        stream_io=num_microbatches % num_stages == 0,
    )
    return microbatch_merge(ys, mesh)


def vision_forward_pp(
    cfg: ViTConfig,
    params,
    images: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = pipeline_axis,
    enclosing_manual: bool = False,
) -> jax.Array:
    """ViT forward ≡ ``models.vit.ViT.__call__`` with pipelined blocks.

    ``params`` is the tower's (unboxed) param subtree; the pre/post pieces
    reuse the exact flax submodules of the tower, so any future change to the
    tower that this function misses trips the exactness oracle.
    """
    validate_pp_tower(cfg, mesh.shape[axis_name], "vision")
    dtype = _dtype(cfg.dtype)
    x = images.astype(dtype)
    x = PatchEmbed(cfg.width, cfg.patch_size, dtype).apply(
        {"params": params["patch_embed"]}, x
    )
    x = x + params["pos_embed"].astype(dtype)

    x = _pipelined_blocks(
        cfg, params["encoder"]["blocks"]["block"], x,
        mesh=mesh, num_microbatches=num_microbatches, axis_name=axis_name,
        enclosing_manual=enclosing_manual,
    )
    x = nn.LayerNorm(dtype=dtype).apply(
        {"params": params["encoder"]["ln_final"]}, x
    )
    if cfg.pool == "map":
        x = MapHead(cfg.width, cfg.num_heads, cfg.mlp_ratio, dtype).apply(
            {"params": params["map_head"]}, x
        )
    else:
        x = x.mean(axis=1)
    if cfg.use_proj:
        x = nn.Dense(cfg.embed_dim, dtype=dtype).apply(
            {"params": params["proj"]}, x
        )
    return x.astype(jnp.float32)


def text_forward_pp(
    cfg: TextConfig,
    params,
    token_ids: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = pipeline_axis,
    enclosing_manual: bool = False,
) -> jax.Array:
    """Text forward ≡ ``models.text.TextTransformer.__call__`` with pipelined
    blocks."""
    validate_pp_tower(cfg, mesh.shape[axis_name], "text")
    dtype = _dtype(cfg.dtype)
    emb = nn.Embed(cfg.vocab_size, cfg.width).apply(
        {"params": params["token_embed"]}, token_ids
    )
    x = emb.astype(dtype) + params["pos_embed"].astype(dtype)

    x = _pipelined_blocks(
        cfg, params["encoder"]["blocks"]["block"], x,
        mesh=mesh, num_microbatches=num_microbatches, causal=cfg.causal,
        axis_name=axis_name, enclosing_manual=enclosing_manual,
    )
    x = nn.LayerNorm(dtype=dtype).apply(
        {"params": params["encoder"]["ln_final"]}, x
    )
    if cfg.pool == "map":
        x = MapHead(cfg.width, cfg.num_heads, cfg.mlp_ratio, dtype).apply(
            {"params": params["map_head"]}, x
        )
    else:
        x = x[:, -1]
    x = nn.Dense(cfg.embed_dim, dtype=dtype).apply({"params": params["proj"]}, x)
    return x.astype(jnp.float32)


def siglip_forward_pp(
    cfg: SigLIPConfig,
    params,
    images: jax.Array,
    token_ids: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    axis_name: str = pipeline_axis,
    enclosing_manual: bool = False,
):
    """Drop-in for ``SigLIP.apply``: ``(zimg, ztxt, loss_params)`` with both
    towers' blocks pipelined over ``axis_name``. ``enclosing_manual``: see
    :func:`_pipelined_blocks` — the compressed step's fully-manual region."""
    zimg = l2_normalize(
        vision_forward_pp(
            cfg.vision, params["visual"], images,
            mesh=mesh, num_microbatches=num_microbatches, axis_name=axis_name,
            enclosing_manual=enclosing_manual,
        )
    )
    ztxt = l2_normalize(
        text_forward_pp(
            cfg.text, params["textual"], token_ids,
            mesh=mesh, num_microbatches=num_microbatches, axis_name=axis_name,
            enclosing_manual=enclosing_manual,
        )
    )
    return zimg, ztxt, {"t_prime": params["t_prime"], "bias": params["bias"]}
