"""User-facing entry point: turn a per-shard loss into a jitted global-batch loss.

The reference's user contract is "construct the loss module, run under DDP, average
grads" (README.md:17-20). The TPU-native contract is simpler: hand this factory a mesh
and it returns one jit-compiled function over *global* arrays; ``shard_map`` splits them
over the data axis, the variant's collectives stitch shards together, and the returned
scalar is the ``pmean`` over shards — so ``jax.grad`` of it IS the DP-averaged gradient
(the reference needs an explicit ``all_reduce(SUM)/W`` pass,
test_distributed_sigmoid_loss.py:79-83).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Literal

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map

from distributed_sigmoid_loss_tpu.parallel.allgather_loss import allgather_sigmoid_loss
from distributed_sigmoid_loss_tpu.parallel.ring_loss import ring_sigmoid_loss

__all__ = ["make_per_shard_loss", "make_sharded_loss_fn"]


def make_per_shard_loss(
    *,
    family: Literal["sigmoid", "softmax"] = "sigmoid",
    variant: Literal["all_gather", "ring"] = "all_gather",
    axis_name: str = "dp",
    bidir: bool = True,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool = False,
    loss_impl: Literal["fused", "chunked"] = "fused",
    ring_overlap: bool = False,
    quant: str = "",
) -> Callable:
    """The ONE family/variant dispatch, shared by :func:`make_sharded_loss_fn`
    and the train step — returns ``per_shard(zimg, ztxt, t_prime, bias)`` for
    use inside ``shard_map`` (``bias`` is ignored by the softmax family, which
    has no bias term).

    ``loss_impl="chunked"`` (all-gather sigmoid only) streams the gathered
    negatives chunk-by-chunk instead of materializing the full
    ``(local_b, W·local_b)`` logits; ``ring_overlap=True`` (ring sigmoid only)
    double-buffers the hop loop so the ppermute rides behind the block
    matmuls. ``use_pallas`` (sigmoid, any variant/impl) makes the streaming
    2-D Pallas kernel the block body — since the kernel never materializes
    more than one tile, it composes with the chunked scan and the ring's
    per-hop blocks (the round-7 "memory-optimal OR kernel-fast" refusal is
    gone); ``quant="int8"`` (with use_pallas) runs the block products on the
    int8 MXU path (STE semantics). Remaining flag/variant mismatches REFUSE
    rather than silently no-op — a record or run claiming a memory/overlap
    recipe that never executed is the config drift these checks exist to
    prevent.

    Each refusal below is mirrored by a named constraint in
    ``analysis/config_space.CONSTRAINTS`` (``chunked-needs-allgather``,
    ``overlap-needs-ring``, ``softmax-fused-only``, ``pallas-sigmoid-only``,
    …) and the lint drift probe calls this function for every point of the
    raw config product — add/remove a refusal here without updating the
    table and ``lint`` fails with ``config-space-drift``.
    """
    if family not in ("sigmoid", "softmax"):
        raise ValueError(f"unknown family: {family!r}")
    if variant not in ("all_gather", "ring"):
        raise ValueError(f"unknown loss variant: {variant!r}")
    if loss_impl not in ("fused", "chunked"):
        raise ValueError(f"unknown loss_impl: {loss_impl!r}")
    if loss_impl == "chunked" and variant != "all_gather":
        raise ValueError(
            "loss_impl='chunked' applies to the all-gather variant only (the "
            "ring already streams negatives one chunk per hop)"
        )
    if ring_overlap and variant != "ring":
        raise ValueError(
            "ring_overlap applies to the ring variant only (the all-gather "
            "variant has no hop loop to overlap)"
        )
    if family == "softmax" and (loss_impl != "fused" or ring_overlap):
        raise ValueError(
            "loss_impl/ring_overlap apply to the sigmoid family only (the "
            "softmax ring already streams its logsumexp)"
        )
    if quant not in ("", "int8"):
        raise ValueError(f"unknown loss quant: {quant!r}")
    if quant and not use_pallas:
        # Refuse, don't drop: the int8 loss matmul lives in the streaming
        # kernel — without it the flag would silently run full precision.
        raise ValueError(
            "quant='int8' for the loss requires use_pallas (the int8 MXU "
            "block product is the streaming kernel's; the XLA path has none)"
        )
    if quant and family != "sigmoid":
        raise ValueError("loss quant applies to the sigmoid family only")

    if family == "softmax":
        from distributed_sigmoid_loss_tpu.parallel.contrastive import (
            allgather_contrastive_loss,
            ring_contrastive_loss,
        )

        if use_pallas:
            raise ValueError("use_pallas applies to the sigmoid family only")
        fn = {
            "all_gather": allgather_contrastive_loss,
            "ring": ring_contrastive_loss,
        }[variant]

        def per_shard(zimg, ztxt, t_prime, bias=None):
            del bias  # InfoNCE has no bias term
            return fn(zimg, ztxt, t_prime, axis_name=axis_name, precision=precision)

        return per_shard

    if variant == "all_gather":
        return partial(
            allgather_sigmoid_loss,
            axis_name=axis_name, precision=precision, use_pallas=use_pallas,
            loss_impl=loss_impl, quant=quant,
        )
    return partial(
        ring_sigmoid_loss,
        axis_name=axis_name, bidir=bidir, precision=precision,
        use_pallas=use_pallas, overlap=ring_overlap, quant=quant,
    )


def make_sharded_loss_fn(
    mesh: Mesh,
    *,
    variant: Literal["all_gather", "ring"] = "all_gather",
    family: Literal["sigmoid", "softmax"] = "sigmoid",
    axis_name: str = "dp",
    bidir: bool = True,
    precision=lax.Precision.HIGHEST,
    use_pallas: bool = False,
    loss_impl: Literal["fused", "chunked"] = "fused",
    ring_overlap: bool = False,
    quant: str = "",
    jit: bool = True,
) -> Callable:
    """Build ``loss_fn(params, zimg, ztxt) -> scalar`` over global arrays.

    Args:
      mesh: 1-D (or wider) mesh whose ``axis_name`` axis shards the batch.
      variant: ``"all_gather"`` (reference ``DDPSigmoidLoss``) or ``"ring"``
        (reference ``SigLipLoss``).
      family: ``"sigmoid"`` (SigLIP, the reference's loss — params
        ``t_prime``/``bias``) or ``"softmax"`` (CLIP/InfoNCE, the open_clip
        loss the reference's ring variant was a PR against — params
        ``t_prime`` only, see ``ops.init_clip_loss_params``; ring streams the
        logsumexp with the online-softmax recurrence).
      bidir: sigmoid ring only — bidirectional paired hops vs unidirectional
        (reference rwightman_sigmoid_loss.py:30, default True).
      params: dict with scalar leaves ``t_prime`` and (sigmoid only) ``bias``
        (see :func:`distributed_sigmoid_loss_tpu.ops.init_loss_params`).

    The returned scalar is the mean over shards of the per-shard loss (each normalized
    by local batch), i.e. exactly the quantity whose gradient the reference computes via
    per-rank backward + ``all_reduce(SUM)/W``.
    """
    per_shard = make_per_shard_loss(
        family=family, variant=variant, axis_name=axis_name, bidir=bidir,
        precision=precision, use_pallas=use_pallas, loss_impl=loss_impl,
        ring_overlap=ring_overlap, quant=quant,
    )

    def shard_loss(params, zimg, ztxt):
        # Sigmoid requires its bias param — fail with the param's name here
        # rather than an opaque type error inside the loss math; softmax has
        # no bias term and ignores the slot.
        bias = params["bias"] if family == "sigmoid" else params.get("bias")
        loss = per_shard(zimg, ztxt, params["t_prime"], bias)
        return lax.pmean(loss, axis_name)

    batch_spec = P(axis_name)
    fn = shard_map(
        shard_loss,
        mesh=mesh,
        in_specs=(P(), batch_spec, batch_spec),
        out_specs=P(),
        # The pallas interpreter (CPU tests) can't yet type varying/unvarying mixes
        # through its internal dynamic_slice; jax's own error message prescribes
        # disabling the replication check for such bodies. The chunked scan's
        # replicated-init f32 accumulator trips the same typing (the carry
        # turns varying on the first add) — its grads are pinned against the
        # checked fused path by the parity oracles instead.
        check_vma=not (use_pallas or loss_impl == "chunked"),
    )
    return jax.jit(fn) if jit else fn
