"""Distributed softmax (CLIP/InfoNCE) contrastive loss — both comm patterns.

The sigmoid loss's blocks are independent, so its ring variant just sums block
losses (ring_loss.py). Softmax is harder: every row's normalizer is a
logsumexp over ALL global negatives. The two variants here mirror the sigmoid
pair's communication structure exactly:

- :func:`allgather_contrastive_loss` — gather both modalities, one (n, W·n)
  logit block per direction (the open_clip ``ClipLoss(gather_with_grad=True)``
  pattern, torch.distributed.nn.all_gather → here ``lax.all_gather``).
- :func:`ring_contrastive_loss` — stream both modalities' blocks around the
  ``ppermute`` ring keeping a running (rowmax, sumexp) pair per local row —
  the online-softmax recurrence of ring attention applied to the loss
  normalizer. O(local²) logits in flight; exact (not approximate).

Both are per-shard functions for ``shard_map``; the global loss is the
``pmean`` of per-shard means (each shard owns local_b of the W·local_b rows of
each direction, so the mean-of-means IS the global row mean).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from distributed_sigmoid_loss_tpu.parallel.collectives import ring_shift_right

__all__ = ["allgather_contrastive_loss", "ring_contrastive_loss"]


def allgather_contrastive_loss(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    *,
    axis_name: str = "dp",
    precision=lax.Precision.HIGHEST,
) -> jax.Array:
    """Per-shard symmetric InfoNCE with all-gathered negatives.

    i2t rows: this shard's images against every text; t2i rows: this shard's
    texts against every image. Positives sit at global column
    ``idx * local_b + row``.
    """
    local_b, d = zimg.shape
    w = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    scale = jnp.exp(t_prime)

    all_img = lax.all_gather(zimg, axis_name).reshape(w * local_b, d)
    all_txt = lax.all_gather(ztxt, axis_name).reshape(w * local_b, d)

    rows = jnp.arange(local_b)
    pos_col = idx * local_b + rows

    # f32 logits before the logsumexp so bf16 embedding runs keep the same
    # numerics as the ring variant (which upcasts its blocks identically).
    f32 = jnp.float32
    i2t_logits = (scale * jnp.dot(zimg, all_txt.T, precision=precision)).astype(f32)
    i2t = jax.nn.logsumexp(i2t_logits, axis=1) - i2t_logits[rows, pos_col]

    t2i_logits = (scale * jnp.dot(ztxt, all_img.T, precision=precision)).astype(f32)
    t2i = jax.nn.logsumexp(t2i_logits, axis=1) - t2i_logits[rows, pos_col]

    return (jnp.mean(i2t) + jnp.mean(t2i)) / 2


def ring_contrastive_loss(
    zimg: jax.Array,
    ztxt: jax.Array,
    t_prime: jax.Array,
    *,
    axis_name: str = "dp",
    precision=lax.Precision.HIGHEST,
) -> jax.Array:
    """Per-shard symmetric InfoNCE with ring-streamed negatives (exact).

    Hop 0 scores the local (n, n) block (positives on its diagonal); each of
    the W-1 ``ppermute`` hops brings the next shard's embeddings of BOTH
    modalities, and the per-row normalizer is maintained with the online
    recurrence ``m' = max(m, rowmax); s' = s·e^{m-m'} + Σe^{logits-m'}`` —
    numerically identical (up to fp reassociation) to materializing the full
    row. Peak memory O(local_b²) vs the all-gather's O(W·local_b²).
    """
    w = lax.axis_size(axis_name)
    scale = jnp.exp(t_prime)
    f32 = jnp.float32

    def row_stats(logits):
        m = jnp.max(logits, axis=1)
        return m, jnp.sum(jnp.exp(logits - m[:, None]), axis=1)

    def block_stats(a, b_block):
        """Row stats of the (n, n) block scale·a@b_block.T: (rowmax, rowsumexp, diag)."""
        logits = (scale * jnp.dot(a, b_block.T, precision=precision)).astype(f32)
        m, s = row_stats(logits)
        return m, s, jnp.diagonal(logits)

    # Hop 0: ONE local logit block serves both directions (the t2i block is its
    # transpose); the shared diagonal is the positives.
    logits0 = (scale * jnp.dot(zimg, ztxt.T, precision=precision)).astype(f32)
    m_i, s_i = row_stats(logits0)
    m_t, s_t = row_stats(logits0.T)
    pos_i = pos_t = jnp.diagonal(logits0)

    def merge(m, s, bm, bs):
        m_new = jnp.maximum(m, bm)
        return m_new, s * jnp.exp(m - m_new) + bs * jnp.exp(bm - m_new)

    def hop(carry, _):
        img_blk, txt_blk, m_i, s_i, m_t, s_t = carry
        img_blk = ring_shift_right(img_blk, axis_name)
        txt_blk = ring_shift_right(txt_blk, axis_name)
        bm, bs, _ = block_stats(zimg, txt_blk)
        m_i, s_i = merge(m_i, s_i, bm, bs)
        bm, bs, _ = block_stats(ztxt, img_blk)
        m_t, s_t = merge(m_t, s_t, bm, bs)
        return (img_blk, txt_blk, m_i, s_i, m_t, s_t), None

    if w > 1:
        (_, _, m_i, s_i, m_t, s_t), _ = lax.scan(
            hop, (zimg, ztxt, m_i, s_i, m_t, s_t), None, length=w - 1
        )

    i2t = m_i + jnp.log(s_i) - pos_i
    t2i = m_t + jnp.log(s_t) - pos_t
    return (jnp.mean(i2t) + jnp.mean(t2i)) / 2
