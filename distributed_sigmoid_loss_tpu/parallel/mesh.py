"""Mesh construction helpers — the TPU-native replacement for the reference's
``torch.distributed`` process-group runtime.

The reference brings up a Gloo process group with localhost TCP rendezvous
(/root/reference/test_distributed_sigmoid_loss.py:35-51) and fans out OS processes with
``mp.spawn``. On TPU there is no rendezvous code at all: a ``jax.sharding.Mesh`` over
the ICI fabric names the device axes, ``shard_map``/``pjit`` partition arrays over them,
and XLA inserts the collectives. Multi-rank emulation on one host (the reference's
``mp.spawn`` + Gloo trick) becomes ``--xla_force_host_platform_device_count=N`` virtual
CPU devices — same collective semantics, no processes.
"""

from __future__ import annotations

from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis names used across the framework.
data_axis = "dp"  # batch / replica axis — the reference's "world" of DDP ranks
model_axis = "tp"  # tensor-parallel axis for tower weights (absent in the reference)
sequence_axis = "sp"  # sequence-parallel axis for long-context ring attention


def make_mesh(
    world_size: int | None = None,
    axis_name: str = data_axis,
    *,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """1-D mesh of ``world_size`` devices along ``axis_name``.

    ``world_size=None`` uses every visible device. Using fewer devices than visible is
    allowed (e.g. a 3-device mesh out of 8 virtual CPU devices, mirroring the
    reference's odd world_size=3 test configs, test_distributed_sigmoid_loss.py:144).
    """
    if devices is None:
        devices = jax.devices()
    if world_size is None:
        world_size = len(devices)
    if world_size > len(devices):
        raise ValueError(
            f"world_size={world_size} exceeds visible devices ({len(devices)}); "
            "for CPU emulation set XLA_FLAGS=--xla_force_host_platform_device_count=N"
        )
    return Mesh(np.asarray(devices[:world_size]), (axis_name,))


def make_2d_mesh(
    dp: int,
    tp: int,
    *,
    devices: Sequence[jax.Device] | None = None,
    axis_names: tuple[str, str] = (data_axis, model_axis),
) -> Mesh:
    """(dp × tp) mesh for combined data + tensor parallelism of the towers."""
    if devices is None:
        devices = jax.devices()
    if dp * tp > len(devices):
        raise ValueError(f"dp*tp={dp * tp} exceeds visible devices ({len(devices)})")
    grid = np.asarray(devices[: dp * tp]).reshape(dp, tp)
    return Mesh(grid, axis_names)
