"""Ulysses-style all-to-all sequence parallelism.

The second canonical long-context topology (alongside ring attention,
parallel/ring_attention.py): instead of streaming K/V blocks around a ring, one
``all_to_all`` re-shards the activations from sequence-sharded to *head*-sharded, every
chip runs exact dense attention over the full sequence for its head slice, and a second
``all_to_all`` restores sequence sharding. Comm volume is O(1) hops (two all-to-alls)
instead of W-1 ring steps, at the cost of requiring ``num_heads % W == 0`` and holding
the full-sequence activations for the local heads.

The reference has no sequence dimension at all — its ring variant shifts the *batch*
dimension of contrastive negatives (rwightman_sigmoid_loss.py:71-122). Ring attention
generalizes that topology to sequence; Ulysses is the all-to-all alternative the task
calls for. Differentiability is free: ``lax.all_to_all``'s transpose is the reverse
all-to-all, so grads re-shard back without hand-written autograd (contrast the
reference's custom ``NeighbourExchange`` backward, distributed_utils.py:65-98).

Both entry points must run inside ``shard_map`` over ``axis_name``.
"""

from __future__ import annotations

import functools

import jax
from jax import lax
from jax.sharding import PartitionSpec as P

from distributed_sigmoid_loss_tpu.parallel.ring_attention import dense_attention

__all__ = ["ulysses_self_attention", "make_ulysses_attention"]


def ulysses_self_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "sp",
    causal: bool = False,
    scale: float | None = None,
) -> jax.Array:
    """Exact sequence-parallel attention via head-scatter / sequence-gather all-to-all.

    Args:
      q, k, v: (b, s_local, h, dh) — this shard's sequence block; the global sequence
        is the axis-index-ordered concatenation of shards (same contract as
        ``ring_self_attention``).
      causal: global-position causal mask (exact: the full sequence is materialized
        per chip after the first all-to-all).

    Returns (b, s_local, h, dh). Requires ``h % axis_size == 0``.
    """
    w = lax.axis_size(axis_name)
    h = q.shape[2]
    if h % w != 0:
        raise ValueError(
            f"ulysses requires num_heads ({h}) divisible by axis size ({w})"
        )

    # Sequence-sharded -> head-sharded: split the head axis W ways, send slice j to
    # chip j, concatenate received sequence blocks in axis order (= global order).
    def seq_to_heads(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1, tiled=True)

    q_g = seq_to_heads(q)  # (b, s_global, h/W, dh)
    k_g = seq_to_heads(k)
    v_g = seq_to_heads(v)

    out = dense_attention(q_g, k_g, v_g, causal=causal, scale=scale)

    # Head-sharded -> sequence-sharded (the inverse re-shard).
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2, tiled=True)


def make_ulysses_attention(mesh, axis_name: str = "sp", **kw):
    """Convenience wrapper: global (b, S, h, dh) arrays in, sequence sharded over
    ``axis_name`` (mirror of ``make_ring_attention``)."""
    fn = functools.partial(ulysses_self_attention, axis_name=axis_name, **kw)
    spec = P(None, axis_name)
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    )
